"""Structure-aware mutation fuzzing for the decode boundary.

Generates small pristine corpora with the package's own writers (BAM,
BGZF, CRAM, .sbi), mutates them at *structural field boundaries* (length
prefixes, counts, magics, sizes — the fields the guards in
``core/guard.py`` fence), and asserts the decode contract on every
mutant:

1. **no hang** — the parse finishes within a wall-clock bound;
2. **no allocation blow-up** — peak traced allocation stays under the
   active ``DecodeLimits.alloc_budget``;
3. **typed failure** — strict mode either parses cleanly or raises a
   typed error (``MalformedInputError`` and the fault-layer types);
   tolerant mode additionally may quarantine the damaged record/block
   and resume.

Anything else — an untyped ``Exception`` escaping a parser, a parse that
overruns the time or allocation budget — is recorded as a *violation*.
``run_fuzz`` is deterministic for a given seed (splitmix64, the same mix
as ``core/faults.py``), so every violation comes with a one-line repro.

Entry points: ``spark-bam-tpu fuzz-decode`` (CLI), ``tools/fuzz_decode.py``
(repo script), and the ``fuzz``-marked pytest smoke in
``tests/test_malformed.py``.
"""

from __future__ import annotations

import struct
import tempfile
import time
import tracemalloc
import zlib
from pathlib import Path

import numpy as np

from spark_bam_tpu.bam.header import BamHeader, ContigLengths, parse_header
from spark_bam_tpu.bam.iterators import RecordStream
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bam.writer import BGZF_EOF, compress_block, encode_bam_header
from spark_bam_tpu.bgzf.header import HeaderSearchFailedException
from spark_bam_tpu.bgzf.stream import BlockStream, MetadataStream, UncompressedBytes
from spark_bam_tpu.check.checker import NoReadFoundException
from spark_bam_tpu.core import guard
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.faults import (
    BlockCorruptionError,
    BlockGapError,
    ShortReadError,
)
from spark_bam_tpu.core.guard import (
    DecodeLimits,
    MalformedInputError,
    scoped_limits,
)
from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.cram.reader import CramReader
from spark_bam_tpu.cram.writer import CramWriter
from spark_bam_tpu.load.api import load_reads_and_positions
from spark_bam_tpu.sbi.format import (
    PLAN_POS,
    PlanEntry,
    SbiIndex,
    decode_sbi,
    encode_sbi,
    fingerprint_of,
)

FORMATS = ("bam", "bgzf", "cram", "sbi")

#: Typed outcomes the contract accepts from a strict parse of hostile
#: bytes. ``EOFError`` is the pinned clean-truncation signal (PR 2);
#: ``NoReadFoundException`` / ``HeaderSearchFailedException`` are the
#: checker's explicit "no sound structure here" diagnoses.
TYPED_ERRORS = (
    MalformedInputError,
    BlockCorruptionError,
    ShortReadError,
    BlockGapError,
    EOFError,
    NoReadFoundException,
    HeaderSearchFailedException,
)

#: Per-mutant budgets. The corpora are a few KiB, so a healthy parse takes
#: milliseconds and allocates a few hundred KiB — these bounds only trip
#: on quadratic blow-ups a mutation managed to smuggle past the guards.
TIME_LIMIT_S = 5.0
FUZZ_LIMITS = DecodeLimits(alloc_budget=64 << 20)

_M64 = (1 << 64) - 1


class _Rng:
    """splitmix64 — the same mixer as ``core/faults.py``, so fuzz runs are
    reproducible from the seed alone across platforms and sessions."""

    def __init__(self, seed: int):
        self.s = seed & _M64

    def next(self) -> int:
        self.s = (self.s + 0x9E3779B97F4A7C15) & _M64
        z = self.s
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
        return (z ^ (z >> 31)) & _M64

    def below(self, n: int) -> int:
        return self.next() % n

    def choice(self, seq):
        return seq[self.below(len(seq))]


# ------------------------------------------------------------- mutations

#: Adversarial i32 values: sign flips, off-by-one around the minimum
#: record body (33), and allocation-sized extremes.
_I32_POISON = (-1, -2, 0, 1, 32, 33, -(1 << 31), (1 << 31) - 1, 1 << 30)


def _mutate(data: bytes, off: int, rng: _Rng) -> bytes:
    """One structural mutation at ``off``; returns the mutated bytes."""
    buf = bytearray(data)
    op = rng.below(5)
    if op == 0 and off + 4 <= len(buf):
        struct.pack_into("<i", buf, off, rng.choice(_I32_POISON))
    elif op == 1:
        buf[off] ^= 1 << rng.below(8)
    elif op == 2:
        buf[off] = rng.choice((0, 0x80, 0xFF))
    elif op == 3:
        return bytes(buf[: max(off, 1)])  # truncate mid-structure
    elif off + 2 <= len(buf):
        struct.pack_into("<H", buf, off, 0xFFFF)
    else:
        buf[off] ^= 0xFF
    return bytes(buf)


# --------------------------------------------------------------- corpora

def _base_contigs() -> ContigLengths:
    return ContigLengths({0: ("chr1", 100_000), 1: ("chr2", 50_000)})


def _base_records(n: int = 24) -> list[BamRecord]:
    recs = []
    for i in range(n):
        recs.append(
            BamRecord(
                i % 2, 100 + 50 * i, 30, 0, 16 if i % 3 == 0 else 0,
                -1, -1, 0, f"read{i:03d}", [(32, 0)],
                "ACGT" * 8, b"I" * 32, b"",
            )
        )
    return recs


def _bam_uncompressed() -> tuple[bytes, list[int]]:
    """Uncompressed BAM stream + every structural field offset in it."""
    header = BamHeader(
        _base_contigs(), Pos(0, 0), 0,
        "@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:100000\n@SQ\tSN:chr2\tLN:50000\n",
    )
    blob = bytearray(encode_bam_header(header))
    offsets = [0, 4]  # magic, l_text
    (text_len,) = struct.unpack_from("<i", blob, 4)
    o = 8 + text_len
    offsets.append(o)  # n_ref
    (n_ref,) = struct.unpack_from("<i", blob, o)
    o += 4
    for _ in range(n_ref):
        offsets.append(o)  # l_name
        (l_name,) = struct.unpack_from("<i", blob, o)
        o += 4 + l_name
        offsets.append(o)  # l_ref
        o += 4
    # Fixed-field offsets inside each record (block_size .. tlen).
    fixed = (0, 4, 8, 12, 13, 14, 16, 18, 20, 24, 28, 32)
    for rec in _base_records():
        start = len(blob)
        offsets.extend(start + d for d in fixed)
        blob += rec.encode()
    return bytes(blob), offsets


def _bgzf_compress(payload: bytes, chunk: int = 4096) -> tuple[bytes, list[int]]:
    """BGZF-compress ``payload`` into multiple blocks; returns the
    compressed bytes and each block's start offset (EOF block included)."""
    out = bytearray()
    starts = []
    for i in range(0, len(payload), chunk):
        starts.append(len(out))
        out += compress_block(payload[i : i + chunk])
    starts.append(len(out))
    out += BGZF_EOF
    return bytes(out), starts


def _cram_corpus(tmp: Path) -> tuple[bytes, list[int]]:
    path = tmp / "base.cram"
    contigs = ContigLengths({0: ("chr1", 100_000)})
    with CramWriter(
        path, contigs, sam_text="@SQ\tSN:chr1\tLN:100000\n",
        records_per_container=8, index=False,
    ) as w:
        for i in range(16):
            w.write(
                BamRecord(
                    0, 100 + 10 * i, 30, 0, 0, -1, -1, 0, f"q{i}",
                    [(20, 0)], "ACGTACGTACGTACGTACGT", b"I" * 20, b"",
                )
            )
    data = path.read_bytes()
    # Structural hot spots: file definition, SAM-header container, and
    # the first ~32 bytes of every data container (header itf8 fields,
    # first block headers).
    offsets = list(range(0, min(64, len(data))))
    with CramReader(path) as r:
        for info in r.container_infos():
            offsets.extend(
                off for off in range(info.offset, min(info.offset + 32, len(data)))
            )
    return data, sorted(set(offsets))


def _sbi_corpus(bam_path: Path) -> tuple[bytes, list[int]]:
    cfg = Config()
    fp = fingerprint_of(bam_path, cfg)
    ms = MetadataStream(open_channel(bam_path))
    blocks = list(ms)
    u = UncompressedBytes(BlockStream(open_channel(bam_path)))
    hdr = parse_header(u)
    starts = np.array(
        [pos.to_htsjdk() for pos, _ in RecordStream(u, hdr)], dtype=np.uint64
    )
    index = SbiIndex(
        fp,
        blocks=blocks,
        split_plans={65536: [PlanEntry(0, PLAN_POS, Pos(0, 0))]},
        record_starts=starts,
    )
    data = encode_sbi(index)
    # Fixed header fields, then the section table (tag, payload length,
    # and each payload's leading count — the fields _Reader.count fences).
    hdr_end = 4 + 2 + 2 + 24
    offsets = [0, 4, 6, 8, 16, 24, 28, hdr_end]
    (n_sections,) = struct.unpack_from("<I", data, hdr_end)
    o = hdr_end + 4
    for _ in range(n_sections):
        offsets.extend((o, o + 4, o + 12))
        (payload_len,) = struct.unpack_from("<Q", data, o + 4)
        o += 12 + payload_len
    return data, offsets


# -------------------------------------------------------------- consumers

def _consume_bam(path, tolerant: bool) -> int:
    spec = "retries=0" + (",mode=tolerant" if tolerant else "")
    ds = load_reads_and_positions(str(path), config=Config(faults=spec))
    n = 0
    for split in ds.partitions:
        for _ in ds.compute(split):
            n += 1
    return n


def _consume_bgzf(path, tolerant: bool) -> int:
    stream = BlockStream(open_channel(str(path)), tolerant=tolerant)
    n = 0
    try:
        it = iter(stream)
        while True:
            try:
                next(it)
                n += 1
            except StopIteration:
                return n
            except BlockGapError as gap:
                if not tolerant:
                    raise
                if gap.resync is None:
                    return n
                # Channel is already positioned at the resync point.
    finally:
        stream.close()


def _consume_cram(path, tolerant: bool) -> int:
    with CramReader(str(path)) as r:
        return sum(1 for _ in r.records())


def _consume_sbi(path, tolerant: bool) -> int:
    index = decode_sbi(Path(path).read_bytes())
    n = len(index.blocks or [])
    if index.record_starts is not None:
        n += int(index.record_starts.size)
    return n


# ----------------------------------------------------------------- engine

def _repro(seed: int, fmt: str, mutants: int) -> str:
    return (
        f"python tools/fuzz_decode.py --seed {seed} "
        f"--mutants {mutants} --formats {fmt}"
    )


def _run_case(consume, path, tolerant: bool) -> dict:
    """Execute one consumer under the fuzz budgets; classify the outcome."""
    rec0, blk0 = guard.loss_totals()
    tracemalloc.start()
    t0 = time.monotonic()
    outcome, detail = "clean", ""
    try:
        with scoped_limits(FUZZ_LIMITS):
            consume(path, tolerant)
    except TYPED_ERRORS as e:
        outcome, detail = f"malformed:{type(e).__name__}", str(e)[:200]
    except Exception as e:  # the contract breach we are hunting
        outcome, detail = "untyped", f"{type(e).__name__}: {e}"[:300]
    elapsed = time.monotonic() - t0
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    rec1, blk1 = guard.loss_totals()
    if outcome == "clean" and (rec1 > rec0 or blk1 > blk0):
        outcome = "quarantined"
        detail = f"lost {rec1 - rec0} records, {blk1 - blk0} blocks"
    return {
        "outcome": outcome,
        "detail": detail,
        "elapsed_s": round(elapsed, 3),
        "peak_bytes": peak,
    }


def _mutants_for(fmt: str, tmp: Path, rng: _Rng, count: int):
    """Yield ``count`` mutated byte strings for one format."""
    if fmt == "bam":
        payload, offsets = _bam_uncompressed()
        for _ in range(count):
            off = rng.choice(offsets) if rng.below(4) else rng.below(len(payload))
            mutated = _mutate(payload, off, rng)
            yield _bgzf_compress(mutated)[0]
    elif fmt == "bgzf":
        payload, _ = _bam_uncompressed()
        comp, starts = _bgzf_compress(payload)
        offsets = []
        for i, s in enumerate(starts):
            # Block header fields (magic, FLG, XLEN, BC subfield, BSIZE)
            # and the previous block's CRC32/ISIZE trailer.
            offsets.extend(s + d for d in (0, 1, 3, 10, 12, 16, 17) if s + d < len(comp))
            if i > 0:
                offsets.extend((s - 8, s - 4))
        for _ in range(count):
            off = rng.choice(offsets) if rng.below(4) else rng.below(len(comp))
            yield _mutate(comp, off, rng)
    elif fmt == "cram":
        data, offsets = _cram_corpus(tmp)
        for _ in range(count):
            off = rng.choice(offsets) if rng.below(4) else rng.below(len(data))
            yield _mutate(data, off, rng)
    elif fmt == "sbi":
        data, offsets = _sbi_corpus(tmp / "base.bam")
        body = data[:-4]
        for _ in range(count):
            off = rng.choice(offsets) if rng.below(4) else rng.below(len(body))
            mutated = _mutate(body, off, rng)
            if rng.below(4) == 0:
                # Leave the trailer stale: exercises the CRC gate itself.
                yield mutated + data[-4:]
            else:
                # Re-fix the trailer so the mutation reaches the inner
                # count guards instead of being masked by the CRC check.
                yield mutated + struct.pack("<I", zlib.crc32(mutated) & 0xFFFFFFFF)
    else:
        raise ValueError(f"unknown fuzz format {fmt!r}")


_CONSUMERS = {
    "bam": (_consume_bam, True),   # (consumer, has tolerant mode)
    "bgzf": (_consume_bgzf, True),
    "cram": (_consume_cram, False),
    "sbi": (_consume_sbi, False),
}


def run_fuzz(
    seed: int = 0,
    mutants_per_format: int = 200,
    formats: tuple[str, ...] = FORMATS,
) -> dict:
    """Run the mutation fuzz campaign; returns a JSON-able summary whose
    ``"violations"`` list is empty iff every mutant honored the contract."""
    summary: dict = {
        "seed": seed,
        "mutants_per_format": mutants_per_format,
        "formats": list(formats),
        "counts": {},
        "violations": [],
    }
    with tempfile.TemporaryDirectory(prefix="sbt-fuzz-") as d:
        tmp = Path(d)
        # The sbi corpus fingerprints a real BAM; give every format one.
        base_bam, _ = _bam_uncompressed()
        (tmp / "base.bam").write_bytes(_bgzf_compress(base_bam)[0])
        for fmt in formats:
            consume, has_tolerant = _CONSUMERS[fmt]
            counts: dict[str, int] = {}
            rng = _Rng((seed << 16) ^ zlib.crc32(fmt.encode()))
            for idx, mutant in enumerate(_mutants_for(fmt, tmp, rng, mutants_per_format)):
                path = tmp / f"mutant.{fmt}"
                path.write_bytes(mutant)
                modes = (False, True) if has_tolerant else (False,)
                for tolerant in modes:
                    res = _run_case(consume, path, tolerant)
                    if not tolerant:
                        key = res["outcome"]
                        counts[key] = counts.get(key, 0) + 1
                    problems = []
                    if res["outcome"] == "untyped":
                        problems.append(f"untyped error: {res['detail']}")
                    if res["elapsed_s"] > TIME_LIMIT_S:
                        problems.append(f"wall clock {res['elapsed_s']}s > {TIME_LIMIT_S}s")
                    if res["peak_bytes"] > FUZZ_LIMITS.alloc_budget:
                        problems.append(
                            f"peak alloc {res['peak_bytes']} > {FUZZ_LIMITS.alloc_budget}"
                        )
                    for problem in problems:
                        summary["violations"].append(
                            {
                                "format": fmt,
                                "mutant": idx,
                                "mode": "tolerant" if tolerant else "strict",
                                "problem": problem,
                                "repro": _repro(seed, fmt, mutants_per_format),
                            }
                        )
            summary["counts"][fmt] = counts
    return summary


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        description="Structure-aware mutation fuzzing of the decode boundary"
    )
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mutants", type=int, default=200)
    ap.add_argument("--formats", default=",".join(FORMATS))
    ap.add_argument("-o", "--out", default=None)
    args = ap.parse_args(argv)
    summary = run_fuzz(
        seed=args.seed,
        mutants_per_format=args.mutants,
        formats=tuple(f.strip() for f in args.formats.split(",") if f.strip()),
    )
    text = json.dumps(summary, indent=2, sort_keys=True)
    if args.out:
        Path(args.out).write_text(text + "\n")
    print(text)
    return 1 if summary["violations"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
