"""Operational tooling shipped with the package (fuzzing, diagnostics)."""
