"""Declarative SLOs with multi-window burn-rate alerting.

Objectives are compact strings — ``serve.latency:p99<1500ms@5m``,
``serve.errors:ratio<0.1%@1h`` — threaded through ``Config.slo`` /
``SPARK_BAM_SLO`` / ``--slo`` like every other knob surface
(core/config.py string-spec pattern). Grammar::

    <metric>:<agg><cmp><threshold>[@<window>]

- ``metric``: a registered obs series, with two friendly aliases —
  ``<layer>.latency`` reads the ``<layer>.latency_ms`` histogram, and a
  ``ratio`` objective on ``<layer>.errors`` divides by
  ``<layer>.requests`` (error-budget ratio).
- ``agg``: ``p50``/``p90``/``p99`` (quantile over the window, from the
  time-series ring's observation tail), ``mean``, ``rate`` (per second),
  ``ratio``.
- ``cmp``: ``<`` (budget objectives: latency, error ratio) or ``>``
  (floor objectives: throughput).
- ``threshold``: ``1500ms``/``1.5s`` (normalized to ms), ``0.1%``
  (normalized to a fraction), or a bare number.
- ``window``: ``30s``/``5m``/``1h`` — the objective's *fast* window.

Evaluation is Prometheus-style multi-window burn rate: each objective is
measured over its fast window AND a slow confirmation window
(``slow=1h`` by default, degrading to available history on fresh
processes), and ``burn = measured/threshold`` (inverted for ``>``
objectives). An alert FIRES when both windows burn at ≥ the ``burn``
threshold (default 1.0) — the fast window catches the storm, the slow
window keeps one spiky scrape from paging. Alert transitions land in the
flight recorder (``slo_alert`` events), the ``slo.*`` metric family, and
a bounded ledger the ``alerts`` serve op (and the CI failure artifact)
serializes. The fabric autoscaler steers on the resulting burn rate
instead of the raw p99 (fabric/autoscaler.py).

Non-objective ``k=v`` entries in the spec configure the engine itself
and the tail sampler (obs/sampler.py): ``fast``/``slow`` windows,
``every`` (evaluation cadence = ring scrape cadence), ``burn``
(alerting threshold), ``sample`` (tail-sampler keep fraction) and
``seed``. Example full spec::

    serve.latency:p99<1500ms@5m;serve.errors:ratio<0.1%@1h;sample=0.1
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import lru_cache

_AGGS = ("p50", "p90", "p99", "mean", "rate", "ratio")
_WINDOW_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h)$")
_OBJ_RE = re.compile(
    r"^(?P<metric>[a-z_][a-z0-9_.]*):(?P<agg>[a-z0-9]+)"
    r"(?P<cmp><|>)(?P<threshold>[^@]+)(?:@(?P<window>.+))?$"
)
#: alert-ledger ring capacity (the ``alerts`` op / CI artifact tail).
_LEDGER_CAP = 256


def parse_window_s(text: str) -> float:
    """``"90s"``/``"5m"``/``"1h"``/``"500ms"`` → seconds."""
    m = _WINDOW_RE.match(text.strip())
    if not m:
        raise ValueError(
            f"Bad SLO window {text!r}: expected e.g. 30s, 5m, 1h"
        )
    mult = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}[m.group(2)]
    return float(m.group(1)) * mult


def _parse_threshold(text: str) -> "tuple[float, str]":
    """Threshold with unit → (normalized value, unit tag)."""
    text = text.strip()
    if text.endswith("%"):
        return float(text[:-1]) / 100.0, "ratio"
    if text.endswith("ms"):
        return float(text[:-2]), "ms"
    if text.endswith("s"):
        return float(text[:-1]) * 1000.0, "ms"
    return float(text), ""


@dataclass(frozen=True)
class Objective:
    """One parsed objective. ``name`` is the canonical spec string —
    it is the alert identity (ledger entries, ``slo.*`` labels, the
    autoscaler's cited reason)."""

    name: str
    metric: str          # resolved series name (aliases expanded)
    agg: str             # one of _AGGS
    cmp: str             # "<" | ">"
    threshold: float     # ms for latency-like, fraction for ratio
    window_s: float      # the objective's fast window
    denominator: str = ""  # ratio objectives: the traffic counter

    @staticmethod
    def parse(text: str, default_window_s: float = 300.0) -> "Objective":
        m = _OBJ_RE.match(text.strip())
        if not m:
            raise ValueError(
                f"Bad SLO objective {text!r}: expected "
                "<metric>:<agg><cmp><threshold>[@<window>], e.g. "
                "serve.latency:p99<1500ms@5m"
            )
        metric, agg = m.group("metric"), m.group("agg")
        if agg not in _AGGS:
            raise ValueError(
                f"Bad SLO aggregation {agg!r} in {text!r}: expected one of "
                f"{', '.join(_AGGS)}"
            )
        threshold, unit = _parse_threshold(m.group("threshold"))
        window_s = (parse_window_s(m.group("window"))
                    if m.group("window") else default_window_s)
        denominator = ""
        if agg == "ratio":
            layer, _, stage = metric.rpartition(".")
            denominator = f"{layer}.requests" if layer else ""
            if stage != "errors" or not denominator:
                raise ValueError(
                    f"Bad ratio objective {text!r}: ratio is defined for "
                    "<layer>.errors (divided by <layer>.requests)"
                )
        elif metric.endswith(".latency"):
            metric = metric + "_ms"
        if threshold <= 0:
            raise ValueError(f"SLO threshold must be > 0 in {text!r}")
        if unit == "ratio" and agg != "ratio":
            raise ValueError(
                f"Percent threshold needs a ratio aggregation in {text!r}"
            )
        return Objective(
            name=text.strip(), metric=metric, agg=agg, cmp=m.group("cmp"),
            threshold=threshold, window_s=window_s, denominator=denominator,
        )


@dataclass(frozen=True)
class SloConfig:
    """Parsed ``Config.slo`` spec: objectives + engine/sampler knobs."""

    objectives: "tuple[Objective, ...]" = ()
    fast_s: float = 300.0        # default objective window (5m)
    slow_s: float = 3600.0       # confirmation window (1h)
    every_ms: float = 1000.0     # scrape + evaluation cadence
    burn: float = 1.0            # alert when both windows burn ≥ this
    sample: float = 0.1          # tail-sampler keep fraction (fast traces)
    seed: int = 0                # tail-sampler hash seed
    slow_trace_ms: float = 0.0   # sampler slow-trace bar; 0 ⇒ derive from
                                 # the tightest latency objective

    def __post_init__(self):
        if not (0.0 <= self.sample <= 1.0):
            raise ValueError(f"slo sample must be in [0,1]: {self.sample}")
        if self.every_ms <= 0 or self.fast_s <= 0 or self.slow_s <= 0:
            raise ValueError("slo windows/cadence must be > 0")
        if self.burn <= 0:
            raise ValueError(f"slo burn threshold must be > 0: {self.burn}")

    @property
    def enabled(self) -> bool:
        return bool(self.objectives)

    def sampler_slow_ms(self) -> float:
        """The tail sampler's always-keep latency bar: explicit
        ``slow_ms`` wins, else the tightest latency objective's
        threshold, else 1000 ms."""
        if self.slow_trace_ms > 0:
            return self.slow_trace_ms
        lat = [o.threshold for o in self.objectives
               if o.agg.startswith("p") or o.agg in ("mean",)]
        return min(lat) if lat else 1000.0

    _KNOBS = ("fast", "slow", "every", "burn", "sample", "seed", "slow_ms")

    @staticmethod
    @lru_cache(maxsize=64)
    def parse(spec: str) -> "SloConfig":
        """``"serve.latency:p99<1500ms@5m;serve.errors:ratio<0.1%@1h;
        sample=0.1,seed=7"`` (``""`` ⇒ disabled). ``;``-separated;
        entries with a comparator are objectives, ``k=v`` entries are
        engine/sampler knobs (comma-separated within one entry)."""
        kw: dict = {}
        texts: "list[str]" = []
        for part in (spec or "").split(";"):
            part = part.strip()
            if not part:
                continue
            if "<" in part or ">" in part:
                texts.append(part)
                continue
            for entry in part.split(","):
                entry = entry.strip()
                if not entry:
                    continue
                if "=" not in entry:
                    raise ValueError(
                        f"Bad SLO entry {entry!r} in {spec!r}: neither an "
                        "objective nor a k=v knob"
                    )
                key, value = (t.strip() for t in entry.split("=", 1))
                key = key.replace("-", "_")
                if key not in SloConfig._KNOBS:
                    raise ValueError(
                        f"Unknown SLO knob {key!r}: expected one of "
                        f"{', '.join(SloConfig._KNOBS)}"
                    )
                if key in ("fast", "slow"):
                    kw[f"{key}_s"] = parse_window_s(value)
                elif key == "every":
                    kw["every_ms"] = parse_window_s(value) * 1000.0
                elif key == "seed":
                    kw["seed"] = int(value)
                elif key == "slow_ms":
                    kw["slow_trace_ms"] = float(value)
                else:
                    kw[key] = float(value)
        fast = kw.get("fast_s", 300.0)
        objectives = tuple(
            Objective.parse(t, default_window_s=fast) for t in texts
        )
        return SloConfig(objectives=objectives, **kw)

    @staticmethod
    def from_env(env=None) -> "SloConfig":
        import os

        return SloConfig.parse((env or os.environ).get("SPARK_BAM_SLO", ""))


# ----------------------------------------------------------------- engine

def _measure(view, obj: Objective, window_s: float) -> "float | None":
    """One objective's measured value over one window, against any
    delta/rate/ratio/quantile view (live RingStore or SeriesView)."""
    if obj.agg == "ratio":
        return view.ratio(obj.metric, obj.denominator, window_s)
    if obj.agg == "rate":
        return view.rate(obj.metric, window_s)
    if obj.agg in ("p50", "p90", "p99"):
        return view.quantile(obj.metric, int(obj.agg[1:]) / 100.0, window_s)
    if obj.agg == "mean":
        return view.hist_mean(obj.metric, window_s)
    return None


def burn_rate(obj: Objective, value: "float | None") -> float:
    """How fast the objective's budget is burning: 1.0 = exactly at
    target. ``<`` objectives burn as measured/threshold; ``>`` floor
    objectives invert. No data burns nothing."""
    if value is None:
        return 0.0
    if obj.cmp == "<":
        return value / obj.threshold
    return obj.threshold / value if value > 0 else float("inf")


class SloEngine:
    """Evaluate objectives against a ring view; own the alert state.

    ``view_fn`` returns the query surface each evaluation reads
    (normally the worker's live :class:`RingStore`); statuses, a bounded
    alert ledger, and firing flags are kept here and serialized by
    ``status()`` — the payload behind the ``alerts`` op, the stats
    ``slo`` block the autoscaler steers on, and the dashboard ``/slo``
    endpoint.
    """

    def __init__(self, scfg: SloConfig, view_fn):
        self.scfg = scfg
        self._view_fn = view_fn
        self._lock = threading.Lock()
        self._statuses: "list[dict]" = []
        self._firing: "set[str]" = set()
        self.ledger: "deque[dict]" = deque(maxlen=_LEDGER_CAP)

    def evaluate(self) -> "list[dict]":
        """One evaluation pass; returns the per-objective statuses."""
        from spark_bam_tpu import obs
        from spark_bam_tpu.obs import flight

        view = self._view_fn()
        obs.count("slo.evals")
        statuses: "list[dict]" = []
        now = round(time.time(), 3)
        for obj in self.scfg.objectives:
            fast_w = obj.window_s
            slow_w = max(self.scfg.slow_s, fast_w)
            value_fast = _measure(view, obj, fast_w)
            value_slow = _measure(view, obj, slow_w)
            bf = burn_rate(obj, value_fast)
            bs = burn_rate(obj, value_slow)
            firing = bf >= self.scfg.burn and bs >= self.scfg.burn
            st = {
                "objective": obj.name,
                "metric": obj.metric,
                "window_s": fast_w,
                "value_fast": value_fast,
                "value_slow": value_slow,
                "burn_fast": round(bf, 4),
                "burn_slow": round(bs, 4),
                "threshold": obj.threshold,
                "firing": firing,
                "t": now,
            }
            statuses.append(st)
            obs.gauge("slo.burn_rate", objective=obj.name).set(round(bf, 4))
            obs.gauge("slo.firing", objective=obj.name).set(int(firing))
            with self._lock:
                was = obj.name in self._firing
                if firing and not was:
                    self._firing.add(obj.name)
                    # flight.context() carries the chaos seed/spec when
                    # one is installed — an alert fired during a chaos
                    # run names the run that provoked it.
                    entry = dict(st, state="firing", **flight.context())
                    self.ledger.append(entry)
                    obs.count("slo.alerts")
                    flight.record("slo_alert", **entry)
                elif was and not firing:
                    self._firing.discard(obj.name)
                    entry = dict(st, state="resolved", **flight.context())
                    self.ledger.append(entry)
                    flight.record("slo_alert", **entry)
        with self._lock:
            self._statuses = statuses
        return statuses

    def note_event(self, name: str, **fields) -> dict:
        """Out-of-band ledger entry for conditions the burn-rate loop
        cannot see — a durable job pausing on ``ResourceExhausted``, a
        quarantined scrub artifact. Lands in the same ledger (and the
        flight recorder) as an objective alert so the ``alerts`` op and
        the CI failure artifact surface it, but never toggles
        objective firing state."""
        from spark_bam_tpu import obs
        from spark_bam_tpu.obs import flight

        entry = dict(
            fields, objective=name, state="firing", event=name,
            t=round(time.time(), 3), **flight.context(),
        )
        with self._lock:
            self.ledger.append(entry)
        obs.count("slo.alerts")
        flight.record("slo_alert", **entry)
        return entry

    # ------------------------------------------------------------- readers
    @property
    def alerting(self) -> bool:
        """Any objective currently firing — the tail sampler's
        keep-everything window."""
        with self._lock:
            return bool(self._firing)

    def firing(self) -> "list[str]":
        with self._lock:
            return sorted(self._firing)

    def summary(self) -> dict:
        """The compact block ``stats`` embeds (what the autoscaler
        reads): max fast burn + the firing objective names."""
        with self._lock:
            statuses = list(self._statuses)
            firing = sorted(self._firing)
        max_burn = max((s["burn_fast"] for s in statuses), default=0.0)
        worst = max(statuses, key=lambda s: s["burn_fast"], default=None)
        return {
            "objectives": len(self.scfg.objectives),
            "max_burn_fast": max_burn,
            "worst": worst["objective"] if worst else None,
            "firing": firing,
        }

    def status(self) -> dict:
        """The full ``alerts`` op / ``/slo`` payload."""
        with self._lock:
            return {
                "enabled": True,
                "burn_threshold": self.scfg.burn,
                "fast_s": self.scfg.fast_s,
                "slow_s": self.scfg.slow_s,
                "objectives": list(self._statuses),
                "firing": sorted(self._firing),
                "ledger": list(self.ledger),
            }
