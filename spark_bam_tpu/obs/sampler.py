"""Tail-based trace sampling with histogram exemplars.

Head sampling (decide at request start) throws away exactly the traces
you wanted: the slow ones, the failed ones, the ones during an incident.
This sampler decides at request *completion* — Dapper-style tail
sampling:

- keep 100% of slow traces (latency ≥ the bar — by default the
  tightest latency objective's threshold, see ``SloConfig``),
- keep 100% of errored traces,
- keep 100% while any SLO alert is firing (the incident window),
- keep a seeded hash fraction of everything else (deterministic per
  trace id — the same trace is kept or dropped on every worker it
  touched, so cross-process merges never see half a tree).

Dropped traces have their span events pruned from the registry's trace
buffer (``Registry.drop_trace`` — lazily compacted, so the per-request
cost is one set-add); histograms, counters and the time-series ring are
untouched — sampling thins *traces*, never metrics.

Kept slow/errored traces additionally pin an **exemplar** on the
request-latency histogram: ``(latency_ms, trace_id)`` pairs, top-K by
latency, carried through snapshots, fleet merges and the Prometheus
exposition — so ``top``/``metrics-report`` can jump straight from "p99
is burning" to the offending trace tree (docs/observability.md).
"""

from __future__ import annotations

import zlib

#: exemplars retained per histogram series (top-K by value).
EXEMPLAR_CAP = 8


def keep_fraction_hash(seed: int, trace_id: str) -> float:
    """Deterministic [0,1) hash of (seed, trace_id): the same trace gets
    the same verdict on every process that saw it."""
    return zlib.crc32(f"{seed}:{trace_id}".encode()) / 2**32


class TailSampler:
    """Completion-time keep/drop decisions + exemplar pinning."""

    def __init__(self, fraction: float = 0.1, seed: int = 0,
                 slow_ms: float = 1000.0, alerting=None,
                 hist_name: str = "serve.latency_ms"):
        if not (0.0 <= fraction <= 1.0):
            raise ValueError(f"sampler fraction must be in [0,1]: {fraction}")
        self.fraction = float(fraction)
        self.seed = int(seed)
        self.slow_ms = float(slow_ms)
        self.alerting = alerting        # () -> bool; the SLO engine's flag
        self.hist_name = hist_name
        self.kept = 0
        self.dropped = 0

    def decide(self, trace_id: str, ms: float,
               error: bool = False) -> "tuple[bool, str]":
        """(keep?, reason) — pure; ``note`` applies the side effects."""
        if error:
            return True, "error"
        if ms >= self.slow_ms:
            return True, "slow"
        if self.alerting is not None and self.alerting():
            return True, "alert_window"
        if keep_fraction_hash(self.seed, trace_id) < self.fraction:
            return True, "sampled"
        return False, "unsampled"

    def note(self, trace_id: "str | None", ms: float,
             error: bool = False) -> bool:
        """Apply the tail decision for one finished request: prune the
        trace on drop, pin an exemplar on slow/errored keeps. Returns
        whether the trace was kept (no-op True without a trace id)."""
        from spark_bam_tpu import obs

        if trace_id is None:
            return True
        keep, reason = self.decide(trace_id, ms, error=error)
        reg = obs.registry()
        if not keep:
            self.dropped += 1
            obs.count("sampler.dropped")
            if reg is not None:
                reg.drop_trace(trace_id)
            return False
        self.kept += 1
        obs.count("sampler.kept")
        if reg is not None and reason in ("error", "slow", "alert_window"):
            # Label-less on purpose: this is the hist obs.observe() writes
            # (only span-derived hists carry unit="ms").
            reg.histogram(self.hist_name).add_exemplar(ms, trace_id)
            obs.count("sampler.exemplars")
        return True

    def stats(self) -> dict:
        return {
            "fraction": self.fraction,
            "slow_ms": self.slow_ms,
            "kept": int(self.kept),
            "dropped": int(self.dropped),
        }


def merge_exemplars(lists, cap: int = EXEMPLAR_CAP) -> "list[list]":
    """Fold per-worker exemplar lists (``[value_ms, trace_id, t]``) into
    the fleet's top-``cap`` by value — ``merge_snapshots``' helper."""
    out: "list[tuple]" = []
    for lst in lists:
        for e in lst or ():
            out.append(tuple(e))
    out.sort(key=lambda e: -float(e[0]))
    return [list(e) for e in out[:cap]]
