"""Per-request cost accounting: who is spending the fleet's resources.

Every admitted serve request owns one :class:`RequestCost` — a small
mutable vector of the resources it consumed:

- ``queue_ms``  — batcher queue wait, summed over the request's rows
  (the same per-row number the ``serve.queue_ms`` histogram observes);
- ``device_ms`` — its share of each device tick it rode (``tick_ms``
  divided evenly across the tick's live rows, so shares sum back to the
  ``serve.tick`` histogram exactly);
- ``h2d_bytes`` — the window bytes it shipped to the device (mirrored
  by the global ``serve.h2d_bytes`` counter);
- ``host_ms``   — everything else: handler time minus queue and device
  shares (parse, encode, index work), clamped at zero;
- ``bytes_served`` — response bytes (JSON line + binary frames).

The accumulator travels by contextvar, exactly like the trace context
(obs/trace.py): the service binds it around the handler, ``RowTask``
captures it at creation, and the batcher attributes per-row costs at
dispatch — so a tick shared by many requests still bills each request
its own rows. Completed vectors roll up per-op and per-tenant (tenant =
the optional ``tenant`` field on the protocol line, docs/serving.md);
``stats``/``top`` expose the rollups, and the bench's conservation gate
asserts the per-request sums equal the global counters within rounding.

This is the measurement half of fair-share admission (ROADMAP item 1):
before the gate can throttle a tenant, something must know what each
tenant costs.
"""

from __future__ import annotations

import threading
from contextvars import ContextVar

#: the cost vector's fields, in rollup order.
COST_FIELDS = ("queue_ms", "host_ms", "device_ms", "h2d_bytes",
               "bytes_served")

#: rollup-table cardinality guard: an unbounded tenant header must not
#: grow the registry without limit (same concern as obs/names.py).
_MAX_KEYS = 256

_current: "ContextVar[RequestCost | None]" = ContextVar(
    "spark_bam_request_cost", default=None
)


def current() -> "RequestCost | None":
    """The cost accumulator bound to this context, if any (the batcher's
    row-attribution hook — mirrors ``obs.trace.current``)."""
    return _current.get()


def bind(cost: "RequestCost | None"):
    """Bind ``cost`` for the current context; returns the reset token."""
    return _current.set(cost)


def reset(token) -> None:
    _current.reset(token)


class RequestCost:
    """One request's mutable cost vector (adds are lock-guarded: the
    batcher thread attributes rows while the handler thread owns the
    request)."""

    __slots__ = ("op", "tenant", "queue_ms", "host_ms", "device_ms",
                 "h2d_bytes", "bytes_served", "rows", "_lock")

    def __init__(self, op: str, tenant: "str | None" = None):
        self.op = op
        self.tenant = tenant or "-"
        self.queue_ms = 0.0
        self.host_ms = 0.0
        self.device_ms = 0.0
        self.h2d_bytes = 0
        self.bytes_served = 0
        self.rows = 0
        self._lock = threading.Lock()

    def add(self, queue_ms: float = 0.0, device_ms: float = 0.0,
            h2d_bytes: int = 0, rows: int = 0) -> None:
        with self._lock:
            self.queue_ms += queue_ms
            self.device_ms += device_ms
            self.h2d_bytes += h2d_bytes
            self.rows += rows

    def vector(self) -> dict:
        with self._lock:
            return {
                "queue_ms": round(self.queue_ms, 3),
                "host_ms": round(self.host_ms, 3),
                "device_ms": round(self.device_ms, 3),
                "h2d_bytes": int(self.h2d_bytes),
                "bytes_served": int(self.bytes_served),
            }


def _zero() -> dict:
    return {"requests": 0, "errors": 0, "rows": 0, "ms": 0.0,
            **{f: 0.0 if f.endswith("_ms") else 0 for f in COST_FIELDS}}


class Accountant:
    """Thread-safe per-op / per-tenant rollup of finished cost vectors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ops: "dict[str, dict]" = {}
        self._tenants: "dict[str, dict]" = {}
        self._totals = _zero()

    def begin(self, op: str, tenant: "str | None" = None) -> RequestCost:
        return RequestCost(op, tenant)

    def finish(self, cost: RequestCost, total_ms: float,
               bytes_served: int, ok: bool = True) -> dict:
        """Seal a request's vector (derive ``host_ms`` as the handler
        time not spent queued or on device) and roll it up. Returns the
        sealed vector (flight/debug hooks)."""
        from spark_bam_tpu import obs

        with cost._lock:
            cost.bytes_served = int(bytes_served)
            cost.host_ms = max(
                0.0, total_ms - cost.queue_ms - cost.device_ms
            )
        vec = cost.vector()
        with self._lock:
            for table, key in ((self._ops, cost.op),
                               (self._tenants, cost.tenant)):
                if key not in table and len(table) >= _MAX_KEYS:
                    key = "~overflow"
                acc = table.setdefault(key, _zero())
                self._fold(acc, vec, cost.rows, total_ms, ok)
            self._fold(self._totals, vec, cost.rows, total_ms, ok)
            n_tenants = len(self._tenants)
        obs.count("account.requests")
        obs.gauge("account.tenants").set(n_tenants)
        return vec

    @staticmethod
    def _fold(acc: dict, vec: dict, rows: int, total_ms: float,
              ok: bool) -> None:
        acc["requests"] += 1
        acc["errors"] += 0 if ok else 1
        acc["rows"] += rows
        acc["ms"] += total_ms
        for f in COST_FIELDS:
            acc[f] += vec[f]

    def snapshot(self) -> dict:
        """``{"ops": {...}, "tenants": {...}, "totals": {...}}`` with
        ms fields rounded — the ``stats`` op's ``accounting`` block."""
        def shape(acc: dict) -> dict:
            return {k: (round(v, 3) if isinstance(v, float) else v)
                    for k, v in acc.items()}

        with self._lock:
            return {
                "ops": {k: shape(v) for k, v in sorted(self._ops.items())},
                "tenants": {
                    k: shape(v) for k, v in sorted(self._tenants.items())
                },
                "totals": shape(self._totals),
            }


def merge_accounting(snapshots: "list[dict | None]") -> dict:
    """Sum per-worker ``Accountant.snapshot()`` dicts into a fleet view
    (the router's ``telemetry`` merge, alongside snapshot/series)."""
    out = {"ops": {}, "tenants": {}, "totals": _zero()}
    for snap in snapshots:
        if not snap:
            continue
        for table in ("ops", "tenants"):
            for key, acc in snap.get(table, {}).items():
                cur = out[table].setdefault(key, _zero())
                for f, v in acc.items():
                    cur[f] = cur.get(f, 0) + v
        for f, v in snap.get("totals", {}).items():
            out["totals"][f] = out["totals"].get(f, 0) + v
    for table in ("ops", "tenants"):
        out[table] = {
            k: {f: (round(v, 3) if isinstance(v, float) else v)
                for f, v in acc.items()}
            for k, acc in sorted(out[table].items())
        }
    out["totals"] = {
        f: (round(v, 3) if isinstance(v, float) else v)
        for f, v in out["totals"].items()
    }
    return out
