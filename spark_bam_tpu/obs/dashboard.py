"""Zero-dependency HTTP dashboard: /metrics, /slo, /series, and HTML.

``--dashboard host:port`` on ``serve``/``fabric`` starts one of these
next to the accept loop (stdlib ``http.server`` on a daemon thread — no
web framework, no static assets):

- ``GET /metrics`` — Prometheus text exposition of the current (fleet-
  merged, on a router) snapshot, exemplar comments included;
- ``GET /slo``     — JSON: SLO statuses, burn rates, the alert ledger,
  and the accounting rollups (the CI failure artifact grabs this);
- ``GET /series``  — JSON time-series ring snapshot (sparkline feed);
- ``GET /``        — a self-contained HTML page (inline JS/SVG, no CDN)
  polling /series + /slo and drawing per-series sparklines with burn
  badges — the "is the fleet ok" page (docs/observability.md).

The server owns no state: a ``provider`` callable assembles the payload
per request — a worker's provider reads its local registry/engine, the
router's crosses the event-loop boundary via
``asyncio.run_coroutine_threadsafe`` (cli/main.py wires both).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>spark-bam-tpu fleet</title>
<style>
 body{font-family:ui-monospace,monospace;background:#111;color:#ddd;
      margin:1.5em}
 h1{font-size:1.1em} h2{font-size:1em;color:#9ad;margin:1.2em 0 .3em}
 .slo{display:inline-block;margin:.2em .6em .2em 0;padding:.25em .6em;
      border-radius:4px;background:#263}
 .slo.firing{background:#a33}
 .row{display:flex;align-items:center;gap:.8em;margin:.15em 0}
 .name{width:22em;overflow:hidden;text-overflow:ellipsis;color:#aaa}
 .val{width:8em;text-align:right}
 svg{background:#181818;border-radius:3px}
 #err{color:#f88}
</style></head><body>
<h1>spark-bam-tpu fleet dashboard</h1>
<div id="slo"></div><div id="err"></div>
<h2>series</h2><div id="series"></div>
<script>
function spark(pts){
  if(!pts.length) return '';
  const W=220,H=26,vs=pts.map(p=>p[1]);
  const lo=Math.min(...vs),hi=Math.max(...vs),span=(hi-lo)||1;
  const t0=pts[0][0],t1=pts[pts.length-1][0],dt=(t1-t0)||1;
  const d=pts.map((p,i)=>(i?'L':'M')+((p[0]-t0)/dt*W).toFixed(1)+','+
    (H-2-(p[1]-lo)/span*(H-4)).toFixed(1)).join(' ');
  return '<svg width="'+W+'" height="'+H+'"><path d="'+d+
    '" fill="none" stroke="#6cf" stroke-width="1.2"/></svg>';
}
function fmt(v){
  if(v==null) return '-';
  if(Math.abs(v)>=1e9) return (v/1e9).toFixed(1)+'G';
  if(Math.abs(v)>=1e6) return (v/1e6).toFixed(1)+'M';
  if(Math.abs(v)>=1e3) return (v/1e3).toFixed(1)+'k';
  return (Math.round(v*100)/100).toString();
}
async function tick(){
  try{
    const slo=await (await fetch('slo')).json();
    const ser=await (await fetch('series')).json();
    document.getElementById('err').textContent='';
    const objs=(slo.slo&&slo.slo.objectives)||[];
    document.getElementById('slo').innerHTML=objs.length?
      objs.map(o=>'<span class="slo'+(o.firing?' firing':'')+'">'+
        o.objective+' burn '+fmt(o.burn_fast)+'×</span>').join(''):
      '<span class="name">no SLO objectives configured</span>';
    const rows=(ser.series||[]).filter(s=>s.points.length>1)
      .sort((a,b)=>a.name<b.name?-1:1).map(s=>{
        const pts=s.kind==='hist'?s.points.map(p=>[p[0],p[1]]):s.points;
        const last=pts[pts.length-1][1];
        return '<div class="row"><span class="name">'+s.name+
          (s.kind==='hist'?' (count)':'')+'</span>'+spark(pts)+
          '<span class="val">'+fmt(last)+'</span></div>';
      });
    document.getElementById('series').innerHTML=rows.join('');
  }catch(e){document.getElementById('err').textContent='scrape: '+e;}
}
tick();setInterval(tick,2000);
</script></body></html>
"""


def parse_listen(spec: str) -> "tuple[str, int]":
    """``"host:port"`` (or ``":port"`` / bare ``"port"``) → (host, port);
    port 0 binds an ephemeral port (tests)."""
    spec = str(spec).strip()
    if ":" in spec:
        host, _, port = spec.rpartition(":")
        host = host or "127.0.0.1"
    else:
        host, port = "127.0.0.1", spec
    try:
        return host, int(port)
    except ValueError as exc:
        raise ValueError(
            f"Bad dashboard address {spec!r}: expected host:port"
        ) from exc


class DashboardServer:
    """The HTTP surface over one ``provider()`` payload assembler.

    ``provider()`` returns a dict with (any of) ``snapshot``, ``slo``,
    ``series``, ``accounting``, ``flight`` — missing keys render empty,
    a raising provider answers 503, and the accept loop is never
    touched: this is a *read-side* plane.
    """

    def __init__(self, listen: str, provider):
        self.host, self.port = parse_listen(listen)
        self.provider = provider
        self._httpd: "ThreadingHTTPServer | None" = None
        self._thread: "threading.Thread | None" = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "DashboardServer":
        from spark_bam_tpu.obs.exporters import prometheus_text

        provider = self.provider

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):    # no per-request stderr noise
                pass

            def _send(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/":
                    self._send(200, "text/html; charset=utf-8",
                               _PAGE.encode())
                    return
                try:
                    payload = provider() or {}
                except Exception as exc:
                    self._send(503, "text/plain",
                               f"provider error: {exc}".encode())
                    return
                if path == "/metrics":
                    snap = payload.get("snapshot") or {}
                    self._send(200, "text/plain; version=0.0.4",
                               prometheus_text(snap).encode())
                elif path == "/slo":
                    body = json.dumps({
                        "slo": payload.get("slo"),
                        "accounting": payload.get("accounting"),
                        "flight": payload.get("flight"),
                    }, sort_keys=True).encode()
                    self._send(200, "application/json", body)
                elif path == "/series":
                    body = json.dumps(
                        payload.get("series")
                        or {"cadence_ms": 0, "series": []},
                        sort_keys=True,
                    ).encode()
                    self._send(200, "application/json", body)
                else:
                    self._send(404, "text/plain", b"not found\n")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-dashboard",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
