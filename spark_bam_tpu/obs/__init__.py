"""Unified observability: metrics registry, span tracing, exporters.

See ``spark_bam_tpu.obs.registry`` for the design and
``docs/observability.md`` for usage. Import the package and use the
module-level entry points::

    from spark_bam_tpu import obs

    with obs.span("inflate.window", blocks=len(metas)):
        ...
    obs.count("bgzf.blocks_read", len(metas))

Everything is a shared no-op until ``obs.configure()`` runs (the CLI's
``--metrics-out`` / the ``SPARK_BAM_METRICS_OUT`` env var does this).
"""

from spark_bam_tpu.obs import account, flight, sampler, slo, timeseries, trace
from spark_bam_tpu.obs.noise import install_noise_filter
from spark_bam_tpu.obs.registry import (
    NOOP,
    Counter,
    Gauge,
    Histogram,
    Registry,
    Span,
    configure,
    count,
    counter,
    enabled,
    export_jsonl,
    gauge,
    histogram,
    observe,
    read_jsonl,
    registry,
    resolve_metrics_path,
    shutdown,
    span,
)

__all__ = [
    "NOOP",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "Span",
    "account",
    "configure",
    "count",
    "counter",
    "enabled",
    "export_jsonl",
    "flight",
    "gauge",
    "histogram",
    "install_noise_filter",
    "observe",
    "read_jsonl",
    "registry",
    "resolve_metrics_path",
    "sampler",
    "shutdown",
    "slo",
    "span",
    "timeseries",
    "trace",
]
