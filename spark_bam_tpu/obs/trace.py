"""Cross-process trace context: mint, bind, and carry trace/span ids.

One serve request should read as ONE trace no matter how many processes
it crosses (client → router → worker → batcher tick → device dispatch).
This module is the glue: a ``TraceContext`` (trace_id + the span_id of
the caller's active span) bound to the current execution context via
``contextvars`` — which follows both threads (when explicitly rebound at
the pool seam, see ``parallel.executor``) and asyncio tasks — plus a
wire carrier shape for the newline-JSON serve protocol.

Wire format: requests carry an optional ``"trace": {"id": ..., "span":
...}`` field. ``ServeClient`` mints it when observability is enabled in
the client process; the fabric router mints on behalf of bare clients
and relays it to workers; the worker's serve loop rebinds it around the
request handler so every span opened downstream inherits the same
trace_id and parents under the caller's span. ``metrics-report`` then
merges per-process JSONL files by trace_id into one tree.

Ids are opaque hex: 16 hex chars (64 bits) for trace_id and span_id —
collision-safe at fleet request rates, cheap to mint (one urandom call).

Everything here is independent of whether the live registry is
installed; binding a context with obs disabled costs one contextvar set.
"""

from __future__ import annotations

import contextlib
import contextvars
import os


class TraceContext:
    """An immutable (trace_id, parent span_id) pair."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str | None = None):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"


_current: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "spark_bam_trace", default=None
)


def new_id() -> str:
    return os.urandom(8).hex()


def mint() -> TraceContext:
    """A fresh root context (new trace_id, no parent span yet)."""
    return TraceContext(new_id())


def current() -> TraceContext | None:
    """The context bound to this thread/task, or None."""
    return _current.get()


@contextlib.contextmanager
def bind(ctx: TraceContext | None):
    """Bind ``ctx`` for the duration of the block (None unbinds)."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def set_current(ctx: TraceContext | None) -> contextvars.Token:
    """Non-contextmanager bind for callback seams; pair with ``reset``."""
    return _current.set(ctx)


def reset(token: contextvars.Token) -> None:
    _current.reset(token)


# ------------------------------------------------------------------ wire
def carrier(ctx: TraceContext | None = None) -> dict | None:
    """The request-field dict for ``ctx`` (default: the bound context)."""
    if ctx is None:
        ctx = _current.get()
    if ctx is None:
        return None
    c = {"id": ctx.trace_id}
    if ctx.span_id:
        c["span"] = ctx.span_id
    return c


def from_carrier(c) -> TraceContext | None:
    """Parse a request's ``trace`` field back into a context (lenient:
    malformed carriers yield None rather than failing the request)."""
    if not isinstance(c, dict):
        return None
    tid = c.get("id")
    if not isinstance(tid, str) or not tid:
        return None
    sid = c.get("span")
    return TraceContext(tid, sid if isinstance(sid, str) and sid else None)
