"""Benign-noise filtering for worker stderr and CLI output.

``jax.numpy`` import on a CPU-only host logs ``Platform '<x>' is
experimental`` through the xla_bridge logger. Every fabric worker
re-imports jax, so without filtering the line lands in N worker stderrs,
flight-recorder dumps, and every CLI invocation. bench.py already
scrubbed it from *captured child output*; this installs the filter at
the source — a ``logging.Filter`` on the jax/absl loggers plus a
matching ``warnings`` rule — so live processes are quiet too.

Only the known-benign pattern is dropped; anything else (real platform
errors, deprecations, OOM warnings) passes through untouched, and
``tests/test_obs.py`` pins that behavior.
"""

from __future__ import annotations

import logging
import re
import warnings

BENIGN_NOISE = re.compile(r"Platform '\w+' is experimental")

# Loggers jax has used for the platform banner across versions, plus
# absl (which jax routes through when present).
_NOISY_LOGGERS = ("jax._src.xla_bridge", "jax.xla_bridge", "absl")


class BenignNoiseFilter(logging.Filter):
    """Drops records matching ``BENIGN_NOISE``; passes everything else."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            msg = record.getMessage()
        except Exception:
            return True
        return not BENIGN_NOISE.search(msg)


_installed: BenignNoiseFilter | None = None


def install_noise_filter() -> BenignNoiseFilter:
    """Attach the filter to the known noisy loggers (idempotent)."""
    global _installed
    if _installed is None:
        _installed = BenignNoiseFilter()
        warnings.filterwarnings(
            "ignore", message=r".*Platform '\w+' is experimental.*"
        )
    for name in _NOISY_LOGGERS:
        lg = logging.getLogger(name)
        if _installed not in lg.filters:
            lg.addFilter(_installed)
    return _installed
