"""Registry exporters: Prometheus text snapshot + stats-format summary.

The JSONL trace exporter lives with the registry (``obs.export_jsonl`` —
it needs the event buffer); this module renders *snapshots*:

- ``prometheus_text``: the text exposition format — counters and gauges
  verbatim, histograms as summaries (quantiles from the retained
  samples). Metric names sanitize ``layer.stage`` dots to underscores.
- ``stats_summary``: the reference's descriptive-stats format
  (``core/stats.py`` — N/μ/σ, med/mad, percentile ladder), one block per
  histogram series, plus counter/gauge listings. This is the same shape
  the CLI golden reports use, so per-stage timings read like the rest of
  the toolkit's output.
- ``stage_totals``: compact ``{span_name: {count, total_ms}}`` dict —
  the per-stage breakdown bench.py attaches to BENCH_*.json captures.
"""

from __future__ import annotations

import re

from spark_bam_tpu.core.stats import Stats, fmt_num

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _prom_escape(value) -> str:
    # Exposition-format label value escaping: backslash first, then the
    # quote and newline (the three characters the format reserves).
    return (str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_prom_name(str(k))}="{_prom_escape(v)}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


_LABEL_RE = re.compile(r'([a-zA-Z0-9_:]+)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPES = {"n": "\n", '"': '"', "\\": "\\"}


def parse_prom_labels(block: str) -> dict:
    """Invert ``_prom_labels`` (round-trip testing + scrape tooling):
    parse ``{k="v",...}`` back into a dict, unescaping values in one
    left-to-right pass (sequential ``str.replace`` would corrupt a
    literal backslash-n)."""
    return {
        k: _UNESCAPE_RE.sub(lambda m: _UNESCAPES.get(m.group(1), m.group(1)), v)
        for k, v in _LABEL_RE.findall(block)
    }


def prometheus_text(snapshot: dict) -> str:
    """Render a ``Registry.snapshot()`` in Prometheus text format."""
    out: list[str] = []
    seen_type: set[str] = set()

    def type_line(name: str, kind: str):
        if name not in seen_type:
            seen_type.add(name)
            out.append(f"# TYPE {name} {kind}")

    for c in snapshot.get("counters", []):
        name = _prom_name(c["name"])
        type_line(name, "counter")
        out.append(f"{name}{_prom_labels(c.get('labels', {}))} {c['value']}")
    for g in snapshot.get("gauges", []):
        name = _prom_name(g["name"])
        type_line(name, "gauge")
        out.append(f"{name}{_prom_labels(g.get('labels', {}))} {g['value']}")
    for h in snapshot.get("hists", []):
        name = _prom_name(h["name"])
        type_line(name, "summary")
        labels = h.get("labels", {})
        values = sorted(h.get("values", []))
        if values:
            for q in (0.5, 0.9, 0.99):
                idx = min(len(values) - 1, int(q * len(values)))
                ql = dict(labels, quantile=q)
                out.append(f"{name}{_prom_labels(ql)} {values[idx]}")
        out.append(f"{name}_sum{_prom_labels(labels)} {h['sum']}")
        out.append(f"{name}_count{_prom_labels(labels)} {h['count']}")
        # Tail-sampler exemplars as comment lines: the classic text
        # format has no exemplar syntax (that's OpenMetrics), and a
        # comment keeps every scraper happy while still shipping the
        # trace ids next to the series they explain.
        for e in h.get("exemplars", []) or ():
            out.append(
                f"# exemplar {name}"
                f'{{trace_id="{_prom_escape(e[1])}"}} {e[0]}'
            )
    return "\n".join(out) + "\n"


def _series_title(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}[{inner}]"


def stats_summary(snapshot: dict, spans_by_name: dict | None = None) -> str:
    """Human summary in the reference stats format.

    ``spans_by_name`` ({name: [durations_ms]}), when given (the
    metrics-report path, rebuilt from trace events), replaces histogram
    series whose name matches — trace events are the full-fidelity
    source when both exist.
    """
    blocks: list[str] = []
    spans_by_name = dict(spans_by_name or {})
    hists = list(snapshot.get("hists", []))
    seen: set[str] = set()
    for h in hists:
        name = h["name"]
        values = spans_by_name.pop(name, None)
        if values is None:
            values = h.get("values", [])
        seen.add(name)
        title = _series_title(name, h.get("labels", {}))
        if values:
            blocks.append(f"{title}:\n{Stats(values).show()}")
        else:
            blocks.append(
                f"{title}:\nN: {h['count']}, sum: {fmt_num(h['sum'])}"
                f" (samples not retained)"
            )
    for name, values in sorted(spans_by_name.items()):
        blocks.append(f"{name}[unit=ms]:\n{Stats(values).show()}")

    counters = snapshot.get("counters", [])
    if counters:
        lines = ["counters:"]
        for c in sorted(counters, key=lambda c: c["name"]):
            lines.append(
                f"\t{_series_title(c['name'], c.get('labels', {}))}:"
                f" {c['value']}"
            )
        blocks.append("\n".join(lines))
    gauges = snapshot.get("gauges", [])
    if gauges:
        lines = ["gauges:"]
        for g in sorted(gauges, key=lambda g: g["name"]):
            peak = g.get("max")
            suffix = f" (peak {fmt_num(peak)})" if peak is not None else ""
            lines.append(
                f"\t{_series_title(g['name'], g.get('labels', {}))}:"
                f" {fmt_num(g['value'])}{suffix}"
            )
        blocks.append("\n".join(lines))
    return "\n\n".join(blocks) + ("\n" if blocks else "")


def merge_snapshots(snapshots: list[dict]) -> dict:
    """Merge per-worker ``Registry.snapshot()`` dicts into one fleet view.

    Counters sum; gauges sum values (queue depths and in-flight counts
    read as fleet totals) and take the max of peaks; histograms sum
    count/sum, merge min/max, and concatenate retained samples (capped)
    so fleet p50/p99 come from a cross-worker sample. Series identity is
    ``(name, sorted labels)`` — the registry's own key.
    """
    from spark_bam_tpu.obs.registry import _HIST_SAMPLE_CAP

    def key(entry):
        return (entry["name"], tuple(sorted(entry.get("labels", {}).items())))

    counters: dict = {}
    gauges: dict = {}
    hists: dict = {}
    dropped = 0
    for snap in snapshots:
        if not snap:
            continue
        dropped += int(snap.get("dropped_events", 0))
        for c in snap.get("counters", []):
            cur = counters.setdefault(
                key(c), {"name": c["name"],
                         "labels": dict(c.get("labels", {})), "value": 0})
            cur["value"] += c["value"]
        for g in snap.get("gauges", []):
            cur = gauges.setdefault(
                key(g), {"name": g["name"],
                         "labels": dict(g.get("labels", {})),
                         "value": 0.0, "max": None})
            cur["value"] += g["value"]
            gmax = g.get("max")
            if gmax is not None and (cur["max"] is None or gmax > cur["max"]):
                cur["max"] = gmax
        for h in snap.get("hists", []):
            cur = hists.setdefault(
                key(h), {"name": h["name"],
                         "labels": dict(h.get("labels", {})),
                         "count": 0, "sum": 0.0, "min": None, "max": None,
                         "values": [], "exemplars": []})
            cur["count"] += h["count"]
            cur["sum"] += h["sum"]
            if h.get("exemplars"):
                from spark_bam_tpu.obs.sampler import merge_exemplars

                cur["exemplars"] = merge_exemplars(
                    [cur["exemplars"], h["exemplars"]]
                )
            for bound, better in (("min", lambda a, b: b < a),
                                  ("max", lambda a, b: b > a)):
                v = h.get(bound)
                if v is not None and (cur[bound] is None
                                      or better(cur[bound], v)):
                    cur[bound] = v
            room = _HIST_SAMPLE_CAP - len(cur["values"])
            if room > 0:
                cur["values"].extend(h.get("values", [])[:room])
    for cur in hists.values():
        if not cur["exemplars"]:
            del cur["exemplars"]
    return {
        "counters": list(counters.values()),
        "gauges": list(gauges.values()),
        "hists": list(hists.values()),
        "dropped_events": dropped,
    }


def stage_totals(snapshot: dict) -> dict:
    """``{span_name: {"count": n, "total_ms": x}}`` for every ms-unit
    histogram — the compact per-stage breakdown for bench captures."""
    out: dict[str, dict] = {}
    for h in snapshot.get("hists", []):
        if h.get("labels", {}).get("unit") != "ms":
            continue
        out[h["name"]] = {
            "count": h["count"],
            "total_ms": round(h["sum"], 3),
        }
    return out
