"""Process-wide metrics registry and span tracing.

The reference's only observability is ad-hoc ``Timer.time`` blocks and
heartbeat log lines (SURVEY.md §5; its docs admit "no profiling having
been done"). This subsystem replaces that shape with first-class,
artifact-producing instrumentation for the BGZF→inflate→check→load hot
path:

- **Metrics**: labeled ``Counter`` / ``Gauge`` / ``Histogram`` series in
  one process-wide ``Registry`` (``obs.counter("bgzf.blocks_read")``).
- **Spans**: ``with obs.span("inflate.window", blocks=n):`` context
  managers that nest (contextvar stack — per asyncio task, per thread),
  record wall time, emit one structured JSONL event each, and feed a
  per-name duration histogram so aggregate timings survive even when the
  raw trace is capped.
- **Exporters** (``obs.exporters``): JSONL trace file, Prometheus
  text-format snapshot, and a human summary in the reference's stats
  format (``core/stats.py``).

Disabled by default: until ``configure()`` installs a live registry,
every entry point returns a shared no-op singleton — no allocation, no
locking, no timestamps — so instrumented hot loops cost one attribute
load + one ``is None`` test. ``--metrics-out PATH`` on any CLI
subcommand (or the ``SPARK_BAM_METRICS_OUT`` env var) enables it for
that run and writes the trace on exit.

Span naming convention: dotted ``layer.stage`` names — ``bgzf.read``,
``inflate.window``, ``check.window``, ``load.partition``, ``mesh.step``
— so reports group naturally by hot-path layer.
"""

from __future__ import annotations

import contextvars
import json
import os
import random
import threading
import time
import zlib
from typing import Iterator

from spark_bam_tpu.obs import trace as _trace

# The open-span stack rides the execution CONTEXT, not the thread: on an
# asyncio loop every task shares one thread, and a thread-local stack
# would parent task B's span under whatever span task A still has open —
# grafting B onto A's trace and, once interleaved exits leak an entry,
# poisoning every later span on that thread. Immutable tuples + contextvar
# give each task (and each thread — fresh threads start with an empty
# context) its own properly-nested stack.
_SPAN_STACK: "contextvars.ContextVar[tuple]" = contextvars.ContextVar(
    "spark_bam_span_stack", default=()
)

# Histograms keep raw samples (for reference-style stats rendering) up to
# this many observations; beyond it a uniform reservoir (algorithm R)
# replaces slots at random so long serve runs stay bounded while p50/p99
# remain stable. count/sum/min/max stay exact throughout.
_HIST_SAMPLE_CAP = 4096
# The JSONL trace buffer stops appending events past this; dropped events
# are counted and still feed the per-name duration histograms.
_TRACE_EVENT_CAP = 200_000


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class Counter:
    """Monotonic counter; ``inc`` is the only mutator."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-write-wins scalar, plus a running max (peak tracking)."""

    __slots__ = ("name", "labels", "value", "max")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self.value = 0.0
        self.max = None

    def set(self, v) -> None:
        self.value = v
        if self.max is None or v > self.max:
            self.max = v


class Histogram:
    """Sample distribution: exact count/sum/min/max; raw values retained
    up to ``_HIST_SAMPLE_CAP``, then reservoir-downsampled (algorithm R)
    so hot serve paths never grow memory while quantiles stay a uniform
    sample of the full stream. The RNG is seeded from the series name so
    quantile renders are reproducible run-to-run.

    Two optional attachments (both None until something asks for them,
    so the default observe path pays nothing):

    - ``ring``: a bounded deque of ``(t, value)`` recent observations,
      installed when a time-series RingStore attaches to the registry —
      the source for quantile-over-window queries (obs/timeseries.py).
    - ``exemplars``: top-K ``[value, trace_id, t]`` triples pinned by
      the tail sampler (obs/sampler.py), linking a burning percentile
      to the exact trace that burned it.
    """

    __slots__ = ("name", "labels", "count", "sum", "min", "max", "values",
                 "_rng", "ring", "exemplars")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self.values: list[float] = []
        self._rng = None
        self.ring = None
        self.exemplars = None

    def observe(self, v: float) -> None:
        self.count += 1
        self.sum += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        r = self.ring
        if r is not None:
            r.append((time.time(), v))
        if len(self.values) < _HIST_SAMPLE_CAP:
            self.values.append(v)
        else:
            rng = self._rng
            if rng is None:
                seed = zlib.crc32(repr((self.name, self.labels)).encode())
                rng = self._rng = random.Random(seed)
            j = rng.randrange(self.count)
            if j < _HIST_SAMPLE_CAP:
                self.values[j] = v

    def add_exemplar(self, v: float, trace_id: str,
                     cap: int = 8) -> None:
        """Pin ``(v, trace_id)``, keeping the top-``cap`` by value."""
        ex = self.exemplars
        if ex is None:
            ex = self.exemplars = []
        ex.append([round(float(v), 3), trace_id, round(time.time(), 3)])
        if len(ex) > cap:
            ex.sort(key=lambda e: -e[0])
            del ex[cap:]


class Span:
    """One timed, nesting unit of work. Use via ``obs.span(name, **attrs)``.

    When a :mod:`spark_bam_tpu.obs.trace` context is bound (a serve
    request carried a trace_id across the wire), the span joins that
    trace: it mints its own span_id, parents under the caller's span
    (or the enclosing local span), and rebinds the trace contextvar for
    its duration so nested work — including threads that capture the
    context at the seam — lands in the same tree. With no trace bound,
    spans behave exactly as before (local name-parenting only).
    """

    __slots__ = ("registry", "name", "attrs", "parent", "depth", "_t0",
                 "t_wall", "trace_id", "span_id", "parent_span_id",
                 "_ctx_token", "_stack_token")

    def __init__(self, registry: "Registry", name: str, attrs: dict):
        self.registry = registry
        self.name = name
        self.attrs = attrs
        self.parent = None
        self.depth = 0
        self._t0 = 0.0
        self.t_wall = 0.0
        self.trace_id = None
        self.span_id = None
        self.parent_span_id = None
        self._ctx_token = None
        self._stack_token = None

    def set(self, **attrs) -> None:
        """Attach attributes mid-span (e.g. measured device time)."""
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        stack = _SPAN_STACK.get()
        if stack:
            top = stack[-1]
            self.parent = top.name
            self.depth = len(stack)
            if top.trace_id is not None:
                self.trace_id = top.trace_id
                self.parent_span_id = top.span_id
        if self.trace_id is None:
            ctx = _trace.current()
            if ctx is not None:
                self.trace_id = ctx.trace_id
                self.parent_span_id = ctx.span_id
        if self.trace_id is not None:
            self.span_id = _trace.new_id()
            self._ctx_token = _trace.set_current(
                _trace.TraceContext(self.trace_id, self.span_id)
            )
        self._stack_token = _SPAN_STACK.set(stack + (self,))
        self.t_wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        ms = (time.perf_counter() - self._t0) * 1e3
        # reset() restores the exact entry-time stack — exits from
        # interleaved asyncio tasks can't pop each other's spans.
        _SPAN_STACK.reset(self._stack_token)
        if self._ctx_token is not None:
            _trace.reset(self._ctx_token)
            self._ctx_token = None
        self.registry._finish_span(self, ms)


class _NoopMetric:
    """Shared do-nothing Counter/Gauge/Histogram stand-in."""

    __slots__ = ()

    def inc(self, n: int = 1) -> None:
        pass

    def set(self, v=None, **attrs) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    # Context-manager face: span() returns this same singleton when
    # observability is disabled — zero allocation on the hot path.
    def __enter__(self) -> "_NoopMetric":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOOP = _NoopMetric()


class Registry:
    """Process-wide metric store + span trace buffer (thread-safe)."""

    def __init__(self, max_events: int = _TRACE_EVENT_CAP):
        self._lock = threading.Lock()
        self._counters: dict[tuple, Counter] = {}
        self._gauges: dict[tuple, Gauge] = {}
        self._hists: dict[tuple, Histogram] = {}
        self._events: list[dict] = []
        self._dropped = 0
        self._max_events = max_events
        self.t_start = time.time()
        # Time-series attachment (obs/timeseries.py): once a RingStore
        # attaches, new and existing histograms grow an observation ring
        # so quantile-over-window queries have raw samples to read.
        self.rings = None
        self._ring_obs_cap = 0
        # Tail-sampled trace drops are batched: ids land in this set and
        # the event buffer compacts once the set is large enough, so a
        # dropped request costs one set-add, not an O(events) sweep.
        self._dropped_traces: set = set()

    # ------------------------------------------------------------- metrics
    def _get(self, table: dict, cls, name: str, labels: dict):
        key = (name, _label_key(labels))
        m = table.get(key)
        if m is None:
            with self._lock:
                m = table.setdefault(key, cls(name, labels))
            if (cls is Histogram and self._ring_obs_cap
                    and m.ring is None):
                from collections import deque

                m.ring = deque(maxlen=self._ring_obs_cap)
        return m

    def attach_rings(self, store) -> None:
        """Install a time-series RingStore: existing and future
        histograms get bounded ``(t, value)`` observation rings."""
        from collections import deque

        self.rings = store
        with self._lock:
            self._ring_obs_cap = int(store.obs_cap)
            hists = list(self._hists.values())
        for h in hists:
            if h.ring is None:
                h.ring = deque(maxlen=self._ring_obs_cap)

    def counter(self, name: str, **labels) -> Counter:
        return self._get(self._counters, Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(self._gauges, Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(self._hists, Histogram, name, labels)

    # --------------------------------------------------------------- spans
    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def _finish_span(self, span: Span, ms: float) -> None:
        self.histogram(span.name, unit="ms").observe(ms)
        event = {
            "e": "span",
            "name": span.name,
            "ms": round(ms, 3),
            "t": round(span.t_wall, 6),
            "depth": span.depth,
        }
        if span.parent is not None:
            event["parent"] = span.parent
        if span.trace_id is not None:
            event["trace"] = span.trace_id
            event["span"] = span.span_id
            if span.parent_span_id is not None:
                event["pspan"] = span.parent_span_id
        if span.attrs:
            event["attrs"] = {
                k: (v if isinstance(v, (int, float, str, bool, type(None)))
                    else str(v))
                for k, v in span.attrs.items()
            }
        self._append_event(event)

    def _append_event(self, event: dict) -> None:
        with self._lock:
            if len(self._events) < self._max_events:
                self._events.append(event)
            else:
                self._dropped += 1

    def emit_span_event(self, name: str, ms: float, *,
                        trace_id: str | None = None,
                        span_id: str | None = None,
                        parent_span_id: str | None = None,
                        t_wall: float | None = None,
                        **attrs) -> str | None:
        """Record a pre-timed span event without entering a context.

        The batcher uses this: one device tick serves rows from many
        traces, so the tick itself is a normal span while each row gets
        a synthetic per-trace event parented under its request span.
        Returns the (possibly minted) span_id.
        """
        self.histogram(name, unit="ms").observe(ms)
        event = {
            "e": "span",
            "name": name,
            "ms": round(ms, 3),
            "t": round(t_wall if t_wall is not None else time.time(), 6),
            "depth": 0,
        }
        if trace_id is not None:
            if span_id is None:
                span_id = _trace.new_id()
            event["trace"] = trace_id
            event["span"] = span_id
            if parent_span_id is not None:
                event["pspan"] = parent_span_id
        if attrs:
            event["attrs"] = {
                k: (v if isinstance(v, (int, float, str, bool, type(None)))
                    else str(v))
                for k, v in attrs.items()
            }
        self._append_event(event)
        return span_id

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> dict:
        """A point-in-time copy of every series (no trace events)."""
        with self._lock:
            return {
                "counters": [
                    {"name": c.name, "labels": c.labels, "value": c.value}
                    for c in self._counters.values()
                ],
                "gauges": [
                    {"name": g.name, "labels": g.labels, "value": g.value,
                     "max": g.max}
                    for g in self._gauges.values()
                ],
                "hists": [
                    {"name": h.name, "labels": h.labels, "count": h.count,
                     "sum": h.sum, "min": h.min, "max": h.max,
                     "values": list(h.values),
                     **({"exemplars": [list(e) for e in h.exemplars]}
                        if h.exemplars else {})}
                    for h in self._hists.values()
                ],
                "dropped_events": self._dropped,
            }

    def events(self) -> list[dict]:
        with self._lock:
            if not self._dropped_traces:
                return list(self._events)
            dropped = self._dropped_traces
            return [e for e in self._events
                    if e.get("trace") not in dropped]

    # -------------------------------------------------- tail-sample pruning
    #: pending trace drops before the event buffer compacts.
    _DROP_COMPACT = 64

    def drop_trace(self, trace_id: str) -> None:
        """Prune one trace's span events (tail sampling's drop verdict,
        obs/sampler.py). Batched: the id is noted now, the buffer
        compacts every ``_DROP_COMPACT`` drops; ``events()`` filters
        pending ids so readers never see a half-dropped state."""
        with self._lock:
            self._dropped_traces.add(trace_id)
            if len(self._dropped_traces) >= self._DROP_COMPACT:
                dropped = self._dropped_traces
                self._events = [
                    e for e in self._events
                    if e.get("trace") not in dropped
                ]
                self._dropped_traces = set()


# ------------------------------------------------------- module-level state

_active: Registry | None = None
_lock = threading.Lock()


def configure(max_events: int = _TRACE_EVENT_CAP) -> Registry:
    """Install (or return) the process-wide live registry."""
    global _active
    with _lock:
        if _active is None:
            _active = Registry(max_events=max_events)
        return _active


def shutdown() -> None:
    """Drop the live registry; instrumentation reverts to no-ops."""
    global _active
    with _lock:
        _active = None


def enabled() -> bool:
    return _active is not None


def registry() -> Registry | None:
    """The live registry, or None when observability is disabled."""
    return _active


def counter(name: str, **labels):
    r = _active
    return NOOP if r is None else r.counter(name, **labels)


def gauge(name: str, **labels):
    r = _active
    return NOOP if r is None else r.gauge(name, **labels)


def histogram(name: str, **labels):
    r = _active
    return NOOP if r is None else r.histogram(name, **labels)


def span(name: str, **attrs):
    """A nesting wall-clock span; the shared no-op when disabled."""
    r = _active
    return NOOP if r is None else Span(r, name, attrs)


def count(name: str, n: int = 1) -> None:
    """One-shot unlabeled counter bump — the hot-loop shorthand."""
    r = _active
    if r is not None:
        r.counter(name).inc(n)


def observe(name: str, v: float, **labels) -> None:
    """One-shot histogram observation."""
    r = _active
    if r is not None:
        r.histogram(name, **labels).observe(v)


def export_jsonl(path, reg: Registry | None = None) -> str:
    """Write a registry's trace + final metric snapshot as JSONL.

    One JSON object per line: a ``meta`` header, every span event in
    completion order, then ``counter``/``gauge``/``hist`` snapshot lines.
    Exports the live registry by default (safe to call with observability
    disabled — writes an empty-run file); pass ``reg`` to export an
    explicit instance (per-worker test registries).
    """
    r = reg if reg is not None else _active
    lines: list[str] = []
    meta = {
        "e": "meta",
        "version": 1,
        "t": round(time.time(), 6),
        "enabled": r is not None,
        "pid": os.getpid(),
    }
    lines.append(json.dumps(meta))
    if r is not None:
        for ev in r.events():
            lines.append(json.dumps(ev))
        snap = r.snapshot()
        for c in snap["counters"]:
            lines.append(json.dumps({"e": "counter", **c}))
        for g in snap["gauges"]:
            lines.append(json.dumps({"e": "gauge", **g}))
        for h in snap["hists"]:
            lines.append(json.dumps({"e": "hist", **h}))
        if snap["dropped_events"]:
            lines.append(json.dumps(
                {"e": "dropped", "count": snap["dropped_events"]}
            ))
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return str(path)


def resolve_metrics_path(raw) -> "str | None":
    """Expand a ``--metrics-out`` / ``SPARK_BAM_METRICS_OUT`` value for
    THIS process: a ``{pid}`` placeholder is substituted, and a
    directory grows a ``trace-<pid>.jsonl`` inside it — so N fabric
    workers inheriting one env var write N distinct trace files instead
    of clobbering each other. Plain file paths pass through unchanged."""
    if not raw:
        return None
    raw = str(raw)
    if "{pid}" in raw:
        return raw.replace("{pid}", str(os.getpid()))
    if raw.endswith(os.sep) or os.path.isdir(raw):
        return os.path.join(raw, f"trace-{os.getpid()}.jsonl")
    return raw


def read_jsonl(path) -> Iterator[dict]:
    """Parse a JSONL trace back into event dicts (blank lines skipped)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)
