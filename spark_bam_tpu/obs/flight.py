"""Flight recorder: a bounded ring of recent request/error events.

Failover tests (and real fleets) recover from worker death but lose the
*explanation* — the SIGKILL'd worker's in-flight requests, its last
errors, what it was doing. This module keeps a small always-on ring
(one deque append per recorded event; events are per-request, not
per-row, so the hot path never sees it) that can be dumped to a
postmortem JSONL:

- the worker itself dumps on SIGTERM drain and on crash (the serve
  loop's unhandled-exception path);
- the *router* dumps on an observed ``WorkerLost`` — the SIGKILL case,
  where the dead worker can't speak for itself — naming the lost worker
  and the request ids that were in flight on that link.

Dumps land in ``SPARK_BAM_FLIGHT_DIR`` (defaults to the process cwd
only when a dump is explicitly requested with no directory configured
→ disabled: ``dump_auto`` is a no-op without the env var, so normal
runs never scatter files). The ring itself is independent of the obs
registry: it records even when metrics are disabled, because the one
moment you need it is precisely the crash you didn't plan to profile.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

FLIGHT_DIR_ENV = "SPARK_BAM_FLIGHT_DIR"
_RING_CAP = 512

# Process-wide dump context: stable facts every artifact must carry to
# be reproducible on its own (chaos seed/spec, primarily). Merged into
# each dump's flight_meta line and readable by other artifact writers
# (obs/slo.py stamps it into alert-ledger entries).
_context: dict = {}
_context_lock = threading.Lock()


def set_context(**fields) -> None:
    """Attach reproducibility facts (e.g. ``chaos_seed``/``chaos_spec``)
    to every subsequent dump from this process."""
    with _context_lock:
        _context.update(fields)


def clear_context(*names) -> None:
    """Drop named context keys (all of them when called bare)."""
    with _context_lock:
        if not names:
            _context.clear()
        for n in names:
            _context.pop(n, None)


def context() -> dict:
    """A snapshot of the current dump context."""
    with _context_lock:
        return dict(_context)


class FlightRecorder:
    """Thread-safe bounded event ring with a JSONL dump."""

    def __init__(self, cap: int = _RING_CAP):
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=cap)
        self.cap = cap

    def record(self, kind: str, **fields) -> None:
        ev = {"e": kind, "t": round(time.time(), 6)}
        for k, v in fields.items():
            ev[k] = (v if isinstance(v, (int, float, str, bool, list, dict,
                                         type(None))) else str(v))
        with self._lock:
            self._ring.append(ev)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def dump(self, path, reason: str, extra: dict | None = None) -> str:
        """Write meta + ring to ``path`` as JSONL; returns the path."""
        lines = [json.dumps({
            "e": "flight_meta",
            "version": 1,
            "reason": reason,
            "t": round(time.time(), 6),
            "pid": os.getpid(),
            **context(),
            **(extra or {}),
        })]
        for ev in self.events():
            lines.append(json.dumps(ev))
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        return str(path)


_recorder = FlightRecorder()


def recorder() -> FlightRecorder:
    """The process-wide flight recorder."""
    return _recorder


def record(kind: str, **fields) -> None:
    _recorder.record(kind, **fields)


def dump_path(reason: str, who: str | None = None) -> str | None:
    """Where an automatic dump for ``reason`` would land, or None when
    ``SPARK_BAM_FLIGHT_DIR`` is unset (auto-dumping disabled)."""
    d = os.environ.get(FLIGHT_DIR_ENV)
    if not d:
        return None
    tag = f"-{who}" if who else ""
    return os.path.join(d, f"flight-{os.getpid()}{tag}-{reason}.jsonl")


def dump_auto(reason: str, who: str | None = None,
              extra: dict | None = None) -> str | None:
    """Dump the ring if ``SPARK_BAM_FLIGHT_DIR`` is configured.

    Never raises: a postmortem writer that crashes the postmortem path
    would be worse than no artifact.
    """
    path = dump_path(reason, who)
    if path is None:
        return None
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        return _recorder.dump(path, reason, extra=extra)
    except OSError:
        return None


def read_dump(path) -> list[dict]:
    """Parse a flight dump back into event dicts."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
