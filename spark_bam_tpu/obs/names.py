"""Registered metric/span name catalog — the obs naming contract.

Every metric and span name the package emits lives here, grouped by
hot-path layer (the dotted ``layer.stage`` convention from
obs/registry.py). The ``obs-contract`` lint pass
(analysis/rules/obs_contract.py) enforces it: a literal name passed to
``obs.count``/``observe``/``span``/``counter``/``gauge``/``histogram``
that is not in :data:`NAMES` fails the gate, and dynamic (f-string)
names are flagged unless their literal prefix is a registered layer AND
the call site carries an inline allow justifying bounded cardinality.

Why a registry: the PR 11 telemetry plane merges snapshots across
processes by name (obs/exporters.py ``merge_snapshots``) and renders
fleet dashboards from them — an ad-hoc name in one worker silently
forks a series the merge can't join, and an unbounded name (one series
per request id) OOMs the registry. Adding a metric = adding one line
here; the whole-repo lint test fails until you do.
"""

from __future__ import annotations

#: Layer prefixes (the segment before the first dot). A new layer means
#: a new subsystem — add it here alongside its names.
LAYERS = frozenset({
    "account", "agg", "bgzf", "cache", "chaos", "check", "cli",
    "columnar", "compress", "deflate", "fabric", "faults", "funnel",
    "guard", "inflate", "jobs", "load", "mesh", "progress", "remote",
    "sampler", "scrub", "serve", "slo", "timer", "transport", "ts",
})

NAMES = frozenset({
    # account — per-request cost accounting (obs/account.py)
    "account.requests", "account.tenants",
    # agg — fused on-device aggregation plane (docs/analytics.md)
    "agg.bytes_out", "agg.encode", "agg.host_fallbacks", "agg.reduce",
    "agg.requests", "agg.rows",
    # bgzf — block streaming (docs/design.md)
    "bgzf.blocks_read", "bgzf.blocks_scanned", "bgzf.bytes_inflated",
    "bgzf.bytes_read", "bgzf.read",
    # cache — .sbi split-index sidecars (docs/caching.md)
    "cache.bytes", "cache.evictions", "cache.hits", "cache.invalidations",
    "cache.misses", "cache.read_ms", "cache.write_errors", "cache.write_ms",
    # chaos — deterministic fault injection (docs/robustness.md);
    # chaos.disk_* are the filesystem-seam kinds (core/faults.py)
    "chaos.corrupted_bytes", "chaos.io_errors", "chaos.latency_spikes",
    "chaos.short_reads",
    "chaos.disk_enospc", "chaos.disk_eio", "chaos.disk_short_writes",
    "chaos.disk_torn_writes", "chaos.disk_rename_fails",
    # check — record-boundary checker
    "check.accepted", "check.candidates", "check.count_escape_retries",
    "check.defer_resolved", "check.defer_retries", "check.deferred",
    "check.escaped", "check.find_record_start", "check.positions",
    "check.window", "check.windows",
    # cli — root spans, one per subcommand (cli/main.py)
    "cli.aggregate", "cli.check-bam", "cli.check-blocks",
    "cli.compare-splits", "cli.compute-splits", "cli.count-reads",
    "cli.export", "cli.fabric",
    "cli.full-check", "cli.fuzz-decode", "cli.htsjdk-rewrite",
    "cli.index", "cli.index-bam", "cli.index-blocks", "cli.index-records",
    "cli.lint", "cli.metrics-report", "cli.rewrite", "cli.scrub",
    "cli.serve", "cli.time-load", "cli.top",
    # columnar — record-batch analytics plane (docs/analytics.md)
    "columnar.build_ms", "columnar.bytes_out", "columnar.encode_ms",
    "columnar.export", "columnar.rows",
    # compress — write-path member/batch ledger (docs/design.md)
    "compress.batches", "compress.bytes_in", "compress.bytes_out",
    "compress.fixed", "compress.members", "compress.stored",
    # deflate — device-side BGZF compression (docs/design.md, write path)
    "deflate.d2h_ms", "deflate.demotions", "deflate.device_ms",
    "deflate.device_windows", "deflate.dispatch", "deflate.host_ms",
    "deflate.pack_ms",
    # fabric — control plane (docs/fabric.md); fabric.<counter> names are
    # emitted through Router._count's bounded literal set
    "fabric.relay", "fabric.autoscale_moves", "fabric.drained",
    "fabric.ejected", "fabric.failovers", "fabric.lost",
    "fabric.reinstated", "fabric.relayed_overload", "fabric.routed",
    "fabric.spilled",
    # fabric.breaker — per-link circuit breakers (docs/robustness.md)
    "fabric.breaker.opened", "fabric.breaker.half_open",
    "fabric.breaker.closed", "fabric.breaker.holddowns",
    # fabric resilience: retry budget, brownout, streaming failover,
    # durable-job orphan rescue (docs/robustness.md)
    "fabric.budget_spent", "fabric.budget_exhausted",
    "fabric.brownout_shed", "fabric.streamed", "fabric.stream_frames",
    "fabric.resumed", "fabric.job_rescues",
    # fabric.chaos — fleet-seam fault injection (fabric/chaos.py)
    "fabric.chaos.drops", "fabric.chaos.delays", "fabric.chaos.dups",
    "fabric.chaos.truncs", "fabric.chaos.slowed",
    "fabric.chaos.accept_delays", "fabric.chaos.kills",
    "fabric.chaos.wedges",
    # fabric.chaos shm seam — rolled per frame record by the serve
    # accept loop (docs/serving.md "Transport")
    "fabric.chaos.shm_crcs", "fabric.chaos.shm_truncs",
    "fabric.chaos.shm_unlinks",
    # faults — retry/hedge/quarantine ledger (docs/robustness.md)
    "faults.attempt_ms", "faults.hedges", "faults.quarantined",
    "faults.quarantined_blocks", "faults.retries",
    # funnel — two-stage checker candidate funnel (docs/design.md)
    "funnel.positions", "funnel.reduction", "funnel.survivors",
    "funnel.window_survivors",
    # guard — untrusted-byte decode boundary (core/guard.py)
    "guard.quarantined_blocks", "guard.quarantined_records",
    # inflate — device-resident BGZF inflate (docs/design.md)
    "inflate.block", "inflate.blocks", "inflate.bytes",
    "inflate.device_kernel", "inflate.device_ms", "inflate.device_windows",
    "inflate.h2d", "inflate.h2d_bytes", "inflate.h2d_ms", "inflate.host_ms",
    "inflate.pack", "inflate.rounds", "inflate.stall_ms", "inflate.stalls",
    "inflate.tokenize", "inflate.tokenize_blocks",
    "inflate.tokenize_demotions", "inflate.tokenize_device",
    "inflate.tokenize_device_ms", "inflate.tokenize_host_ms",
    "inflate.window", "inflate.windows",
    # jobs — durable job plane: WAL + crash-resumable runners
    # (docs/robustness.md "Durable jobs & scrubbing")
    "jobs.cancelled", "jobs.checkpoint_bytes", "jobs.checkpoints",
    "jobs.completed", "jobs.deferred", "jobs.export", "jobs.failed",
    "jobs.journal_appends", "jobs.journal_skipped",
    "jobs.journal_truncated", "jobs.paused", "jobs.preflight_rejects",
    "jobs.redone_bytes", "jobs.resumed", "jobs.rewrite", "jobs.scrub",
    "jobs.submitted",
    # load — partition execution
    "load.count", "load.fleet_files", "load.parse", "load.partition",
    "load.partitions", "load.record_starts", "load.records",
    "load.split_resolutions",
    # mesh — compiled-step registry + shard_map dispatch
    "mesh.dirty_steps", "mesh.dispatch", "mesh.escapes",
    "mesh.patch_chunk_positions", "mesh.patch_chunks", "mesh.patch_rows",
    "mesh.step", "mesh.steps",
    # progress — long-run heartbeats
    "progress.beats",
    # remote — plan-driven data plane (docs/remote.md)
    "remote.bucket_wait_ms", "remote.bytes", "remote.depth",
    "remote.evictions", "remote.get_ms", "remote.gets", "remote.hedge_wins",
    "remote.hedges", "remote.plan_segments", "remote.quota_wait_ms",
    "remote.stalls", "remote.unplanned_gets",
    # sampler — tail-based trace sampling (obs/sampler.py)
    "sampler.dropped", "sampler.exemplars", "sampler.kept",
    # scrub — end-to-end integrity scrubber (jobs/scrub.py)
    "scrub.artifacts", "scrub.findings", "scrub.quarantined",
    "scrub.records_checked",
    # serve — split-service daemon (docs/serving.md)
    "serve.batch_encode", "serve.batch_rows", "serve.batches",
    "serve.connections", "serve.device_dispatch", "serve.errors",
    "serve.h2d_bytes", "serve.latency_ms", "serve.overloaded",
    "serve.parse", "serve.queue_depth", "serve.queue_ms", "serve.request",
    "serve.requests", "serve.rewrite", "serve.shed", "serve.stream_aborts",
    "serve.tick", "serve.tuned",
    # serve shm — segment lifecycle + encoded-frame cache
    # (docs/serving.md "Transport")
    "serve.frame_cache_hits", "serve.frame_cache_misses",
    "serve.shm_crc_errors", "serve.shm_orphans_cleaned",
    "serve.shm_segments",
    # slo — burn-rate objective engine (obs/slo.py)
    "slo.alerts", "slo.burn_rate", "slo.evals", "slo.firing",
    # transport — zero-copy data plane: shm rings, descriptor relay,
    # handshake downgrades (docs/serving.md "Transport")
    "transport.downgrades", "transport.inline_frames",
    "transport.relay_descriptors", "transport.ring_full_waits",
    "transport.segment_announces", "transport.shm_bytes",
    "transport.shm_connections", "transport.shm_frames",
    # ts — time-series ring scraper (obs/timeseries.py)
    "ts.scrapes", "ts.series",
})


def is_registered(name: str) -> bool:
    return name in NAMES


def layer_of(name: str) -> str:
    return name.split(".", 1)[0]
