"""Render a JSONL metrics trace as a human report (reference stats format).

The ``spark-bam-tpu metrics-report`` subcommand and ``tools/tpu_watch.py``
both consume this: parse the JSONL a ``--metrics-out`` run emitted,
regroup span events by name, and render per-stage duration statistics
with the same ``core/stats.py`` formatting the golden CLI reports use.
"""

from __future__ import annotations

from spark_bam_tpu.obs.exporters import stats_summary
from spark_bam_tpu.obs.registry import read_jsonl


def load_trace(path) -> dict:
    """Parse a trace file into ``{"spans_by_name", "snapshot", "meta"}``."""
    spans_by_name: dict[str, list[float]] = {}
    snapshot: dict = {"counters": [], "gauges": [], "hists": []}
    meta: dict = {}
    dropped = 0
    for ev in read_jsonl(path):
        kind = ev.get("e")
        if kind == "span":
            spans_by_name.setdefault(ev["name"], []).append(float(ev["ms"]))
        elif kind == "counter":
            snapshot["counters"].append(ev)
        elif kind == "gauge":
            snapshot["gauges"].append(ev)
        elif kind == "hist":
            snapshot["hists"].append(ev)
        elif kind == "meta":
            meta = ev
        elif kind == "dropped":
            dropped = int(ev.get("count", 0))
    snapshot["dropped_events"] = dropped
    return {"spans_by_name": spans_by_name, "snapshot": snapshot, "meta": meta}


def render_report(path) -> str:
    """The full metrics-report text for one trace file."""
    trace = load_trace(path)
    spans = trace["spans_by_name"]
    header = [
        f"metrics trace: {path}",
        f"span events: {sum(len(v) for v in spans.values())}"
        + (f" (+{trace['snapshot']['dropped_events']} dropped)"
           if trace["snapshot"]["dropped_events"] else ""),
    ]
    body = stats_summary(trace["snapshot"], spans_by_name=spans)
    return "\n".join(header) + "\n\n" + body


def stage_summary_line(path, top: int = 5) -> str:
    """One-line ``name=total_ms×count`` digest of the heaviest stages —
    the tpu_watch per-capture log format."""
    trace = load_trace(path)
    totals = [
        (name, sum(ms), len(ms))
        for name, ms in trace["spans_by_name"].items()
    ]
    totals.sort(key=lambda t: -t[1])
    return " ".join(
        f"{name}={total:.0f}ms×{n}" for name, total, n in totals[:top]
    )
