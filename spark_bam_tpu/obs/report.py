"""Render JSONL metrics traces as a human report (reference stats format).

The ``spark-bam-tpu metrics-report`` subcommand and ``tools/tpu_watch.py``
both consume this: parse the JSONL a ``--metrics-out`` run emitted,
regroup span events by name, and render per-stage duration statistics
with the same ``core/stats.py`` formatting the golden CLI reports use.

Multi-process traces: when several files are given (router + N fabric
workers, each exporting its own registry), span events carrying trace
ids are merged *across files* by ``trace_id`` and rendered as one tree
per trace — the cross-process view a single serve request produces.
"""

from __future__ import annotations

from spark_bam_tpu.obs.exporters import merge_snapshots, stats_summary
from spark_bam_tpu.obs.registry import read_jsonl


def load_trace(path) -> dict:
    """Parse a trace file into
    ``{"spans_by_name", "snapshot", "meta", "span_events"}``."""
    spans_by_name: dict[str, list[float]] = {}
    span_events: list[dict] = []
    snapshot: dict = {"counters": [], "gauges": [], "hists": []}
    meta: dict = {}
    dropped = 0
    for ev in read_jsonl(path):
        kind = ev.get("e")
        if kind == "span":
            spans_by_name.setdefault(ev["name"], []).append(float(ev["ms"]))
            span_events.append(ev)
        elif kind == "counter":
            snapshot["counters"].append(ev)
        elif kind == "gauge":
            snapshot["gauges"].append(ev)
        elif kind == "hist":
            snapshot["hists"].append(ev)
        elif kind == "meta":
            meta = ev
        elif kind == "dropped":
            dropped = int(ev.get("count", 0))
    snapshot["dropped_events"] = dropped
    return {"spans_by_name": spans_by_name, "snapshot": snapshot,
            "meta": meta, "span_events": span_events}


def merge_traces(paths) -> dict:
    """Merge several per-process trace files into one view.

    Returns ``{"spans_by_name", "snapshot", "metas", "traces"}`` where
    ``traces`` maps each trace_id to its span events gathered across
    *all* files, sorted by start time — the single-request,
    cross-process span tree.
    """
    spans_by_name: dict[str, list[float]] = {}
    snapshots: list[dict] = []
    metas: list[dict] = []
    traces: dict[str, list[dict]] = {}
    for path in paths:
        t = load_trace(path)
        metas.append(dict(t["meta"], file=str(path)))
        snapshots.append(t["snapshot"])
        for name, vals in t["spans_by_name"].items():
            spans_by_name.setdefault(name, []).extend(vals)
        pid = t["meta"].get("pid")
        for ev in t["span_events"]:
            tid = ev.get("trace")
            if tid:
                traces.setdefault(tid, []).append(dict(ev, pid=pid))
    for evs in traces.values():
        evs.sort(key=lambda e: e.get("t", 0.0))
    return {"spans_by_name": spans_by_name,
            "snapshot": merge_snapshots(snapshots),
            "metas": metas, "traces": traces}


def render_trace_tree(events: list[dict]) -> str:
    """One trace's events as an indented parent→child tree.

    Events carry ``span``/``pspan`` ids; roots are events whose parent
    id is absent from the set (the minting process's root span).
    Children render under their parent ordered by start time.
    """
    by_id = {ev["span"]: ev for ev in events if ev.get("span")}
    children: dict[str, list[dict]] = {}
    roots: list[dict] = []
    for ev in events:
        pspan = ev.get("pspan")
        if pspan and pspan in by_id:
            children.setdefault(pspan, []).append(ev)
        else:
            roots.append(ev)
    lines: list[str] = []

    def walk(ev: dict, depth: int) -> None:
        pid = ev.get("pid")
        where = f" pid={pid}" if pid is not None else ""
        lines.append(
            f"{'  ' * depth}{ev['name']} {ev['ms']:.3f}ms{where}"
        )
        for child in sorted(children.get(ev.get("span") or "", []),
                            key=lambda e: e.get("t", 0.0)):
            walk(child, depth + 1)

    for root in roots:
        walk(root, 0)
    return "\n".join(lines)


def render_report(path) -> str:
    """The full metrics-report text for one trace file."""
    trace = load_trace(path)
    spans = trace["spans_by_name"]
    header = [
        f"metrics trace: {path}",
        f"span events: {sum(len(v) for v in spans.values())}"
        + (f" (+{trace['snapshot']['dropped_events']} dropped)"
           if trace["snapshot"]["dropped_events"] else ""),
    ]
    body = stats_summary(trace["snapshot"], spans_by_name=spans)
    return "\n".join(header) + "\n\n" + body


def render_merged_report(paths, max_traces: int = 8) -> str:
    """The metrics-report text for several per-process trace files:
    fleet-merged stats plus one span tree per trace_id (largest first,
    capped at ``max_traces`` trees to keep the report readable)."""
    merged = merge_traces(paths)
    spans = merged["spans_by_name"]
    header = [
        "metrics traces: " + ", ".join(str(p) for p in paths),
        f"processes: {len(merged['metas'])}"
        f"  span events: {sum(len(v) for v in spans.values())}"
        f"  traces: {len(merged['traces'])}",
    ]
    blocks = ["\n".join(header), stats_summary(
        merged["snapshot"], spans_by_name=spans).rstrip("\n")]
    ranked = sorted(merged["traces"].items(),
                    key=lambda kv: -len(kv[1]))[:max_traces]
    for tid, events in ranked:
        blocks.append(
            f"trace {tid} ({len(events)} spans):\n"
            + render_trace_tree(events)
        )
    if len(merged["traces"]) > max_traces:
        blocks.append(
            f"... {len(merged['traces']) - max_traces} more traces omitted"
        )
    return "\n\n".join(blocks) + "\n"


def stage_summary_line(path, top: int = 5) -> str:
    """One-line ``name=total_ms×count`` digest of the heaviest stages —
    the tpu_watch per-capture log format."""
    trace = load_trace(path)
    totals = [
        (name, sum(ms), len(ms))
        for name, ms in trace["spans_by_name"].items()
    ]
    totals.sort(key=lambda t: -t[1])
    return " ".join(
        f"{name}={total:.0f}ms×{n}" for name, total, n in totals[:top]
    )
