"""Time-series ring: bounded (t, value) sample history per metric series.

The registry's counters/gauges/histograms are *cumulative* — a snapshot
says where a series is, never how fast it is moving. This module adds
the missing time axis: a :class:`RingStore` scrapes the live registry at
a fixed cadence into one bounded ring of ``(t, value)`` points per
series, plus a timestamped ring of recent raw observations per
histogram (attached at the :class:`~spark_bam_tpu.obs.registry.Histogram`
itself — see ``Registry.attach_rings``), so windowed queries become
possible:

- ``rate(name, window_s)`` / ``delta(name, window_s)`` — counter slope
  over the trailing window (requests/s, error deltas);
- ``quantile(name, q, window_s)`` — p50/p99 *of the last N seconds*,
  from the histogram's observation ring, not the lifetime reservoir;
- ``ratio(num, den, window_s)`` — delta/delta (error ratios).

These are exactly the primitives burn-rate SLO evaluation needs
(obs/slo.py); the sparkline dashboard (obs/dashboard.py) renders the
same rings. ``snapshot()`` serializes a store for the wire — the fabric
router collects per-worker ring snapshots through the ``telemetry`` op
and :func:`merge_series` folds them into one fleet view, bucketing
timestamps to the scrape cadence so unaligned workers still sum.

Everything here is stdlib + the registry: no numpy on the scrape path,
one daemon thread per store, and the store is inert (zero hot-path
cost) until ``start()``.
"""

from __future__ import annotations

import threading
import time
from collections import deque

#: ring capacity per series — at the 1 s default cadence, 10 minutes of
#: history, comfortably beyond the slow SLO window's needs (the slow
#: window degrades to available history on fresh processes, by design).
_POINT_CAP = 600
#: raw observations retained per histogram for windowed quantiles.
_OBS_CAP = 2048
#: observation points shipped per series in a wire snapshot (tail).
_WIRE_OBS_CAP = 512


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class RingStore:
    """Scrape-cadence sample rings over one live registry (thread-safe).

    ``scrape()`` takes one sample pass; ``start()`` spawns the cadence
    thread (optionally invoking ``on_scrape`` after each pass — the SLO
    engine's evaluation hook rides this, so alert latency is one scrape,
    not a second timer).
    """

    def __init__(self, registry, cadence_ms: float = 1000.0,
                 cap: int = _POINT_CAP, obs_cap: int = _OBS_CAP):
        self.registry = registry
        self.cadence_ms = float(cadence_ms)
        self.cap = int(cap)
        self.obs_cap = int(obs_cap)
        self._series: "dict[tuple, dict]" = {}
        self._lock = threading.Lock()
        self._thread: "threading.Thread | None" = None
        self._stop = threading.Event()
        registry.attach_rings(self)

    # ------------------------------------------------------------- lifecycle
    def start(self, on_scrape=None) -> "RingStore":
        def _loop():
            while not self._stop.wait(self.cadence_ms / 1000.0):
                try:
                    self.scrape()
                    if on_scrape is not None:
                        on_scrape()
                except Exception:
                    # A scrape must never kill the daemon thread; the
                    # next tick retries.
                    pass

        with self._lock:
            if self._thread is not None:
                return self
            self._thread = t = threading.Thread(
                target=_loop, name="obs-ringstore", daemon=True
            )
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    # --------------------------------------------------------------- scrape
    def _ring(self, name: str, labels: dict, kind: str) -> dict:
        key = (name, _label_key(labels), kind)
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = {
                "name": name, "labels": dict(labels), "kind": kind,
                "points": deque(maxlen=self.cap),
            }
        return s

    def scrape(self, now: "float | None" = None) -> None:
        """One sample pass over every live series."""
        from spark_bam_tpu import obs

        snap = self.registry.snapshot()
        t = round(time.time() if now is None else now, 3)
        with self._lock:
            for c in snap["counters"]:
                self._ring(c["name"], c["labels"], "counter")[
                    "points"].append((t, c["value"]))
            for g in snap["gauges"]:
                self._ring(g["name"], g["labels"], "gauge")[
                    "points"].append((t, g["value"]))
            for h in snap["hists"]:
                self._ring(h["name"], h["labels"], "hist")[
                    "points"].append((t, h["count"], h["sum"]))
            n_series = len(self._series)
        obs.count("ts.scrapes")
        obs.gauge("ts.series").set(n_series)

    # -------------------------------------------------------------- queries
    def _points(self, name: str, kind: str, labels: "dict | None"):
        lk = _label_key(labels) if labels is not None else None
        with self._lock:
            for (n, k, kd), s in self._series.items():
                if n == name and kd == kind and (lk is None or k == lk):
                    return list(s["points"])
        return []

    def delta(self, name: str, window_s: float,
              labels: "dict | None" = None) -> "float | None":
        """Counter increase over the trailing window (None: no samples)."""
        pts = self._points(name, "counter", labels)
        return _delta(pts, window_s)

    def rate(self, name: str, window_s: float,
             labels: "dict | None" = None) -> "float | None":
        """Counter increase per second over the trailing window."""
        pts = self._points(name, "counter", labels)
        return _rate(pts, window_s)

    def ratio(self, num: str, den: str, window_s: float) -> "float | None":
        """delta(num)/delta(den) over the window; None until the
        denominator moved (no traffic ⇒ no error-budget spend)."""
        dn = self.delta(num, window_s)
        dd = self.delta(den, window_s)
        if dd is None or dd <= 0:
            return None
        return (dn or 0.0) / dd

    def _hist_rings(self, name: str) -> list:
        """Every same-name histogram's observation ring (label sets pool:
        spans record under ``unit="ms"``, ``obs.observe`` under none)."""
        with self.registry._lock:
            hists = list(self.registry._hists.values())
        return [h.ring for h in hists if h.name == name and h.ring]

    def quantile(self, name: str, q: float, window_s: float,
                 labels: "dict | None" = None) -> "float | None":
        """Nearest-rank quantile of the histogram's raw observations in
        the trailing window (the obs ring lives on the Histogram)."""
        lo = time.time() - window_s
        vals: "list[float]" = []
        for ring in self._hist_rings(name):
            vals.extend(v for (t, v) in list(ring) if t >= lo)
        vals.sort()
        return _nearest_rank(vals, q)

    def hist_mean(self, name: str, window_s: float,
                  labels: "dict | None" = None) -> "float | None":
        """Mean observation over the window, from hist count/sum deltas
        (same-name label sets pool, as in :meth:`quantile`)."""
        with self._lock:
            all_pts = [
                list(s["points"])
                for (n, k, kd), s in self._series.items()
                if n == name and kd == "hist"
                and (labels is None or k == _label_key(labels))
            ]
        return _pooled_hist_mean(all_pts, window_s)

    def gauge_last(self, name: str,
                   labels: "dict | None" = None) -> "float | None":
        pts = self._points(name, "gauge", labels)
        return pts[-1][1] if pts else None

    # ----------------------------------------------------------------- wire
    def snapshot(self) -> dict:
        """Serializable store state (the ``telemetry`` op's ``series``
        payload). Histogram observation rings ship a bounded tail so the
        router can answer fleet quantile-over-window."""
        out: list[dict] = []
        with self._lock:
            series = [
                {"name": s["name"], "labels": dict(s["labels"]),
                 "kind": s["kind"],
                 "points": [list(p) for p in s["points"]]}
                for s in self._series.values()
            ]
        for s in series:
            if s["kind"] == "hist":
                h = self.registry.histogram(s["name"], **s["labels"])
                ring = getattr(h, "ring", None)
                if ring:
                    s["obs"] = [
                        [round(t, 3), v]
                        for (t, v) in list(ring)[-_WIRE_OBS_CAP:]
                    ]
            out.append(s)
        return {"cadence_ms": self.cadence_ms, "series": out}


# -------------------------------------------------------- snapshot algebra

def _delta(points, window_s: float) -> "float | None":
    if not points:
        return None
    now = points[-1][0]
    lo = now - window_s
    base = points[0]
    for p in points:
        if p[0] >= lo:
            base = p
            break
    return points[-1][1] - base[1]


def _rate(points, window_s: float) -> "float | None":
    if len(points) < 2:
        return None
    now = points[-1][0]
    lo = now - window_s
    base = points[0]
    for p in points:
        if p[0] >= lo:
            base = p
            break
    dt = points[-1][0] - base[0]
    if dt <= 0:
        return None
    return (points[-1][1] - base[1]) / dt


def _pooled_hist_mean(series_points, window_s: float) -> "float | None":
    """Mean over the window from hist (t, count, sum) deltas, pooled
    across series. A window that saw no new observations falls back to
    the lifetime mean (fresh processes, idle tails)."""
    dc = ds = 0.0
    life_c = life_s = 0.0
    for points in series_points:
        if not points:
            continue
        now = points[-1][0]
        lo = now - window_s
        base = points[0]
        for p in points:
            if p[0] >= lo:
                base = p
                break
        dc += points[-1][1] - base[1]
        ds += points[-1][2] - base[2]
        life_c += points[-1][1]
        life_s += points[-1][2]
    if dc > 0:
        return ds / dc
    return life_s / life_c if life_c else None


def _nearest_rank(sorted_vals, q: float) -> "float | None":
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1,
            max(0, int(round(q * (len(sorted_vals) - 1)))))
    return float(sorted_vals[i])


class SeriesView:
    """Query facade over a *serialized* ring snapshot (a worker's wire
    payload or :func:`merge_series` output) with the same delta/rate/
    ratio/quantile surface as a live :class:`RingStore` — the router,
    the dashboard, and tests all read series through this one shape."""

    def __init__(self, snapshot: "dict | None"):
        self.snapshot = snapshot or {"cadence_ms": 1000.0, "series": []}

    def _find(self, name: str, kind: str):
        for s in self.snapshot.get("series", []):
            if s["name"] == name and s["kind"] == kind:
                return s
        return None

    def _find_all(self, name: str, kind: str) -> list:
        return [s for s in self.snapshot.get("series", [])
                if s["name"] == name and s["kind"] == kind]

    def delta(self, name: str, window_s: float) -> "float | None":
        s = self._find(name, "counter")
        return _delta([tuple(p) for p in s["points"]], window_s) if s else None

    def rate(self, name: str, window_s: float) -> "float | None":
        s = self._find(name, "counter")
        return _rate([tuple(p) for p in s["points"]], window_s) if s else None

    def ratio(self, num: str, den: str, window_s: float) -> "float | None":
        dn = self.delta(num, window_s)
        dd = self.delta(den, window_s)
        if dd is None or dd <= 0:
            return None
        return (dn or 0.0) / dd

    def quantile(self, name: str, q: float,
                 window_s: float) -> "float | None":
        vals: "list[float]" = []
        for s in self._find_all(name, "hist"):
            obs_pts = s.get("obs") or []
            if not obs_pts:
                continue
            lo = obs_pts[-1][0] - window_s
            vals.extend(v for (t, v) in obs_pts if t >= lo)
        vals.sort()
        return _nearest_rank(vals, q)

    def hist_mean(self, name: str, window_s: float) -> "float | None":
        series = [
            [tuple(p) for p in s["points"]]
            for s in self._find_all(name, "hist")
        ]
        return _pooled_hist_mean(series, window_s)

    def gauge_last(self, name: str) -> "float | None":
        s = self._find(name, "gauge")
        if s is None or not s["points"]:
            return None
        return s["points"][-1][1]


def merge_series(snapshots: "list[dict | None]") -> dict:
    """Fold per-worker ring snapshots into one fleet snapshot.

    Counter/gauge points are bucketed to the scrape cadence and summed
    per bucket (fleet totals over time despite unaligned scrape clocks);
    hist points sum count/sum per bucket and observation tails
    concatenate (capped), so fleet quantile-over-window reads a
    cross-worker sample — the same merge contract as
    ``exporters.merge_snapshots``, with a time axis.
    """
    snaps = [s for s in snapshots if s]
    cadence = max((float(s.get("cadence_ms") or 1000.0) for s in snaps),
                  default=1000.0)
    step = max(cadence / 1000.0, 1e-3)
    merged: "dict[tuple, dict]" = {}
    for snap in snaps:
        for s in snap.get("series", []):
            key = (s["name"], _label_key(s.get("labels", {})), s["kind"])
            cur = merged.setdefault(key, {
                "name": s["name"], "labels": dict(s.get("labels", {})),
                "kind": s["kind"], "_buckets": {}, "obs": [],
            })
            for p in s.get("points", []):
                b = int(p[0] / step)
                acc = cur["_buckets"].setdefault(b, [p[0]] + [0.0] * (len(p) - 1))
                acc[0] = max(acc[0], p[0])
                for i in range(1, len(p)):
                    acc[i] += p[i]
            cur["obs"].extend(tuple(o) for o in s.get("obs", []))
    out = []
    for cur in merged.values():
        points = [cur["_buckets"][b] for b in sorted(cur["_buckets"])]
        s = {"name": cur["name"], "labels": cur["labels"],
             "kind": cur["kind"], "points": points}
        if cur["obs"]:
            obs_pts = sorted(cur["obs"])[-_WIRE_OBS_CAP:]
            s["obs"] = [list(o) for o in obs_pts]
        out.append(s)
    return {"cadence_ms": cadence, "series": out}
