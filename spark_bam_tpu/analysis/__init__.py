"""Project-native static analysis (``spark-bam-tpu lint``).

The reference's value proposition is a battery of structural checks that
drove split false positives to zero (docs/motivation.md); this package
applies the same philosophy to the codebase itself. Every open roadmap
item is a concurrency- and tracer-heavy refactor of hot paths, and these
AST rule passes mechanically prevent the classic regressions:

- ``jit-purity``      Python branches on traced values / varying
                      ``static_argnums`` that defeat the ``MeshSteps``
                      compile cache (tpu/, parallel/)
- ``blocking-async``  blocking calls dropped into the router / health /
                      autoscaler event loops (serve/, fabric/)
- ``guard-boundary``  ``struct.unpack`` on untrusted bytes reachable
                      outside the core/guard.py taxonomy (bam/, bgzf/,
                      cram/, sbi/, columnar/)
- ``shared-state``    attributes mutated from both the event loop and
                      batcher/executor threads without a lock
- ``obs-contract``    metric/span names not in the registered catalog
                      (obs/names.py) or with unbounded cardinality

Run ``spark-bam-tpu lint`` (docs/static-analysis.md). Findings carry
``file:line`` + a fix hint; grandfathered findings live in the committed
``lint-baseline.json``; one-off waivers use an inline
``# lint: allow[rule-id] reason`` comment on (or above) the line.
"""

from spark_bam_tpu.analysis.base import RULES, LintContext, Rule, register
from spark_bam_tpu.analysis.baseline import Baseline
from spark_bam_tpu.analysis.findings import Finding, Severity
from spark_bam_tpu.analysis.runner import (
    LintReport,
    lint_source,
    render_report,
    run_lint,
)

__all__ = [
    "Baseline",
    "Finding",
    "LintContext",
    "LintReport",
    "RULES",
    "Rule",
    "Severity",
    "lint_source",
    "register",
    "render_report",
    "run_lint",
]
