"""Baseline suppression file: grandfathered findings, with justifications.

The committed ``lint-baseline.json`` holds findings that are understood
and deliberately tolerated (e.g. GIL-atomic histogram mutation the obs
layer accepts by design). Each entry pins ``(rule, path, key)`` — the
key is content-addressed (findings.py), so entries survive line shifts
but die with the offending line, and a fixed finding leaves a *stale*
entry the runner reports so the baseline only ever shrinks.

Every entry MUST carry a non-empty ``justification``; the runner treats
an unjustified entry as invalid and the finding stays live.
"""

from __future__ import annotations

import json


class Baseline:
    def __init__(self, entries: "list[dict] | None" = None, path=None):
        self.path = path
        self.entries = entries or []
        self._index: dict[tuple, dict] = {}
        self._matched: set = set()
        for e in self.entries:
            just = str(e.get("justification") or "").strip()
            if not just:
                continue            # unjustified entries do not suppress
            self._index[(e.get("rule"), e.get("path"), e.get("key"))] = e

    @classmethod
    def load(cls, path) -> "Baseline":
        try:
            with open(path) as f:
                data = json.load(f)
        except FileNotFoundError:
            return cls(path=path)
        if not isinstance(data, dict) or "entries" not in data:
            raise ValueError(f"bad baseline file {path}: expected an object "
                             "with an 'entries' list")
        return cls(list(data["entries"]), path=path)

    def match(self, finding) -> "dict | None":
        """The suppressing entry for this finding, if any (marks it used)."""
        key = (finding.rule, finding.path, finding.key)
        e = self._index.get(key)
        if e is not None:
            self._matched.add(key)
        return e

    def stale_entries(self) -> "list[dict]":
        """Justified entries that matched nothing this run — the finding
        was fixed, so the entry should be deleted."""
        return [e for k, e in self._index.items() if k not in self._matched]

    @staticmethod
    def write(path, findings, justification: str) -> int:
        """Write a baseline covering ``findings`` (the --write-baseline
        bootstrap; the operator then edits per-entry justifications)."""
        entries = [
            {
                "rule": f.rule,
                "path": f.path,
                "key": f.key,
                "line": f.line,          # informational; matching ignores it
                "message": f.message,    # informational
                "justification": justification,
            }
            for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
        ]
        with open(path, "w") as fh:
            json.dump({"version": 1, "entries": entries}, fh, indent=2,
                      sort_keys=False)
            fh.write("\n")
        return len(entries)
