"""Lint runner: walk files, apply rules, resolve suppressions, report.

Suppression resolution order per finding:

1. inline ``# lint: allow[rule-id] reason`` on the flagged line or the
   line directly above (reason required — a bare allow is itself a
   finding);
2. a justified entry in the baseline file (baseline.py);
3. otherwise the finding is live and P1/P2 findings fail the gate.
"""

from __future__ import annotations

import ast
import json
import os
import re
import time
from dataclasses import dataclass, field

from spark_bam_tpu.analysis.base import RULES, LintContext
from spark_bam_tpu.analysis.baseline import Baseline
from spark_bam_tpu.analysis.findings import Finding, Severity, assign_keys

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\[([a-z0-9_,\- ]+)\]\s*(.*)")


def _ensure_rules_loaded() -> None:
    # Importing the rules package registers every rule (base.register).
    from spark_bam_tpu.analysis import rules  # noqa: F401


@dataclass
class LintReport:
    findings: "list[Finding]" = field(default_factory=list)   # live only
    suppressed: "list[Finding]" = field(default_factory=list)
    stale_baseline: "list[dict]" = field(default_factory=list)
    errors: "list[str]" = field(default_factory=list)
    files: int = 0
    rules: "tuple[str, ...]" = ()
    elapsed_ms: float = 0.0

    @property
    def failing(self) -> "list[Finding]":
        return [f for f in self.findings
                if f.severity in (Severity.P1, Severity.P2)]

    @property
    def ok(self) -> bool:
        return not self.failing and not self.stale_baseline and not self.errors

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "files": self.files,
            "rules": list(self.rules),
            "elapsed_ms": round(self.elapsed_ms, 1),
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [f.to_json() for f in self.suppressed],
            "stale_baseline": self.stale_baseline,
            "errors": self.errors,
        }


def _inline_allows(lines: "list[str]") -> "dict[int, tuple[set, str]]":
    """line → (rule ids allowed, reason). An allow on a line that holds
    only the comment applies to the next NON-comment line (so the reason
    may wrap onto continuation comment lines); otherwise to its own."""
    allows: dict[int, tuple[set, str]] = {}
    for i, text in enumerate(lines, start=1):
        m = _ALLOW_RE.search(text)
        if not m:
            continue
        ids = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = m.group(2).strip()
        if text.lstrip().startswith("#"):
            target = i + 1
            while (target <= len(lines)
                   and lines[target - 1].lstrip().startswith("#")):
                target += 1
        else:
            target = i
        allows[target] = (ids, reason)
    return allows


def lint_source(rel_path: str, source: str,
                rules: "list | None" = None) -> "list[Finding]":
    """Run (a subset of) the suite over one in-memory file. The fixture
    tests drive rules through this; the CLI path goes through
    :func:`run_lint`. Inline allows are honored; no baseline."""
    _ensure_rules_loaded()
    active = rules if rules is not None else list(RULES.values())
    tree = ast.parse(source, filename=rel_path)
    ctx = LintContext(rel_path, source, tree)
    found: list[Finding] = []
    for rule in active:
        if rule.applies_to(rel_path):
            found.extend(rule.check(ctx))
    assign_keys(found, ctx.lines)
    allows = _inline_allows(ctx.lines)
    live = []
    for f in sorted(found, key=lambda f: (f.line, f.col, f.rule)):
        allowed = allows.get(f.line)
        if allowed and (f.rule in allowed[0] or "*" in allowed[0]):
            ids, reason = allowed
            if not reason:
                f.message += " (inline allow has no reason; not suppressed)"
                live.append(f)
                continue
            f.suppressed = "inline"
            f.justification = reason
            continue
        live.append(f)
    return live


def iter_py_files(root: str):
    """Yield (abs_path, rel_path) for package sources under ``root``,
    skipping caches and the rule-fixture corpus (fixtures violate on
    purpose)."""
    skip_dirs = {"__pycache__", ".git", "fixtures"}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in skip_dirs)
        for name in sorted(filenames):
            if name.endswith(".py"):
                abs_path = os.path.join(dirpath, name)
                yield abs_path, os.path.relpath(abs_path, root).replace(
                    os.sep, "/"
                )


def run_lint(root: "str | None" = None, paths: "list[str] | None" = None,
             rule_ids: "list[str] | None" = None,
             baseline: "Baseline | str | None" = None) -> LintReport:
    """Lint the package (or explicit ``paths``) and resolve suppressions.

    ``root`` defaults to the installed ``spark_bam_tpu`` package
    directory; rel paths in findings are package-relative (e.g.
    ``serve/batcher.py``).
    """
    _ensure_rules_loaded()
    t0 = time.perf_counter()
    if root is None:
        import spark_bam_tpu

        root = os.path.dirname(os.path.abspath(spark_bam_tpu.__file__))
    if rule_ids:
        unknown = [r for r in rule_ids if r not in RULES]
        if unknown:
            raise ValueError(
                f"unknown rule id(s): {', '.join(unknown)} "
                f"(have: {', '.join(sorted(RULES))})"
            )
        active = [RULES[r] for r in rule_ids]
    else:
        active = list(RULES.values())
    if isinstance(baseline, str):
        baseline = Baseline.load(baseline)

    report = LintReport(rules=tuple(r.id for r in active))
    if paths:
        files = []
        for p in paths:
            ap = os.path.abspath(p)
            if os.path.isdir(ap):
                files.extend(iter_py_files(ap))
            else:
                files.append((ap, os.path.relpath(ap, root).replace(os.sep, "/")))
    else:
        files = list(iter_py_files(root))

    for abs_path, rel_path in files:
        report.files += 1
        try:
            with open(abs_path, encoding="utf-8") as f:
                source = f.read()
            found = lint_source(rel_path, source, rules=active)
        except (OSError, SyntaxError) as exc:
            report.errors.append(f"{rel_path}: {exc}")
            continue
        for f in found:
            entry = baseline.match(f) if baseline is not None else None
            if entry is not None:
                f.suppressed = "baseline"
                f.justification = str(entry.get("justification", ""))
                report.suppressed.append(f)
            else:
                report.findings.append(f)
    if baseline is not None:
        # Stale reporting only makes sense for a full-scope run: a
        # --rules or paths subset never visits the other entries, and
        # calling them stale would make every narrowed run red.
        if not paths and not rule_ids:
            report.stale_baseline = baseline.stale_entries()
    report.findings.sort(
        key=lambda f: (Severity.rank(f.severity), f.path, f.line)
    )
    report.elapsed_ms = (time.perf_counter() - t0) * 1000.0
    return report


def render_report(report: LintReport, verbose: bool = False) -> str:
    out = []
    for f in report.findings:
        out.append(f.render())
    for e in report.stale_baseline:
        out.append(
            f"{e.get('path')}: stale baseline entry for [{e.get('rule')}] "
            f"key={e.get('key')} — finding no longer exists; delete the entry"
        )
    for err in report.errors:
        out.append(f"error: {err}")
    if verbose and report.suppressed:
        out.append("")
        for f in report.suppressed:
            out.append(f"suppressed ({f.suppressed}): {f.location()} "
                       f"[{f.rule}] — {f.justification}")
    n_fail = len(report.failing)
    n_adv = len(report.findings) - n_fail
    tail = (
        f"lint: {report.files} files, {len(report.rules)} rules, "
        f"{n_fail} failing finding{'s' if n_fail != 1 else ''}"
        + (f", {n_adv} advisory" if n_adv else "")
        + (f", {len(report.suppressed)} suppressed" if report.suppressed else "")
        + (f", {len(report.stale_baseline)} stale baseline entries"
           if report.stale_baseline else "")
        + f" ({report.elapsed_ms:.0f} ms)"
    )
    out.append(tail)
    return "\n".join(out)


def write_json(report: LintReport, path: str) -> None:
    with open(path, "w") as f:
        json.dump(report.to_json(), f, indent=2)
        f.write("\n")
