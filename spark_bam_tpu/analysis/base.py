"""Rule base class, registry, and the per-file lint context.

A rule is a class with an ``id``, a default ``severity``, a path
``scope`` (repo-relative prefixes it applies to; empty = every file),
and a ``check(ctx)`` generator yielding :class:`Finding`s. Registration
is declarative — ``@register`` at class-definition time — so importing
:mod:`spark_bam_tpu.analysis.rules` assembles the whole suite and a new
rule is one new module with one decorated class (docs/static-analysis.md
"Adding a rule").
"""

from __future__ import annotations

import ast

from spark_bam_tpu.analysis.findings import Finding


class LintContext:
    """Everything a rule sees for one file: path, source, parsed tree,
    and a parent map (``ast`` has no parent links; rules that reason
    about enclosing ``try``/function blocks need them)."""

    def __init__(self, rel_path: str, source: str, tree: ast.AST):
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._parents: "dict[ast.AST, ast.AST] | None" = None

    @property
    def parents(self) -> "dict[ast.AST, ast.AST]":
        if self._parents is None:
            self._parents = {
                child: parent
                for parent in ast.walk(self.tree)
                for child in ast.iter_child_nodes(parent)
            }
        return self._parents

    def ancestors(self, node: ast.AST):
        """Innermost-first chain of enclosing nodes."""
        p = self.parents.get(node)
        while p is not None:
            yield p
            p = self.parents.get(p)

    def enclosing_function(self, node: ast.AST):
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    def line_text(self, lineno: int) -> str:
        if 0 < lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


class Rule:
    """Base class; subclasses set ``id``/``severity``/``scope`` and
    implement ``check``. ``scope`` entries are path prefixes relative to
    the package root (e.g. ``("serve/", "fabric/")``); ``exclude``
    prefixes are carved back out."""

    id: str = ""
    severity: str = "P2"
    scope: tuple = ()
    exclude: tuple = ()
    doc: str = ""

    def applies_to(self, rel_path: str) -> bool:
        if any(rel_path.startswith(e) for e in self.exclude):
            return False
        if not self.scope:
            return True
        return any(rel_path.startswith(s) for s in self.scope)

    def check(self, ctx: LintContext):
        raise NotImplementedError

    def finding(self, ctx: LintContext, node, message: str,
                hint: str = "", severity: "str | None" = None) -> Finding:
        return Finding(
            rule=self.id,
            severity=severity or self.severity,
            path=ctx.rel_path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
            hint=hint or self.doc,
        )


#: id → rule instance; populated by ``@register`` at import time.
RULES: "dict[str, Rule]" = {}


def register(cls):
    """Class decorator: instantiate and add to the suite."""
    rule = cls()
    if not rule.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if rule.id in RULES:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    RULES[rule.id] = rule
    return cls


# ------------------------------------------------------------ shared helpers

def dotted_name(node: ast.AST) -> str:
    """Render ``a.b.c`` call targets for matching; '' when not a plain
    name/attribute chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if isinstance(node, ast.Call):
        inner = dotted_name(node.func)
        return f"{inner}()" if inner else ""
    return ""


def const_str(node) -> "str | None":
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
