"""Rule passes: importing this package registers the whole suite."""

from spark_bam_tpu.analysis.rules import (  # noqa: F401
    blocking_async,
    guard_boundary,
    jit_purity,
    obs_contract,
    shared_state,
)
