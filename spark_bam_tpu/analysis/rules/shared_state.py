"""shared-state: unsynchronized mutation of attributes shared across
threads.

The serve/fabric plane mixes three execution domains in one process:
the asyncio accept loop, the batcher tick thread, and the executor pool
(docs/serving.md). An attribute written from one domain and read from
another without a lock is a torn-read / lost-update waiting for load —
exactly the ``Batcher.pause``/``tune`` seam the fabric autoscaler pokes
at runtime.

Per class, this pass:

1. finds *thread-entry* methods — ``target=self.X`` handed to
   ``threading.Thread`` or ``pool.submit(self.X)`` — and closes them
   over ``self.Y()`` calls (the thread domain);
2. treats every other method (sync or async) as the foreign domain —
   public mutators like ``set_batch_rows`` are called from the loop or
   request threads;
3. flags ``self.attr`` assignments outside ``__init__`` that are not
   inside a ``with self.<lock>`` block, when the attribute is also
   touched from the other domain.

Classes that spawn no threads and hold no ``threading`` lock are
skipped (single-domain). Attributes whose value is itself a
synchronization primitive (``Event``/``Lock``/``Condition``/
``Semaphore``/``Queue``) are exempt — mutating THROUGH them is the
fix, not the bug. ``asyncio`` locks do not count: they serialize
coroutines, not threads.
"""

from __future__ import annotations

import ast

from spark_bam_tpu.analysis.base import LintContext, Rule, dotted_name, register

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}
_SYNC_CTORS = _LOCK_CTORS | {
    "threading.Event", "Event", "threading.Semaphore", "Semaphore",
    "threading.BoundedSemaphore", "queue.Queue", "Queue",
    "concurrent.futures.Future", "Future",
}


def _self_attr(node: ast.AST) -> "str | None":
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _method_map(cls: ast.ClassDef) -> dict:
    return {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _attr_kinds(cls: ast.ClassDef) -> "tuple[set, set]":
    """(lock attrs, all sync-primitive attrs) assigned anywhere in the
    class from a threading/queue constructor."""
    locks, sync = set(), set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        ctor = dotted_name(node.value.func)
        for t in node.targets:
            a = _self_attr(t)
            if a is None:
                continue
            if ctor in _LOCK_CTORS:
                locks.add(a)
                sync.add(a)
            elif ctor in _SYNC_CTORS:
                sync.add(a)
    return locks, sync


def _thread_entries(cls: ast.ClassDef) -> set:
    """Method names handed to Thread(target=...) or pool.submit(self.X)."""
    entries = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name.split(".")[-1] == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    a = _self_attr(kw.value)
                    if a:
                        entries.add(a)
        elif name.endswith(".submit") and node.args:
            a = _self_attr(node.args[0])
            if a:
                entries.add(a)
    return entries


def _close_over_calls(cls: ast.ClassDef, seeds: set) -> set:
    """Transitive closure of ``self.X()`` calls from seed methods."""
    methods = _method_map(cls)
    domain = set(seeds)
    frontier = list(seeds)
    while frontier:
        m = methods.get(frontier.pop())
        if m is None:
            continue
        for node in ast.walk(m):
            if isinstance(node, ast.Call):
                a = _self_attr(node.func)
                if a and a in methods and a not in domain:
                    domain.add(a)
                    frontier.append(a)
    return domain


def _locked(ctx: LintContext, node: ast.AST, locks: set) -> bool:
    """Is ``node`` inside ``with self.<lock>:`` for a known lock attr?"""
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                a = _self_attr(item.context_expr)
                if a in locks:
                    return True
    return False


@register
class SharedStateRule(Rule):
    id = "shared-state"
    severity = "P1"
    scope = ("serve/", "fabric/", "obs/", "parallel/")
    doc = ("guard cross-thread attribute writes with the class lock, or "
           "hand off through an Event/Queue (docs/serving.md)")

    def check(self, ctx: LintContext):
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            entries = _thread_entries(cls)
            locks, sync_attrs = _attr_kinds(cls)
            if not entries and not locks:
                continue            # single-domain class
            methods = _method_map(cls)
            thread_domain = _close_over_calls(cls, entries) if entries else set()
            # Per-attribute touch map: method → reads/writes (+lock state).
            touches: dict[str, dict] = {}
            for mname, m in methods.items():
                for node in ast.walk(m):
                    a = None
                    wrote = False
                    if isinstance(node, (ast.Assign, ast.AugAssign)):
                        targets = (node.targets
                                   if isinstance(node, ast.Assign)
                                   else [node.target])
                        for t in targets:
                            a = _self_attr(t)
                            if a is not None:
                                wrote = True
                                break
                    if a is None and isinstance(node, ast.Attribute):
                        a = _self_attr(node)
                    if a is None or a in sync_attrs:
                        continue
                    rec = touches.setdefault(
                        a, {"writes": [], "readers": set()}
                    )
                    if wrote:
                        rec["writes"].append(
                            (mname, node, _locked(ctx, node, locks))
                        )
                    else:
                        rec["readers"].add(mname)

            for attr, rec in sorted(touches.items()):
                toucher_methods = ({m for m, _, _ in rec["writes"]}
                                   | rec["readers"])
                if entries:
                    in_thread = toucher_methods & thread_domain
                    foreign = toucher_methods - thread_domain - {"__init__"}
                    cross = bool(in_thread) and bool(foreign)
                else:
                    # Lock-owning class with no visible thread spawn: it
                    # declared itself shared; any touch beyond __init__
                    # from 2+ methods is treated as cross-domain.
                    cross = len(toucher_methods - {"__init__"}) >= 2
                if not cross:
                    continue
                for mname, node, locked in rec["writes"]:
                    if mname == "__init__" or locked:
                        continue
                    yield self.finding(
                        ctx, node,
                        f"`{cls.name}.{attr}` is written in `{mname}` "
                        "without a lock but is shared across the "
                        "loop/thread boundary "
                        f"(also touched by: "
                        f"{', '.join(sorted(toucher_methods - {mname}))})",
                        hint=(f"take `with self.{sorted(locks)[0]}:` around "
                              "the write" if locks else
                              "add a threading.Lock/Condition to the class"),
                    )
