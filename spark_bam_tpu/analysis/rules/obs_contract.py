"""obs-contract: metric/span names from the registered catalog, with
bounded cardinality.

The PR 11 telemetry plane merges per-process snapshots by series name
(obs/exporters.py ``merge_snapshots``) — an ad-hoc name in one worker
forks a series the fleet view can't join, and a per-request dynamic
name grows the registry without bound. The contract (obs/names.py):

- a literal name passed to ``obs.count``/``observe``/``span``/
  ``counter``/``gauge``/``histogram`` must be in ``obs.names.NAMES``
  and follow the dotted lower-case ``layer.stage`` convention (P1 when
  unregistered — add the constant to obs/names.py);
- an f-string name is P2 when its literal prefix starts with a
  registered ``layer.`` (bounded suffix sets like flag-bit names are
  fine — justify with an inline allow), P1 when fully dynamic;
- label kwargs on ``counter``/``gauge``/``histogram``/``observe`` must
  be literal values (P2) — labels are series keys, not payload.

``obs/`` itself is exempt: the registry/exporter plumbing passes names
through by design. Span ``attrs`` kwargs are payload, not series keys,
and are not checked.
"""

from __future__ import annotations

import ast
import re

from spark_bam_tpu.analysis.base import LintContext, Rule, const_str, register
from spark_bam_tpu.obs import names as obs_names

#: obs entry points whose first positional arg is a series/span name
NAME_FNS = {"count", "observe", "span", "counter", "gauge", "histogram"}
#: of those, the ones whose kwargs are series labels (span kwargs = attrs)
LABELED_FNS = {"observe", "counter", "gauge", "histogram"}

_NAME_RE = re.compile(r"^[a-z0-9_\-]+(\.[a-z0-9_\-]+)+$")


def _obs_call(node: ast.Call) -> "str | None":
    """The obs entry-point name when this is ``obs.<fn>(...)`` or any
    ``<recv>.emit_span_event(...)``, else None."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    if f.attr == "emit_span_event":
        return f.attr
    if isinstance(f.value, ast.Name) and f.value.id == "obs" \
            and f.attr in NAME_FNS:
        return f.attr
    return None


@register
class ObsContractRule(Rule):
    id = "obs-contract"
    severity = "P1"
    scope = ()                      # whole package
    exclude = ("obs/",)             # the plumbing layer passes names through
    doc = ("register new metric/span names in obs/names.py; keep "
           "cardinality bounded (docs/observability.md)")

    def check(self, ctx: LintContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _obs_call(node)
            if fn is None or not node.args:
                continue
            arg = node.args[0]
            lit = const_str(arg)
            if lit is not None:
                if not _NAME_RE.match(lit):
                    yield self.finding(
                        ctx, arg,
                        f"obs name {lit!r} does not follow the dotted "
                        "lower-case `layer.stage` convention",
                        hint="rename and register it in obs/names.py",
                    )
                elif not obs_names.is_registered(lit):
                    layer = obs_names.layer_of(lit)
                    extra = ("" if layer in obs_names.LAYERS else
                             f" (layer {layer!r} is new — add it to LAYERS)")
                    yield self.finding(
                        ctx, arg,
                        f"obs name {lit!r} is not in the registered catalog"
                        f"{extra}",
                        hint="add the constant to obs/names.py NAMES so "
                             "fleet snapshot merges can join the series",
                    )
            elif isinstance(arg, ast.JoinedStr):
                prefix = ""
                if arg.values and isinstance(arg.values[0], ast.Constant):
                    prefix = str(arg.values[0].value)
                layer = prefix.split(".", 1)[0] if "." in prefix else ""
                if layer in obs_names.LAYERS:
                    yield self.finding(
                        ctx, arg,
                        f"dynamic obs name with prefix {prefix!r}: series "
                        "cardinality is only as bounded as the suffix set",
                        hint="justify the bound with an inline "
                             "`# lint: allow[obs-contract] ...`, or "
                             "enumerate the names in obs/names.py",
                        severity="P2",
                    )
                else:
                    yield self.finding(
                        ctx, arg,
                        f"unbounded dynamic obs name in `obs.{fn}` — one "
                        "series per distinct value",
                        hint="use a registered literal name; put the "
                             "varying part in the event payload, not the "
                             "series name",
                    )
            else:
                yield self.finding(
                    ctx, arg,
                    f"non-literal obs name in `obs.{fn}` — the catalog "
                    "cannot vouch for it",
                    hint="pass a literal registered name (obs/names.py)",
                )
            if fn in LABELED_FNS:
                for kw in node.keywords:
                    if kw.arg is None:
                        continue
                    if not isinstance(kw.value, ast.Constant):
                        yield self.finding(
                            ctx, kw.value,
                            f"non-literal label value for {kw.arg!r} on "
                            f"`obs.{fn}` — labels key the series; dynamic "
                            "values explode cardinality",
                            hint="use a bounded literal label, or move the "
                                 "value into a histogram observation",
                            severity="P2",
                        )
