"""jit-purity: Python control flow on traced values inside jitted code.

A Python ``if``/``while``/``for``/``assert`` on a traced array forces a
concretization error at best and, with shape-dependent branching, a
silent recompile per distinct shape at worst — defeating the
``MeshSteps`` compiled-step registry that the serve daemon's whole perf
story rests on (docs/serving.md). This pass finds, inside functions
reachable as jit roots:

- Python branches/loops whose condition mentions a traced parameter
  (``.shape``/``.ndim``/``.dtype``/``.size``/``len()`` access is static
  and exempt, as are ``is``/``is not`` None-sentinel tests);
- host concretizations: ``int()``/``bool()``/``float()`` on traced
  values, ``.item()``/``.tolist()`` calls;
- non-literal ``static_argnums``/``static_argnames`` at any ``jax.jit``
  site (varying statics silently fork the compile cache).

Taint is intraprocedural: traced = non-static parameters plus names
assigned from expressions that mention traced names (through the static
exemptions). Nested ``def``s (vmap/shard_map bodies) extend the traced
set with their own parameters.
"""

from __future__ import annotations

import ast

from spark_bam_tpu.analysis.base import LintContext, Rule, dotted_name, register

#: attribute reads on a tracer that are static at trace time
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "nbytes"}
#: builtins whose application to a tracer concretizes (ConcretizationError)
CONCRETIZERS = {"int", "bool", "float"}
CONCRETIZER_METHODS = {"item", "tolist"}
JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
PARTIAL_NAMES = {"functools.partial", "partial"}


def _is_jit_func(node: ast.AST) -> bool:
    return dotted_name(node) in JIT_NAMES


def _static_names_from_kwargs(keywords) -> "tuple[set, set, list]":
    """(static_argnames, static_argnums, non-literal kw nodes)."""
    names: set = set()
    nums: set = set()
    bad = []
    for kw in keywords or ():
        if kw.arg not in ("static_argnames", "static_argnums"):
            continue
        v = kw.value
        elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        ok = True
        for e in elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                names.add(e.value)
            elif isinstance(e, ast.Constant) and isinstance(e.value, int):
                nums.add(e.value)
            else:
                ok = False
        if not ok:
            bad.append(kw)
    return names, nums, bad


def _jit_decoration(fn: ast.FunctionDef):
    """(is_jitted, static_argnames, static_argnums, bad_kw_nodes)."""
    for dec in fn.decorator_list:
        if _is_jit_func(dec):
            return True, set(), set(), []
        if isinstance(dec, ast.Call):
            if _is_jit_func(dec.func):
                names, nums, bad = _static_names_from_kwargs(dec.keywords)
                return True, names, nums, bad
            if (dotted_name(dec.func) in PARTIAL_NAMES and dec.args
                    and _is_jit_func(dec.args[0])):
                names, nums, bad = _static_names_from_kwargs(dec.keywords)
                return True, names, nums, bad
    return False, set(), set(), []


def _callsite_jitted_names(tree: ast.AST):
    """Function names passed to ``jax.jit(f, ...)`` / ``jax.jit(
    shard_map(f, ...))`` call sites, plus static kwargs seen there."""
    jitted: dict[str, tuple] = {}
    bad_static: list = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_jit_func(node.func)
                and node.args):
            continue
        names, nums, bad = _static_names_from_kwargs(node.keywords)
        bad_static.extend(bad)
        target = node.args[0]
        if (isinstance(target, ast.Call)
                and dotted_name(target.func).endswith("shard_map")
                and target.args):
            target = target.args[0]
        if isinstance(target, ast.Name):
            jitted[target.id] = (names, nums)
    return jitted, bad_static


class _TaintScanner:
    """Walk one jit-root function; yield (node, why) violations."""

    def __init__(self, ctx: LintContext, fn: ast.FunctionDef,
                 static_names: set, static_nums: set):
        self.ctx = ctx
        self.fn = fn
        params = [a.arg for a in (
            fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
        )]
        self.tainted = {
            p for i, p in enumerate(params)
            if p not in static_names and i not in static_nums
            and p not in ("self", "cls")
        }
        # Parameters with non-array defaults (str/bool/None sentinels) are
        # config-shaped, not data: branching on them retraces at most once
        # per distinct config — the compile-cache contract, not a bug.
        defaults = fn.args.defaults
        if defaults:
            for a, d in zip(fn.args.args[-len(defaults):], defaults):
                if isinstance(d, ast.Constant):
                    self.tainted.discard(a.arg)
        for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if isinstance(d, ast.Constant):
                self.tainted.discard(a.arg)

    def _traced_name_in(self, expr: ast.AST):
        """The first Name node in ``expr`` that reads a traced value in a
        non-static position, else None."""
        parents = self.ctx.parents
        for n in ast.walk(expr):
            if not (isinstance(n, ast.Name) and n.id in self.tainted):
                continue
            p = parents.get(n)
            # x.shape / x.ndim / ... are static metadata.
            if isinstance(p, ast.Attribute) and p.attr in STATIC_ATTRS:
                continue
            # len(x) is static (leading-axis length).
            if (isinstance(p, ast.Call) and isinstance(p.func, ast.Name)
                    and p.func.id == "len"):
                continue
            # `x is None` / `x is not None` sentinel tests are host-level.
            if isinstance(p, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in p.ops
            ):
                continue
            return n
        return None

    def scan(self):
        # Propagate taint through simple assignments first (top to bottom).
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) and self._traced_name_in(node.value):
                    self.tainted.add(t.id)
            elif isinstance(node, ast.FunctionDef) and node is not self.fn:
                # vmap/shard_map bodies: their params are traced too.
                for a in node.args.args:
                    if a.arg not in ("self", "cls"):
                        self.tainted.add(a.arg)

        for node in ast.walk(self.fn):
            if isinstance(node, (ast.If, ast.While)):
                hit = self._traced_name_in(node.test)
                if hit is not None:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield node, (
                        f"Python `{kind}` on traced value {hit.id!r} inside "
                        f"jitted `{self.fn.name}` — branches must be "
                        "jnp.where/lax.cond/lax.while_loop, or the argument "
                        "must be static"
                    )
            elif isinstance(node, ast.IfExp):
                hit = self._traced_name_in(node.test)
                if hit is not None:
                    yield node, (
                        f"conditional expression on traced value {hit.id!r} "
                        f"inside jitted `{self.fn.name}` — use jnp.where"
                    )
            elif isinstance(node, ast.Assert):
                hit = self._traced_name_in(node.test)
                if hit is not None:
                    yield node, (
                        f"assert on traced value {hit.id!r} inside jitted "
                        f"`{self.fn.name}` — concretizes at trace time; use "
                        "checkify or a host-side precondition"
                    )
            elif isinstance(node, ast.For):
                hit = self._traced_name_in(node.iter)
                if (hit is not None and isinstance(node.iter, ast.Name)):
                    yield node, (
                        f"Python `for` iterating traced value {hit.id!r} "
                        f"inside jitted `{self.fn.name}` — use lax.scan or "
                        "lax.fori_loop"
                    )
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if (name in CONCRETIZERS and node.args
                        and self._traced_name_in(node.args[0]) is not None):
                    yield node, (
                        f"`{name}()` concretizes a traced value inside "
                        f"jitted `{self.fn.name}` — forces a host sync / "
                        "trace error; keep it an array op"
                    )
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in CONCRETIZER_METHODS
                        and self._traced_name_in(node.func.value) is not None):
                    yield node, (
                        f"`.{node.func.attr}()` on a traced value inside "
                        f"jitted `{self.fn.name}` — device→host sync defeats "
                        "async dispatch"
                    )


@register
class JitPurityRule(Rule):
    id = "jit-purity"
    severity = "P1"
    scope = ("tpu/", "parallel/")
    doc = ("keep jitted bodies trace-pure: lax control flow for traced "
           "values, literal static_argnums/argnames (docs/design.md)")

    def check(self, ctx: LintContext):
        callsite_jitted, bad_static = _callsite_jitted_names(ctx.tree)
        for kw in bad_static:
            yield self.finding(
                ctx, kw.value,
                "non-literal static_argnums/static_argnames at a jax.jit "
                "site — varying statics fork the compile cache per call",
                hint="pass a literal int/str tuple; route dynamic choices "
                     "through MeshSteps keys instead",
            )
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            jitted, names, nums, bad = _jit_decoration(node)
            for kw in bad:
                yield self.finding(
                    ctx, kw.value,
                    f"non-literal static_argnums/static_argnames on jitted "
                    f"`{node.name}`",
                    hint="use a literal tuple of names/positions",
                )
            if not jitted and node.name in callsite_jitted:
                jitted = True
                names, nums = callsite_jitted[node.name]
            if not jitted:
                continue
            for bad_node, msg in _TaintScanner(ctx, node, names, nums).scan():
                yield self.finding(ctx, bad_node, msg)
