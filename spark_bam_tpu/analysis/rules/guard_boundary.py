"""guard-boundary: untrusted-byte unpacks outside the guard taxonomy.

The PR 4 contract (core/guard.py, docs/robustness.md "Malformed
inputs"): every parser consuming untrusted bytes fails with a typed
``MalformedInputError`` — never a bare ``struct.error`` that the fault
model would misread as retryable and the fuzz harness would count as a
contract violation. New decode code must not silently regress that.

A ``struct.unpack``/``unpack_from`` call in a parser module is
*guarded* when any of:

1. it sits inside a ``try`` whose handlers catch ``struct.error``, a
   taxonomy type (``MalformedInputError`` and subclasses, including
   module-local ones like ``SbiFormatError``), ``ValueError``, or
   ``Exception``;
2. its enclosing function raises a taxonomy type itself — the
   validate-lengths-then-unpack idiom (bam/record.py ``decode``), where
   the raises prove the function participates in the taxonomy;
3. every module-local call site of its enclosing function satisfies (1)
   — the parse-helper-wrapped-by-reader idiom (bam/bai.py ``_parse``);
4. its byte source is a call to a same-module taxonomy-raising helper —
   the guarded-feeder idiom (sbi/format.py ``_Reader.unpack`` feeds
   ``struct.unpack`` from ``self.take(calcsize(fmt))``, which raises
   ``SbiFormatError`` before short bytes ever reach the unpack).

Anything else is a P1: a corrupt length field away from an untyped
crash.
"""

from __future__ import annotations

import ast

from spark_bam_tpu.analysis.base import LintContext, Rule, dotted_name, register

#: the core taxonomy; module-local subclasses are discovered per file
TAXONOMY = {
    "MalformedInputError", "TruncatedInput", "StructurallyInvalid",
    "LimitExceeded", "RecordGapError", "BlockGapError",
}
#: broad handlers that necessarily cover struct.error
BROAD_HANDLERS = {"Exception", "ValueError", "struct.error", "error"}


def _local_taxonomy(tree: ast.AST) -> set:
    """TAXONOMY plus classes in this module derived from it (directly or
    through other local classes)."""
    names = set(TAXONOMY)
    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
    changed = True
    while changed:
        changed = False
        for cls in classes:
            if cls.name in names:
                continue
            for base in cls.bases:
                b = dotted_name(base)
                if b.split(".")[-1] in names:
                    names.add(cls.name)
                    changed = True
                    break
    return names


def _handler_names(handler: ast.ExceptHandler) -> set:
    t = handler.type
    if t is None:
        return {"Exception"}          # bare except
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = set()
    for e in elts:
        name = dotted_name(e)
        out.add(name)
        out.add(name.split(".")[-1])
    return out


def _in_guarded_try(ctx: LintContext, node: ast.AST, taxonomy: set) -> bool:
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.Try):
            # Only the try BODY is protected by the handlers.
            if not any(node is b or _contains(b, node) for b in anc.body):
                continue
            for h in anc.handlers:
                caught = _handler_names(h)
                if caught & BROAD_HANDLERS or caught & taxonomy:
                    return True
    return False


def _contains(tree: ast.AST, node: ast.AST) -> bool:
    return any(n is node for n in ast.walk(tree))


def _raises_taxonomy(fn: ast.AST, taxonomy: set) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Raise) and n.exc is not None:
            exc = n.exc
            name = dotted_name(exc.func) if isinstance(exc, ast.Call) \
                else dotted_name(exc)
            if name.split(".")[-1] in taxonomy:
                return True
        # Delegating to a guard helper (`_bai_count(...)`, `r.take(...)`)
        # counts when the helper itself raises taxonomy — approximated by
        # a same-module helper check at the call layer below.
    return False


def _guarded_feeder(node: ast.Call, guarded_names: set) -> bool:
    """True when an argument of this unpack is produced by a call to a
    same-module taxonomy-raising helper (``self.take(...)``): the feeder
    validates sizing and fails typed before bytes reach the unpack."""
    for arg in node.args:
        for sub in ast.walk(arg):
            if (isinstance(sub, ast.Call)
                    and dotted_name(sub.func).split(".")[-1]
                    in guarded_names):
                return True
    return False


def _is_unpack_call(node: ast.Call) -> bool:
    if not isinstance(node.func, ast.Attribute):
        return False
    if node.func.attr not in ("unpack", "unpack_from"):
        return False
    # struct.unpack / struct.unpack_from / <Struct instance>.unpack_from —
    # exclude obvious non-struct receivers? The attr names are specific
    # enough in parser modules; keep the match broad so _FIXED.unpack_from
    # (a precompiled Struct) is covered.
    return True


@register
class GuardBoundaryRule(Rule):
    id = "guard-boundary"
    severity = "P1"
    scope = ("bam/", "bgzf/", "cram/", "sbi/", "columnar/")
    doc = ("untrusted bytes must fail typed: validate lengths then "
           "unpack, or catch struct.error and raise TruncatedInput "
           "(core/guard.py, docs/robustness.md)")

    def check(self, ctx: LintContext):
        taxonomy = _local_taxonomy(ctx.tree)
        # Functions whose body raises the taxonomy (the validate-then-
        # unpack idiom) — their unpacks are guarded.
        guarded_fns = set()
        fns = [n for n in ast.walk(ctx.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in fns:
            if _raises_taxonomy(fn, taxonomy):
                guarded_fns.add(fn)
        # One-hop call-site guarding: helper functions whose every
        # module-local call site sits in a guarded try (bai._parse).
        callsite_guarded = set()
        for fn in fns:
            if fn in guarded_fns:
                continue
            sites = [
                c for c in ast.walk(ctx.tree)
                if isinstance(c, ast.Call)
                and dotted_name(c.func).split(".")[-1] == fn.name
            ]
            if sites and all(
                _in_guarded_try(ctx, c, taxonomy)
                or ctx.enclosing_function(c) in guarded_fns
                for c in sites
            ):
                callsite_guarded.add(fn)
        guarded_names = {fn.name for fn in guarded_fns}

        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and _is_unpack_call(node)):
                continue
            if _in_guarded_try(ctx, node, taxonomy):
                continue
            if _guarded_feeder(node, guarded_names):
                continue
            fn = ctx.enclosing_function(node)
            if fn is not None and (fn in guarded_fns
                                   or fn in callsite_guarded):
                continue
            where = f" in `{fn.name}`" if fn is not None else ""
            yield self.finding(
                ctx, node,
                f"bare `{dotted_name(node.func)}` on untrusted bytes"
                f"{where}: a corrupt input raises untyped struct.error",
                hint="bounds-check first and raise TruncatedInput/"
                     "StructurallyInvalid, or wrap in try/except "
                     "struct.error (core/guard.py)",
            )
