"""blocking-async: blocking calls on the router/health/autoscaler loops.

One ``time.sleep`` in an ``async def`` stalls EVERY connection the
accept loop multiplexes — the serve daemon's contract is that the event
loop only parses lines and shuttles futures (serve/server.py docstring);
real work belongs on the service's thread pool. This pass flags, inside
``async def`` bodies in serve/ and fabric/:

- ``time.sleep`` (P1 — use ``await asyncio.sleep``);
- ``subprocess.*`` / ``os.system`` / ``os.popen`` / ``os.wait*`` (P1);
- synchronous network clients: ``ServeClient`` (its socket I/O blocks),
  ``urllib.request.urlopen``, ``requests.*``, ``socket.create_connection``
  (P1 — use the async link, or run_in_executor);
- ``Future.result()`` / ``.join()`` on threads (P1 — await
  ``asyncio.wrap_future`` instead);
- filesystem I/O: ``open()`` and pathlib ``read_*``/``write_*`` (P2 —
  tolerable for tiny config reads, but hot paths must move to the pool).

Code inside nested ``def``/``lambda`` is exempt: that is exactly how
work is handed to ``run_in_executor``/``to_thread``.
"""

from __future__ import annotations

import ast

from spark_bam_tpu.analysis.base import LintContext, Rule, dotted_name, register

_P1_CALLS = {
    "time.sleep": "await asyncio.sleep(...) instead",
    "os.system": "use asyncio.create_subprocess_exec",
    "os.popen": "use asyncio.create_subprocess_exec",
    "os.wait": "use asyncio.create_subprocess_exec + await proc.wait()",
    "os.waitpid": "use asyncio.create_subprocess_exec + await proc.wait()",
    "socket.create_connection": "use asyncio.open_connection",
    "urllib.request.urlopen": "run it in the executor",
    "ServeClient": "ServeClient does blocking socket I/O; use the async "
                   "WorkerLink (fabric/router.py) or run_in_executor",
}
_P1_PREFIXES = {
    "subprocess.": "use asyncio.create_subprocess_exec",
    "requests.": "run it in the executor",
}
_P1_METHODS = {
    "result": "await asyncio.wrap_future(fut) instead of fut.result()",
}
_P2_CALLS = {
    "open": "file I/O blocks the loop; loop.run_in_executor for hot paths",
}
_P2_METHODS = {
    "read_text": "pathlib I/O blocks the loop; run_in_executor on hot paths",
    "read_bytes": "pathlib I/O blocks the loop; run_in_executor on hot paths",
    "write_text": "pathlib I/O blocks the loop; run_in_executor on hot paths",
    "write_bytes": "pathlib I/O blocks the loop; run_in_executor on hot paths",
}


def _async_body_calls(fn: ast.AsyncFunctionDef):
    """Call nodes executed ON the loop: walk the async body but do not
    descend into nested function definitions or lambdas."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


@register
class BlockingAsyncRule(Rule):
    id = "blocking-async"
    severity = "P1"
    scope = ("serve/", "fabric/")
    doc = ("the event loop only parses lines and shuttles futures; "
           "blocking work goes to the pool (docs/serving.md)")

    def check(self, ctx: LintContext):
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for call in _async_body_calls(fn):
                name = dotted_name(call.func)
                if name in _P1_CALLS:
                    yield self.finding(
                        ctx, call,
                        f"blocking call `{name}` in async `{fn.name}` stalls "
                        "the event loop",
                        hint=_P1_CALLS[name],
                    )
                    continue
                pref = next(
                    (p for p in _P1_PREFIXES if name.startswith(p)), None
                )
                if pref is not None:
                    yield self.finding(
                        ctx, call,
                        f"blocking call `{name}` in async `{fn.name}` stalls "
                        "the event loop",
                        hint=_P1_PREFIXES[pref],
                    )
                    continue
                if isinstance(call.func, ast.Attribute):
                    m = call.func.attr
                    if m in _P1_METHODS:
                        yield self.finding(
                            ctx, call,
                            f"blocking `.{m}()` in async `{fn.name}` stalls "
                            "the event loop",
                            hint=_P1_METHODS[m],
                        )
                        continue
                    if m in _P2_METHODS:
                        yield self.finding(
                            ctx, call,
                            f"blocking `.{m}()` in async `{fn.name}`",
                            hint=_P2_METHODS[m], severity="P2",
                        )
                        continue
                if name in _P2_CALLS:
                    yield self.finding(
                        ctx, call,
                        f"blocking call `{name}` in async `{fn.name}`",
                        hint=_P2_CALLS[name], severity="P2",
                    )
