"""Finding objects: what a rule reports and how it is addressed.

A finding pins a rule violation to ``file:line``, carries a fix hint,
and owns a *stable key* — a content hash of the flagged source line plus
its occurrence index — so baseline entries survive unrelated edits that
shift line numbers (the same property ``.sbi`` fingerprints give split
plans: identity by content, not position).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass


class Severity:
    """P1 fails the gate outright; P2 fails unless baselined; P3 is
    advisory (reported, never fails). Ordering: P1 < P2 < P3."""

    P1 = "P1"
    P2 = "P2"
    P3 = "P3"
    ORDER = (P1, P2, P3)

    @classmethod
    def rank(cls, sev: str) -> int:
        return cls.ORDER.index(sev)


@dataclass
class Finding:
    rule: str
    severity: str
    path: str            # repo-relative posix path
    line: int
    col: int
    message: str
    hint: str = ""
    key: str = ""        # content hash; filled by the runner
    justification: str = ""   # set when suppressed by baseline/inline
    suppressed: str = ""      # "", "baseline", or "inline"

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def render(self) -> str:
        out = f"{self.location()}: {self.severity} [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
            "key": self.key,
            "suppressed": self.suppressed,
            "justification": self.justification,
        }


def finding_key(rule: str, line_text: str, occurrence: int) -> str:
    """Stable identity for one finding: the rule, the flagged line's
    stripped text, and which same-text occurrence in the file this is.
    Line numbers deliberately excluded — edits above the finding must
    not orphan its baseline entry."""
    crc = zlib.crc32(line_text.strip().encode("utf-8", "replace"))
    return f"{rule}:{crc:08x}:{occurrence}"


def assign_keys(findings: "list[Finding]", lines: "list[str]") -> None:
    """Fill ``key`` on every finding of ONE file (findings must carry
    1-based line numbers into ``lines``)."""
    seen: dict[tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.line, f.col)):
        text = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        ident = (f.rule, text.strip())
        n = seen.get(ident, 0)
        seen[ident] = n + 1
        f.key = finding_key(f.rule, text, n)
