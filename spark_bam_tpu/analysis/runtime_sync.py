"""Runtime complement to the static ``shared-state`` rule: a lock-order
and happens-before recorder.

The AST pass (rules/shared_state.py) proves an attribute *could* be
touched from two thread domains; this harness observes what actually
happens under load and catches the two failure classes statics can't:

- **lock-order inversion** — thread A holds L1 and wants L2 while
  thread B holds L2 and wants L1. Recorded as edges in a held→acquired
  graph; any cycle is a potential deadlock even if the run got lucky.
- **unsynchronized sharing** — an object accessed from two threads with
  no lock held on either side and no happens-before edge between them.

Usage (the ``slow``-marked test in tests/test_lint.py drives this over
the serve batcher seam)::

    rec = LockOrderRecorder()
    lock_a = rec.wrap(threading.Lock(), "a")
    lock_b = rec.wrap(threading.Lock(), "b")
    ... run the workload ...
    assert rec.cycles() == []

Pure stdlib, no monkeypatching: callers wrap the locks they care about.
"""

from __future__ import annotations

import threading
from collections import defaultdict


class _WrappedLock:
    """Context-manager proxy recording acquire/release order per thread."""

    def __init__(self, lock, name: str, recorder: "LockOrderRecorder"):
        self._lock = lock
        self.name = name
        self._rec = recorder

    def acquire(self, *a, **kw):
        self._rec._note_acquire(self.name)
        got = self._lock.acquire(*a, **kw)
        if not got:
            self._rec._note_release(self.name)
        return got

    def release(self):
        self._lock.release()
        self._rec._note_release(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition-style passthroughs so a wrapped Condition still works.
    def __getattr__(self, item):
        return getattr(self._lock, item)


class LockOrderRecorder:
    """Records the held-set at every acquire and derives an order graph.

    Thread-safe; cheap enough to leave on in a stress test. ``edges``
    maps held-lock → {locks acquired while holding it}; a cycle in that
    graph is a lock-order inversion (potential deadlock), regardless of
    whether this particular run interleaved badly.
    """

    def __init__(self):
        self._guard = threading.Lock()
        self._held = defaultdict(list)        # thread id → [lock names]
        self.edges: "dict[str, set]" = defaultdict(set)
        self.acquisitions: "dict[str, int]" = defaultdict(int)
        #: (thread name, held tuple) per acquire — the happens-before log
        self.log: "list[tuple[str, str, tuple]]" = []

    def wrap(self, lock, name: str) -> _WrappedLock:
        return _WrappedLock(lock, name, self)

    def _note_acquire(self, name: str) -> None:
        tid = threading.get_ident()
        with self._guard:
            held = self._held[tid]
            for h in held:
                if h != name:
                    self.edges[h].add(name)
            self.acquisitions[name] += 1
            self.log.append(
                (threading.current_thread().name, name, tuple(held))
            )
            held.append(name)

    def _note_release(self, name: str) -> None:
        tid = threading.get_ident()
        with self._guard:
            held = self._held[tid]
            if name in held:
                # Remove the most recent acquisition (re-entrant safe).
                for i in range(len(held) - 1, -1, -1):
                    if held[i] == name:
                        del held[i]
                        break

    def cycles(self) -> "list[list[str]]":
        """Every elementary cycle in the held→acquired graph (DFS)."""
        with self._guard:
            graph = {k: set(v) for k, v in self.edges.items()}
        out: list[list[str]] = []
        seen_cycles: set = set()

        def dfs(node, path, on_path):
            for nxt in sorted(graph.get(node, ())):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(cyc)
                    continue
                dfs(nxt, path + [nxt], on_path | {nxt})

        for start in sorted(graph):
            dfs(start, [start], {start})
        return out

    def threads_touching(self, name: str) -> "set[str]":
        """Thread names that acquired ``name`` — ≥2 proves cross-thread
        sharing the static pass inferred."""
        with self._guard:
            return {t for t, n, _ in self.log if n == name}
