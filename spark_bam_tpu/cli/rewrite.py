"""htsjdk-rewrite analog: round-trip a BAM through our writer so record
starts stop being block-aligned — manufactures adversarial inputs for split
testing (reference cli/.../rewrite/HTSJDKRewrite.scala:347-418)."""

from __future__ import annotations

from spark_bam_tpu.bam.index_records import index_records
from spark_bam_tpu.bam.iterators import RecordStream
from spark_bam_tpu.bam.writer import write_bam
from spark_bam_tpu.bgzf.index_blocks import index_blocks
from spark_bam_tpu.cli.output import Printer
from spark_bam_tpu.core.channel import open_channel


def run(
    in_path,
    out_path,
    p: Printer,
    block_payload: int = 0xFF00,
    reindex: bool = False,
) -> None:
    with open_channel(in_path) as ch:
        stream = RecordStream.open(ch)
        header = stream.header
        count = write_bam(
            out_path, header, (rec for _, rec in stream), block_payload=block_payload
        )
    p.echo(f"Wrote {count} reads to {out_path}")
    if reindex:
        _, n_blocks = index_blocks(out_path)
        _, n_records = index_records(out_path)
        p.echo(f"Indexed {n_blocks} blocks, {n_records} records")
