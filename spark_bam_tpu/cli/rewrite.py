"""htsjdk-rewrite analog: a real re-blocking transform.

Round-trips a BAM through our writer so record starts stop being
block-aligned — the adversarial-input manufacture of the reference
(cli/.../rewrite/HTSJDKRewrite.scala:347-418) — and, since PR 14, the
transform half of the system: ``--block-payload`` re-blocks,
``--deflate`` routes the members through the device compressor
(compress/), the output lands atomically (core/atomic.py via
``write_bam_result``), and ``--index`` emits the ``.blocks`` /
``.records`` / ``.sbi`` sidecars *during* the write — every record
start and block boundary is known as we pack, so the sidecars cost no
re-read and the ``.sbi`` (blocks + record starts + a split plan for the
config's split size) serves warm loads of the output immediately
(docs/caching.md, the PR 3 cache).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

from spark_bam_tpu.bam.iterators import RecordStream
from spark_bam_tpu.bam.writer import DEFAULT_BLOCK_PAYLOAD, WriteResult, write_bam_result
from spark_bam_tpu.cli.output import Printer
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.pos import Pos


@dataclass
class RewriteResult:
    count: int = 0
    bytes_out: int = 0
    n_blocks: int = 0
    #: sidecar kind → written path ("blocks" / "records" / "sbi")
    sidecars: "dict[str, str]" = field(default_factory=dict)


def _flat_to_pos(blocks, flats: "list[int]") -> "list[Pos]":
    """Flat uncompressed offsets → virtual positions, from the writer's
    own block table (no re-read; the searchsorted half of
    ``sbi.format.record_starts_to_virtual`` without needing a FlatView).
    """
    starts = np.array([m.start for m in blocks], dtype=np.int64)
    flat0 = np.cumsum([0] + [m.uncompressed_size for m in blocks])[:-1]
    f = np.asarray(flats, dtype=np.int64)
    idx = np.searchsorted(flat0, f, side="right") - 1
    return [
        Pos(int(starts[i]), int(off))
        for i, off in zip(idx, f - flat0[idx])
    ]


def _synth_split_plan(blocks, positions: "list[Pos]", splits):
    """The split plan live resolution would produce, computed from the
    write-time block table and record starts (sbi/plan.py semantics:
    first block boundary at/after the split start, then the first record
    start at/after that block; the first-record fast path mirrors
    ``load.api._resolve_split_start``)."""
    from spark_bam_tpu.sbi.format import PLAN_NONE, PLAN_POS, PlanEntry

    block_starts = np.asarray([m.start for m in blocks], dtype=np.int64)
    # A record at (block, offset) is at/after Pos(b, 0) iff block >= b
    # (offsets are non-negative), so the record search is one
    # searchsorted over record block positions.
    rec_blocks = np.asarray([p.block_pos for p in positions], dtype=np.int64)
    entries = []
    first = positions[0] if positions else None
    for split in splits:
        if first is not None and split.start <= first.block_pos < split.end:
            entries.append(PlanEntry(split.start, PLAN_POS, first))
            continue
        i = int(np.searchsorted(block_starts, split.start, side="left"))
        if i >= len(block_starts) or block_starts[i] >= split.end:
            entries.append(PlanEntry(split.start, PLAN_NONE, None))
            continue
        j = int(np.searchsorted(rec_blocks, block_starts[i], side="left"))
        if j >= len(positions):
            entries.append(PlanEntry(split.start, PLAN_NONE, None))
        else:
            entries.append(PlanEntry(split.start, PLAN_POS, positions[j]))
    return entries


def emit_sidecars(out_path, result: WriteResult, config: Config) -> "dict[str, str]":
    """``.blocks`` + ``.records`` + ``.sbi`` for a just-written BAM, all
    from the in-memory :class:`WriteResult` — index-aligned output for
    free. The ``.sbi`` carries blocks, record starts AND a synthesized
    split plan for the config's load split size, so a warm load of the
    rewritten file does zero ``load.split_resolutions``."""
    from spark_bam_tpu import sbi
    from spark_bam_tpu.bam.index_records import format_record_line
    from spark_bam_tpu.bgzf.index_blocks import format_block_line
    from spark_bam_tpu.load.splits import file_splits

    out_path = str(out_path)
    positions = _flat_to_pos(result.blocks, result.record_flats)
    written: dict[str, str] = {}

    def atomic_text(path: str, lines) -> None:
        tmp = f"{path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                for line in lines:
                    f.write(line + "\n")
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):  # failure path only
                os.unlink(tmp)

    blocks_path = out_path + ".blocks"
    atomic_text(blocks_path, (format_block_line(m) for m in result.blocks))
    written["blocks"] = blocks_path
    records_path = out_path + ".records"
    atomic_text(records_path, (format_record_line(p) for p in positions))
    written["records"] = records_path

    size = config.split_size_or(Config.LOAD_SPLIT_SIZE_DEFAULT)
    splits = file_splits(out_path, size)
    virtual = np.array(
        [(p.block_pos << 16) | p.offset for p in positions], dtype=np.uint64
    )
    index = sbi.SbiIndex(
        sbi.fingerprint_of(out_path, config),
        blocks=list(result.blocks),
        split_plans={size: _synth_split_plan(result.blocks, positions, splits)},
        record_starts=virtual,
    )
    store = sbi.CacheStore.from_env(policy=config.fault_policy)
    sbi_path = store.store(out_path, index)
    if sbi_path:
        written["sbi"] = sbi_path
    return written


def rewrite_bam(
    in_path,
    out_path,
    block_payload: int = DEFAULT_BLOCK_PAYLOAD,
    level: int = 6,
    deflate: "str | None" = None,
    index: bool = False,
    config: Config = Config(),
) -> RewriteResult:
    """The transform core (shared by the CLI and the serve ``rewrite``
    op): stream records out of ``in_path``, re-block + re-compress into
    ``out_path`` (atomic), optionally emitting sidecars from the packing
    metadata."""
    spec = deflate if deflate is not None else config.deflate
    with open_channel(in_path) as ch:
        stream = RecordStream.open(ch)
        result = write_bam_result(
            out_path, stream.header, stream,
            block_payload=block_payload, level=level, deflate=spec,
        )
    out = RewriteResult(
        count=result.count, bytes_out=result.bytes_out,
        n_blocks=len(result.blocks),
    )
    if index:
        out.sidecars = emit_sidecars(out_path, result, config)
    return out


def run(
    in_path,
    out_path,
    p: Printer,
    block_payload: int = DEFAULT_BLOCK_PAYLOAD,
    reindex: bool = False,
    level: int = 6,
    deflate: "str | None" = None,
    config: Config = Config(),
) -> None:
    res = rewrite_bam(
        in_path, out_path,
        block_payload=block_payload, level=level, deflate=deflate,
        index=reindex, config=config,
    )
    p.echo(f"Wrote {res.count} reads to {out_path}")
    if reindex:
        n_records = res.count
        p.echo(f"Indexed {res.n_blocks} blocks, {n_records} records")
        if "sbi" in res.sidecars:
            p.echo(f"Split index: {res.sidecars['sbi']}")
