"""spark-bam-tpu CLI: the reference's 10 subcommands
(cli/.../bam/Main.scala:21-41), same names and comparable output formats.

    spark-bam-tpu check-bam [-s|-u] [-m SIZE] [-l LIMIT] [-o OUT] PATH
    spark-bam-tpu check-blocks ...
    spark-bam-tpu full-check ...
    spark-bam-tpu compute-splits [-s|-u] [-m SIZE] PATH
    spark-bam-tpu compare-splits [-m SIZE] BAMS-FILE
    spark-bam-tpu count-reads [-m SIZE] [-n N] [-s] PATH
    spark-bam-tpu time-load [-m SIZE] PATH
    spark-bam-tpu export [-i LOCI] [--format F] [--columns C] -o OUT PATH
        (beyond the 10: columnar analytics export, docs/analytics.md)
    spark-bam-tpu index [-m SIZE] [--record-starts] PATH   (beyond the 10:
        ahead-of-time .sbi split-index cache builder, docs/caching.md)
    spark-bam-tpu index-blocks PATH
    spark-bam-tpu index-records PATH
    spark-bam-tpu htsjdk-rewrite [--durable] [--disk-chaos SEED:SPEC] IN OUT
    spark-bam-tpu scrub [--source BAM] [--quarantine] PATHS...
        (beyond the 10: end-to-end integrity scrubber, docs/robustness.md)
"""

from __future__ import annotations

import argparse
import sys

from spark_bam_tpu.cli.output import UsageError
from spark_bam_tpu.core.config import Config, parse_bytes


def _positive_int(s: str) -> int:
    v = int(s)
    if v <= 0:
        raise argparse.ArgumentTypeError(f"must be a positive integer: {s}")
    return v


def _add_metrics(sub):
    sub.add_argument(
        "--metrics-out", default=None, metavar="PATH",
        help="enable observability for this run and write the JSONL "
             "metrics trace here on exit — a directory or a {pid} "
             "placeholder gives each fabric worker its own file "
             "(SPARK_BAM_METRICS_OUT env var works too; render with the "
             "metrics-report subcommand)",
    )
    sub.add_argument(
        "--profile", default=None, metavar="DIR",
        help="capture ONE inflate window with jax.profiler.trace into "
             "this directory (TensorBoard format; SPARK_BAM_PROFILE env "
             "var works too — fabric workers inherit it)",
    )


def _add_faults(sub):
    sub.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="fault-tolerance policy for partition execution, e.g. "
             "'retries=3,backoff=0.05,deadline=60,hedge=2,mode=tolerant' "
             "(SPARK_BAM_FAULTS env var works too; docs/robustness.md)",
    )
    sub.add_argument(
        "--chaos", default=None, metavar="SEED:SPEC",
        help="deterministic fault injection on every opened channel, e.g. "
             "'7:io=0.1,latency=0.05x10,short=0.02,corrupt=1e-6' — same "
             "seed replays the same faults (docs/robustness.md)",
    )


def _add_disk_chaos(sub):
    sub.add_argument(
        "--disk-chaos", default=None, metavar="SEED:SPEC",
        help="deterministic filesystem-fault injection on every guarded "
             "write, e.g. '7:enospc=0.02+eio=0.01+short=0.01+torn=0.01+"
             "rename=0.05' — same seed replays the same faults; fabric "
             "workers inherit it via SPARK_BAM_DISK_CHAOS "
             "(docs/robustness.md)",
    )


def _add_durable(sub):
    sub.add_argument(
        "--durable", action="store_true",
        help="run through the journaled job runner: checkpoints to a "
             "write-ahead log, a re-run after a crash resumes from the "
             "last durable checkpoint and produces a byte-identical "
             "artifact (SPARK_BAM_JOBS tunes the job dir/cadence; "
             "docs/robustness.md)",
    )
    sub.add_argument(
        "--checkpoint", type=_positive_int, default=None, metavar="N",
        help="with --durable: checkpoint cadence (records for rewrite, "
             "frames for export; default from SPARK_BAM_JOBS)",
    )
    _add_jobs(sub)


def _add_jobs(sub):
    sub.add_argument(
        "--jobs", default=None, metavar="SPEC",
        help="durable-job plane knobs, e.g. 'dir=/var/jobs,checkpoint="
             "5000,frames=8,mem=0.92,max=2' (SPARK_BAM_JOBS env var "
             "works too; docs/robustness.md)",
    )


def _add_cache(sub):
    sub.add_argument(
        "--cache", default=None, metavar="MODE",
        help="split-index (.sbi) cache mode: off|read|write|readwrite, "
             "optional ',strict' suffix raises on stale sidecars "
             "(SPARK_BAM_CACHE env var works too; docs/caching.md)",
    )


def _add_limits(sub):
    sub.add_argument(
        "--limits", default=None, metavar="SPEC",
        help="decode resource limits for untrusted input, e.g. "
             "'record=32MB,refs=1000,cigar=65536,alloc=1GB' "
             "(SPARK_BAM_LIMITS env var works too; docs/robustness.md)",
    )


def _add_remote(sub):
    sub.add_argument(
        "--remote", default=None, metavar="SPEC",
        help="remote data-plane tuning, e.g. "
             "'mode=plan,depth=8,gap=128KB,request=512KB,hedge=3,pool=64' "
             "(mode=legacy restores cursor read-ahead; depth=0 adapts; "
             "SPARK_BAM_REMOTE env var works too; docs/remote.md)",
    )


def _add_funnel(sub):
    sub.add_argument(
        "--funnel", default=None, choices=("on", "off", "auto"),
        help="two-stage checker candidate funnel: cheap prefilter over "
             "every position, deep checks on survivors only. auto "
             "(default) funnels verdict paths and keeps the exact "
             "single-pass kernel for full flag-mask output "
             "(SPARK_BAM_FUNNEL env var works too; docs/design.md)",
    )


def _add_columnar(sub):
    sub.add_argument(
        "--columnar", default=None, metavar="SPEC",
        help="columnar-plane knobs, e.g. 'rows=8192,codec=zlib,level=6,"
             "columns=flag+pos+name' (SPARK_BAM_COLUMNAR env var works "
             "too; docs/analytics.md)",
    )


def _add_slo(sub):
    sub.add_argument(
        "--slo", default=None, metavar="SPEC",
        help="SLO objectives + burn-rate alerting, e.g. "
             "'serve.latency:p99<1500ms@5m;serve.errors:ratio<0.1%%@1h;"
             "sample=0.1' (SPARK_BAM_SLO env var works too; "
             "docs/observability.md)",
    )
    sub.add_argument(
        "--dashboard", default=None, metavar="ADDR",
        help="serve the zero-dependency live dashboard on host:port — "
             "HTML sparklines at /, Prometheus text at /metrics, SLO "
             "burn rates + accounting at /slo (docs/observability.md)",
    )


def _add_deflate(sub):
    sub.add_argument(
        "--deflate", default=None, metavar="SPEC",
        help="write-path codec knobs, e.g. 'mode=fixed,lanes=16,"
             "device=auto' — stored/fixed members batch-compressed on "
             "device, host zlib when off (SPARK_BAM_DEFLATE env var "
             "works too; docs/design.md)",
    )


def _add_inflate(sub):
    sub.add_argument(
        "--inflate", default=None, metavar="SPEC",
        help="read-path inflate knobs, e.g. 'tokenize=device,kernel=auto,"
             "donate=on' (bare 'device'/'host' ok) — where the DEFLATE "
             "entropy phase runs for the two-phase device inflate "
             "(SPARK_BAM_INFLATE env var works too; docs/design.md)",
    )


def _add_common(sub, split_default=None):
    _add_metrics(sub)
    _add_faults(sub)
    _add_cache(sub)
    _add_limits(sub)
    _add_remote(sub)
    _add_funnel(sub)
    _add_inflate(sub)
    sub.add_argument("-m", "--max-split-size", default=split_default,
                     help="split size (byte shorthand like 2MB ok)")
    sub.add_argument("-l", "--print-limit", type=int, default=10)
    sub.add_argument("-o", "--out", default=None, help="write output to file")
    sub.add_argument("-w", "--warn", action="store_true", help="root log level WARN")
    sub.add_argument(
        "-i", "--intervals", default=None,
        help="comma-separated compressed byte-ranges (start-end|start+len|point,"
             " byte shorthand ok); only blocks starting inside are checked",
    )
    # Reference FindBlockArgs (-z) / FindReadArgs knobs.
    sub.add_argument("-z", "--bgzf-blocks-to-check", type=int, default=None)
    sub.add_argument("--reads-to-check", type=int, default=None)
    sub.add_argument("--max-read-size", type=int, default=None)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="spark-bam-tpu", description="TPU-native parallel BAM toolkit"
    )
    sp = ap.add_subparsers(dest="command", required=True)

    for name in ("check-bam", "check-blocks"):
        sub = sp.add_parser(name)
        _add_common(sub)
        sub.add_argument("-s", "--spark-bam", action="store_true",
                         help="score the eager checker against the .records index")
        sub.add_argument("-u", "--upstream", action="store_true",
                         help="score the seqdoop checker against the .records index")
        if name == "check-bam":
            sub.add_argument(
                "--sharded", action="store_true",
                help="mesh-scale streaming check vs .records truth across"
                     " all devices (compact summary output)",
            )
        sub.add_argument("path")

    sub = sp.add_parser("full-check")
    _add_common(sub)
    sub.add_argument(
        "--streaming", action="store_true",
        help="WGS-scale O(window)-memory scan; mask-derived sections match"
             " the default report, position lists print unannotated",
    )
    sub.add_argument(
        "--sharded", action="store_true",
        help="with --streaming: run the scan across every device on the "
             "mesh (flag totals psum'd over ICI)",
    )
    sub.add_argument("path")

    sub = sp.add_parser("compute-splits")
    _add_common(sub)
    sub.add_argument("-s", "--spark-bam", action="store_true")
    sub.add_argument("-u", "--upstream", action="store_true")
    sub.add_argument(
        "--plan-hosts", type=_positive_int, default=0, metavar="N",
        help="also print the N-host sharded-run IO plan (per-host "
             "compressed byte ranges — the preferredLocations analog)",
    )
    sub.add_argument(
        "--devices-per-host", type=_positive_int, default=8, metavar="D",
        help="devices per host for --plan-hosts (default 8)",
    )
    sub.add_argument("path")

    sub = sp.add_parser("compare-splits")
    _add_common(sub)
    sub.add_argument("bams", help="file containing one BAM path per line")

    sub = sp.add_parser("count-reads")
    _add_common(sub)
    sub.add_argument("-s", "--spark-bam-first", action="store_true")
    sub.add_argument("-n", "--num-iterations", type=int, default=1)
    sub.add_argument("-F", "--reference", default=None,
                     help="FASTA for reference-based (RR=true) CRAM decode")
    sub.add_argument(
        "--sharded", action="store_true",
        help="mesh-scale streaming count across all devices (no hadoop leg)",
    )
    sub.add_argument(
        "--resident", action="store_true",
        help="resident-scan streaming count: one device dispatch per HBM "
             "chunk (amortizes dispatch latency on remote devices)",
    )
    sub.add_argument("path")

    sub = sp.add_parser("time-load")
    _add_common(sub)
    sub.add_argument("path")

    # Columnar analytics export: record batches to a native container /
    # Arrow IPC / Parquet file (docs/analytics.md).
    sub = sp.add_parser("export")
    _add_metrics(sub)
    _add_faults(sub)
    _add_disk_chaos(sub)
    _add_durable(sub)
    _add_cache(sub)
    _add_limits(sub)
    _add_remote(sub)
    _add_columnar(sub)
    sub.add_argument("-m", "--max-split-size", default=None,
                     help="split size (byte shorthand like 2MB ok)")
    sub.add_argument(
        "-i", "--intervals", default=None, metavar="LOCI",
        help="genomic loci to restrict to, e.g. 'chr1:5k-10k,chr2' "
             "(decimal k/m suffixes; whole contig when no range)",
    )
    sub.add_argument(
        "--format", default="native", choices=("native", "arrow", "parquet"),
        help="output format (arrow/parquet need the pyarrow extra; "
             "default native)",
    )
    sub.add_argument(
        "--columns", default=None, metavar="COLS",
        help="comma-separated column projection (default: all columns)",
    )
    sub.add_argument("-F", "--reference", default=None,
                     help="FASTA for reference-based (RR=true) CRAM decode")
    sub.add_argument("-w", "--warn", action="store_true",
                     help="root log level WARN")
    sub.add_argument("-o", "--out", dest="export_out", required=True,
                     help="output file path")
    sub.add_argument("path")

    # On-device aggregation: reduce a query to kilobytes of statistics
    # without materializing records (docs/analytics.md "Aggregation").
    sub = sp.add_parser("aggregate")
    _add_metrics(sub)
    _add_faults(sub)
    _add_cache(sub)
    _add_limits(sub)
    _add_remote(sub)
    sub.add_argument("-m", "--max-split-size", default=None,
                     help="split size (byte shorthand like 2MB ok)")
    sub.add_argument(
        "-a", "--agg", default=None, metavar="SPEC",
        help="';'-separated metric[:k=v,...] spec — count, flagstat, "
             "mapq, tlen[:max=N], coverage[:bin=N,bins=N,cap=N] "
             "(default: every metric at defaults, or SPARK_BAM_AGG)",
    )
    sub.add_argument(
        "-i", "--intervals", default=None, metavar="LOCI",
        help="genomic loci to restrict to, e.g. 'chr1:5k-10k,chr2' "
             "(decimal k/m suffixes; whole contig when no range)",
    )
    sub.add_argument("--flags-required", type=int, default=0,
                     help="only records with ALL these SAM flag bits")
    sub.add_argument("--flags-forbidden", type=int, default=0,
                     help="only records with NONE of these SAM flag bits")
    sub.add_argument(
        "-t", "--tag", action="append", default=None, metavar="TG",
        help="only records carrying this two-char tag (repeatable; "
             "all must be present)",
    )
    sub.add_argument("--format", default="tsv", choices=("tsv", "json"),
                     help="report format (default tsv)")
    sub.add_argument("-F", "--reference", default=None,
                     help="FASTA for reference-based (RR=true) CRAM decode")
    sub.add_argument("-w", "--warn", action="store_true",
                     help="root log level WARN")
    sub.add_argument("-o", "--out", default=None,
                     help="write the report here instead of stdout")
    sub.add_argument("path")

    sub = sp.add_parser("index-blocks")
    _add_metrics(sub)
    sub.add_argument("-o", "--out", default=None)
    sub.add_argument("path")

    # Ahead-of-time .sbi builder: warm the split-index cache so the first
    # load is already served from the sidecar (docs/caching.md).
    sub = sp.add_parser("index")
    _add_metrics(sub)
    _add_faults(sub)
    sub.add_argument("-m", "--max-split-size", default=None,
                     help="split size to plan for (byte shorthand like 2MB ok)")
    sub.add_argument("-o", "--out", default=None,
                     help="write the .sbi here instead of the resolved "
                          "cache location")
    sub.add_argument("-w", "--warn", action="store_true",
                     help="root log level WARN")
    sub.add_argument(
        "--record-starts", action="store_true",
        help="also index every record-start virtual position (runs the "
             "vectorized checker once over the file)",
    )
    sub.add_argument("-z", "--bgzf-blocks-to-check", type=int, default=None)
    sub.add_argument("--reads-to-check", type=int, default=None)
    sub.add_argument("--max-read-size", type=int, default=None)
    sub.add_argument("path")

    sub = sp.add_parser("index-records")
    _add_metrics(sub)
    sub.add_argument("-o", "--out", default=None)
    sub.add_argument("-t", "--throw-on-truncation", action="store_true")
    sub.add_argument("path")

    # Beyond the reference's 10 commands: the samtools-index role for the
    # built-in .bai writer (the reference consumes .bai but can't produce
    # one; ours can, so indexed interval loads work on any sorted BAM).
    sub = sp.add_parser("index-bam")
    _add_metrics(sub)
    sub.add_argument("-o", "--out", default=None)
    sub.add_argument("path")

    sub = sp.add_parser("htsjdk-rewrite", aliases=["rewrite"])
    _add_metrics(sub)
    _add_cache(sub)
    _add_deflate(sub)
    _add_disk_chaos(sub)
    _add_durable(sub)
    sub.add_argument("-o", "--out", default=None, help="write output to file")
    sub.add_argument("-b", "--block-payload", default="65280")
    sub.add_argument("--level", type=int, default=6,
                     help="zlib level for the host codec path (default 6)")
    sub.add_argument("-i", "--index", action="store_true",
                     help="also write .blocks/.records/.sbi sidecars for "
                          "the output, built from the packing metadata "
                          "(no re-read)")
    sub.add_argument("in_path")
    sub.add_argument("out_path")

    # Structure-aware mutation fuzzing of the decode boundary
    # (tools/fuzz_decode.py; docs/robustness.md "Malformed inputs").
    sub = sp.add_parser("fuzz-decode")
    _add_limits(sub)
    sub.add_argument("--seed", type=int, default=0,
                     help="base seed; the same seed replays the same mutants")
    sub.add_argument("--mutants", type=int, default=200,
                     help="mutants per corpus format (default 200)")
    sub.add_argument(
        "--formats", default="bam,bgzf,cram,sbi",
        help="comma-separated corpus formats to fuzz (default all)",
    )
    sub.add_argument("-o", "--out", default=None,
                     help="write the JSON summary here instead of stdout")

    # End-to-end integrity scrubber over rewritten artifacts: BGZF frame
    # CRCs, sidecar cross-checks, native-container validation, spot
    # record-parity against the source (docs/robustness.md).
    sub = sp.add_parser("scrub")
    _add_metrics(sub)
    _add_limits(sub)
    sub.add_argument(
        "--source", default=None, metavar="BAM",
        help="original BAM the artifacts were rewritten from — enables "
             "spot record-parity (every --stride'th record compared "
             "byte-for-byte)",
    )
    sub.add_argument(
        "--quarantine", action="store_true",
        help="rename artifacts with findings to <path>.quarantined so "
             "downstream pipelines cannot consume them",
    )
    sub.add_argument(
        "--stride", type=_positive_int, default=16, metavar="N",
        help="record-parity sampling stride (default 16; 1 = compare "
             "every record)",
    )
    sub.add_argument("-o", "--out", default=None,
                     help="write the JSON report here instead of stdout")
    sub.add_argument("-w", "--warn", action="store_true",
                     help="root log level WARN")
    sub.add_argument(
        "paths", nargs="+",
        help="artifacts to scrub (BAM pulls its .blocks/.records/.sbi "
             "sidecars in automatically; native containers stand alone)",
    )

    # Long-running split/record daemon over the device mesh: warm steps,
    # warm flat views, warm .sbi tier; newline-JSON protocol
    # (docs/serving.md).
    sub = sp.add_parser("serve")
    _add_metrics(sub)
    _add_faults(sub)
    _add_disk_chaos(sub)
    _add_cache(sub)
    _add_limits(sub)
    _add_remote(sub)
    _add_funnel(sub)
    _add_columnar(sub)
    _add_deflate(sub)
    _add_slo(sub)
    _add_jobs(sub)
    sub.add_argument(
        "--serve", default=None, metavar="SPEC",
        help="serving knobs, e.g. 'batch=16,tick=2,plan_queue=64,"
             "scan_queue=128,workers=2,window=1MB,halo=64KB,cache=256MB' "
             "(SPARK_BAM_SERVE env var works too; docs/serving.md)",
    )
    sub.add_argument(
        "--listen", default="tcp:127.0.0.1:8765", metavar="ADDR",
        help="unix:<path> or tcp:<host>:<port> (default tcp:127.0.0.1:8765)",
    )
    sub.add_argument("--reads-to-check", type=int, default=None)
    sub.add_argument("-w", "--warn", action="store_true",
                     help="root log level WARN")

    # Serve fabric control plane: launch (or attach to) N serve workers
    # and front them with the affinity router + health prober + SLO
    # autoscaler (docs/fabric.md). Same wire protocol as `serve`.
    sub = sp.add_parser("fabric")
    _add_metrics(sub)
    _add_faults(sub)
    _add_disk_chaos(sub)
    _add_slo(sub)
    sub.add_argument(
        "--fabric", default=None, metavar="SPEC",
        help="fabric knobs, e.g. 'workers=3,slo=200,probe=500,spill=8,"
             "batch_ceil=32' (SPARK_BAM_FABRIC env var works too; "
             "docs/fabric.md). Resilience: budget/budget_rate, flap_k/"
             "flap_window/holddown, brownout[_frac], stream=1 for "
             "resumable streaming relay. Seeded fleet chaos: "
             "'chaos=SEED:drop=0.05+trunc=0.02+delay=0.1x20' "
             "(docs/robustness.md)",
    )
    sub.add_argument(
        "--serve", default=None, metavar="SPEC",
        help="per-worker serving knobs, forwarded to every launched "
             "worker (docs/serving.md)",
    )
    sub.add_argument(
        "--listen", default="tcp:127.0.0.1:8765", metavar="ADDR",
        help="router address: unix:<path> or tcp:<host>:<port> "
             "(default tcp:127.0.0.1:8765)",
    )
    sub.add_argument(
        "--attach", action="append", default=None, metavar="ADDR",
        help="attach to an already-running worker instead of launching "
             "(repeatable — point one at every host's `multihost --serve` "
             "address for the multi-host fabric)",
    )
    sub.add_argument(
        "--worker-devices", type=int, default=0, metavar="N",
        help="virtual CPU devices per LAUNCHED worker (dev boxes; "
             "0 = each worker's real local devices)",
    )
    sub.add_argument("-w", "--warn", action="store_true",
                     help="root log level WARN")

    # Render --metrics-out JSONL trace(s) as the reference stats format.
    # Several files (e.g. a fabric run's per-worker trace directory) are
    # merged by trace_id into one cross-process report.
    sub = sp.add_parser("metrics-report")
    sub.add_argument("-o", "--out", default=None, help="write output to file")
    sub.add_argument("-l", "--print-limit", type=int, default=10)
    sub.add_argument(
        "trace", nargs="+",
        help="JSONL trace(s) --metrics-out runs wrote; pass every "
             "per-process file of one fleet run to merge spans by "
             "trace_id",
    )

    # One-shot fleet telemetry view: per-worker health, queue depth,
    # per-op p50/p99, host/H2D/device ms split (docs/observability.md).
    sub = sp.add_parser("top")
    sub.add_argument("-o", "--out", default=None, help="write output to file")
    sub.add_argument(
        "--prometheus", action="store_true",
        help="print the (fleet-merged) Prometheus exposition text "
             "instead of the human view",
    )
    sub.add_argument(
        "--watch", action="store_true",
        help="live mode: clear and re-render every --interval seconds "
             "(Ctrl-C to stop)",
    )
    sub.add_argument(
        "--interval", type=float, default=2.0, metavar="S",
        help="--watch refresh cadence in seconds (default 2)",
    )
    sub.add_argument(
        "address",
        help="serve worker or fabric router address "
             "(tcp:host:port or unix:path)",
    )

    # Project-native static analysis: AST rules guarding the jit,
    # asyncio, and untrusted-byte seams (docs/static-analysis.md).
    sub = sp.add_parser("lint")
    sub.add_argument("-o", "--out", default=None, help="write output to file")
    sub.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the installed "
             "spark_bam_tpu package)",
    )
    sub.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all registered)",
    )
    sub.add_argument(
        "--baseline", default=None,
        help="baseline suppression file (default: lint-baseline.json "
             "next to the package; missing file = empty baseline)",
    )
    sub.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline file — report every finding",
    )
    sub.add_argument(
        "--json", dest="json_out", default=None, metavar="PATH",
        help="also write the full findings report as JSON (the CI "
             "artifact format)",
    )
    sub.add_argument(
        "--write-baseline", default=None, metavar="REASON",
        help="write the current live findings to the baseline file with "
             "REASON as the justification stub, then exit 0 (edit "
             "per-entry justifications before committing)",
    )
    sub.add_argument(
        "-v", "--verbose", action="store_true",
        help="also list suppressed findings with their justifications",
    )

    return ap


def _service_dashboard(service, listen: str):
    """Start a :class:`~spark_bam_tpu.obs.dashboard.DashboardServer`
    reading one worker's local registry/engine/accountant."""
    from spark_bam_tpu import obs
    from spark_bam_tpu.obs import flight
    from spark_bam_tpu.obs.dashboard import DashboardServer

    def provider():
        reg = obs.registry()
        return {
            "snapshot": reg.snapshot() if reg is not None else {},
            "series": service.rings.snapshot() if service.rings else None,
            "slo": (service.slo_engine.status()
                    if service.slo_engine is not None
                    else {"enabled": False, "objectives": []}),
            "accounting": service.accountant.snapshot(),
            "flight": flight.recorder().events(),
        }

    return DashboardServer(listen, provider).start()


def _router_dashboard(router, listen: str):
    """Start a dashboard over a fabric router: each request crosses into
    the router's event loop (``run_coroutine_threadsafe``) and reads the
    same ``telemetry``/``alerts`` fan-outs clients get. Before the loop
    runs (no request yet), render the router-local flight ring only."""
    import asyncio

    from spark_bam_tpu.obs import flight
    from spark_bam_tpu.obs.dashboard import DashboardServer

    def provider():
        loop = router._loop
        if loop is None or not loop.is_running():
            return {"snapshot": {}, "flight": flight.recorder().events()}
        tel = asyncio.run_coroutine_threadsafe(
            router.submit({"op": "telemetry"}), loop
        ).result(timeout=10)
        al = asyncio.run_coroutine_threadsafe(
            router.submit({"op": "alerts"}), loop
        ).result(timeout=10)
        # Fleet SLO view: per objective, the worst worker's status.
        objs: dict = {}
        for r in (al.get("workers") or {}).values():
            for st in (r.get("slo") or {}).get("objectives", ()):
                cur = objs.get(st.get("objective"))
                if cur is None or (st.get("burn_fast") or 0) > (
                        cur.get("burn_fast") or 0):
                    objs[st.get("objective")] = st
        return {
            "snapshot": tel.get("fleet") or {},
            "series": tel.get("series"),
            "slo": {
                "enabled": bool(objs),
                "objectives": sorted(
                    objs.values(), key=lambda s: s.get("objective") or ""
                ),
                "firing": al.get("firing") or [],
                "ledger": al.get("ledger") or [],
                "moves": al.get("moves") or [],
            },
            "accounting": tel.get("accounting"),
            "flight": tel.get("flight"),
        }

    return DashboardServer(listen, provider).start()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import logging
    import os

    # --warn: root log level to WARN (reference args/LogArgs.scala:30-33).
    logging.basicConfig(
        level=logging.WARNING if getattr(args, "warn", False) else logging.INFO
    )
    from spark_bam_tpu import obs
    from spark_bam_tpu.cli.output import Printer

    # Known-benign backend banners (xla_bridge's "Platform ... is
    # experimental") stay out of every subcommand's stderr; real
    # warnings still pass (obs/noise.py).
    obs.install_noise_filter()
    out = open(args.out, "w") if getattr(args, "out", None) else None
    p = Printer(out=out, limit=getattr(args, "print_limit", 10))
    config = Config.from_env()
    split = getattr(args, "max_split_size", None)
    if split is not None:
        config = config.replace(split_size=parse_bytes(split))
    for knob in ("bgzf_blocks_to_check", "reads_to_check", "max_read_size"):
        value = getattr(args, knob, None)
        if value is not None:
            config = config.replace(**{knob: value})

    from spark_bam_tpu.core.faults import (
        FaultPolicy, install_chaos, install_disk_chaos, uninstall_chaos,
        uninstall_disk_chaos,
    )
    from spark_bam_tpu.parallel.executor import last_report, reset_last_report

    chaos_state = None
    disk_state = None
    try:
        if getattr(args, "faults", None):
            FaultPolicy.parse(args.faults)  # fail before any work starts
            config = config.replace(faults=args.faults)
        if getattr(args, "cache", None) is not None:
            from spark_bam_tpu.sbi.store import CacheMode

            CacheMode.parse(args.cache)  # fail before any work starts
            config = config.replace(cache=args.cache)
        if getattr(args, "limits", None) is not None:
            from spark_bam_tpu.core.guard import DecodeLimits, set_limits

            # Fail before any work starts, then install process-wide so
            # every parser this invocation touches decodes under them.
            set_limits(DecodeLimits.parse(args.limits))
            config = config.replace(limits=args.limits)
        if getattr(args, "remote", None) is not None:
            from spark_bam_tpu.core.remote_plan import (
                RemoteConfig, set_remote_config,
            )

            # Fail before any work starts, then install process-wide so
            # every channel this invocation opens rides the tuned plane.
            set_remote_config(RemoteConfig.parse(args.remote))
            config = config.replace(remote=args.remote)
        if getattr(args, "funnel", None) is not None:
            config = config.replace(funnel=args.funnel)
        config.funnel_enabled()  # fail early on a bad SPARK_BAM_FUNNEL
        if getattr(args, "columnar", None) is not None:
            from spark_bam_tpu.columnar import ColumnarConfig

            ColumnarConfig.parse(args.columnar)  # fail before any work starts
            config = config.replace(columnar=args.columnar)
        if getattr(args, "deflate", None) is not None:
            from spark_bam_tpu.compress.config import DeflateConfig

            DeflateConfig.parse(args.deflate)  # fail before any work starts
            config = config.replace(deflate=args.deflate)
        if getattr(args, "inflate", None) is not None:
            from spark_bam_tpu.core.inflate_config import InflateConfig

            InflateConfig.parse(args.inflate)  # fail before any work starts
            config = config.replace(inflate=args.inflate)
        if getattr(args, "serve", None) is not None:
            from spark_bam_tpu.serve import ServeConfig

            ServeConfig.parse(args.serve)  # fail before any work starts
            config = config.replace(serve=args.serve)
        if getattr(args, "fabric", None) is not None:
            from spark_bam_tpu.fabric import FabricConfig

            FabricConfig.parse(args.fabric)  # fail before any work starts
            config = config.replace(fabric=args.fabric)
        if getattr(args, "slo", None) is not None:
            from spark_bam_tpu.obs.slo import SloConfig

            SloConfig.parse(args.slo)  # fail before any work starts
            config = config.replace(slo=args.slo)
        if getattr(args, "jobs", None) is not None:
            from spark_bam_tpu.jobs.manager import JobsConfig

            JobsConfig.parse(args.jobs)  # fail before any work starts
            config = config.replace(jobs=args.jobs)
        if getattr(args, "dashboard", None):
            from spark_bam_tpu.obs.dashboard import parse_listen

            parse_listen(args.dashboard)  # fail before any work starts
        if getattr(args, "listen", None) is not None:
            from spark_bam_tpu.serve import ServeAddress

            ServeAddress(args.listen)  # fail before any work starts
        if getattr(args, "chaos", None):
            chaos_state = install_chaos(args.chaos)
        if getattr(args, "disk_chaos", None):
            # In-process seam for rewrite/export/serve; the fabric branch
            # additionally exports SPARK_BAM_DISK_CHAOS so every launched
            # worker installs the same seeded schedule.
            disk_state = install_disk_chaos(args.disk_chaos)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        if chaos_state is not None:
            uninstall_chaos()
        return 2
    reset_last_report()
    # Cache-status events are per-run (module-global): clear leftovers so
    # the status line describes THIS invocation only.
    from spark_bam_tpu.sbi.store import reset_cache_events

    reset_cache_events()

    # --metrics-out (or the env var) turns the process-wide registry on
    # for this run; everything below the root ``cli.<command>`` span
    # records into it and the trace is written on the way out.
    metrics_out = (
        getattr(args, "metrics_out", None)
        or os.environ.get("SPARK_BAM_METRICS_OUT")
    )
    if metrics_out:
        obs.configure()
        metrics_out = obs.resolve_metrics_path(metrics_out)
    elif config.slo or getattr(args, "dashboard", None):
        # The SLO engine evaluates against the live registry's ring and
        # the dashboard scrapes it — both need metrics on even without a
        # trace file to write.
        obs.configure()
    # --profile rides the env var so the inflate pipeline (and any
    # fabric worker subprocess inheriting the environment) sees it.
    profile_set = getattr(args, "profile", None)
    if profile_set:
        os.environ["SPARK_BAM_PROFILE"] = profile_set
    cmd = args.command
    # lint: allow[obs-contract] cmd bounded by the subparser set; every
    # cli.<subcommand> span is enumerated in obs/names.py
    root_span = obs.span(f"cli.{cmd}")
    root_span.__enter__()
    try:
        if cmd in ("check-bam", "check-blocks", "full-check", "compute-splits",
                   "time-load"):
            from spark_bam_tpu.cli.app import CheckerContext
            from spark_bam_tpu.core.ranges import parse_ranges

            ctx = CheckerContext(
                args.path, config, p,
                ranges=parse_ranges(getattr(args, "intervals", None)),
            )
            if cmd == "check-bam":
                from spark_bam_tpu.cli import check_bam

                check_bam.run(
                    ctx, args.spark_bam, args.upstream, sharded=args.sharded
                )
            elif cmd == "check-blocks":
                from spark_bam_tpu.cli import check_blocks

                check_blocks.run(ctx, args.spark_bam, args.upstream)
            elif cmd == "full-check":
                from spark_bam_tpu.cli import full_check

                if args.sharded and not args.streaming:
                    raise UsageError(
                        "full-check --sharded requires --streaming (the "
                        "in-memory report has no mesh mode)"
                    )
                if args.streaming:
                    full_check.run_streaming(ctx, sharded=args.sharded)
                else:
                    full_check.run(ctx)
            elif cmd == "compute-splits":
                from spark_bam_tpu.cli import compute_splits

                compute_splits.run(
                    ctx,
                    config.split_size_or(Config.LOAD_SPLIT_SIZE_DEFAULT),
                    args.spark_bam,
                    args.upstream,
                )
                if args.plan_hosts:
                    compute_splits.print_host_plan(
                        ctx, args.plan_hosts, args.devices_per_host
                    )
            elif cmd == "time-load":
                from spark_bam_tpu.cli import time_load

                time_load.run(ctx, config.split_size_or(Config.LOAD_SPLIT_SIZE_DEFAULT))
        elif cmd == "compare-splits":
            from spark_bam_tpu.cli import compare_splits

            compare_splits.run(
                args.bams, p, config.split_size_or(Config.LOAD_SPLIT_SIZE_DEFAULT),
                config,
            )
        elif cmd == "count-reads":
            from spark_bam_tpu.cli import count_reads

            count_reads.run(
                args.path, p, config.split_size_or(Config.LOAD_SPLIT_SIZE_DEFAULT),
                config, args.spark_bam_first, args.num_iterations,
                reference=args.reference, sharded=args.sharded,
                resident=args.resident,
            )
        elif cmd == "export":
            from spark_bam_tpu.cli import export as export_cmd
            from spark_bam_tpu.load.intervals import BadLociError, LociSet

            loci = getattr(args, "intervals", None)
            if loci:
                try:
                    LociSet.parse(loci)  # fail before any work starts
                except BadLociError as e:
                    raise UsageError(str(e)) from e
            if args.columns:
                from spark_bam_tpu.columnar import normalize_columns

                try:
                    normalize_columns(args.columns)
                except ValueError as e:
                    raise UsageError(str(e)) from e
            if args.durable:
                # Journaled export: checkpoints at container-frame
                # boundaries, crash-resumable (docs/robustness.md). The
                # runner streams whole-file native frames, so the knobs
                # that change the frame list are out of scope here.
                if args.format != "native":
                    raise UsageError(
                        "--durable export supports --format native only"
                    )
                if loci or args.reference:
                    raise UsageError(
                        "--durable export does not take -i/--reference"
                    )
                import json as _json

                from spark_bam_tpu.jobs.manager import job_id_of
                from spark_bam_tpu.jobs.runner import run_export_job

                spec = {"op": "export", "path": args.path,
                        "out": args.export_out, "columns": args.columns}
                spec = {k: v for k, v in spec.items() if v is not None}
                jcfg = config.jobs_config
                res = run_export_job(
                    spec, os.path.join(jcfg.root(), job_id_of(spec)),
                    config=config,
                    checkpoint=args.checkpoint or jcfg.frames,
                )
                p.echo(_json.dumps(res, indent=2, sort_keys=True))
            else:
                export_cmd.run(
                    args.path, p, config, args.export_out, fmt=args.format,
                    loci=loci, columns=args.columns, reference=args.reference,
                )
        elif cmd == "aggregate":
            from spark_bam_tpu.agg.plan import AggConfig
            from spark_bam_tpu.cli import aggregate as aggregate_cmd
            from spark_bam_tpu.load.intervals import BadLociError, LociSet

            loci = getattr(args, "intervals", None)
            if loci:
                try:
                    LociSet.parse(loci)  # fail before any work starts
                except BadLociError as e:
                    raise UsageError(str(e)) from e
            try:
                AggConfig.parse(args.agg or config.agg)
                for t in args.tag or ():
                    if len(t) != 2:
                        raise ValueError(
                            f"tag names are exactly two chars: {t!r}"
                        )
            except ValueError as e:
                raise UsageError(str(e)) from e
            aggregate_cmd.run(
                args.path, p, config, agg=args.agg, loci=loci,
                flags_required=args.flags_required,
                flags_forbidden=args.flags_forbidden,
                tags_required=tuple(args.tag or ()),
                fmt=args.format, reference=args.reference,
            )
        elif cmd == "index-blocks":
            from spark_bam_tpu.bgzf.index_blocks import index_blocks

            out_path, count = index_blocks(args.path, args.out)
            print(f"Wrote {count} blocks to {out_path}", file=sys.stderr)
        elif cmd == "index":
            from spark_bam_tpu.cli import index_sbi

            index_sbi.run(
                args.path, p,
                config.split_size_or(Config.LOAD_SPLIT_SIZE_DEFAULT),
                config, out=args.out, record_starts=args.record_starts,
            )
        elif cmd == "index-records":
            from spark_bam_tpu.bam.index_records import index_records

            out_path, count = index_records(
                args.path, args.out, strict=args.throw_on_truncation
            )
            print(f"Wrote {count} records to {out_path}", file=sys.stderr)
        elif cmd == "index-bam":
            from spark_bam_tpu.bam.bai import index_bam

            out_path, idx = index_bam(args.path, args.out)
            n_chunks = sum(
                len(cs) for ref in idx.references for cs in ref.bins.values()
            )
            print(
                f"Wrote {out_path}: {len(idx.references)} references, "
                f"{n_chunks} chunks, {idx.n_no_coor} unplaced reads",
                file=sys.stderr,
            )
        elif cmd in ("htsjdk-rewrite", "rewrite"):
            if args.durable:
                # Journaled rewrite: the WAL + segment files live under
                # the job dir keyed by the spec hash, so re-running the
                # same command after a crash resumes from the last
                # checkpoint and emits a byte-identical artifact.
                import json as _json

                from spark_bam_tpu.jobs.manager import job_id_of
                from spark_bam_tpu.jobs.runner import run_rewrite_job

                spec = {"op": "rewrite", "path": args.in_path,
                        "out": args.out_path,
                        "block_payload": parse_bytes(args.block_payload),
                        "level": args.level,
                        "index": True if args.index else None}
                spec = {k: v for k, v in spec.items() if v is not None}
                jcfg = config.jobs_config
                res = run_rewrite_job(
                    spec, os.path.join(jcfg.root(), job_id_of(spec)),
                    config=config,
                    checkpoint=args.checkpoint or jcfg.checkpoint,
                )
                p.echo(_json.dumps(res, indent=2, sort_keys=True))
            else:
                from spark_bam_tpu.cli import rewrite

                rewrite.run(
                    args.in_path, args.out_path, p,
                    block_payload=parse_bytes(args.block_payload),
                    reindex=args.index,
                    level=args.level,
                    deflate=config.deflate,
                    config=config,
                )
        elif cmd == "fuzz-decode":
            from spark_bam_tpu.tools.fuzz_decode import run_fuzz

            summary = run_fuzz(
                seed=args.seed,
                mutants_per_format=args.mutants,
                formats=tuple(
                    f for f in args.formats.split(",") if f.strip()
                ),
            )
            import json

            p.echo(json.dumps(summary, indent=2, sort_keys=True))
            if summary["violations"]:
                return 1
        elif cmd == "scrub":
            from spark_bam_tpu.cli import scrub as scrub_cmd

            rc = scrub_cmd.run(
                args.paths, p, source=args.source,
                quarantine=args.quarantine, stride=args.stride,
            )
            if rc:
                return rc
        elif cmd == "serve":
            from spark_bam_tpu.serve import ServeAddress, SplitService, serve_forever

            service = SplitService(config)
            addr = ServeAddress(args.listen)
            where = addr.path if addr.kind == "unix" else f"{addr.host}:{addr.port}"
            print(
                f"serving on {args.listen} ({where}; "
                f"{service.mesh.devices.size} devices) — Ctrl-C to stop",
                file=sys.stderr,
            )
            dash = None
            if args.dashboard:
                dash = _service_dashboard(service, args.dashboard)
                print(f"dashboard on http://{dash.address}/ "
                      "(/metrics, /slo, /series)", file=sys.stderr)
            try:
                serve_forever(service, args.listen)
            except KeyboardInterrupt:
                pass
            finally:
                if dash is not None:
                    dash.stop()
                service.close()
        elif cmd == "fabric":
            import os
            import signal as _signal

            from spark_bam_tpu.fabric import Router, WorkerPool
            from spark_bam_tpu.obs import flight
            from spark_bam_tpu.serve import serve_forever

            fcfg = config.fabric_config
            # Workers inherit the fabric spec via env so a chaos run's
            # seed lands in THEIR flight dumps too (fabric/worker.py).
            worker_env = None
            if config.fabric or getattr(args, "disk_chaos", None):
                worker_env = dict(os.environ)
                if config.fabric:
                    worker_env["SPARK_BAM_FABRIC"] = config.fabric
                if getattr(args, "disk_chaos", None):
                    # Disk faults ride the env into every launched
                    # worker (fabric/worker.py installs from it).
                    worker_env["SPARK_BAM_DISK_CHAOS"] = args.disk_chaos
            pool = WorkerPool(
                workers=fcfg.workers, devices=args.worker_devices,
                serve=config.serve, columnar=config.columnar,
                slo=config.slo, attach=args.attach, env=worker_env,
            )
            addresses = pool.start()
            router = Router(addresses, config=config, pool=pool)

            def _graceful(signum, frame):
                # Drain: stop routing new work; workers get SIGTERM in
                # the finally and finish their in-flight ticks unshed.
                flight.record("sigterm", signum=int(signum), who="router")
                router.draining = True
                raise KeyboardInterrupt

            # Handler installed BEFORE the announce: a supervisor that
            # SIGTERMs on seeing the line must still get a clean drain.
            _signal.signal(_signal.SIGTERM, _graceful)
            dash = None
            try:
                chaos_note = (
                    f" [chaos {router.chaos.describe()}]"
                    if router.chaos is not None else ""
                )
                print(
                    f"fabric: routing on {args.listen} over "
                    f"{len(addresses)} workers "
                    f"({'attached' if args.attach else 'launched'}: "
                    f"{', '.join(addresses)}){chaos_note} — Ctrl-C to stop",
                    file=sys.stderr,
                )
                if args.dashboard:
                    dash = _router_dashboard(router, args.dashboard)
                    print(f"dashboard on http://{dash.address}/ "
                          "(/metrics, /slo, /series)", file=sys.stderr)
                serve_forever(router, args.listen)
            except KeyboardInterrupt:
                pass
            except BaseException as exc:
                # The router's own postmortem (satellite of the worker
                # dumps from PR 11): narrate the crash before unwinding —
                # a dead router otherwise leaves no artifact naming what
                # was in flight at the fleet edge.
                flight.dump_auto("crash", who="router",
                                 extra={"error": repr(exc),
                                        "workers": addresses})
                raise
            finally:
                if dash is not None:
                    dash.stop()
                pool.terminate()
                # Graceful-path artifact: the drain dump records the
                # router's routing counters + move ledger tail.
                flight.dump_auto(
                    "drain", who="router",
                    extra={"counters": dict(router.counters),
                           "moves": list(router.moves)[-32:]},
                )
        elif cmd == "metrics-report":
            from spark_bam_tpu.cli import metrics_report

            metrics_report.run(args.trace, p)
        elif cmd == "top":
            from spark_bam_tpu.cli import top

            top.run(args.address, p, prometheus=args.prometheus,
                    watch=args.watch, interval_s=args.interval)
        elif cmd == "lint":
            import spark_bam_tpu as _pkg
            from spark_bam_tpu.analysis import Baseline, render_report, run_lint
            from spark_bam_tpu.analysis.runner import write_json

            pkg_dir = os.path.dirname(os.path.abspath(_pkg.__file__))
            baseline_path = args.baseline or os.path.join(
                os.path.dirname(pkg_dir), "lint-baseline.json"
            )
            rule_ids = ([r.strip() for r in args.rules.split(",") if r.strip()]
                        if args.rules else None)
            try:
                if args.write_baseline is not None:
                    rep = run_lint(paths=args.paths or None,
                                   rule_ids=rule_ids)
                    n = Baseline.write(baseline_path, rep.findings,
                                       args.write_baseline)
                    p.echo(f"wrote {n} entries to {baseline_path} — edit "
                           "per-entry justifications before committing")
                    return 0
                rep = run_lint(
                    paths=args.paths or None, rule_ids=rule_ids,
                    baseline=None if args.no_baseline else baseline_path,
                )
            except ValueError as e:
                raise UsageError(str(e)) from e
            if args.json_out:
                write_json(rep, args.json_out)
            p.echo(render_report(rep, verbose=args.verbose))
            return 0 if rep.ok else 1
        # Fault-tolerance postscript: whenever partition execution had to
        # retry/hedge/quarantine, say so (the quarantine list is the
        # operator's cue that the output is a degraded-but-complete run).
        rep = last_report()
        if rep is not None and (rep.retries or rep.hedges or rep.quarantined
                                or rep.lost_records or rep.lost_blocks):
            p.echo(rep.summary())
        if chaos_state is not None:
            injected = ", ".join(
                f"{k}={v}" for k, v in chaos_state.injected.items() if v
            )
            p.echo(f"chaos(seed={chaos_state.seed}): injected "
                   f"{injected or 'nothing'}")
        if disk_state is not None:
            injected = ", ".join(
                f"{k}={v}" for k, v in disk_state.injected.items() if v
            )
            p.echo(f"disk-chaos(seed={disk_state.seed}): injected "
                   f"{injected or 'nothing'}")
        return 0
    except UsageError as e:
        # Flag-combination errors (e.g. --sharded with -u or CRAM) present
        # as one-line usage errors; library failures keep their tracebacks.
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        if profile_set:
            os.environ.pop("SPARK_BAM_PROFILE", None)
        if chaos_state is not None:
            uninstall_chaos()
        if disk_state is not None:
            uninstall_disk_chaos()
        if getattr(args, "remote", None) is not None:
            from spark_bam_tpu.core.remote_plan import set_remote_config

            set_remote_config(None)  # in-process callers (tests) reset clean
        root_span.__exit__(None, None, None)
        if metrics_out:
            # Export after the root span closes so it lands in the trace;
            # shutdown so in-process callers (tests) start the next run
            # from a clean disabled state.
            obs.export_jsonl(metrics_out)
            obs.shutdown()
        if out:
            out.close()


if __name__ == "__main__":
    sys.exit(main())
