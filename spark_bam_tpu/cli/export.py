"""``spark-bam-tpu export``: columnar analytics export (docs/analytics.md).

    spark-bam-tpu export [-i LOCI] [--format native|arrow|parquet]
                         [--columns flag,pos,...] [--columnar SPEC]
                         [-F FASTA] -o OUT PATH

One line of summary per run: rows, batches, bytes, wall time, and the
fault-tolerance postscript when partitions retried or were quarantined.
"""

from __future__ import annotations

from spark_bam_tpu.core.config import Config, format_bytes


def run(
    path,
    p,
    config: Config,
    out: str,
    fmt: str = "native",
    loci=None,
    columns=None,
    reference=None,
) -> None:
    from spark_bam_tpu.load.api import export

    summary = export(
        path, out, loci=loci, fmt=fmt, columns=columns, config=config,
        reference=reference,
    )
    cols = ",".join(summary["columns"])
    p.echo(
        f"exported {summary['rows']} rows in {summary['batches']} batches "
        f"({format_bytes(summary['bytes'])}, {summary['format']}) to "
        f"{summary['path']} in {summary['seconds']:.2f}s [{cols}]"
    )
    if summary["lost_records"] or summary["quarantined"]:
        p.echo(
            f"\tdegraded: {summary['lost_records']} records lost, "
            f"{summary['quarantined']} partitions quarantined"
        )
