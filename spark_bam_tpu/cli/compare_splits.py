"""compare-splits: split-computation comparison across many BAMs (one task
per BAM; reference cli/.../spark/compare/CompareSplits.scala:15-166)."""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from spark_bam_tpu.cli.app import CheckerContext
from spark_bam_tpu.cli.output import Printer
from spark_bam_tpu.cli.splits_util import diff_splits, spark_bam_splits
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.stats import Stats
from spark_bam_tpu.load.hadoop import hadoop_bam_splits
from spark_bam_tpu.parallel.executor import ParallelConfig, map_partitions


@dataclass
class PathResult:
    path: str
    our_ms: int
    their_ms: int
    num_ours: int
    num_theirs: int
    diffs: list  # [(side, Split)]


def check_path(path: str, split_size: int, config: Config) -> PathResult:
    ctx = CheckerContext(path, config)
    t0 = time.perf_counter()
    ours = spark_bam_splits(ctx, split_size)
    our_ms = int((time.perf_counter() - t0) * 1000)
    t0 = time.perf_counter()
    theirs = hadoop_bam_splits(path, split_size, config=config)
    their_ms = int((time.perf_counter() - t0) * 1000)
    return PathResult(
        path, our_ms, their_ms, len(ours), len(theirs), diff_splits(ours, theirs)
    )


def run(
    bams_path,
    p: Printer,
    split_size: int,
    config: Config = Config(),
    parallel: ParallelConfig = ParallelConfig(),
) -> None:
    paths = [line.strip() for line in open(bams_path) if line.strip()]
    results = map_partitions(
        lambda path: check_path(path, split_size, config), paths, parallel
    )

    total_ours = sum(r.num_ours for r in results)
    total_theirs = sum(r.num_theirs for r in results)
    bad = [r for r in results if r.diffs]
    if bad:
        n_our_bad = sum(sum(1 for side, _ in r.diffs if side == "ours") for r in bad)
        n_their_bad = sum(
            sum(1 for side, _ in r.diffs if side == "theirs") for r in bad
        )
        p.echo(
            f"{len(bad)} of {len(results)} BAMs' splits didn't match"
            f" (totals: {total_ours}, {total_theirs};"
            f" {n_our_bad}, {n_their_bad} unmatched)",
            "",
        )
    else:
        p.echo(
            f"All {len(results)} BAMs' splits"
            f" (totals: {total_ours}, {total_theirs}) matched!",
            "",
        )

    p.echo("Total split-computation time:")
    p.echo(f"\thadoop-bam:\t{sum(r.their_ms for r in results)}")
    p.echo(f"\tspark-bam:\t{sum(r.our_ms for r in results)}")
    p.echo("")

    ratios = [
        r.their_ms / r.our_ms if r.our_ms else float(r.their_ms) for r in results
    ]
    if len(ratios) > 1:
        p.echo("Ratios:")
        p.echo(Stats(ratios).show(), "")
    else:
        p.echo("Ratio: %s" % round(ratios[0], 2), "")

    for r in bad:
        n_ours = sum(1 for side, _ in r.diffs if side == "ours")
        n_theirs = sum(1 for side, _ in r.diffs if side == "theirs")
        p.echo(
            f"\t{os.path.basename(r.path)}: {len(r.diffs)} splits differ"
            f" (totals: {r.num_ours}, {r.num_theirs};"
            f" mismatched: {n_ours}, {n_theirs}):"
        )
        for side, s in r.diffs:
            indent = "\t\t\t" if side == "theirs" else "\t\t"
            p.echo(f"{indent}{s.start}-{s.end}")
        p.echo("")
    p.echo("")
