"""time-load: collect each partition's first read via both loaders; isolates
split-computation latency (reference cli/.../spark/compare/TimeLoad.scala)."""

from __future__ import annotations

from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.cli.app import CheckerContext
from spark_bam_tpu.cli.splits_util import spark_bam_splits
from spark_bam_tpu.load.hadoop import (
    hadoop_bam_read_split,
    hadoop_bam_splits,
)
from spark_bam_tpu.utils.timer import Timer


def run(ctx: CheckerContext, split_size: int) -> None:
    p = ctx.printer

    with Timer("time_load.spark_bam") as t:
        our_splits = spark_bam_splits(ctx, split_size)
        our_first = []
        for split in our_splits:
            flat = ctx.view.flat_of_pos(
                split.start.block_pos, split.start.offset
            )
            rec, _ = BamRecord.decode(ctx.view.data, flat)
            our_first.append(rec.read_name)
    our_ms = int(t.ms)
    p.echo(f"spark-bam first-read collection time: {our_ms}")

    try:
        with Timer("time_load.hadoop_bam") as t:
            their_splits = hadoop_bam_splits(
                ctx.path, split_size, config=ctx.config
            )
            their_first = []
            for split in their_splits:
                for _, rec in hadoop_bam_read_split(
                    ctx.view, len(ctx.contigs), split
                ):
                    their_first.append(rec.read_name)
                    break
        their_ms = int(t.ms)
    except Exception as e:
        p.echo(
            "",
            f"spark-bam collected {len(our_first)} partitions' first-reads",
            "hadoop-bam threw an exception:",
            f"{type(e).__module__}.{type(e).__name__}: {e}",
        )
        return

    p.echo(f"hadoop-bam first-read collection time: {their_ms}", "")
    ours, theirs = set(our_first), set(their_first)
    if ours == theirs:
        p.echo(f"All {len(our_splits)} partition-start reads matched", "")
    else:
        only_ours = sorted(ours - theirs)
        only_theirs = sorted(theirs - ours)
        p.echo(
            f"{len(only_ours)} spark-bam-only reads, {len(only_theirs)} hadoop-bam-only:"
        )
        for name in only_ours:
            p.echo(f"\t{name}")
        p.echo("")
        for name in only_theirs:
            p.echo(f"\t\t{name}")
        p.echo("")
