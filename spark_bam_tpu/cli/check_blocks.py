"""check-blocks: compare checkers' first read-start per BGZF block.

A cheaper proxy than check-bam: mismatched blocks are weighted by the
previous block's compressed size — the share of compressed positions that
would resolve to a bad split (reference cli/.../check/blocks/
CheckBlocks.scala:25-201).
"""

from __future__ import annotations

import numpy as np

from spark_bam_tpu.bgzf.index_blocks import blocks_metadata
from spark_bam_tpu.cli.app import CheckerContext
from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.core.stats import Stats, format_bytes_binary


def _next_read_start(view, verdict_flat, flat, max_read_size):
    j = int(np.searchsorted(verdict_flat, flat))
    if j < len(verdict_flat) and verdict_flat[j] - flat < max_read_size:
        return Pos(*view.pos_of_flat(int(verdict_flat[j])))
    return None


def run(ctx: CheckerContext, spark_bam: bool = False, hadoop_bam: bool = False) -> None:
    p = ctx.printer
    if spark_bam and not hadoop_bam:
        v1, v2 = ctx.truth, ctx.eager_verdict
    elif hadoop_bam and not spark_bam:
        v1, v2 = ctx.truth, ctx.seqdoop_verdict
    else:
        v1, v2 = ctx.eager_verdict, ctx.seqdoop_verdict
    flat1 = np.flatnonzero(v1)
    flat2 = np.flatnonzero(v2)

    metas = [
        m
        for m in blocks_metadata(ctx.path)
        if ctx.ranges is None or m.start in ctx.ranges
    ]
    total_compressed = ctx.compressed_size
    max_read_size = ctx.config.max_read_size

    mismatches = []  # (block start, prev compressed size, pos1, pos2)
    offsets_hist: dict[int | None, int] = {}
    prev = None
    for meta in metas:
        flat = ctx.view.flat_of_pos(meta.start, 0)
        pos1 = _next_read_start(ctx.view, flat1, flat, max_read_size)
        pos2 = _next_read_start(ctx.view, flat2, flat, max_read_size)
        offset = pos1.offset if pos1 is not None and pos1.block_pos == meta.start else None
        offsets_hist[offset] = offsets_hist.get(offset, 0) + 1
        if pos1 != pos2:
            mismatches.append(
                (meta.start, prev.compressed_size if prev else 1, pos1, pos2)
            )
        prev = meta

    def print_offsets_info():
        keys = set(offsets_hist)
        n_empty = offsets_hist.get(None, 0)
        if keys == {None, 0}:
            p.echo(
                "",
                f"{offsets_hist[0]} blocks start with a read,"
                f" {n_empty} blocks didn't contain a read",
            )
        elif keys == {0}:
            p.echo("", "All blocks start with reads")
        else:
            stats = Stats.from_hist(
                [(k, v) for k, v in offsets_hist.items() if k is not None],
                rounded=True,
            )
            p.echo(
                "",
                f"Offsets of blocks' first reads ({n_empty} blocks didn't contain a read start):",
                stats.show(),
            )

    if not mismatches:
        p.echo(
            f"First read-position matched in {len(metas)} BGZF blocks totaling"
            f" {format_bytes_binary(total_compressed, include_b=True)} (compressed)"
        )
        print_offsets_info()
    else:
        bad_compressed = sum(m[1] for m in mismatches)
        p.echo(
            f"First read-position mismatched in {len(mismatches)} of {len(metas)} BGZF blocks",
            "",
            f"{bad_compressed} of {total_compressed}"
            f" ({bad_compressed / total_compressed}) compressed positions"
            " would lead to bad splits",
        )
        print_offsets_info()
        p.echo("")

        def show_pos(pos):
            return str(pos) if pos is not None else "-"

        p.print_limited(
            [
                f"{start} (prev block size: {prev_size}):\t{show_pos(p1)}\t{show_pos(p2)}"
                for start, prev_size, p1, p2 in mismatches
            ],
            header=f"{len(mismatches)} mismatched blocks:",
            truncated_header=lambda n: f"{n} of {len(mismatches)} mismatched blocks:",
        )
