"""``spark-bam-tpu aggregate``: on-device aggregate statistics
(docs/analytics.md "Aggregation").

    spark-bam-tpu aggregate [-a SPEC] [-i LOCI] [--flags-required N]
                            [--flags-forbidden N] [-t TG]...
                            [--format tsv|json] [-F FASTA] PATH

One ``metric<TAB>key<TAB>value`` line per populated bucket (tsv, the
default), or the whole result as one JSON object. The reduction runs on
device over the parsed planes (agg/kernels.py) for BAM and through the
partition executor's numpy oracle for CRAM/SAM — identical numbers
either way.
"""

from __future__ import annotations

import json
import time

from spark_bam_tpu.core.config import Config

#: SAM flag bit → flagstat row label, in wire order (agg/plan.py).
_FLAG_LABELS = (
    "paired", "proper_pair", "unmapped", "mate_unmapped", "reverse",
    "mate_reverse", "read1", "read2", "secondary", "qc_fail", "dup",
    "supplementary",
)


def _tsv_lines(result: dict):
    """Flatten a ``load.api.aggregate`` result into tsv rows — only
    populated buckets print, so a WGS coverage vector stays readable."""
    contigs = result["contigs"]
    for name, vec in result["metrics"].items():
        if name == "count":
            for label, v in zip(("records", "mapped", "bases"), vec):
                yield f"count\t{label}\t{int(v)}"
        elif name == "flagstat":
            yield f"flagstat\ttotal\t{int(vec[0])}"
            for label, v in zip(_FLAG_LABELS, vec[1:]):
                yield f"flagstat\t{label}\t{int(v)}"
        elif name in ("mapq", "tlen"):
            top = len(vec) - 1
            for i, v in enumerate(vec):
                if v:
                    key = (
                        f">{top - 1}" if name == "tlen" and i == top
                        else str(i)
                    )
                    yield f"{name}\t{key}\t{int(v)}"
        elif name == "coverage":
            nc = len(contigs) or 1
            bins = len(vec) // nc
            grid = vec.reshape(nc, bins)
            # Bucket width comes from the canonical spec the result
            # carries (agg/plan.py defaults when unstated).
            params = {}
            spec = _coverage_spec(result)
            if ":" in spec:
                for kv in spec.split(":", 1)[1].split(","):
                    key, _, value = kv.partition("=")
                    if value:
                        params[key] = int(value)
            width = params.get("bin", 1000)
            for (cname, clen), row in zip(contigs, grid):
                for k, v in enumerate(row):
                    if v:
                        lo = k * width
                        hi = clen if k == bins - 1 else min((k + 1) * width, clen)
                        yield f"coverage\t{cname}:{lo}-{hi}\t{int(v)}"
        else:
            for i, v in enumerate(vec):
                if v:
                    yield f"{name}\t{i}\t{int(v)}"


def _coverage_spec(result: dict) -> str:
    for part in result["agg"].split(";"):
        if part.split(":", 1)[0] == "coverage":
            return part
    return "coverage"


def run(
    path,
    p,
    config: Config,
    agg=None,
    loci=None,
    flags_required: int = 0,
    flags_forbidden: int = 0,
    tags_required=(),
    fmt: str = "tsv",
    reference=None,
) -> None:
    from spark_bam_tpu.load.api import aggregate

    t0 = time.monotonic()
    result = aggregate(
        path, agg=agg or "", loci=loci, flags_required=flags_required,
        flags_forbidden=flags_forbidden, tags_required=tags_required,
        config=config, reference=reference,
    )
    seconds = time.monotonic() - t0
    if fmt == "json":
        p.echo(json.dumps({
            "agg": result["agg"],
            "rows": result["rows"],
            "contigs": [[n, int(ln)] for n, ln in result["contigs"]],
            "metrics": {
                k: [int(x) for x in v] for k, v in result["metrics"].items()
            },
        }, sort_keys=True))
    else:
        for line in _tsv_lines(result):
            p.echo(line)
    import sys

    print(
        f"aggregated {result['rows']} rows [{result['agg']}] "
        f"in {seconds:.2f}s",
        file=sys.stderr,
    )
