"""Shared context for the checker commands.

The reference's ``CheckerApp`` (cli/.../check/CheckerApp.scala:31-223) built
around Spark broadcasts/accumulators; here one ``CheckerContext`` inflates
the file into a flat view once, evaluates whichever vectorized engines a
command needs, and renders the shared report blocks (position totals,
confusion matrix, annotated false positives).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property

import numpy as np

from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.bam.index_records import read_records_index
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.bgzf.flat import FlatView, flatten_file
from spark_bam_tpu.check.flags import Flags
from spark_bam_tpu.check.seqdoop import seqdoop_check_flat
from spark_bam_tpu.check.vectorized import ChainResult, check_flat
from spark_bam_tpu.cli.output import Printer
from spark_bam_tpu.core.channel import path_exists, path_size
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.core.stats import format_bytes_binary


def render_record(rec: BamRecord, contigs) -> str:
    """HTSJDK-style record rendering + the reference's location suffix
    (check/.../PosMetadata.scala:35-55)."""
    pair = ""
    if rec.flag & 0x1:
        pair = " 2/2" if rec.flag & 0x80 else " 1/2"
    kind = "unmapped" if rec.is_unmapped else "aligned"
    s = f"{rec.read_name}{pair} {rec.read_length}b {kind} read"
    num_contigs = len(contigs)
    if rec.is_unmapped and rec.pos >= 0 and 0 <= rec.ref_id < num_contigs:
        s += f" (placed at {contigs.name(rec.ref_id)}:{rec.pos + 1})"
    elif not rec.is_unmapped:
        s += f" @ {contigs.name(rec.ref_id)}:{rec.pos + 1}"
    return s


@dataclass
class PosAnnotation:
    pos: Pos
    delta: int | None
    record_str: str | None
    flags: Flags

    def __str__(self) -> str:
        rec = (
            f"{self.delta} before {self.record_str}"
            if self.record_str is not None
            else "no next record"
        )
        return f"{self.pos}:\t{rec}. Failing checks: {self.flags}"


def print_report_header(p, total: int, compressed: int, num_reads: int):
    """The golden report's four-line header (positions / compressed size /
    ratio / reads) — one renderer for the in-memory and sharded paths."""
    p.echo(
        f"{total} uncompressed positions",
        f"{format_bytes_binary(compressed)} compressed",
        "Compression ratio: %.2f" % (total / compressed),
        f"{num_reads} reads",
    )


def funnel_status_line(
    config: Config,
    stats: dict | None = None,
    device: bool = True,
    full_masks: bool = False,
) -> str:
    """One ``funnel: …`` line for the check commands (sibling of
    ``sbi.store.cache_status_line``): the configured mode, whether the
    two-stage prefilter actually ran on this path, and — when the engine
    recorded ``funnel_stats`` — the measured reduction."""
    mode = config.funnel
    if not device or not config.funnel_enabled(full_masks):
        if mode == "off":
            why = "disabled"
        elif not device:
            why = "host engine, no device hot path"
        else:
            why = "full per-position flag masks requested"
        return f"funnel: off ({mode}: {why})"
    if stats and stats.get("screened"):
        screened = int(stats["screened"])
        survivors = int(stats["survivors"])
        reduction = screened / max(survivors, 1)
        return (
            f"funnel: on ({mode}): {screened} positions -> "
            f"{survivors} survivors, {reduction:.1f}x reduction"
        )
    return f"funnel: on ({mode})"


class CheckerContext:
    def __init__(
        self,
        path,
        config: Config = Config(),
        printer: Printer | None = None,
        ranges=None,
    ):
        self.path = str(path)
        self.config = config
        self.printer = printer or Printer()
        self.ranges = ranges  # RangeSet of compressed byte ranges, or None

    @cached_property
    def position_mask(self) -> np.ndarray | None:
        """Mask of flat positions whose *block start* is inside the byte
        ranges (reference Blocks.Args --intervals, Blocks.scala:33-41)."""
        if self.ranges is None:
            return None
        mask = np.zeros(self.view.size, dtype=bool)
        starts = self.view.block_starts
        flats = self.view.block_flat
        for i, start in enumerate(starts):
            if int(start) in self.ranges:
                end = self.view.size if i + 1 == len(flats) else int(flats[i + 1])
                mask[int(flats[i]): end] = True
        return mask

    @cached_property
    def header(self):
        return read_header(self.path)

    @cached_property
    def contigs(self):
        return self.header.contig_lengths

    @cached_property
    def lengths(self) -> np.ndarray:
        return np.array(self.contigs.lengths_list(), dtype=np.int32)

    @cached_property
    def view(self) -> FlatView:
        return flatten_file(self.path)

    @cached_property
    def compressed_size(self) -> int:
        return path_size(self.path)

    @cached_property
    def selected_compressed_size(self) -> int:
        """Sum of the checked blocks' compressed sizes (the reference's
        compressedSizeAccumulator: per-block, honors --intervals, excludes
        the EOF sentinel)."""
        from spark_bam_tpu.bgzf.index_blocks import blocks_metadata

        return sum(
            m.compressed_size
            for m in blocks_metadata(self.path)
            if self.ranges is None or m.start in self.ranges
        )

    # ------------------------------------------------------------- engines
    @cached_property
    def eager_result(self) -> ChainResult:
        if self._use_tpu_backend():
            from spark_bam_tpu.tpu.checker import TpuChecker

            want = min(self.config.window_size, max(self.view.size, 1))
            window = 1 << max(20, (want - 1).bit_length())
            checker = TpuChecker(
                self.lengths,
                window=window,
                halo=min(self.config.halo_size, window // 4),
                reads_to_check=self.config.reads_to_check,
                flags_impl=self.config.flags_impl,
            )
            res = checker.check_buffer(self.view.data, at_eof=True)
            return ChainResult(
                verdict=res.verdict,
                reads_parsed=res.reads_parsed,
                fail_mask=res.fail_mask,
                reads_before=res.reads_before,
                exact=res.exact,
                escaped=res.escaped,
            )
        return check_flat(
            self.view.data,
            self.lengths,
            at_eof=True,
            reads_to_check=self.config.reads_to_check,
        )

    def _use_tpu_backend(self) -> bool:
        if self.config.backend == "numpy":
            return False
        if self.config.backend in ("tpu", "pallas"):
            return True
        if self.config.backend == "auto":
            # Device pays off once the input outweighs kernel compile+launch;
            # small files resolve faster in the NumPy engine.
            if self.view.size < (32 << 20):
                return False
            # Probed in a subprocess with a timeout: in-process backend init
            # hangs indefinitely when a TPU tunnel is down, and an auto
            # decision must never hang the CLI with it.
            from spark_bam_tpu.core.platform import probe_default_backend

            return probe_default_backend() in ("tpu", "axon")
        return False

    @cached_property
    def eager_verdict(self) -> np.ndarray:
        return self.eager_result.verdict

    @cached_property
    def seqdoop_verdict(self) -> np.ndarray:
        return seqdoop_check_flat(self.view, len(self.contigs))

    @cached_property
    def truth(self) -> np.ndarray:
        truth = np.zeros(self.view.size, dtype=bool)
        for pos in read_records_index(self.records_path):
            truth[self.view.flat_of_pos(pos.block_pos, pos.offset)] = True
        return truth

    @property
    def records_path(self) -> str:
        return self.path + ".records"

    @property
    def has_records_index(self) -> bool:
        return path_exists(self.records_path)

    def verdict_for(self, name: str) -> np.ndarray:
        if name == "eager":
            return self.eager_verdict
        if name == "seqdoop":
            return self.seqdoop_verdict
        if name == "indexed":
            return self.truth
        raise KeyError(name)

    # --------------------------------------------------------- annotations
    def annotate(self, flat_idx: int) -> PosAnnotation:
        """Next-record metadata + full-checker flags for one position
        (reference PosMetadata.apply)."""
        pos = Pos(*self.view.pos_of_flat(flat_idx))
        mask = int(self.eager_result.fail_mask[flat_idx])
        flags = Flags.from_mask(mask, int(self.eager_result.reads_before[flat_idx]))
        true_flat = self.true_flat_eager
        j = int(np.searchsorted(true_flat, flat_idx))
        if j < len(true_flat) and true_flat[j] - flat_idx < self.config.max_read_size:
            nxt = int(true_flat[j])
            rec, _ = BamRecord.decode(self.view.data, nxt)
            return PosAnnotation(
                pos, nxt - flat_idx, render_record(rec, self.contigs), flags
            )
        return PosAnnotation(pos, None, None, flags)

    @cached_property
    def true_flat_eager(self) -> np.ndarray:
        return np.flatnonzero(self.eager_verdict)

    # ------------------------------------------------------------- reports
    def print_header_and_confusion(
        self, expected: np.ndarray, actual: np.ndarray
    ) -> None:
        """The shared check-bam/full-check report (CheckerApp.scala:64-222)."""
        p = self.printer
        sel = self.position_mask
        if sel is not None:
            expected = expected & sel
            actual = actual & sel
            in_scope = int(sel.sum())
        else:
            in_scope = self.view.size
        tp = int((expected & actual).sum())
        fp_idx = np.flatnonzero(~expected & actual)
        fn_idx = np.flatnonzero(expected & ~actual)
        num_reads = tp + len(fn_idx)
        tn = in_scope - num_reads - len(fp_idx)
        total = in_scope
        print_report_header(p, total, self.selected_compressed_size, num_reads)

        if not len(fp_idx) and not len(fn_idx):
            p.echo("All calls matched!")
            return

        p.echo(f"{len(fp_idx)} false positives, {len(fn_idx)} false negatives", "")

        if len(fp_idx):
            annotations = [self.annotate(int(i)) for i in fp_idx]
            hist: dict[str, int] = {}
            for a in annotations:
                key = str(a.flags)
                hist[key] = hist.get(key, 0) + 1
            rows = [
                f"{count}:\t{flags}"
                for flags, count in sorted(hist.items(), key=lambda kv: -kv[1])
            ]
            p.print_limited(
                rows,
                header="False-positive-site flags histogram:",
                truncated_header=lambda n: "False-positive-site flags histogram:",
            )
            p.echo("")
            p.print_limited(
                [str(a) for a in annotations],
                header="False positives with succeeding read info:",
                truncated_header=lambda n: (
                    f"{n} of {len(fp_idx)} false positives with succeeding read info::"
                ),
            )

        if len(fn_idx):
            p.print_limited(
                [str(Pos(*self.view.pos_of_flat(int(i)))) for i in fn_idx],
                header=f"{len(fn_idx)} false negatives:",
                truncated_header=lambda n: f"{n} of {len(fn_idx)} false negatives:",
            )
