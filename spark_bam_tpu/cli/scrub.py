"""``spark-bam-tpu scrub`` — end-to-end artifact integrity scrubbing.

Walks rewritten BAMs, their ``.blocks``/``.records``/``.sbi`` sidecars
and native columnar containers through jobs/scrub.py: per-frame CRCs,
structural validation, sidecar cross-checks against the actual BGZF
member table, and (with ``--source``) spot record-parity against the
file the artifact was rewritten from. Exit code 0 means every artifact
came back clean; 3 means findings (listed in the JSON report), with
``--quarantine`` additionally renaming damaged artifacts to
``<path>.quarantined`` so a pipeline can't consume them by accident
(docs/robustness.md "Durable jobs & scrubbing").
"""

from __future__ import annotations

import json

from spark_bam_tpu.cli.output import Printer

#: exit code when the scrub found (and reported) integrity findings —
#: distinct from 2 (usage error) and 1 (crash) so CI can branch on it.
RC_FINDINGS = 3


def run(paths, p: Printer, source: "str | None" = None,
        quarantine: bool = False, stride: int = 16) -> int:
    from spark_bam_tpu.jobs.scrub import scrub_paths

    report = scrub_paths(
        paths, source=source, quarantine=quarantine, stride=stride
    )
    p.echo(json.dumps(report.summary(), indent=2, sort_keys=True))
    return 0 if report.clean else RC_FINDINGS
