"""full-check: run the full checker everywhere; report which checks fail
how often, highlighting "critical" (single-check) and two-check positions
(reference cli/.../check/full/FullCheck.scala:31-311)."""

from __future__ import annotations

import numpy as np

from spark_bam_tpu.check.flags import (
    FLAG_NAMES,
    considered_mask,
    num_failing_fields,
)
from spark_bam_tpu.cli.app import CheckerContext



def _counts_lines(
    counts: dict[str, int], hide_bit0: bool = False, include_zeros: bool = False
) -> list[str]:
    items = [
        (name, counts.get(name, 0))
        for name in FLAG_NAMES
        if (include_zeros or counts.get(name, 0))
        and not (hide_bit0 and name == "tooFewFixedBlockBytes")
    ]
    if not items:
        return []
    items.sort(key=lambda kv: -kv[1])
    name_w = max(len(n) for n, _ in items)
    count_w = max(len(str(c)) for _, c in items)
    return [f"{name:>{name_w}}:\t{str(count):>{count_w}}" for name, count in items]


def _mask_counts(masks: np.ndarray) -> dict[str, int]:
    out = {}
    for i, name in enumerate(FLAG_NAMES):
        c = int(((masks >> i) & 1).sum())
        if c:
            out[name] = c
    return out


def run(ctx: CheckerContext) -> None:
    p = ctx.printer
    res = ctx.eager_result

    if ctx.has_records_index:
        expected = ctx.truth
        mismatch = np.flatnonzero(res.verdict != expected)
        if len(mismatch):
            i = int(mismatch[0])
            kind = "positive" if res.verdict[i] else "negative"
            raise RuntimeError(
                f"False {kind} at {ctx.view.pos_of_flat(i)}"
            )
        ctx.print_header_and_confusion(expected, res.verdict)
        p.echo("")

    masks = res.fail_mask
    rb = res.reads_before
    considered = considered_mask(masks, rb)
    if ctx.position_mask is not None:
        considered &= ctx.position_mask
    num_fields = num_failing_fields(masks, rb)

    def bucket(k: int) -> np.ndarray:
        return np.flatnonzero(considered & (num_fields == k))

    ones = bucket(1)
    if len(ones) == 0:
        p.echo("No positions where only one check failed")
    else:
        p.echo("Critical error counts (true negatives where only one check failed):")
        p.echo(*("\t" + l for l in _counts_lines(_mask_counts(masks[ones]))))
        p.echo("")
        p.print_limited(
            [str(ctx.annotate(int(i))) for i in ones[: max(p.limit, 1)]],
            total=len(ones),
            header=f"{len(ones)} critical positions:",
            truncated_header=lambda n: f"{n} of {len(ones)} critical positions:",
        )

    p.echo("")

    twos = bucket(2)
    if len(twos) == 0:
        p.echo("No positions where exactly two checks failed", "")
    else:
        p.print_limited(
            [str(ctx.annotate(int(i))) for i in twos[: max(p.limit, 1)]],
            total=len(twos),
            header=f"{len(twos)} positions where exactly two checks failed:",
            truncated_header=lambda n: (
                f"{n} of {len(twos)} positions where exactly two checks failed:"
            ),
        )
        p.echo("")
        combo_hist: dict[int, int] = {}
        for m in masks[twos]:
            combo_hist[int(m)] = combo_hist.get(int(m), 0) + 1

        def combo_str(mask: int) -> str:
            return ",".join(n for i, n in enumerate(FLAG_NAMES) if mask & (1 << i))

        top = sorted(combo_hist.items(), key=lambda kv: -kv[1])
        if top[0][1] > 1:
            with p.indent():
                p.print_limited(
                    [f"{count}:\t{combo_str(mask)}" for mask, count in top],
                    header="Histogram:",
                    truncated_header=lambda n: "Histogram:",
                )
            p.echo("")
        with p.indent():
            p.echo("Per-flag totals:")
            p.echo(*("\t" + l for l in _counts_lines(_mask_counts(masks[twos]))))
        p.echo("")

    all_considered = np.flatnonzero(considered)
    p.echo("Total error counts:")
    # include_zeros: the reference's Counts.lines defaults to showing zero
    # counts here (only the critical/per-flag sections exclude them).
    p.echo(*(
        "\t" + l
        for l in _counts_lines(
            _mask_counts(masks[all_considered]), hide_bit0=True, include_zeros=True
        )
    ))
    p.echo("")
