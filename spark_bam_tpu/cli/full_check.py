"""full-check: run the full checker everywhere; report which checks fail
how often, highlighting "critical" (single-check) and two-check positions
(reference cli/.../check/full/FullCheck.scala:31-311)."""

from __future__ import annotations

import numpy as np

from spark_bam_tpu.check.flags import (
    FLAG_NAMES,
    considered_mask,
    num_failing_fields,
)
from spark_bam_tpu.cli.app import CheckerContext, funnel_status_line



def _counts_lines(
    counts: dict[str, int], hide_bit0: bool = False, include_zeros: bool = False
) -> list[str]:
    items = [
        (name, counts.get(name, 0))
        for name in FLAG_NAMES
        if (include_zeros or counts.get(name, 0))
        and not (hide_bit0 and name == "tooFewFixedBlockBytes")
    ]
    if not items:
        return []
    items.sort(key=lambda kv: -kv[1])
    name_w = max(len(n) for n, _ in items)
    count_w = max(len(str(c)) for _, c in items)
    return [f"{name:>{name_w}}:\t{str(count):>{count_w}}" for name, count in items]


def _mask_counts(masks: np.ndarray) -> dict[str, int]:
    out = {}
    for i, name in enumerate(FLAG_NAMES):
        c = int(((masks >> i) & 1).sum())
        if c:
            out[name] = c
    return out


def _render_report(p, crit_idx, crit_masks, two_idx, two_masks,
                   total_counts, fmt_pos) -> None:
    """The critical / two-check / total sections, shared by the in-memory
    and streaming paths so the mask-derived output cannot diverge.
    ``fmt_pos(flat_idx)`` renders one position (annotated in-memory,
    ``block:offset`` streaming)."""

    def limited(idx):
        # Respect limit=0 = unlimited; otherwise avoid formatting more
        # than the printer will show.
        return idx if not p.limit else idx[: p.limit]

    if len(crit_idx) == 0:
        p.echo("No positions where only one check failed")
    else:
        p.echo("Critical error counts (true negatives where only one check failed):")
        p.echo(*("\t" + l for l in _counts_lines(_mask_counts(crit_masks))))
        p.echo("")
        p.print_limited(
            [fmt_pos(int(i)) for i in limited(crit_idx)],
            total=len(crit_idx),
            header=f"{len(crit_idx)} critical positions:",
            truncated_header=lambda n: f"{n} of {len(crit_idx)} critical positions:",
        )

    p.echo("")

    if len(two_idx) == 0:
        p.echo("No positions where exactly two checks failed", "")
    else:
        p.print_limited(
            [fmt_pos(int(i)) for i in limited(two_idx)],
            total=len(two_idx),
            header=f"{len(two_idx)} positions where exactly two checks failed:",
            truncated_header=lambda n: (
                f"{n} of {len(two_idx)} positions where exactly two checks failed:"
            ),
        )
        p.echo("")
        combo_hist: dict[int, int] = {}
        for m in two_masks:
            combo_hist[int(m)] = combo_hist.get(int(m), 0) + 1

        def combo_str(mask: int) -> str:
            return ",".join(n for i, n in enumerate(FLAG_NAMES) if mask & (1 << i))

        top = sorted(combo_hist.items(), key=lambda kv: -kv[1])
        if top[0][1] > 1:
            with p.indent():
                p.print_limited(
                    [f"{count}:\t{combo_str(mask)}" for mask, count in top],
                    header="Histogram:",
                    truncated_header=lambda n: "Histogram:",
                )
            p.echo("")
        with p.indent():
            p.echo("Per-flag totals:")
            p.echo(*("\t" + l for l in _counts_lines(_mask_counts(two_masks))))
        p.echo("")

    p.echo("Total error counts:")
    p.echo(*(
        "\t" + l
        for l in _counts_lines(total_counts, hide_bit0=True, include_zeros=True)
    ))
    p.echo("")


def run_streaming(ctx: CheckerContext, sharded: bool = False) -> None:
    """The WGS-scale face: same aggregations via ``full_spans`` in
    O(window) host memory. Mask-derived sections render through the same
    code as the in-memory report (byte-identical); position lists print
    as ``block:offset`` without the record annotations (those need
    per-hit record decodes, which the default in-memory path provides).
    The device/NumPy engine choice honors ``spark.bam.backend`` through
    the same hang-proof probe as the in-memory path. ``sharded`` runs the
    scan across every device on the mesh
    (``parallel.stream_mesh.full_check_summary_sharded`` — identical
    output; deferred lanes fall back to this single-device path)."""
    from spark_bam_tpu.bgzf.flat import metas_block_table, pos_of_flat_tables
    from spark_bam_tpu.bgzf.index_blocks import blocks_metadata
    from spark_bam_tpu.cli.output import UsageError
    from spark_bam_tpu.tpu.stream_check import full_check_summary_streaming

    if ctx.ranges is not None:
        raise UsageError(
            "--streaming scans the whole file; -i/--intervals is not "
            "supported on the streaming path"
        )
    from spark_bam_tpu.utils.timer import heartbeat_progress

    p = ctx.printer
    metas = list(blocks_metadata(ctx.path))  # one scan: summary + pos tables
    mode = "--streaming --sharded" if sharded else "--streaming"
    with heartbeat_progress(
        f"full-check {mode} {ctx.path}", unit="window"
    ) as progress:
        if sharded:
            from spark_bam_tpu.parallel.stream_mesh import (
                full_check_summary_sharded,
            )

            s = full_check_summary_sharded(
                ctx.path, ctx.config, metas=metas, progress=progress,
                fallback_use_device=ctx._use_tpu_backend(),
            )
        else:
            s = full_check_summary_streaming(
                ctx.path, ctx.config, use_device=ctx._use_tpu_backend(),
                metas=metas, progress=progress,
            )
    block_starts, block_flat = metas_block_table(metas)

    def pos_str(i: int) -> str:
        b, o = pos_of_flat_tables(block_starts, block_flat, i)
        return f"{b}:{o}"

    _render_report(
        p,
        s["critical_positions"], s["critical_masks"],
        s["two_check_positions"], s["two_check_masks"],
        s["per_flag"], pos_str,
    )
    # full-check needs every per-position flag mask, so the funnel's
    # verdict-only projection never applies; say so rather than go silent.
    p.echo(funnel_status_line(ctx.config, full_masks=True))


def run(ctx: CheckerContext) -> None:
    p = ctx.printer
    res = ctx.eager_result

    if ctx.has_records_index:
        expected = ctx.truth
        mismatch = np.flatnonzero(res.verdict != expected)
        if len(mismatch):
            i = int(mismatch[0])
            kind = "positive" if res.verdict[i] else "negative"
            raise RuntimeError(
                f"False {kind} at {ctx.view.pos_of_flat(i)}"
            )
        ctx.print_header_and_confusion(expected, res.verdict)
        p.echo("")

    masks = res.fail_mask
    rb = res.reads_before
    considered = considered_mask(masks, rb)
    if ctx.position_mask is not None:
        considered &= ctx.position_mask
    num_fields = num_failing_fields(masks, rb)

    def bucket(k: int) -> np.ndarray:
        return np.flatnonzero(considered & (num_fields == k))

    ones = bucket(1)
    twos = bucket(2)
    all_considered = np.flatnonzero(considered)
    _render_report(
        p,
        ones, masks[ones],
        twos, masks[twos],
        _mask_counts(masks[all_considered]),
        lambda i: str(ctx.annotate(i)),
    )
    p.echo(funnel_status_line(ctx.config, device=False))
