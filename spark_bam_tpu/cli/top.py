"""spark-bam-tpu top: fleet telemetry view (one-shot or ``--watch``).

Scrapes the ``telemetry`` op from a serve worker or fabric router and
renders the operator's glance view: per-worker health, queue depth,
per-op p50/p99, the host/H2D/device ms split the inflate attribution
gauges carry, SLO burn rates + firing alerts, per-op/per-tenant cost
rollups, latency exemplars (trace ids of the slowest kept traces —
feed them to ``metrics-report`` to see the offending tree), and the
router's autoscale move ledger with each move's cited reason. Point it
at the same address clients use — the op is an admin op, so it bypasses
admission control and works mid-overload. ``--watch`` re-scrapes every
``--interval`` seconds (Ctrl-C to stop).
"""

from __future__ import annotations

import sys
import time

from spark_bam_tpu.cli.output import Printer


def _ms(v) -> str:
    return "-" if v is None else f"{float(v):.1f}"


def _hd_split(snapshot) -> str:
    """``host/h2d/dev`` last-window ms from the attribution gauges."""
    vals = {}
    for g in (snapshot or {}).get("gauges", []):
        if g.get("name") in ("inflate.host_ms", "inflate.h2d_ms",
                             "inflate.device_ms"):
            vals[g["name"].rsplit(".", 1)[1]] = g.get("value")
    if not vals:
        return "-"
    return "/".join(
        _ms(vals.get(k)) for k in ("host_ms", "h2d_ms", "device_ms")
    )


def _slo_lines(p: Printer, slo: "dict | None", indent: str = "") -> None:
    """Per-objective burn rates + the firing set (obs/slo.py status)."""
    if not slo or not slo.get("objectives"):
        return
    for st in slo["objectives"]:
        if not isinstance(st, dict):
            continue
        mark = "FIRING" if st.get("firing") else "ok"
        p.echo(
            f"{indent}slo {st.get('objective')}: "
            f"burn={st.get('burn_fast')}x/{st.get('burn_slow')}x "
            f"value={st.get('value_fast')} [{mark}]"
        )


def _accounting_lines(p: Printer, acc: "dict | None",
                      indent: str = "") -> None:
    """Per-tenant cost rollups (obs/account.py snapshot)."""
    tenants = (acc or {}).get("tenants") or {}
    if not tenants:
        return
    for tenant, a in sorted(tenants.items()):
        p.echo(
            f"{indent}tenant {tenant}: n={a.get('requests', 0)} "
            f"queue={_ms(a.get('queue_ms'))}ms "
            f"host={_ms(a.get('host_ms'))}ms "
            f"dev={_ms(a.get('device_ms'))}ms "
            f"h2d={a.get('h2d_bytes', 0)}B "
            f"out={a.get('bytes_served', 0)}B"
        )


def _exemplar_lines(p: Printer, snapshot: "dict | None",
                    indent: str = "") -> None:
    """Latency exemplars: trace ids of the slowest kept traces — the
    jump from "p99 is burning" to ``metrics-report``'s trace tree."""
    for h in (snapshot or {}).get("hists", []):
        for e in (h.get("exemplars") or [])[:3]:
            p.echo(
                f"{indent}exemplar {h['name']}: {_ms(e[0])}ms "
                f"trace={e[1]}"
            )


def _worker_lines(p: Printer, label: str, tel: dict, indent: str = "") -> None:
    stats = tel.get("stats") or {}
    snap = tel.get("snapshot")
    p.echo(
        f"{indent}{label}: pid={tel.get('pid')} "
        f"served={stats.get('served', 0)} "
        f"queue={stats.get('queue_depth', 0)} "
        f"p50={_ms(stats.get('latency_p50_ms'))}ms "
        f"p99={_ms(stats.get('latency_p99_ms'))}ms "
        f"host/h2d/dev={_hd_split(snap)}ms"
        + ("" if tel.get("telemetry_enabled") else " (metrics disabled)")
    )
    ops = stats.get("ops") or {}
    for op, s in sorted(ops.items()):
        p.echo(
            f"{indent}  {op}: n={s.get('requests', 0)} "
            f"rows={s.get('rows', 0)} "
            f"p50={_ms(s.get('p50_ms'))}ms p99={_ms(s.get('p99_ms'))}ms"
        )
    _slo_lines(p, tel.get("slo"), indent=indent + "  ")
    _accounting_lines(p, tel.get("accounting"), indent=indent + "  ")
    _exemplar_lines(p, snap, indent=indent + "  ")


def _render_fabric(p: Printer, resp: dict) -> None:
    workers = resp.get("workers") or {}
    healthy = sum(1 for w in workers.values() if w.get("healthy"))
    p.echo(
        f"fabric: {len(workers)} workers ({healthy} healthy)"
        + (" DRAINING" if resp.get("draining") else "")
    )
    counters = resp.get("counters") or {}
    if counters:
        p.echo("router: " + " ".join(
            f"{k}={v}" for k, v in sorted(counters.items())
        ))
    for wid, w in sorted(workers.items()):
        state = "up" if w.get("healthy") else "EJECTED"
        if w.get("draining"):
            state = "draining"
        head = (f"{wid} [{w.get('address')}] {state} "
                f"inflight={w.get('inflight', 0)}")
        tel = w.get("telemetry")
        if not tel:
            p.echo(f"{head} (no telemetry)")
            continue
        p.echo(head)
        _worker_lines(p, "worker", tel, indent="  ")
    _accounting_lines(p, resp.get("accounting"), indent="")
    moves = (resp.get("moves") or [])[-5:]
    if moves:
        p.echo("autoscale moves:")
        for m in moves:
            fields = " ".join(
                f"{k}={v}" for k, v in sorted((m.get("move") or {}).items())
            )
            p.echo(f"  {m.get('worker')}: {fields} ({m.get('reason')})")
    flight_tail = (resp.get("flight") or [])[-5:]
    if flight_tail:
        p.echo("recent flight events:")
        for ev in flight_tail:
            kind = ev.get("e", "?")
            rest = " ".join(
                f"{k}={v}" for k, v in sorted(ev.items())
                if k not in ("e", "t") and not isinstance(v, (list, dict))
            )
            p.echo(f"  {kind} {rest}")


def _render_once(p: Printer, resp: dict, prometheus: bool) -> None:
    if prometheus:
        if resp.get("prometheus") is not None:
            p.echo(resp["prometheus"].rstrip("\n"))
        else:
            # Single worker: render its own snapshot locally.
            from spark_bam_tpu.obs.exporters import prometheus_text

            p.echo(prometheus_text(resp.get("snapshot") or {}).rstrip("\n"))
        return
    if resp.get("fabric"):
        _render_fabric(p, resp)
    else:
        _worker_lines(p, "worker", resp)


def run(address: str, p: Printer, prometheus: bool = False,
        watch: bool = False, interval_s: float = 2.0) -> None:
    from spark_bam_tpu.serve.client import ServeClient

    fields = {"prometheus": True} if prometheus else {}
    with ServeClient(address) as client:
        resp = client.request("telemetry", **fields)
        if not watch:
            _render_once(p, resp, prometheus)
            return
        try:
            while True:
                # ANSI clear + home, straight to the terminal (the
                # Printer may be teed to a file; the control codes are
                # display-only).
                sys.stderr.write("\x1b[2J\x1b[H")
                sys.stderr.flush()
                _render_once(p, resp, prometheus)
                time.sleep(max(0.1, float(interval_s)))
                resp = client.request("telemetry", **fields)
        except KeyboardInterrupt:
            pass
