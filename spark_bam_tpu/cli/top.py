"""spark-bam-tpu top: one-shot fleet telemetry view.

Scrapes the ``telemetry`` op from a serve worker or fabric router and
renders the operator's glance view: per-worker health, queue depth,
per-op p50/p99, and the host/H2D/device ms split the inflate attribution
gauges carry. Point it at the same address clients use — the op is an
admin op, so it bypasses admission control and works mid-overload.
"""

from __future__ import annotations

from spark_bam_tpu.cli.output import Printer


def _ms(v) -> str:
    return "-" if v is None else f"{float(v):.1f}"


def _hd_split(snapshot) -> str:
    """``host/h2d/dev`` last-window ms from the attribution gauges."""
    vals = {}
    for g in (snapshot or {}).get("gauges", []):
        if g.get("name") in ("inflate.host_ms", "inflate.h2d_ms",
                             "inflate.device_ms"):
            vals[g["name"].rsplit(".", 1)[1]] = g.get("value")
    if not vals:
        return "-"
    return "/".join(
        _ms(vals.get(k)) for k in ("host_ms", "h2d_ms", "device_ms")
    )


def _worker_lines(p: Printer, label: str, tel: dict, indent: str = "") -> None:
    stats = tel.get("stats") or {}
    snap = tel.get("snapshot")
    p.echo(
        f"{indent}{label}: pid={tel.get('pid')} "
        f"served={stats.get('served', 0)} "
        f"queue={stats.get('queue_depth', 0)} "
        f"p50={_ms(stats.get('latency_p50_ms'))}ms "
        f"p99={_ms(stats.get('latency_p99_ms'))}ms "
        f"host/h2d/dev={_hd_split(snap)}ms"
        + ("" if tel.get("telemetry_enabled") else " (metrics disabled)")
    )
    ops = stats.get("ops") or {}
    for op, s in sorted(ops.items()):
        p.echo(
            f"{indent}  {op}: n={s.get('requests', 0)} "
            f"rows={s.get('rows', 0)} "
            f"p50={_ms(s.get('p50_ms'))}ms p99={_ms(s.get('p99_ms'))}ms"
        )


def _render_fabric(p: Printer, resp: dict) -> None:
    workers = resp.get("workers") or {}
    healthy = sum(1 for w in workers.values() if w.get("healthy"))
    p.echo(
        f"fabric: {len(workers)} workers ({healthy} healthy)"
        + (" DRAINING" if resp.get("draining") else "")
    )
    counters = resp.get("counters") or {}
    if counters:
        p.echo("router: " + " ".join(
            f"{k}={v}" for k, v in sorted(counters.items())
        ))
    for wid, w in sorted(workers.items()):
        state = "up" if w.get("healthy") else "EJECTED"
        if w.get("draining"):
            state = "draining"
        head = (f"{wid} [{w.get('address')}] {state} "
                f"inflight={w.get('inflight', 0)}")
        tel = w.get("telemetry")
        if not tel:
            p.echo(f"{head} (no telemetry)")
            continue
        p.echo(head)
        _worker_lines(p, "worker", tel, indent="  ")
    flight_tail = (resp.get("flight") or [])[-5:]
    if flight_tail:
        p.echo("recent flight events:")
        for ev in flight_tail:
            kind = ev.get("e", "?")
            rest = " ".join(
                f"{k}={v}" for k, v in sorted(ev.items())
                if k not in ("e", "t") and not isinstance(v, (list, dict))
            )
            p.echo(f"  {kind} {rest}")


def run(address: str, p: Printer, prometheus: bool = False) -> None:
    from spark_bam_tpu.serve.client import ServeClient

    fields = {"prometheus": True} if prometheus else {}
    with ServeClient(address) as client:
        resp = client.request("telemetry", **fields)
    if prometheus:
        if resp.get("prometheus") is not None:
            p.echo(resp["prometheus"].rstrip("\n"))
        else:
            # Single worker: render its own snapshot locally.
            from spark_bam_tpu.obs.exporters import prometheus_text

            p.echo(prometheus_text(resp.get("snapshot") or {}).rstrip("\n"))
        return
    if resp.get("fabric"):
        _render_fabric(p, resp)
    else:
        _worker_lines(p, "worker", resp)
