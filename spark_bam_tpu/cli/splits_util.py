"""Split computation for the CLI comparison commands.

``spark_bam_splits`` resolves each raw split boundary through the load
path's native tri-state window scan when built (bounded inflate per
boundary — the same engine ``load_bam`` uses; a whole-file pass for a
handful of boundaries is the wrong altitude at GB scale), else through
the vectorized eager engine of a ``CheckerContext`` (one flag pass
serves all boundaries — right for fixture-sized files and the only
option without the native library). Ends tile to the next start
(reference cli/.../spark/LoadReads.scala:164-174,
CanLoadBam.scala:262-274).

Both engines emit the raw per-boundary ``PlanEntry`` plan (sbi/plan.py)
so a ``--cache``-enabled run serves warm ``compute-splits`` straight
from the ``.sbi`` sidecar and writes through on a miss.
"""

from __future__ import annotations


import numpy as np

from spark_bam_tpu.bgzf.find_block_start import find_block_start
from spark_bam_tpu.cli.app import CheckerContext
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.load.splits import Split
from spark_bam_tpu.sbi.format import (
    PLAN_NONE,
    PLAN_POS,
    PLAN_UNRESOLVED,
    PlanEntry,
)
from spark_bam_tpu.sbi.plan import plan_split_starts


def _plan_native(ctx: CheckerContext, split_size: int) -> list[PlanEntry] | None:
    """Per-boundary resolution via ``load.api._resolve_split_start``
    (native scan + exact confirmation; individual boundaries may demote
    to the Python oracle, staying correct). None when the native library
    is unavailable or the config pins ``backend=python`` — those callers
    get the vectorized whole-file pass instead."""
    from spark_bam_tpu.check.checker import NoReadFoundException
    from spark_bam_tpu.load.api import _resolve_split_start
    from spark_bam_tpu.load.splits import FileSplit
    from spark_bam_tpu.native.build import load_native

    if ctx.config.backend == "python" or load_native() is None:
        return None
    size = ctx.compressed_size
    header = ctx.header
    entries: list[PlanEntry] = []
    for s in range(0, size, split_size):
        fs = FileSplit(str(ctx.path), s, min(s + split_size, size))
        try:
            pos = _resolve_split_start(ctx.path, fs, header, ctx.config)
        except NoReadFoundException:
            # No read within max_read_size of this boundary.
            entries.append(PlanEntry(s, PLAN_UNRESOLVED, None))
            continue
        entries.append(
            PlanEntry(s, PLAN_NONE if pos is None else PLAN_POS, pos)
        )
    return entries


def _plan_vectorized(ctx: CheckerContext, split_size: int) -> list[PlanEntry]:
    """Boundary resolution against the whole-file eager verdicts."""
    size = ctx.compressed_size
    true_flat = ctx.true_flat_eager
    entries: list[PlanEntry] = []
    with open_channel(ctx.path) as ch:
        for s in range(0, size, split_size):
            e = min(s + split_size, size)
            block = find_block_start(
                ch, s, ctx.config.bgzf_blocks_to_check, path=ctx.path
            )
            if block >= e:
                entries.append(PlanEntry(s, PLAN_NONE, None))
                continue
            flat = ctx.view.flat_of_pos(block, 0)
            j = int(np.searchsorted(true_flat, flat))
            if j >= len(true_flat):
                entries.append(PlanEntry(s, PLAN_NONE, None))
                continue
            if true_flat[j] - flat >= ctx.config.max_read_size:
                # The live scan would exhaust its budget here.
                entries.append(PlanEntry(s, PLAN_UNRESOLVED, None))
                continue
            start = Pos(*ctx.view.pos_of_flat(int(true_flat[j])))
            entries.append(PlanEntry(s, PLAN_POS, start))
    return entries


def split_plan(ctx: CheckerContext, split_size: int) -> list[PlanEntry]:
    """The raw per-boundary plan, cache-aware: a valid ``.sbi`` sidecar
    serves it with zero checker work; a miss computes and (in a write
    mode) persists it."""
    config = ctx.config
    mode = config.cache_mode
    store = None
    if mode.enabled:
        from spark_bam_tpu.sbi.store import CacheStore

        store = CacheStore.from_env(policy=config.fault_policy)
        if mode.read:
            index = store.load(ctx.path, config, strict=mode.strict)
            if index is not None and split_size in index.split_plans:
                return index.split_plans[split_size]
    entries = _plan_native(ctx, split_size)
    if entries is None:
        entries = _plan_vectorized(ctx, split_size)
    if store is not None and mode.write:
        from spark_bam_tpu.sbi.format import SbiIndex, fingerprint_of

        store.merge_and_store(
            ctx.path, config,
            SbiIndex(
                fingerprint_of(ctx.path, config),
                split_plans={split_size: entries},
            ),
        )
    return entries


def spark_bam_splits(ctx: CheckerContext, split_size: int) -> list[Split]:
    entries = split_plan(ctx, split_size)
    starts, ends = plan_split_starts(entries, ctx.compressed_size)
    return [Split(s, e) for s, e in zip(starts, ends)]


def diff_splits(ours: list[Split], theirs: list[Split]) -> list[tuple[str, Split]]:
    """Ordered symmetric difference keyed on split *start* (the reference's
    orMerge on start Pos, ComputeSplits.scala:111-121). Tagged 'ours'/'theirs'."""
    our_by_start = {s.start: s for s in ours}
    their_by_start = {s.start: s for s in theirs}
    out: list[tuple[str, Split]] = []
    for start in sorted(set(our_by_start) | set(their_by_start)):
        o, t = our_by_start.get(start), their_by_start.get(start)
        if o is not None and t is not None:
            continue
        if t is not None:
            out.append(("theirs", t))
        else:
            out.append(("ours", o))
    return out
