"""Split computation for the CLI comparison commands.

``spark_bam_splits`` resolves each raw split boundary through the load
path's native tri-state window scan when built (bounded inflate per
boundary — the same engine ``load_bam`` uses; a whole-file pass for a
handful of boundaries is the wrong altitude at GB scale), else through
the vectorized eager engine of a ``CheckerContext`` (one flag pass
serves all boundaries — right for fixture-sized files and the only
option without the native library). Ends tile to the next start
(reference cli/.../spark/LoadReads.scala:164-174,
CanLoadBam.scala:262-274).
"""

from __future__ import annotations


import numpy as np

from spark_bam_tpu.bgzf.find_block_start import find_block_start
from spark_bam_tpu.cli.app import CheckerContext
from spark_bam_tpu.core.channel import open_channel
from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.load.splits import Split


def _splits_native(ctx: CheckerContext, split_size: int) -> list[Pos] | None:
    """Per-boundary resolution via ``load.api._resolve_split_start``
    (native scan + exact confirmation; individual boundaries may demote
    to the Python oracle, staying correct). None when the native library
    is unavailable or the config pins ``backend=python`` — those callers
    get the vectorized whole-file pass instead."""
    from spark_bam_tpu.check.checker import NoReadFoundException
    from spark_bam_tpu.load.api import _resolve_split_start
    from spark_bam_tpu.load.splits import FileSplit
    from spark_bam_tpu.native.build import load_native

    if ctx.config.backend == "python" or load_native() is None:
        return None
    size = ctx.compressed_size
    header = ctx.header
    starts: list[Pos] = []
    for s in range(0, size, split_size):
        fs = FileSplit(str(ctx.path), s, min(s + split_size, size))
        try:
            pos = _resolve_split_start(ctx.path, fs, header, ctx.config)
        except NoReadFoundException:
            continue  # no read within max_read_size of this boundary
        if pos is None:
            continue  # split owns no blocks, or clean EOF
        if not starts or starts[-1] != pos:
            starts.append(pos)
    return starts


def spark_bam_splits(ctx: CheckerContext, split_size: int) -> list[Split]:
    size = ctx.compressed_size
    starts = _splits_native(ctx, split_size)
    if starts is None:
        true_flat = ctx.true_flat_eager
        starts = []
        with open_channel(ctx.path) as ch:
            for s in range(0, size, split_size):
                e = min(s + split_size, size)
                block = find_block_start(
                    ch, s, ctx.config.bgzf_blocks_to_check, path=ctx.path
                )
                if block >= e:
                    continue
                flat = ctx.view.flat_of_pos(block, 0)
                j = int(np.searchsorted(true_flat, flat))
                if j >= len(true_flat):
                    continue
                if true_flat[j] - flat >= ctx.config.max_read_size:
                    continue
                start = Pos(*ctx.view.pos_of_flat(int(true_flat[j])))
                if not starts or starts[-1] != start:
                    starts.append(start)
    eof = Pos(size, 0)
    return [
        Split(start, starts[i + 1] if i + 1 < len(starts) else eof)
        for i, start in enumerate(starts)
    ]


def diff_splits(ours: list[Split], theirs: list[Split]) -> list[tuple[str, Split]]:
    """Ordered symmetric difference keyed on split *start* (the reference's
    orMerge on start Pos, ComputeSplits.scala:111-121). Tagged 'ours'/'theirs'."""
    our_by_start = {s.start: s for s in ours}
    their_by_start = {s.start: s for s in theirs}
    out: list[tuple[str, Split]] = []
    for start in sorted(set(our_by_start) | set(their_by_start)):
        o, t = our_by_start.get(start), their_by_start.get(start)
        if o is not None and t is not None:
            continue
        if t is not None:
            out.append(("theirs", t))
        else:
            out.append(("ours", o))
    return out
