"""metrics-report: render ``--metrics-out`` JSONL trace(s) as a human
report in the reference stats format (obs/report.py does the parsing and
formatting; this is just the CLI face).

One file renders the classic single-process report. Several files — the
per-worker traces a fabric run leaves when ``--metrics-out`` names a
directory — are merged: metric snapshots combine into one fleet view and
span events join across processes by ``trace_id``, so one serve request
reads as one tree (router relay → worker → tick → device dispatch).
"""

from __future__ import annotations

from spark_bam_tpu.cli.output import Printer
from spark_bam_tpu.obs.report import render_merged_report, render_report


def run(trace_paths, p: Printer) -> None:
    if isinstance(trace_paths, (str, bytes)) or not hasattr(
        trace_paths, "__iter__"
    ):
        trace_paths = [trace_paths]
    paths = list(trace_paths)
    if len(paths) == 1:
        p.echo(render_report(paths[0]))
    else:
        p.echo(render_merged_report(paths))
