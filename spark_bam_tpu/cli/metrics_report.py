"""metrics-report: render a ``--metrics-out`` JSONL trace as a human
report in the reference stats format (obs/report.py does the parsing and
formatting; this is just the CLI face)."""

from __future__ import annotations

from spark_bam_tpu.cli.output import Printer
from spark_bam_tpu.obs.report import render_report


def run(trace_path, p: Printer) -> None:
    p.echo(render_report(trace_path))
