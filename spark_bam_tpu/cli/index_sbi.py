"""``index``: build a ``.sbi`` split-index sidecar ahead of time.

The warm-start analog of hadoop-bam's ``.sbi`` writer: pay the block
scan + boundary resolution once, up front, so the first ``load_bam`` /
``compute-splits`` against the file is already served from the cache
(docs/caching.md). ``--record-starts`` additionally runs the vectorized
checker once over the whole file and indexes every record-start virtual
position — the section ``load.tpu_load.record_starts`` consumes.
"""

from __future__ import annotations

import os

from spark_bam_tpu.bgzf.index_blocks import blocks_metadata
from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.core.config import Config, format_bytes
from spark_bam_tpu.load.splits import file_splits
from spark_bam_tpu.sbi.format import (
    PLAN_POS,
    SbiIndex,
    encode_sbi,
    fingerprint_of,
    record_starts_to_virtual,
)
from spark_bam_tpu.sbi.plan import build_split_plan
from spark_bam_tpu.sbi.store import CacheStore


def run(
    path,
    p,
    split_size: int,
    config: Config = Config(),
    out=None,
    record_starts: bool = False,
) -> None:
    header = read_header(path)
    blocks = list(blocks_metadata(path))
    splits = file_splits(path, split_size)
    entries = build_split_plan(path, splits, header, config)
    index = SbiIndex(
        fingerprint_of(path, config),
        blocks=blocks,
        split_plans={split_size: entries},
    )
    n_record_starts = None
    if record_starts:
        from spark_bam_tpu.load.tpu_load import record_starts as tpu_starts

        # Cache off for the inner call: this IS the build, and recursing
        # into a half-written sidecar would be circular.
        result = tpu_starts(path, config.replace(cache=""))
        index.record_starts = record_starts_to_virtual(
            result.view, result.starts
        )
        n_record_starts = len(result.starts)

    if out is not None:
        # Explicit destination: plain atomic write, no store semantics.
        tmp = f"{out}.tmp{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(encode_sbi(index))
            os.replace(tmp, out)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        dest = str(out)
    else:
        dest = CacheStore.from_env(policy=config.fault_policy).merge_and_store(
            path, config, index
        )
        if dest is None:
            p.echo(
                f"error: cannot place a sidecar for {path} "
                "(remote BAM without SPARK_BAM_CACHE_DIR)"
            )
            return
    resolved = sum(1 for e in entries if e.kind == PLAN_POS)
    parts = [
        f"{len(blocks)} blocks",
        f"split plan @{format_bytes(split_size)} "
        f"({len(entries)} boundaries, {resolved} resolved)",
    ]
    if n_record_starts is not None:
        parts.append(f"{n_record_starts} record starts")
    p.echo(f"Wrote {dest}: " + ", ".join(parts))
