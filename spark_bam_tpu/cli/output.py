"""CLI output helpers: echo / limited sample printing / indentation.

Mirrors the reference's hammerlab print utils semantics: sampled lists print
``{total} things:`` when everything fits the print limit, else
``First {limit} of {total} things:`` followed by a tab-ellipsis line.
"""

from __future__ import annotations

import sys


class UsageError(ValueError):
    """Operator-facing flag/argument misuse: rendered by the CLI as a
    one-line ``error: ...`` with exit code 2 (library failures keep their
    tracebacks)."""


class Printer:
    def __init__(self, out=None, limit: int = 10):
        self.out = out or sys.stdout
        self.limit = limit
        self._indent = 0

    def echo(self, *lines: str) -> None:
        for line in lines:
            for part in str(line).split("\n"):
                self.out.write(("\t" * self._indent + part + "\n") if part else "\n")

    def indent(self):
        printer = self

        class _Ctx:
            def __enter__(self):
                printer._indent += 1

            def __exit__(self, *exc):
                printer._indent -= 1

        return _Ctx()

    def print_limited(
        self,
        items: list,
        total: int | None = None,
        header: str | None = None,
        truncated_header=None,
        item_indent: int = 1,
    ) -> None:
        """Print up to ``limit`` items, each tab-indented, with the
        appropriate header and an ellipsis line when truncated."""
        total = total if total is not None else len(items)
        if self.limit and total > self.limit:
            shown = items[: self.limit]
            if truncated_header:
                self.echo(truncated_header(len(shown)))
            for item in shown:
                self.echo("\t" * item_indent + str(item))
            self.echo("\t…")
        else:
            if header:
                self.echo(header)
            for item in items[:total]:
                self.echo("\t" * item_indent + str(item))
