"""check-bam: evaluate two checkers at every uncompressed position.

Default compares spark-bam's eager checker against the seqdoop
(hadoop-bam-semantics) checker; ``-s``/``-u`` score eager/seqdoop against
the ``.records`` ground truth (reference cli/.../check/eager/CheckBam.scala).
``--sharded`` runs the mesh-scale streaming path instead (verdicts vs the
``.records`` truth across every device, O(window) host memory) and prints
a compact confusion summary — the operator face of
``parallel.stream_mesh.check_bam_sharded``.
"""

from __future__ import annotations

from spark_bam_tpu.cli.app import CheckerContext
from spark_bam_tpu.cli.output import UsageError


def run(
    ctx: CheckerContext,
    spark_bam: bool = False,
    hadoop_bam: bool = False,
    sharded: bool = False,
) -> None:
    if sharded:
        # --sharded IS eager-vs-truth (the -s scoring) at mesh scale, so
        # -s composes; -u (seqdoop oracle) and -i (byte ranges) have no
        # sharded implementation — reject rather than silently ignore.
        if hadoop_bam:
            raise UsageError(
                "--sharded scores the eager checker against the .records "
                "truth; the seqdoop oracle (-u) has no sharded path"
            )
        if ctx.ranges is not None:
            raise UsageError(
                "--sharded checks the whole file; -i/--intervals is not "
                "supported on the sharded path"
            )
        _run_sharded(ctx)
        return
    if spark_bam and not hadoop_bam:
        expected, actual = ctx.truth, ctx.eager_verdict
    elif hadoop_bam and not spark_bam:
        expected, actual = ctx.truth, ctx.seqdoop_verdict
    else:
        expected, actual = ctx.eager_verdict, ctx.seqdoop_verdict
    ctx.print_header_and_confusion(expected, actual)
    _print_cache_status(ctx)
    _print_funnel_status(ctx, device=False)


def _print_cache_status(ctx: CheckerContext) -> None:
    """check-bam doesn't consume the split cache, so this probes the
    sidecar: the operator sees whether the next load would be warm and,
    if not, why (docs/caching.md)."""
    from spark_bam_tpu.sbi.store import cache_status_line

    ctx.printer.echo(cache_status_line(ctx.path, ctx.config))


def _print_funnel_status(
    ctx: CheckerContext, device: bool = True, stats: dict | None = None
) -> None:
    from spark_bam_tpu.cli.app import funnel_status_line

    ctx.printer.echo(funnel_status_line(ctx.config, stats=stats, device=device))


def _run_sharded(ctx: CheckerContext) -> None:
    from spark_bam_tpu.bgzf.index_blocks import blocks_metadata
    from spark_bam_tpu.cli.app import print_report_header
    from spark_bam_tpu.parallel.stream_mesh import check_bam_sharded
    from spark_bam_tpu.utils.timer import heartbeat_progress

    metas = list(blocks_metadata(ctx.path))  # one scan: stats + sizes
    with heartbeat_progress(f"check-bam --sharded {ctx.path}") as progress:
        stats = check_bam_sharded(
            ctx.path, ctx.config, metas=metas, progress=progress
        )
    # Golden semantics: sum of data blocks, excluding the EOF sentinel
    # (the reference's compressedSizeAccumulator) — NOT the raw file size.
    compressed = sum(m.compressed_size for m in metas)
    num_reads = stats["true_positives"] + stats["false_negatives"]
    p = ctx.printer
    print_report_header(p, stats["positions"], compressed, num_reads)
    p.echo(f"checked across {stats['devices']} device(s)")
    _print_cache_status(ctx)
    # Mesh steps psum record-scale counters only, so no survivor totals
    # here — the line reports the mode the device step actually ran with.
    _print_funnel_status(ctx)
    if not stats["false_positives"] and not stats["false_negatives"]:
        p.echo("All calls matched!")
        return
    p.echo(
        f"{stats['false_positives']} false positives, "
        f"{stats['false_negatives']} false negatives"
    )
