"""check-bam: evaluate two checkers at every uncompressed position.

Default compares spark-bam's eager checker against the seqdoop
(hadoop-bam-semantics) checker; ``-s``/``-u`` score eager/seqdoop against
the ``.records`` ground truth (reference cli/.../check/eager/CheckBam.scala).
"""

from __future__ import annotations

from spark_bam_tpu.cli.app import CheckerContext


def run(ctx: CheckerContext, spark_bam: bool = False, hadoop_bam: bool = False) -> None:
    if spark_bam and not hadoop_bam:
        expected, actual = ctx.truth, ctx.eager_verdict
    elif hadoop_bam and not spark_bam:
        expected, actual = ctx.truth, ctx.seqdoop_verdict
    else:
        expected, actual = ctx.eager_verdict, ctx.seqdoop_verdict
    ctx.print_header_and_confusion(expected, actual)
