"""count-reads: count via spark-bam and hadoop-bam loaders, compare
(reference cli/.../spark/compare/CountReads.scala:20-131)."""

from __future__ import annotations

from spark_bam_tpu.cli.output import Printer, UsageError
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.load.api import load_bam, load_reads
from spark_bam_tpu.load.hadoop import hadoop_bam_count
from spark_bam_tpu.utils.timer import Timer


def run(
    path,
    p: Printer,
    split_size: int,
    config: Config = Config(),
    spark_bam_first: bool = False,
    iterations: int = 1,
    reference=None,
    sharded: bool = False,
    resident: bool = False,
) -> None:
    def timed_loop(count_fn):
        """The no-competitor output shape shared by every standalone mode
        (resident / sharded / CRAM): N timed counts, no hadoop-bam leg.
        The named Timer feeds the ``timer.count_reads.spark_bam``
        histogram when a registry is live; output format is unchanged."""
        for _ in range(max(iterations, 1)):
            with Timer("count_reads.spark_bam") as t:
                count = count_fn()
            p.echo(f"spark-bam read-count time: {int(t.ms)}")
            p.echo(f"Read count: {count}", "")

    is_cram = str(path).endswith(".cram")
    if resident and sharded:
        raise UsageError("--resident and --sharded are mutually exclusive")
    if resident and is_cram:
        raise UsageError(
            "--resident supports BAM only: CRAM has no BGZF block "
            "structure to window (use the default count-reads path)"
        )
    if (resident or config.resident_scan) and not is_cram and not sharded:
        # Single-device streaming count in resident-scan mode: windows
        # packed into HBM chunks, one dispatch per chunk — the remote-
        # device configuration. A config-level opt-in (env/dict) applies
        # only where the mode exists, so CRAM counting is unaffected.
        from spark_bam_tpu.cli.app import funnel_status_line
        from spark_bam_tpu.tpu.stream_check import StreamChecker

        checker = StreamChecker(path, config)
        timed_loop(checker.count_reads_resident)
        p.echo(funnel_status_line(config, stats=checker.funnel_stats), "")
        return
    if sharded:
        # Mesh-scale streaming count across every device (no hadoop-bam
        # leg: this is the scale mode; the comparison mode is the default).
        if is_cram:
            raise UsageError(
                "--sharded supports BAM only: CRAM has no BGZF block "
                "structure to window (use the default count-reads path)"
            )
        from spark_bam_tpu.parallel.stream_mesh import count_reads_sharded
        from spark_bam_tpu.utils.timer import heartbeat_progress

        def sharded_once():
            with heartbeat_progress(
                f"count-reads --sharded {path}"
            ) as progress:
                return count_reads_sharded(path, config, progress=progress)

        timed_loop(sharded_once)
        return
    if is_cram:
        # No hadoop-bam leg for CRAM (the reference delegates CRAM entirely;
        # there is no competitor count to diff against). ``reference`` (-F)
        # enables RR=true files with external references.
        timed_loop(
            lambda: load_reads(
                path, split_size, config, reference=reference
            ).count()
        )
        return

    def run_once():
        with Timer("count_reads.spark_bam") as t:
            spark_count = load_bam(path, split_size, config).count()
        spark_ms = int(t.ms)
        try:
            with Timer("count_reads.hadoop_bam") as t:
                hadoop_count = hadoop_bam_count(path, split_size, config)
            return spark_ms, spark_count, int(t.ms), hadoop_count, None
        except Exception as e:
            return spark_ms, spark_count, None, None, e

    results = [run_once() for _ in range(max(iterations, 1))]
    for spark_ms, spark_count, hadoop_ms, hadoop_count, error in results:
        p.echo(f"spark-bam read-count time: {spark_ms}")
        if error is None:
            p.echo(f"hadoop-bam read-count time: {hadoop_ms}", "")
            if spark_count == hadoop_count:
                p.echo(f"Read counts matched: {spark_count}", "")
            else:
                p.echo(
                    f"Read counts mismatched: {spark_count} via spark-bam,"
                    f" {hadoop_count} via hadoop-bam",
                    "",
                )
        else:
            p.echo(
                "",
                f"spark-bam found {spark_count} reads, hadoop-bam threw exception:",
                f"{type(error).__module__}.{type(error).__name__}: {error}",
            )
