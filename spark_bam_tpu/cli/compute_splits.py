"""compute-splits: compute (and compare) spark-bam / hadoop-bam splits
(reference cli/.../spark/ComputeSplits.scala:17-151)."""

from __future__ import annotations

import time

from spark_bam_tpu.cli.app import CheckerContext
from spark_bam_tpu.cli.splits_util import diff_splits, spark_bam_splits
from spark_bam_tpu.core.stats import Stats
from spark_bam_tpu.load.hadoop import hadoop_bam_splits
from spark_bam_tpu.load.splits import Split


def _print_splits(p, splits: list[Split], ratio: float) -> None:
    stats = Stats([s.length(ratio) for s in splits])
    p.echo("Split-size distribution:", stats.show(), "")
    p.print_limited(
        [f"{s.start}-{s.end}" for s in splits],
        header=f"{len(splits)} splits:",
        truncated_header=lambda n: f"First {n} of {len(splits)} splits:",
    )
    p.echo("")


def print_host_plan(ctx: CheckerContext, num_hosts: int, devices_per_host: int) -> None:
    """The N-host sharded-run IO plan: per-host compressed byte ranges
    (incl. halo seam overlap) and owned uncompressed spans — what a
    scheduler needs to place processes near data (the reference's
    ``SplitRDD.preferredLocations`` role, SplitRDD.scala:43-79)."""
    from spark_bam_tpu.core.config import format_bytes
    from spark_bam_tpu.parallel.stream_mesh import host_shard_plan

    plan = host_shard_plan(
        ctx.path, num_hosts, devices_per_host, config=ctx.config
    )
    p = ctx.printer
    p.echo(f"{num_hosts}-host plan ({devices_per_host} devices/host):")
    for row in plan:
        lo, hi = row["compressed_range"]
        g0, g1 = row["groups"]
        p.echo(
            f"\thost {row['host']}: bytes [{lo}, {hi}) "
            f"({format_bytes(hi - lo)} read, "
            f"{format_bytes(row['uncompressed'])} owned uncompressed, "
            f"rows {g0}-{g1})"
        )
    p.echo("")


def _print_cache_status(ctx: CheckerContext) -> None:
    """Why this run was warm or cold (hit/miss/invalidated + reason) —
    the operator-facing face of the split-index cache (docs/caching.md)."""
    from spark_bam_tpu.sbi.store import cache_status_line

    ctx.printer.echo(cache_status_line(ctx.path, ctx.config))


def run(
    ctx: CheckerContext,
    split_size: int,
    spark_bam: bool = False,
    hadoop_bam: bool = False,
) -> None:
    p = ctx.printer
    ratio = ctx.config.estimated_compression_ratio

    def timed_spark():
        t0 = time.perf_counter()
        splits = spark_bam_splits(ctx, split_size)
        return int((time.perf_counter() - t0) * 1000), splits

    def timed_hadoop():
        t0 = time.perf_counter()
        splits = hadoop_bam_splits(ctx.path, split_size, config=ctx.config)
        return int((time.perf_counter() - t0) * 1000), splits

    if hadoop_bam and not spark_bam:
        ms, splits = timed_hadoop()
        p.echo(f"Get hadoop-bam splits: {ms}ms", "")
        _print_splits(p, splits, ratio)
    elif spark_bam and not hadoop_bam:
        ms, splits = timed_spark()
        p.echo(f"Get spark-bam splits: {ms}ms")
        _print_cache_status(ctx)
        p.echo("")
        _print_splits(p, splits, ratio)
    else:
        our_ms, ours = timed_spark()
        p.echo(f"Get spark-bam splits: {our_ms}ms")
        _print_cache_status(ctx)
        their_ms, theirs = timed_hadoop()
        p.echo(f"Get hadoop-bam splits: {their_ms}ms")
        p.echo("")
        diffs = diff_splits(ours, theirs)
        if diffs:
            rows = [
                f"\t{s.start}-{s.end}" if side == "theirs" else f"{s.start}-{s.end}"
                for side, s in diffs
            ]
            p.print_limited(
                rows,
                header=f"{len(diffs)} splits differ (totals: {len(ours)}, {len(theirs)}):",
                truncated_header=lambda n: (
                    f"First {n} of {len(diffs)} splits that differ"
                    f" (totals: {len(ours)}, {len(theirs)}):"
                ),
            )
            p.echo("")
        else:
            p.echo("All splits matched!", "")
            _print_splits(p, ours, ratio)
