"""Batched BAM record parsing on device.

Replaces per-record codec decoding (HTSJDK ``BAMRecordCodec`` in the
reference, RecordStream.scala:48-57) with columnar gathers: given a flat
uncompressed buffer and the record-start offsets the checker produced, every
fixed field of every record is extracted in one fused gather pass, and
interval/flag filters evaluate on-device so only surviving rows return to
the host (BASELINE.json: "returns parsed reads with interval/flag filters
already applied on-device").

Reference spans (for interval overlap) come from a bounded on-device cigar
scan: records with more than ``CIGAR_SCAN_CAP`` ops are flagged and finished
on host — the same escape-不-guess policy as the checker.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

CIGAR_SCAN_CAP = 64  # ops scanned on device; beyond ⇒ host fallback

_I32 = jnp.int32

# Cigar ops that consume reference bases: M, D, N, =, X.
_REF_CONSUMING = (1 << 0) | (1 << 2) | (1 << 3) | (1 << 7) | (1 << 8)


def _u32(p, idx):
    return (
        jnp.take(p, idx, mode="clip").astype(jnp.uint32)
        | (jnp.take(p, idx + 1, mode="clip").astype(jnp.uint32) << 8)
        | (jnp.take(p, idx + 2, mode="clip").astype(jnp.uint32) << 16)
        | (jnp.take(p, idx + 3, mode="clip").astype(jnp.uint32) << 24)
    )


def _i32(p, idx):
    return lax.bitcast_convert_type(_u32(p, idx), jnp.int32)


@functools.partial(jax.jit, static_argnames=("cigar_cap",))
def parse_records(
    padded: jnp.ndarray,   # (N+pad,) uint8 flat uncompressed bytes
    starts: jnp.ndarray,   # (M,) int32 record-start offsets (padding: -1)
    cigar_cap: int = CIGAR_SCAN_CAP,
):
    """Columnar fixed-field extraction for M records in one pass.

    Returns a dict of (M,) arrays; ``valid`` masks real rows, ``span_exact``
    marks rows whose reference span was fully resolved on device.
    """
    valid = starts >= 0
    s = jnp.maximum(starts, 0)

    block_size = _i32(padded, s)
    ref_id = _i32(padded, s + 4)
    pos = _i32(padded, s + 8)
    lnm = _u32(padded, s + 12)
    l_read_name = (lnm & 0xFF).astype(_I32)
    mapq = ((lnm >> 8) & 0xFF).astype(_I32)
    bin_ = ((lnm >> 16) & 0xFFFF).astype(_I32)
    fnc = _u32(padded, s + 16)
    n_cigar = (fnc & 0xFFFF).astype(_I32)
    flag = (fnc >> 16).astype(_I32)
    l_seq = _i32(padded, s + 20)
    next_ref_id = _i32(padded, s + 24)
    next_pos = _i32(padded, s + 28)
    tlen = _i32(padded, s + 32)

    # Bounded cigar scan: ref span = Σ len over ref-consuming ops.
    cig_start = s + 36 + l_read_name
    ks = jnp.arange(cigar_cap, dtype=_I32)

    def span_at(cig_start_m, n_cigar_m):
        ops = _u32(padded, cig_start_m[:, None] + 4 * ks[None, :])
        op = (ops & 0xF).astype(_I32)
        length = lax.bitcast_convert_type(ops >> 4, jnp.int32)
        consumes = ((_I32(_REF_CONSUMING) >> op) & 1) == 1
        in_range = ks[None, :] < n_cigar_m[:, None]
        return jnp.sum(jnp.where(consumes & in_range, length, 0), axis=1)

    span = span_at(cig_start, n_cigar)
    span_exact = n_cigar <= cigar_cap

    return {
        "valid": valid,
        "block_size": block_size,
        "ref_id": ref_id,
        "pos": pos,
        "l_read_name": l_read_name,
        "mapq": mapq,
        "bin": bin_,
        "n_cigar": n_cigar,
        "flag": flag,
        "l_seq": l_seq,
        "next_ref_id": next_ref_id,
        "next_pos": next_pos,
        "tlen": tlen,
        "name_offset": s + 36,
        "ref_span": span,
        "span_exact": span_exact,
    }


@functools.partial(jax.jit, static_argnames=())
def interval_flag_filter(
    cols: dict,
    intervals: jnp.ndarray,      # (R, 3) int32 rows of (ref_id, start, end)
    flags_required: jnp.ndarray,  # () int32: all these bits must be set
    flags_forbidden: jnp.ndarray,  # () int32: none of these bits may be set
):
    """On-device record filter: genomic interval overlap + SAM flag masks.

    Unmapped reads never overlap an interval (reference loadBamIntervals
    region semantics, CanLoadBam.scala:109-133).
    """
    pos = cols["pos"]
    span = jnp.maximum(cols["ref_span"], 1)
    end = pos + span
    ref = cols["ref_id"]
    mapped = (cols["flag"] & 4) == 0

    ivs_ref = intervals[:, 0][None, :]
    ivs_start = intervals[:, 1][None, :]
    ivs_end = intervals[:, 2][None, :]
    overlap = (
        (ref[:, None] == ivs_ref)
        & (pos[:, None] < ivs_end)
        & (ivs_start < end[:, None])
    ).any(axis=1)

    flag = cols["flag"]
    flag_ok = ((flag & flags_required) == flags_required) & ((flag & flags_forbidden) == 0)
    return cols["valid"] & mapped & (ref >= 0) & overlap & flag_ok


_SEQ_CODES = "=ACMGRSVTWYHKDBN"


@dataclass
class ReadBatch:
    """Columnar batch of parsed records (host-side numpy views).

    Fixed fields live in ``columns``; variable-length payloads (name, seq,
    qual) materialize lazily from the flat buffer on demand.
    """

    columns: dict[str, np.ndarray]
    starts: np.ndarray
    buf: np.ndarray | None = None

    def __len__(self) -> int:
        return int(self.columns["valid"].sum())

    def __getitem__(self, key: str) -> np.ndarray:
        return self.columns[key][self.columns["valid"]]

    # ---- lazy variable-length payloads (row index is pre-filter) ----
    def name(self, i: int) -> str:
        off = int(self.columns["name_offset"][i])
        ln = int(self.columns["l_read_name"][i])
        return bytes(self.buf[off: off + ln - 1]).decode("latin-1")

    def seq(self, i: int) -> str:
        off = (
            int(self.columns["name_offset"][i])
            + int(self.columns["l_read_name"][i])
            + 4 * int(self.columns["n_cigar"][i])
        )
        n = int(self.columns["l_seq"][i])
        packed = self.buf[off: off + (n + 1) // 2]
        return "".join(
            _SEQ_CODES[(packed[k >> 1] >> (4 if k % 2 == 0 else 0)) & 0xF]
            for k in range(n)
        )

    def qual(self, i: int) -> bytes:
        n = int(self.columns["l_seq"][i])
        off = (
            int(self.columns["name_offset"][i])
            + int(self.columns["l_read_name"][i])
            + 4 * int(self.columns["n_cigar"][i])
            + (n + 1) // 2
        )
        return bytes(self.buf[off: off + n])


def _next_pow2(n: int) -> int:
    return 1 << max(0, (max(n, 1) - 1).bit_length())


def parse_flat_records(
    buf: np.ndarray, starts: np.ndarray, pad: int = 300_000
) -> ReadBatch:
    """Host entry: pad the buffer, run the device parser, fix up any rows
    whose cigar exceeded the device scan cap.

    Both the buffer and the starts row count pad to powers of two so the
    jit sees at most log2 distinct shapes — without this, every streaming
    window's slightly-different size would trigger a fresh XLA compile
    (the same discipline as the checker's pow2 kernel windows). The
    bucket is ``pow2(len) + pad`` rather than ``pow2(len + pad)``: the
    same O(log) compile bound without nearly doubling the allocation and
    H2D transfer for pow2-sized windows."""
    padded = np.zeros(_next_pow2(len(buf)) + pad, dtype=np.uint8)
    padded[: len(buf)] = buf
    m = len(starts)
    starts_padded = np.full(_next_pow2(m), -1, dtype=np.int32)
    starts_padded[:m] = starts.astype(np.int32)
    cols = parse_records(jnp.asarray(padded), jnp.asarray(starts_padded))
    cols = {k: np.asarray(v)[:m] for k, v in cols.items()}
    inexact = np.flatnonzero(cols["valid"] & ~cols["span_exact"])
    if len(inexact):
        from spark_bam_tpu.bam.record import BamRecord

        for i in inexact:
            rec, _ = BamRecord.decode(buf, int(starts[i]))
            cols["ref_span"][i] = rec.reference_span()
        cols["span_exact"][inexact] = True
    return ReadBatch(cols, starts, buf=np.asarray(buf))
