"""TPU-vectorized record-boundary checker.

The JAX twin of check/vectorized.py (same two-pass algorithm — see that
module's docstring for the design; the NumPy engine is the differential
oracle for this one). Everything here is shape-static and jit-compiled:

- window size ``W`` and ``reads_to_check`` are static; the *valid* byte count
  ``n`` and ``at_eof`` flag are traced scalars, so one compiled kernel serves
  every window of a file including the tail.
- all integer work is int32 (TPU-native); the reference's JVM int32 wrap
  semantics come for free, truncating division is ``lax.div``.
- the chain walk's logical cursor is clamped into sentinel ranges when a
  pathological length-prefix would overflow int32; affected lanes are
  reported inexact and re-checked on host (exactness is never silently lost).

Mapping to the hardware: the flag pass is elementwise VPU work + two
prefix-sum scans that XLA fuses over the window; the chain walk is
``reads_to_check`` gather rounds. Candidate independence (SURVEY.md §2.8
item 6) is what makes the whole battery data-parallel.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_bam_tpu.check.flags import BIT
from spark_bam_tpu.check.vectorized import DEFINITIVE_MASK, ESCAPE_MASK

# Padding beyond any index the flag pass can touch (36 fixed + 255 name +
# 4*65535 cigar + slack), rounded up to a multiple of 1024 so it can double
# as the Pallas slab halo (Mosaic DMA slices tile at 1024 elements) and of 4
# for the stride-4 scan. 257*1024 = 263168 ≥ 262431.
PAD = 257 * 1024

_I32 = jnp.int32


def _i32_at(p: jnp.ndarray, w: int) -> jnp.ndarray:
    """Little-endian u32 at every byte offset of the padded buffer."""
    u = (
        p[:-3].astype(jnp.uint32)
        | (p[1:-2].astype(jnp.uint32) << 8)
        | (p[2:-1].astype(jnp.uint32) << 16)
        | (p[3:].astype(jnp.uint32) << 24)
    )
    return u


def _ref_pos_bits(idx, pos, c, len_at, b_neg_idx, b_large_idx, b_neg_pos, b_large_pos):
    neg_idx = idx < -1
    large_idx = (~neg_idx) & (idx >= c)
    neg_pos = pos < -1
    idx_ok = (~neg_idx) & (~large_idx)
    large_pos = idx_ok & (~neg_pos) & (idx >= 0) & (pos > len_at)
    return (
        jnp.where(neg_idx, _I32(b_neg_idx), _I32(0))
        | jnp.where(large_idx, _I32(b_large_idx), _I32(0))
        | jnp.where(neg_pos, _I32(b_neg_pos), _I32(0))
        | jnp.where(large_pos, _I32(b_large_pos), _I32(0))
    )


def _compute_flags(p, lengths, num_contigs, n):
    """Flag pass over a (W+PAD,)-byte padded buffer; returns F (the 19-bit
    mask per position). ``remaining``/``body_end`` live in ``_compute_misc``
    — shared with the Pallas flag path; XLA CSEs the overlapping slices."""
    w = p.shape[0] - PAD
    u = _i32_at(p, w)
    i32 = lax.bitcast_convert_type(u, jnp.int32)

    remaining = i32[0:w]
    ref_idx = i32[4: w + 4]
    ref_pos = i32[8: w + 8]
    name_len = p[12: w + 12].astype(_I32)  # i32 & 0xff ⇒ the low byte
    fnc = u[16: w + 16]
    n_cigar = (fnc & 0xFFFF).astype(_I32)
    mapped = ((fnc >> 18) & 1) == 0
    seq_len = i32[20: w + 20]
    next_ref_idx = i32[24: w + 24]
    next_ref_pos = i32[28: w + 28]

    c = num_contigs
    cmax = lengths.shape[0]
    len_r = jnp.take(lengths, jnp.clip(ref_idx, 0, cmax - 1), mode="clip")
    len_n = jnp.take(lengths, jnp.clip(next_ref_idx, 0, cmax - 1), mode="clip")

    F = _ref_pos_bits(
        ref_idx, ref_pos, c, len_r,
        BIT["negativeReadIdx"], BIT["tooLargeReadIdx"],
        BIT["negativeReadPos"], BIT["tooLargeReadPos"],
    )
    F = F | _ref_pos_bits(
        next_ref_idx, next_ref_pos, c, len_n,
        BIT["negativeNextReadIdx"], BIT["tooLargeNextReadIdx"],
        BIT["negativeNextReadPos"], BIT["tooLargeNextReadPos"],
    )

    # Implied-size consistency: JVM int32 wrap + truncation toward zero.
    t = seq_len + _I32(1)
    half = lax.div(t, _I32(2))
    rhs = _I32(32) + name_len + _I32(4) * n_cigar + half + seq_len
    F = F | jnp.where(remaining < rhs, _I32(BIT["tooFewRemainingBytesImplied"]), _I32(0))

    idx = jnp.arange(w, dtype=_I32)
    name_start = idx + 36
    name_end = name_start + name_len
    has_name = name_len >= 2
    F = F | jnp.where(name_len == 0, _I32(BIT["noReadName"]), _I32(0))
    F = F | jnp.where(name_len == 1, _I32(BIT["emptyReadName"]), _I32(0))

    name_eof = has_name & (name_end > n)
    F = F | jnp.where(name_eof, _I32(BIT["tooFewBytesForReadName"]), _I32(0))

    name_in = has_name & (~name_eof)
    last_idx = name_end - 1
    last_byte = jnp.take(p, last_idx, mode="clip")
    non_null = name_in & (last_byte != 0)
    F = F | jnp.where(non_null, _I32(BIT["nonNullTerminatedReadName"]), _I32(0))

    allowed = ((p >= 0x21) & (p <= 0x7E) & (p != 0x40)).astype(_I32)
    acc = jnp.concatenate([jnp.zeros(1, _I32), jnp.cumsum(allowed, dtype=_I32)])
    good = jnp.take(acc, last_idx, mode="clip") - jnp.take(acc, name_start, mode="clip")
    bad_chars = name_in & (~non_null) & (good != name_len - 1)
    F = F | jnp.where(bad_chars, _I32(BIT["nonASCIIReadName"]), _I32(0))

    # Cigar: stride-4 suffix sums of bad-op indicators (op = low nibble of the
    # int's first byte). Ints are readable only when fully inside the valid n.
    j = jnp.arange(p.shape[0], dtype=_I32)
    bad_op = (((p & 0xF) > 8) & (j + 4 <= n)).astype(_I32)
    b4 = bad_op.reshape(-1, 4)
    B = jnp.flip(jnp.cumsum(jnp.flip(b4, 0), axis=0, dtype=_I32), 0).reshape(-1)

    cig_start = name_start + jnp.where(name_in, name_len, _I32(0))
    cig_end = cig_start + _I32(4) * n_cigar
    cig_considered = ~name_eof
    bad_count = jnp.take(B, cig_start, mode="clip") - jnp.take(B, cig_end, mode="clip")
    has_bad = cig_considered & (bad_count > 0)
    F = F | jnp.where(has_bad, _I32(BIT["invalidCigarOp"]), _I32(0))
    cig_eof = cig_considered & (~has_bad) & (cig_end > n)
    F = F | jnp.where(cig_eof, _I32(BIT["tooFewBytesForCigarOps"]), _I32(0))
    empty_ok = cig_considered & (~has_bad) & (~cig_eof) & mapped
    empty_seq = empty_ok & (seq_len == 0)
    empty_cig = empty_ok & (n_cigar == 0)
    some_empty = empty_seq | empty_cig
    # Swapped on purpose: reference quirk (see check/vectorized.py).
    F = F | jnp.where(some_empty & empty_seq, _I32(BIT["emptyMappedCigar"]), _I32(0))
    F = F | jnp.where(some_empty & empty_cig, _I32(BIT["emptyMappedSeq"]), _I32(0))

    few_fixed = idx > n - 36
    F = jnp.where(few_fixed, _I32(BIT["tooFewFixedBlockBytes"]), F)
    return F


def _compute_misc(p, n):
    """remaining + body_end only (the non-flag outputs of the flag pass) —
    what the chain walk still needs when the Pallas kernel supplies F."""
    w = p.shape[0] - PAD
    u = _i32_at(p, w)
    i32 = lax.bitcast_convert_type(u, jnp.int32)
    remaining = i32[0:w]
    name_len = p[12: w + 12].astype(_I32)
    n_cigar = (u[16: w + 16] & 0xFFFF).astype(_I32)
    idx = jnp.arange(w, dtype=_I32)
    has_name = name_len >= 2
    name_eof = has_name & (idx + 36 + name_len > n)
    name_in = has_name & (~name_eof)
    cig_start = idx + 36 + jnp.where(name_in, name_len, _I32(0))
    few_fixed = idx > n - 36
    body_end = jnp.where(
        few_fixed,
        idx + 36,
        cig_start + jnp.where(~name_eof, _I32(4) * n_cigar, _I32(0)),
    )
    return remaining, body_end


def _misc_at(p, n, pos):
    """``_compute_misc`` evaluated at arbitrary positions (K,) int32.

    The funnel walk needs remaining/body_end only at lane positions, so it
    gathers the seven fixed-block bytes there instead of materializing two
    full-width arrays; value-identical to indexing ``_compute_misc``'s
    outputs at ``pos`` (``pos`` pre-clipped to [0, w), PAD covers the +17)."""
    def byte(off):
        return jnp.take(p, pos + off, mode="clip").astype(jnp.uint32)

    u = byte(0) | (byte(1) << 8) | (byte(2) << 16) | (byte(3) << 24)
    remaining = lax.bitcast_convert_type(u, jnp.int32)
    name_len = byte(12).astype(_I32)
    n_cigar = (byte(16) | (byte(17) << 8)).astype(_I32)
    has_name = name_len >= 2
    name_eof = has_name & (pos + 36 + name_len > n)
    name_in = has_name & (~name_eof)
    cig_start = pos + 36 + jnp.where(name_in, name_len, _I32(0))
    few_fixed = pos > n - 36
    body_end = jnp.where(
        few_fixed,
        pos + 36,
        cig_start + jnp.where(~name_eof, _I32(4) * n_cigar, _I32(0)),
    )
    return remaining, body_end


# ---------------------------------------------------------------------------
# Candidate funnel: stage 0 = cheap prefilter over every position, stage 1 =
# compact survivors and deep-check only those. The prefilter evaluates ONLY
# fixed-block-derivable bits (remaining bounds, refID/pos ranges, name_len
# sanity, implied-size consistency) — no name-byte scans, no cigar scans — so
# it is provably a superset filter: every bit it can set is also set by the
# full pass at the same position, hence full-pass survivors (F == 0) always
# pass the prefilter. Deep-only bits (name charset/termination, cigar ops,
# empty-mapped) are evaluated once at candidate positions via K-sized gathers
# against word-level hierarchical tables (full-width cumsums cost ~60 ms per
# 8 MB window on CPU XLA; packed-u32 popcount prefixes cost ~3 ms).

_U32 = jnp.uint32


def _prefilter_flags(p, lengths, num_contigs, n):
    """Stage-0 funnel pass: the fixed-block-derivable subset of the 19 bits.

    Mirrors the corresponding prefix of ``_compute_flags`` exactly,
    including the ``tooFewFixedBlockBytes`` *overwrite* (not OR) — so at
    few-fixed positions the prefilter mask equals the full mask."""
    w = p.shape[0] - PAD
    u = _i32_at(p, w)
    i32 = lax.bitcast_convert_type(u, jnp.int32)
    remaining = i32[0:w]
    ref_idx = i32[4: w + 4]
    ref_pos = i32[8: w + 8]
    name_len = p[12: w + 12].astype(_I32)
    n_cigar = (u[16: w + 16] & 0xFFFF).astype(_I32)
    seq_len = i32[20: w + 20]
    next_ref_idx = i32[24: w + 24]
    next_ref_pos = i32[28: w + 28]

    c = num_contigs
    cmax = lengths.shape[0]
    len_r = jnp.take(lengths, jnp.clip(ref_idx, 0, cmax - 1), mode="clip")
    len_n = jnp.take(lengths, jnp.clip(next_ref_idx, 0, cmax - 1), mode="clip")
    F = _ref_pos_bits(
        ref_idx, ref_pos, c, len_r,
        BIT["negativeReadIdx"], BIT["tooLargeReadIdx"],
        BIT["negativeReadPos"], BIT["tooLargeReadPos"],
    )
    F = F | _ref_pos_bits(
        next_ref_idx, next_ref_pos, c, len_n,
        BIT["negativeNextReadIdx"], BIT["tooLargeNextReadIdx"],
        BIT["negativeNextReadPos"], BIT["tooLargeNextReadPos"],
    )
    t = seq_len + _I32(1)
    half = lax.div(t, _I32(2))
    rhs = _I32(32) + name_len + _I32(4) * n_cigar + half + seq_len
    F = F | jnp.where(remaining < rhs, _I32(BIT["tooFewRemainingBytesImplied"]), _I32(0))
    F = F | jnp.where(name_len == 0, _I32(BIT["noReadName"]), _I32(0))
    F = F | jnp.where(name_len == 1, _I32(BIT["emptyReadName"]), _I32(0))
    idx = jnp.arange(w, dtype=_I32)
    few_fixed = idx > n - 36
    F = jnp.where(few_fixed, _I32(BIT["tooFewFixedBlockBytes"]), F)
    return F


def _pack_bits(bits):
    """Pack a bool vector into uint32 words (lane = bit index), zero-padding
    the tail to a word boundary."""
    length = bits.shape[0]
    full = -(-length // 32) * 32
    if full != length:
        bits = jnp.concatenate([bits, jnp.zeros(full - length, dtype=bits.dtype)])
    lanes = jnp.arange(32, dtype=_U32)
    return jnp.sum(bits.reshape(-1, 32).astype(_U32) << lanes[None, :], axis=1)


def _funnel_tables(p, n):
    """Word-level hierarchical prefix tables for the deep checks: packed
    indicator bitmasks + exclusive per-word popcount prefixes. Exact
    per-position prefix counts are recovered at query time with one masked
    popcount, so no full-width cumsum is ever materialized."""
    allowed = (p >= 0x21) & (p <= 0x7E) & (p != 0x40)
    nwords = _pack_bits(allowed)
    nwpc = lax.population_count(nwords).astype(_I32)
    nwpre = jnp.cumsum(nwpc) - nwpc

    j = jnp.arange(p.shape[0], dtype=_I32)
    bad_op = ((p & 0xF) > 8) & (j + 4 <= n)
    cwords = _pack_bits(bad_op)
    cm = _U32(0x11111111)
    wpc4 = jnp.stack(
        [lax.population_count(cwords & (cm << c)).astype(_I32) for c in range(4)],
        axis=1,
    )
    cwpre4 = (jnp.cumsum(wpc4, axis=0) - wpc4).reshape(-1)  # flat: wi*4 + class
    return nwords, nwpre, cwords, cwpre4


def _allowed_before(nwords, nwpre, q):
    """# allowed read-name chars at byte positions < q."""
    wi = q >> 5
    r = (q & 31).astype(_U32)
    word = jnp.take(nwords, wi, mode="clip")
    part = lax.population_count(word & ((_U32(1) << r) - _U32(1)))
    return jnp.take(nwpre, wi, mode="clip") + part.astype(_I32)


def _badops_before(cwords, cwpre4, q, c):
    """# bad cigar-op bytes j < q with j ≡ c (mod 4)."""
    wi = q >> 5
    r = (q & 31).astype(_U32)
    word = jnp.take(cwords, wi, mode="clip")
    cmask = _U32(0x11111111) << c.astype(_U32)
    part = lax.population_count(word & cmask & ((_U32(1) << r) - _U32(1)))
    return jnp.take(cwpre4, wi * 4 + c, mode="clip") + part.astype(_I32)


def _deep_flags_at(p, lengths, num_contigs, n, tables, pos):
    """The full 19-bit mask of ``_compute_flags`` at arbitrary positions
    (K,), via K-sized slab gathers + the hierarchical tables. Field-for-field
    identical to the full pass (same overwrite, same reference quirks)."""
    nwords, nwpre, cwords, cwpre4 = tables
    total = p.shape[0]
    pc = jnp.clip(pos, 0, total - 36)
    slab = jnp.take(p, pc[:, None] + jnp.arange(36, dtype=_I32)[None, :], mode="clip")

    def i32at(off):
        u = (
            slab[:, off].astype(_U32)
            | (slab[:, off + 1].astype(_U32) << 8)
            | (slab[:, off + 2].astype(_U32) << 16)
            | (slab[:, off + 3].astype(_U32) << 24)
        )
        return lax.bitcast_convert_type(u, jnp.int32)

    remaining = i32at(0)
    ref_idx = i32at(4)
    ref_pos = i32at(8)
    name_len = slab[:, 12].astype(_I32)
    fnc = lax.bitcast_convert_type(i32at(16), _U32)
    n_cigar = (fnc & 0xFFFF).astype(_I32)
    mapped = ((fnc >> 18) & 1) == 0
    seq_len = i32at(20)
    next_ref_idx = i32at(24)
    next_ref_pos = i32at(28)

    c = num_contigs
    cmax = lengths.shape[0]
    len_r = jnp.take(lengths, jnp.clip(ref_idx, 0, cmax - 1), mode="clip")
    len_n = jnp.take(lengths, jnp.clip(next_ref_idx, 0, cmax - 1), mode="clip")
    F = _ref_pos_bits(
        ref_idx, ref_pos, c, len_r,
        BIT["negativeReadIdx"], BIT["tooLargeReadIdx"],
        BIT["negativeReadPos"], BIT["tooLargeReadPos"],
    )
    F = F | _ref_pos_bits(
        next_ref_idx, next_ref_pos, c, len_n,
        BIT["negativeNextReadIdx"], BIT["tooLargeNextReadIdx"],
        BIT["negativeNextReadPos"], BIT["tooLargeNextReadPos"],
    )
    t = seq_len + _I32(1)
    half = lax.div(t, _I32(2))
    rhs = _I32(32) + name_len + _I32(4) * n_cigar + half + seq_len
    F = F | jnp.where(remaining < rhs, _I32(BIT["tooFewRemainingBytesImplied"]), _I32(0))
    F = F | jnp.where(name_len == 0, _I32(BIT["noReadName"]), _I32(0))
    F = F | jnp.where(name_len == 1, _I32(BIT["emptyReadName"]), _I32(0))

    name_start = pos + 36
    name_end = name_start + name_len
    has_name = name_len >= 2
    name_eof = has_name & (name_end > n)
    F = F | jnp.where(name_eof, _I32(BIT["tooFewBytesForReadName"]), _I32(0))
    name_in = has_name & (~name_eof)
    last_idx = name_end - 1
    last_byte = jnp.take(p, jnp.clip(last_idx, 0, total - 1), mode="clip")
    non_null = name_in & (last_byte != 0)
    F = F | jnp.where(non_null, _I32(BIT["nonNullTerminatedReadName"]), _I32(0))
    good = (
        _allowed_before(nwords, nwpre, jnp.clip(last_idx, 0, total - 1))
        - _allowed_before(nwords, nwpre, jnp.clip(name_start, 0, total - 1))
    )
    bad_chars = name_in & (~non_null) & (good != name_len - 1)
    F = F | jnp.where(bad_chars, _I32(BIT["nonASCIIReadName"]), _I32(0))

    cig_start = name_start + jnp.where(name_in, name_len, _I32(0))
    cig_end = cig_start + _I32(4) * n_cigar
    cig_considered = ~name_eof
    ccls = cig_start & 3
    bad_count = (
        _badops_before(cwords, cwpre4, jnp.clip(cig_end, 0, total - 1), ccls)
        - _badops_before(cwords, cwpre4, jnp.clip(cig_start, 0, total - 1), ccls)
    )
    has_bad = cig_considered & (bad_count != 0)
    F = F | jnp.where(has_bad, _I32(BIT["invalidCigarOp"]), _I32(0))
    cig_eof = cig_considered & (~has_bad) & (cig_end > n)
    F = F | jnp.where(cig_eof, _I32(BIT["tooFewBytesForCigarOps"]), _I32(0))
    empty_ok = cig_considered & (~has_bad) & (~cig_eof) & mapped
    empty_seq = empty_ok & (seq_len == 0)
    empty_cig = empty_ok & (n_cigar == 0)
    some_empty = empty_seq | empty_cig
    # Swapped on purpose: reference quirk (see check/vectorized.py).
    F = F | jnp.where(some_empty & empty_seq, _I32(BIT["emptyMappedCigar"]), _I32(0))
    F = F | jnp.where(some_empty & empty_cig, _I32(BIT["emptyMappedSeq"]), _I32(0))

    few_fixed = pos > n - 36
    F = jnp.where(few_fixed, _I32(BIT["tooFewFixedBlockBytes"]), F)
    return F


def _compact_mask(mask, capacity: int):
    """Compact set positions of ``mask`` into a (capacity,) index buffer
    (-1 beyond the population) without any full-width cumsum/sort/scatter:
    pack to u32 words, build a word-level popcount prefix (tiny cumsum),
    binary-search the word holding the k-th survivor, then locate the
    in-word bit with masked popcounts. Returns (cand, n_set)."""
    words = _pack_bits(mask)
    wpc = lax.population_count(words).astype(_I32)
    wcnt = jnp.cumsum(wpc)
    n_set = wcnt[-1]
    k = jnp.arange(capacity, dtype=_I32)
    wi = jnp.searchsorted(wcnt, k + 1, side="left").astype(_I32)
    excl = jnp.take(wcnt - wpc, jnp.clip(wi, 0, wcnt.shape[0] - 1), mode="clip")
    r = k + 1 - excl                              # target rank within word: 1..32
    word = jnp.take(words, wi, mode="clip")
    lanes = jnp.arange(32, dtype=_U32)
    incl = (_U32(2) << lanes) - _U32(1)           # inclusive masks (lane 31 wraps to ~0)
    pcnt = lax.population_count(word[:, None] & incl[None, :])
    hit = (pcnt == r[:, None]) & (((word[:, None] >> lanes[None, :]) & 1) == 1)
    lane = jnp.argmax(hit, axis=1).astype(_I32)
    cand = jnp.where(k < n_set, wi * 32 + lane, _I32(-1))
    return cand, n_set


# Sentinel bounds for the logical cursor: anything outside [0, n] behaves
# identically (it can never equal the physical cursor at EOF), so clamping is
# exact unless the cursor needs to *re-enter* range — tracked per lane.
def _check_lanes(
    padded, lengths, num_contigs, n, at_eof,
    reads_to_check: int = 10, flags_impl: str = "xla",
    pallas_interpret: bool = False, funnel: bool = False,
):
    """Flag pass + survivor compaction + lane walk, WITHOUT the full-width
    scatters: the shared core of ``check_window`` (which scatters the lanes
    back to (W,) arrays) and the funnel count path (which reduces the lanes
    directly — for two scalars the scatters are pure overhead that XLA
    cannot eliminate through the sums)."""
    w = padded.shape[0] - PAD
    if funnel:
        if flags_impl == "pallas":
            from spark_bam_tpu.tpu.pallas_kernels import prefilter_check_flags

            F = prefilter_check_flags(
                padded, lengths, num_contigs.reshape(1), n.reshape(1),
                interpret=pallas_interpret,
            )
        else:
            F = _prefilter_flags(padded, lengths, num_contigs, n)
    elif flags_impl == "pallas":
        from spark_bam_tpu.tpu.pallas_kernels import full_check_flags

        F = full_check_flags(
            padded, lengths, num_contigs.reshape(1), n.reshape(1),
            interpret=pallas_interpret,
        )
    else:
        F = _compute_flags(padded, lengths, num_contigs, n)
    if funnel:
        # Lane-width misc: the walk only ever reads remaining/body_end at
        # (capacity,) positions — full-width materialization is the single
        # biggest non-prefilter cost on the funnel path.
        misc_at = functools.partial(_misc_at, padded, n)
    else:
        remaining, body_end = _compute_misc(padded, n)

        def misc_at(pi):
            return (
                jnp.take(remaining, pi, mode="clip"),
                jnp.take(body_end, pi, mode="clip"),
            )

    in_range = jnp.arange(w, dtype=_I32) < n
    definitive0 = F & DEFINITIVE_MASK
    boundary0 = F & ESCAPE_MASK
    survivor = (F == 0) & in_range

    # --- non-survivor resolution straight from F -------------------------
    # (Under the funnel, F here is the prefilter mask: positions it rejects
    # resolve identically — every prefilter bit is definitive except the
    # tooFewFixedBlockBytes overwrite, where prefilter == full mask.)
    fail0 = (F != 0) & ((definitive0 != 0) | (at_eof & (boundary0 != 0)))
    esc0 = (F != 0) & (~at_eof) & (definitive0 == 0) & (boundary0 != 0)
    inexact0 = (F != 0) & (~at_eof) & (definitive0 != 0) & (boundary0 != 0)

    res0 = jnp.where(fail0, jnp.int8(-1), jnp.int8(0))
    res0 = jnp.where(esc0, jnp.int8(2), res0)
    fail_mask0 = jnp.where(fail0, F, _I32(0))

    # --- survivor compaction ---------------------------------------------
    capacity = max(w // 32, 4096)
    if funnel:
        cand, n_survivors = _compact_mask(survivor, capacity)
        overflow = n_survivors > capacity
        live = cand >= 0
        # Stage 1: full 19-bit flags once at candidate positions, scattered
        # to a full-width array so the chain walk can look them up by
        # position. A walked position either passes the prefilter (then its
        # deep mask is here — deep-failing candidates resolve inside the
        # walk's step logic exactly like fail0/esc0/inexact0 above) or
        # fails it (then the prefilter bits alone are verdict-equivalent).
        tables = _funnel_tables(padded, n)
        F_cand = _deep_flags_at(
            padded, lengths, num_contigs, n, tables,
            jnp.where(live, cand, _I32(0)),
        )
        F_cand = jnp.where(live, F_cand, _I32(0))
        tgt0 = jnp.where(live, cand, _I32(w))
        F_deep = jnp.zeros(w + 1, dtype=_I32).at[tgt0].set(
            F_cand, mode="drop"
        )[:w]

        def flags_lookup(pi):
            pre = jnp.take(F, pi, mode="clip")
            return jnp.where(pre == 0, jnp.take(F_deep, pi, mode="clip"), pre)
    else:
        n_survivors = jnp.sum(survivor.astype(_I32))
        overflow = n_survivors > capacity
        (cand,) = jnp.nonzero(survivor, size=capacity, fill_value=-1)
        cand = cand.astype(_I32)
        live = cand >= 0

        def flags_lookup(pi):
            return jnp.take(F, pi, mode="clip")

    logical = jnp.where(live, cand, _I32(0))
    physical = logical
    l_overflowed = jnp.zeros(capacity, dtype=bool)
    res = jnp.where(live, jnp.int8(0), jnp.int8(-1))
    fail_mask = jnp.zeros(capacity, dtype=_I32)
    reads_before = jnp.zeros(capacity, dtype=_I32)
    reads_parsed = jnp.zeros(capacity, dtype=_I32)
    exact = jnp.ones(capacity, dtype=bool)

    def step(state, step_idx):
        logical, physical, l_overflowed, res, fail_mask, reads_before, reads_parsed, exact = state
        run = res == 0

        # --- EOF at record edge (zero bytes): eager/Checker.scala:36-39 ---
        at_end = run & (physical >= n)
        edge = (physical == logical) & (~l_overflowed) & (step_idx > 0)
        maybe_edge = l_overflowed & (step_idx > 0)  # can't trust comparison
        eof_ok = at_end & edge & at_eof
        eof_bad = at_end & (~edge) & (~maybe_edge) & at_eof
        eof_esc = at_end & ((~at_eof) | maybe_edge)
        res = jnp.where(eof_ok, jnp.int8(1), res)
        reads_parsed = jnp.where(eof_ok, step_idx, reads_parsed)
        res = jnp.where(eof_bad, jnp.int8(-1), res)
        fail_mask = jnp.where(eof_bad, _I32(BIT["tooFewFixedBlockBytes"]), fail_mask)
        reads_before = jnp.where(eof_bad, step_idx, reads_before)
        res = jnp.where(eof_esc, jnp.int8(2), res)
        run = res == 0

        f = flags_lookup(jnp.clip(physical, 0, w - 1))
        f = jnp.where(run, f, _I32(0))
        definitive = f & DEFINITIVE_MASK
        boundary = f & ESCAPE_MASK

        fail = run & ((definitive != 0) | (at_eof & (boundary != 0)))
        esc = run & (~at_eof) & (definitive == 0) & (boundary != 0)
        inexact = run & (~at_eof) & (definitive != 0) & (boundary != 0)
        res = jnp.where(fail, jnp.int8(-1), res)
        fail_mask = jnp.where(fail, f, fail_mask)
        reads_before = jnp.where(fail, step_idx, reads_before)
        res = jnp.where(esc, jnp.int8(2), res)
        exact = exact & (~inexact)
        run = res == 0

        ok = run & (f == 0)
        pi = jnp.clip(physical, 0, w - 1)
        rem, b_end = misc_at(pi)
        # int32-safe logical advance: out-of-range values collapse to
        # sentinels (n+64 / -64) that preserve all future comparisons unless
        # the cursor would legitimately re-enter [0, n] — flagged for host
        # re-check via l_overflowed.
        big = rem > n + 64
        small = rem < -(n + 64)
        rem_c = jnp.clip(rem, -(n + 64), n + 64)
        next_logical = logical + 4 + rem_c
        next_logical = jnp.clip(next_logical, -(n + 64), n + 64)
        overflow_now = big | small | (logical + 4 + rem_c != next_logical)
        next_physical = jnp.maximum(b_end, next_logical)
        next_physical = jnp.minimum(next_physical, n)
        # (A chain stepping to/past the buffer end resolves at the next
        #  iteration's EOF check: success/fail when at_eof, escape otherwise.)
        logical = jnp.where(ok, next_logical, logical)
        physical = jnp.where(ok, next_physical, physical)
        l_overflowed = l_overflowed | (ok & overflow_now)
        return (
            logical, physical, l_overflowed, res, fail_mask,
            reads_before, reads_parsed, exact,
        ), None

    state = (logical, physical, l_overflowed, res, fail_mask, reads_before, reads_parsed, exact)
    if funnel:
        # Unrolled walk: the loop-carried scan blocks XLA from fusing the
        # lane gathers with their producers (~25% of the funnel path); ten
        # lane-width steps unroll cheaply. The funnel=False scan is kept
        # verbatim so the funnel A/B baseline measures the original kernel.
        state, _ = lax.scan(
            step, state, jnp.arange(reads_to_check, dtype=_I32), unroll=True
        )
    else:
        state, _ = lax.scan(
            step, state, jnp.arange(reads_to_check, dtype=_I32)
        )
    logical, physical, l_overflowed, res, fail_mask, reads_before, reads_parsed, exact = state

    full_chain = live & (res == 0)
    res = jnp.where(full_chain, jnp.int8(1), res)
    reads_parsed = jnp.where(full_chain, _I32(reads_to_check), reads_parsed)
    return {
        "survivor": survivor, "res0": res0, "fail_mask0": fail_mask0,
        "inexact0": inexact0, "cand": cand, "live": live, "res": res,
        "fail_mask": fail_mask, "reads_before": reads_before,
        "reads_parsed": reads_parsed, "exact": exact,
        "overflow": overflow, "n_survivors": n_survivors,
    }


@functools.partial(
    jax.jit,
    static_argnames=(
        "reads_to_check", "window", "flags_impl", "pallas_interpret", "funnel"
    ),
)
def check_window(
    padded: jnp.ndarray,       # (W+PAD,) uint8; zeros beyond n
    lengths: jnp.ndarray,      # (Cmax,) int32 contig lengths, padded
    num_contigs: jnp.ndarray,  # () int32
    n: jnp.ndarray,            # () int32: valid byte count
    at_eof: jnp.ndarray,       # () bool: buffer end == file end
    reads_to_check: int = 10,
    window: int | None = None,
    flags_impl: str = "xla",   # "xla" | "pallas" (spark.bam.backend=pallas)
    pallas_interpret: bool = False,
    funnel: bool = False,      # two-stage candidate funnel (Config.funnel)
):
    """Flag pass + chain walk over one window; verdicts for every offset.

    The walk runs only over *survivor* lanes (positions whose own record
    passes every check, F==0 — ~0.2% of positions on real data): candidates
    compact into a fixed-capacity lane buffer, walk ``reads_to_check`` gather
    rounds, and scatter back. Non-survivors resolve directly from F. If an
    adversarial input overflows the lane capacity, the whole window escapes
    to the host engine — exactness over speed, never a guess.

    ``funnel=True`` swaps the full-width 19-bit pass for the two-stage
    candidate funnel: the cheap prefilter screens every position, survivors
    compact, and the deep bits are evaluated once at candidate positions
    only. Verdicts (and hence record-start positions) are identical to
    ``funnel=False``; the documented differences are that ``fail_mask`` at
    prefilter-rejected positions carries only the prefilter bits, and
    ``exact`` may be True where the full pass reports a (definitively
    failing) lane as inexact — both only affect forensic projections, which
    run with the funnel off (Config.funnel="auto").

    Returns dict of (W,) arrays: verdict, fail_mask, reads_parsed,
    reads_before, exact, escaped — plus the () int32 ``survivors`` count
    (stage-0 survivors under the funnel; full-pass survivors otherwise).
    """
    w = padded.shape[0] - PAD
    L = _check_lanes(
        padded, lengths, num_contigs, n, at_eof,
        reads_to_check=reads_to_check, flags_impl=flags_impl,
        pallas_interpret=pallas_interpret, funnel=funnel,
    )
    survivor, res0 = L["survivor"], L["res0"]
    fail_mask0, inexact0 = L["fail_mask0"], L["inexact0"]
    cand, live, res = L["cand"], L["live"], L["res"]
    fail_mask, reads_before = L["fail_mask"], L["reads_before"]
    reads_parsed, exact = L["reads_parsed"], L["exact"]
    overflow, n_survivors = L["overflow"], L["n_survivors"]

    # --- scatter survivors back over the F-derived base -------------------
    tgt = jnp.where(live, cand, _I32(w))  # dead lanes scatter into the pad row
    res_full = jnp.zeros(w + 1, dtype=jnp.int8).at[tgt].set(
        jnp.where(live, res, jnp.int8(0)), mode="drop"
    )[:w]
    res_full = jnp.where(survivor, res_full, res0)
    fm_full = jnp.zeros(w + 1, dtype=_I32).at[tgt].set(fail_mask, mode="drop")[:w]
    fm_full = jnp.where(survivor, fm_full, fail_mask0)
    rb_full = jnp.zeros(w + 1, dtype=_I32).at[tgt].set(reads_before, mode="drop")[:w]
    rb_full = jnp.where(survivor, rb_full, _I32(0))
    rp_full = jnp.zeros(w + 1, dtype=_I32).at[tgt].set(reads_parsed, mode="drop")[:w]
    rp_full = jnp.where(survivor, rp_full, _I32(0))
    ex_full = jnp.ones(w + 1, dtype=bool).at[tgt].set(exact, mode="drop")[:w]
    ex_full = jnp.where(survivor, ex_full, ~inexact0)

    # Capacity overflow: the whole window is unresolved (host fallback).
    res_full = jnp.where(overflow, jnp.int8(2), res_full)
    escaped = res_full == 2
    exact_out = ex_full & (~escaped) & (~overflow)
    return {
        "verdict": res_full == 1,
        "fail_mask": jnp.where(overflow, _I32(0), fm_full),
        "reads_parsed": rp_full,
        "reads_before": rb_full,
        "exact": exact_out,
        "escaped": escaped,
        "survivors": n_survivors,
    }


@functools.partial(
    jax.jit,
    static_argnames=(
        "reads_to_check", "window", "flags_impl", "pallas_interpret", "funnel"
    ),
)
def count_window(
    padded, lengths, num_contigs, n, at_eof, lo, own,
    reads_to_check: int = 10, window: int | None = None,
    flags_impl: str = "xla", pallas_interpret: bool = False,
    funnel: bool = False,
):
    """check_window fused with its owned-span count reduction.

    One dispatch per streaming window instead of kernel + separate reduce
    (dispatch round-trips dominate on remote-tunnel devices), and XLA
    dead-code-eliminates everything the two scalars don't need — the
    fail_mask/reads_* scatters and the per-position arrays themselves.
    (Escapes are rare; the caller falls back to the exact spans path when
    ``esc_count`` is ever nonzero.)
    """
    w = padded.shape[0] - PAD
    i = jnp.arange(w, dtype=_I32)
    m = (i >= lo) & (i < own)
    if funnel:
        # Scatter-free reduction: verdicts live only on survivor lanes
        # (non-survivors never reach res==1) and escapes split cleanly into
        # prefilter-rejected positions (res0==2) plus lane escapes, so both
        # scalars reduce over lanes without materializing the (W,) arrays.
        L = _check_lanes(
            padded, lengths, num_contigs, n, at_eof,
            reads_to_check=reads_to_check, flags_impl=flags_impl,
            pallas_interpret=pallas_interpret, funnel=True,
        )
        own_lane = L["live"] & (L["cand"] >= lo) & (L["cand"] < own)
        count = jnp.sum(own_lane & (L["res"] == 1))
        esc = jnp.sum(m & (L["res0"] == 2)) + jnp.sum(
            own_lane & (L["res"] == 2)
        )
        count = jnp.where(L["overflow"], 0, count)
        esc = jnp.where(L["overflow"], jnp.sum(m), esc)
        return {
            "count": count, "esc_count": esc, "survivors": L["n_survivors"],
        }
    res = check_window(
        padded, lengths, num_contigs, n, at_eof,
        reads_to_check=reads_to_check, window=window,
        flags_impl=flags_impl, pallas_interpret=pallas_interpret,
        funnel=funnel,
    )
    return {
        "count": jnp.sum(m & res["verdict"]),
        "esc_count": jnp.sum(m & res["escaped"]),
        "survivors": res["survivors"],
    }


@functools.partial(
    jax.jit,
    static_argnames=(
        "window", "reads_to_check", "iters", "flags_impl", "pallas_interpret",
        "funnel",
    ),
)
def count_repeat(
    padded, lengths, num_contigs, n, at_eof,
    *,
    window: int,
    iters: int,
    reads_to_check: int = 10,
    flags_impl: str = "xla",
    pallas_interpret: bool = False,
    funnel: bool = False,
):
    """The fused count kernel repeated ``iters`` times in ONE dispatch.

    The chip-rate measurement instrument: through a tunnel whose every
    execute blocks for seconds (observed ~4.9 s/call in the r05 live
    window, async dispatch notwithstanding), per-call timing measures the
    tunnel, not the chip. Timing this program at two ``iters`` values and
    taking the slope cancels the round-trip entirely — two executes
    total, any tunnel.

    The body carries a value-neutral data dependency on the running count
    (``n`` is bumped by a predicate that is always false, which XLA
    cannot prove), so the loop cannot be collapsed by loop-invariant
    code motion or CSE into a single evaluation.
    """
    def body(carry, _):
        n_eff = n + jnp.where(carry < 0, _I32(1), _I32(0))
        r = count_window(
            padded, lengths, num_contigs, n_eff, at_eof,
            _I32(0), n_eff,
            reads_to_check=reads_to_check, window=window,
            flags_impl=flags_impl, pallas_interpret=pallas_interpret,
            funnel=funnel,
        )
        return carry + r["count"], None

    total, _ = lax.scan(body, _I32(0), None, length=iters)
    return total


def make_count_repeat(
    window: int, reads_to_check: int = 10, flags_impl: str = "xla",
    funnel: bool = False,
):
    """A jit-compiled ``count_repeat`` for fixed window/iteration count."""
    pallas_interpret = _pallas_interpret_for(flags_impl)

    def run(padded, lengths, num_contigs, n, at_eof, iters: int):
        return count_repeat(
            padded, lengths, num_contigs, n, at_eof,
            window=window, iters=iters, reads_to_check=reads_to_check,
            flags_impl=flags_impl, pallas_interpret=pallas_interpret,
            funnel=funnel,
        )

    return run


def _pallas_interpret_for(flags_impl: str) -> bool:
    """Pallas kernels compile via Mosaic only on real TPUs; everywhere else
    (tests' virtual CPU mesh) they run in interpret mode."""
    return flags_impl == "pallas" and jax.default_backend() != "tpu"


def make_count_window(
    window: int, reads_to_check: int = 10, flags_impl: str = "xla",
    funnel: bool = False,
):
    """A jit-compiled fused count kernel for fixed ``window`` size."""
    pallas_interpret = _pallas_interpret_for(flags_impl)

    def run(padded, lengths, num_contigs, n, at_eof, lo, own):
        return count_window(
            padded, lengths, num_contigs, n, at_eof, lo, own,
            reads_to_check=reads_to_check, window=window,
            flags_impl=flags_impl, pallas_interpret=pallas_interpret,
            funnel=funnel,
        )

    return run


@functools.partial(
    jax.jit,
    static_argnames=(
        "window", "reads_to_check", "flags_impl", "pallas_interpret", "funnel"
    ),
)
def count_scan(
    chunk,      # (L,) uint8 resident chunk; L ≥ max(starts) + window + PAD
    lengths,    # (Cmax,) int32
    num_contigs,  # () int32
    starts,     # (K,) int32: window byte offsets into ``chunk``
    ns,         # (K,) int32: valid byte count per window (0 ⇒ dummy pad row)
    at_eofs,    # (K,) bool
    los,        # (K,) int32 owned-span starts (local to the window)
    owns,       # (K,) int32 owned-span ends   (local to the window)
    *,
    window: int,
    reads_to_check: int = 10,
    flags_impl: str = "xla",
    pallas_interpret: bool = False,
    funnel: bool = False,
):
    """The fused count kernel scanned over K windows in ONE dispatch.

    ``count_window`` pays one dispatch per window; on a remote/tunnelled
    device each dispatch costs seconds of round-trip — 3 orders of
    magnitude over the on-chip kernel time (measured: ~4.9 s/dispatch vs
    ~400 µs of compute for a 32 MB window). Here the whole chunk of the
    uncompressed stream is resident in HBM and ``lax.scan`` drives the
    same window body K times inside one XLA program, so the round-trip is
    paid once per *chunk*. XLA reuses the body's intermediates across
    iterations, so device memory stays O(one window) + the chunk itself.

    Per-window scalar rows (``ns``/``at_eofs``/``los``/``owns``) carry the
    halo-carry ownership discipline of ``stream_check.halo_windows``;
    a row with ``own == lo`` contributes nothing, which is how the caller
    pads K to a bucket size without perturbing counts.

    This is the count-reads workload of reference
    load/.../CanLoadBam.scala:173-243 at whole-chunk granularity.
    """
    def body(carry, xs):
        cnt, esc, surv = carry
        s, n, ae, lo, own = xs
        win = lax.dynamic_slice(chunk, (s,), (window + PAD,))
        r = check_window(
            win, lengths, num_contigs, n, ae,
            reads_to_check=reads_to_check, window=window,
            flags_impl=flags_impl, pallas_interpret=pallas_interpret,
            funnel=funnel,
        )
        i = jnp.arange(window, dtype=_I32)
        m = (i >= lo) & (i < own)
        return (
            cnt + jnp.sum(m & r["verdict"]),
            esc + jnp.sum(m & r["escaped"]),
            surv + r["survivors"],
        ), None

    (cnt, esc, surv), _ = lax.scan(
        body, (_I32(0), _I32(0), _I32(0)),
        (starts, ns, at_eofs, los, owns),
    )
    return {"count": cnt, "esc_count": esc, "survivors": surv}


def make_count_scan(
    window: int, reads_to_check: int = 10, flags_impl: str = "xla",
    funnel: bool = False,
):
    """A jit-compiled resident-chunk count kernel for fixed ``window``."""
    pallas_interpret = _pallas_interpret_for(flags_impl)

    def run(chunk, lengths, num_contigs, starts, ns, at_eofs, los, owns):
        return count_scan(
            chunk, lengths, num_contigs, starts, ns, at_eofs, los, owns,
            window=window, reads_to_check=reads_to_check,
            flags_impl=flags_impl, pallas_interpret=pallas_interpret,
            funnel=funnel,
        )

    return run


@functools.partial(
    jax.jit,
    static_argnames=(
        "window", "halo", "reads_to_check", "flags_impl", "pallas_interpret",
        "funnel",
    ),
)
def count_window_tokens(
    packed,       # (3*B*STRIDE,) uint8 packed lit/dist token planes
    out_lens,     # (B,) int32 inflated size per block row (0 ⇒ pad row)
    carry,        # (halo,) uint8 previous window's tail (valid ≤ carry_len)
    lengths,      # (Cmax,) int32
    num_contigs,  # () int32
    carry_len,    # () int32 valid carry bytes (≤ halo)
    n,            # () int32 = carry_len + Σ out_lens (total window bytes)
    at_eof,       # () bool
    lo,           # () int32 owned-span start
    own,          # () int32 owned-span end
    *,
    window: int,
    halo: int,
    reads_to_check: int = 10,
    flags_impl: str = "xla",
    pallas_interpret: bool = False,
    funnel: bool = False,
):
    """The fully device-resident hot path: LZ77 resolve + window assembly
    + funnel/deep check + chain walk in ONE XLA program.

    The only H2D operands are the packed token planes from the host
    entropy phase plus a handful of scalars; the only D2H results are the
    two count scalars (+ survivors/rounds) and the (halo,) carry — which
    itself stays on device between windows, so in steady state nothing but
    scalars crosses the PCIe/tunnel boundary. Compare
    ``inflate_blocks_device`` → host concatenate → ``count_window``, which
    bounces every inflated byte through host twice.

    Window assembly is gather-based: byte ``i`` of the logical window is
    either ``carry[i]`` (the previous window's halo tail) or byte
    ``j = i - carry_len`` of the concatenated block outputs, located by a
    ``searchsorted`` over the cumulative ``out_lens`` — zero-length rows
    (batch padding, empty final BGZF blocks) occupy no output range and
    are skipped naturally. The new carry is the owned-end tail
    ``val[own : own+halo]`` (zeros beyond ``n``), exactly the
    ``halo_windows`` carry discipline.
    """
    from spark_bam_tpu.tpu.inflate import _resolve_body, _unpack_tokens

    lit, dist = _unpack_tokens(packed)
    resolved, rounds = _resolve_body(lit, dist)
    return _count_from_planes(
        resolved, rounds, out_lens, carry, lengths, num_contigs, carry_len,
        n, at_eof, lo, own, window=window, halo=halo,
        reads_to_check=reads_to_check, flags_impl=flags_impl,
        pallas_interpret=pallas_interpret, funnel=funnel,
    )


def _count_from_planes(
    resolved, rounds, out_lens, carry, lengths, num_contigs, carry_len, n,
    at_eof, lo, own, *, window, halo, reads_to_check, flags_impl,
    pallas_interpret, funnel,
):
    """Shared back half of the fused count kernels: gather-assemble the
    logical window from resolved block rows + the halo carry, run the
    count, slice the next carry. Traced inside both the packed-token and
    raw-payload entry points."""
    from spark_bam_tpu.tpu.inflate import STRIDE

    b = resolved.shape[0]
    cum = jnp.concatenate(
        [jnp.zeros(1, _I32), jnp.cumsum(out_lens.astype(_I32))]
    )
    i = jnp.arange(window, dtype=_I32)
    j = i - carry_len
    blk = jnp.clip(jnp.searchsorted(cum, j, side="right") - 1, 0, b - 1)
    off = jnp.clip(j - cum[blk], 0, STRIDE - 1)
    from_blocks = resolved.reshape(-1)[blk * STRIDE + off]
    carry_v = carry[jnp.clip(i, 0, halo - 1)]
    val = jnp.where(
        i < carry_len, carry_v,
        jnp.where(i < n, from_blocks, jnp.uint8(0)),
    )
    padded = jnp.concatenate([val, jnp.zeros(PAD, jnp.uint8)])
    r = count_window(
        padded, lengths, num_contigs, n, at_eof, lo, own,
        reads_to_check=reads_to_check, window=window,
        flags_impl=flags_impl, pallas_interpret=pallas_interpret,
        funnel=funnel,
    )
    ext = jnp.concatenate([val, jnp.zeros(halo, jnp.uint8)])
    new_carry = lax.dynamic_slice(ext, (own,), (halo,))
    return {**r, "carry": new_carry, "rounds": rounds}


def count_window_raw(
    staged,       # (B_pad, C_pad) uint8 staged raw-DEFLATE payload rows
    clens,        # (B_pad,) int32 compressed length per row (0 ⇒ pad row)
    exp_lens,     # (B_pad,) int32 footer ISIZE per row (0 ⇒ pad row)
    carry,        # (halo,) uint8 previous window's tail (valid ≤ carry_len)
    lengths,      # (Cmax,) int32
    num_contigs,  # () int32
    carry_len,    # () int32
    n,            # () int32 = carry_len + Σ exp_lens
    at_eof,       # () bool
    lo,           # () int32 owned-span start
    own,          # () int32 owned-span end
    *,
    window: int,
    halo: int,
    reads_to_check: int = 10,
    flags_impl: str = "xla",
    pallas_interpret: bool = False,
    funnel: bool = False,
    tok_impl: str = "xla",
):
    """``count_window_tokens`` one step deeper: the H2D operand is the RAW
    compressed payload matrix — the device bit-reader runs the entropy
    phase in the same program as resolve + assemble + count, so the host
    never touches DEFLATE bits at all and the wire carries compressed
    bytes (≈3× less than packed token planes, ≈window-size less than
    inflated bytes).

    Returns the ``count_window_tokens`` dict plus ``tok_ok``: a scalar
    bool, True iff every real row decoded cleanly AND produced exactly its
    footer's ISIZE. The stream driver checks it at each sync and demotes
    the whole count run to the host-tokenize path on the first False —
    window counts from a failed decode are never trusted (the assembly
    below uses the footer lengths, so a lying row cannot shift its
    neighbors' bytes even transiently).
    """
    if tok_impl == "pallas":
        from spark_bam_tpu.tpu.pallas_kernels import tokenize_pallas

        lit, dist, olens, ok = tokenize_pallas(staged, clens)
    else:
        from spark_bam_tpu.tpu.tokenize_device import tokenize_planes

        lit, dist, olens, ok = tokenize_planes(staged, clens)
    from spark_bam_tpu.tpu.inflate import _resolve_body

    pad = clens == 0
    tok_ok = jnp.all((ok | pad) & ((olens == exp_lens) | pad))
    resolved, rounds = _resolve_body(lit, dist)
    out = _count_from_planes(
        resolved, rounds, exp_lens, carry, lengths, num_contigs, carry_len,
        n, at_eof, lo, own, window=window, halo=halo,
        reads_to_check=reads_to_check, flags_impl=flags_impl,
        pallas_interpret=pallas_interpret, funnel=funnel,
    )
    return {**out, "tok_ok": tok_ok}


def make_count_window_raw(
    window: int, halo: int, reads_to_check: int = 10,
    flags_impl: str = "xla", funnel: bool = False, tok_impl: str = "xla",
    donate: bool = True,
):
    """A jit-compiled fused tokenize→resolve→assemble→count kernel for
    fixed window/halo geometry (the ``tokenize=device`` count path of
    stream_check.StreamChecker.count_reads). With ``donate`` the (halo,)
    carry operand aliases the returned carry — the inter-window state ring
    reuses its HBM instead of allocating per window."""
    pallas_interpret = _pallas_interpret_for(flags_impl)

    def run(staged, clens, exp_lens, carry, lengths, num_contigs,
            carry_len, n, at_eof, lo, own):
        return count_window_raw(
            staged, clens, exp_lens, carry, lengths, num_contigs,
            carry_len, n, at_eof, lo, own,
            window=window, halo=halo, reads_to_check=reads_to_check,
            flags_impl=flags_impl, pallas_interpret=pallas_interpret,
            funnel=funnel, tok_impl=tok_impl,
        )

    return jax.jit(run, donate_argnums=(3,)) if donate else jax.jit(run)


def make_count_window_tokens(
    window: int, halo: int, reads_to_check: int = 10,
    flags_impl: str = "xla", funnel: bool = False,
):
    """A jit-compiled fused inflate→assemble→count kernel for fixed
    window/halo geometry (the device-resident count path of
    stream_check.StreamChecker.count_reads)."""
    pallas_interpret = _pallas_interpret_for(flags_impl)

    def run(packed, out_lens, carry, lengths, num_contigs, carry_len, n,
            at_eof, lo, own):
        return count_window_tokens(
            packed, out_lens, carry, lengths, num_contigs, carry_len, n,
            at_eof, lo, own,
            window=window, halo=halo, reads_to_check=reads_to_check,
            flags_impl=flags_impl, pallas_interpret=pallas_interpret,
            funnel=funnel,
        )

    return run


def make_check_window(
    window: int, reads_to_check: int = 10, flags_impl: str = "xla",
    funnel: bool = False,
):
    """A jit-compiled window kernel for fixed ``window`` size.

    ``flags_impl="pallas"`` swaps the flag pass for the Pallas full kernel
    (tpu/pallas_kernels.py); on non-TPU backends it runs in interpret mode.
    ``funnel=True`` swaps in the two-stage candidate funnel (same verdicts,
    see ``check_window``).
    """
    pallas_interpret = _pallas_interpret_for(flags_impl)

    def run(padded, lengths, num_contigs, n, at_eof):
        return check_window(
            padded, lengths, num_contigs, n, at_eof,
            reads_to_check=reads_to_check, window=window,
            flags_impl=flags_impl, pallas_interpret=pallas_interpret,
            funnel=funnel,
        )

    return run


@dataclass
class WindowResult:
    verdict: np.ndarray
    fail_mask: np.ndarray
    reads_parsed: np.ndarray
    reads_before: np.ndarray
    exact: np.ndarray
    escaped: np.ndarray


class TpuChecker:
    """Host wrapper: windows a flat uncompressed stream through the device
    kernel; escaped/inexact candidates fall back to the NumPy engine (and
    ultimately the sequential oracle), so results are always exact.

    The ``Checker`` plugin face of the TPU backend (``spark.bam.backend=tpu``).
    """

    def __init__(
        self,
        contig_lengths: np.ndarray,
        window: int = 16 << 20,
        halo: int = 4 << 20,
        reads_to_check: int = 10,
        cmax: int = 1024,
        flags_impl: str = "xla",
    ):
        self.window = window
        self.halo = halo
        self.reads_to_check = reads_to_check
        self.num_contigs = np.int32(len(contig_lengths))
        cmax = max(cmax, len(contig_lengths))
        self.lengths = np.zeros(cmax, dtype=np.int32)
        self.lengths[: len(contig_lengths)] = contig_lengths
        self._kernel = make_check_window(window, reads_to_check, flags_impl)

    def check_buffer(self, buf: np.ndarray, at_eof: bool = True) -> WindowResult:
        """Check every position of ``buf``; exact everywhere except possibly
        within the final chain-reach when ``at_eof=False`` (those escape)."""
        n_total = len(buf)
        out = {
            k: np.empty(n_total, dtype=d)
            for k, d in [
                ("verdict", bool), ("fail_mask", np.int32),
                ("reads_parsed", np.int32), ("reads_before", np.int32),
                ("exact", bool), ("escaped", bool),
            ]
        }
        w = self.window
        step = max(w - self.halo, 1)
        s = 0
        while True:
            e = min(s + w, n_total)
            chunk_eof = at_eof and e == n_total
            padded = np.zeros(w + PAD, dtype=np.uint8)
            padded[: e - s] = buf[s:e]
            res = self._kernel(
                jnp.asarray(padded),
                jnp.asarray(self.lengths),
                jnp.int32(self.num_contigs),
                jnp.int32(e - s),
                jnp.bool_(chunk_eof),
            )
            res = {k: np.asarray(v) for k, v in res.items()}
            # Own [s, s+step) — the halo tail belongs to the next window —
            # except the last window, which owns through the end.
            own_end = e if e == n_total else min(s + step, n_total)
            for k in out:
                out[k][s:own_end] = res[k][: own_end - s]
            if e == n_total:
                break
            s += step
        result = WindowResult(**out)
        self._host_recheck(buf, result, at_eof)
        return result

    def _host_recheck(self, buf, result: WindowResult, at_eof: bool):
        """Resolve escaped/inexact lanes with the NumPy engine on a widened
        span (covers sentinel-overflow lanes and halo-exceeding chains)."""
        bad = result.escaped | ~result.exact
        if at_eof:
            idxs = np.flatnonzero(bad)
        else:
            # In pure windowed mode the tail escapes are legitimate output.
            idxs = np.flatnonzero(bad[: max(len(buf) - self.halo, 0)])
        if len(idxs) == 0:
            return
        from spark_bam_tpu.check.vectorized import check_flat

        # Escapes are rare (chains outrunning the halo, sentinel overflows);
        # re-run only the suffix that can influence them.
        base = int(idxs.min())
        res = check_flat(
            buf[base:], self.lengths[: int(self.num_contigs)],
            candidates=(idxs - base).astype(np.int64),
            at_eof=at_eof, reads_to_check=self.reads_to_check,
        )
        result.verdict[idxs] = res.verdict
        result.fail_mask[idxs] = res.fail_mask
        result.reads_parsed[idxs] = res.reads_parsed
        result.reads_before[idxs] = res.reads_before
        result.exact[idxs] = res.exact | res.verdict | (res.fail_mask != 0)
        result.escaped[idxs] = res.escaped
