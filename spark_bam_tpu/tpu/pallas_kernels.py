"""Pallas TPU kernels for the checker hot path.

``field_check_kernel`` fuses the per-position field extraction + cheap
structural checks of the flag pass (check/vectorized.py pass 1) into one
VMEM-tiled kernel: each grid step loads a (TILE + halo) byte slab, derives
the little-endian i32 views in-register, and emits the partial flag bitmask
for its tile — no HBM round-trips between the byte loads and the mask.

This covers the checks that are pure functions of a 36-byte neighborhood
(ref/mate position sanity, implied-size consistency, name-length classes);
the prefix-sum-based scans (name charset, cigar ops) stay in XLA where its
fused scans are already near bandwidth. The kernel is the fusion seed for
moving the whole flag pass into Pallas.

Verified against the NumPy engine in interpret mode (tests/test_pallas.py);
on real TPU it compiles via the standard pallas_call path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spark_bam_tpu.check.flags import BIT

TILE = 32 * 1024
# Lookahead for the 36-byte fixed fields; 1024 (not 40) because Mosaic
# requires 1-D uint8 DMA slice sizes aligned to its 1024-element tiling.
HALO = 1024

_I32 = jnp.int32

# Bits this kernel produces (the 36-byte-neighborhood checks).
# (tooLargeReadPos/tooLargeNextReadPos need a contig-length gather, which
# Mosaic only supports in 2D — those two bits stay in the XLA flag pass.)
FIELD_CHECK_BITS = (
    BIT["negativeReadIdx"] | BIT["tooLargeReadIdx"]
    | BIT["negativeReadPos"]
    | BIT["negativeNextReadIdx"] | BIT["tooLargeNextReadIdx"]
    | BIT["negativeNextReadPos"]
    | BIT["tooFewRemainingBytesImplied"]
    | BIT["noReadName"] | BIT["emptyReadName"]
)


def _i32_at(tile: jnp.ndarray, off: int, n: int) -> jnp.ndarray:
    u = (
        tile[off: off + n].astype(jnp.uint32)
        | (tile[off + 1: off + n + 1].astype(jnp.uint32) << 8)
        | (tile[off + 2: off + n + 2].astype(jnp.uint32) << 16)
        | (tile[off + 3: off + n + 3].astype(jnp.uint32) << 24)
    )
    return lax.bitcast_convert_type(u, jnp.int32)


def _field_check_kernel(p_hbm, lengths_ref, nc_ref, out_ref, slab, sem):
    # Manually DMA an overlapping (TILE + HALO) slab: BlockSpec tiling can't
    # express overlap, so the byte buffer stays unblocked and each grid step
    # fetches its slab into VMEM scratch.
    i = pl.program_id(0)
    copy = pltpu.make_async_copy(
        p_hbm.at[pl.ds(i * TILE, TILE + HALO)], slab, sem
    )
    copy.start()
    copy.wait()
    tile = slab[...]
    n = TILE
    remaining = _i32_at(tile, 0, n)
    ref_idx = _i32_at(tile, 4, n)
    ref_pos = _i32_at(tile, 8, n)
    name_len = tile[12: n + 12].astype(_I32)
    fnc = _i32_at(tile, 16, n)
    n_cigar = fnc & 0xFFFF
    seq_len = _i32_at(tile, 20, n)
    next_ref_idx = _i32_at(tile, 24, n)
    next_ref_pos = _i32_at(tile, 28, n)

    c = nc_ref[0]

    def ref_bits(idx, pos, b_neg_idx, b_large_idx, b_neg_pos):
        neg_idx = idx < -1
        large_idx = (~neg_idx) & (idx >= c)
        neg_pos = pos < -1
        return (
            jnp.where(neg_idx, _I32(b_neg_idx), _I32(0))
            | jnp.where(large_idx, _I32(b_large_idx), _I32(0))
            | jnp.where(neg_pos, _I32(b_neg_pos), _I32(0))
        )

    F = ref_bits(
        ref_idx, ref_pos,
        BIT["negativeReadIdx"], BIT["tooLargeReadIdx"], BIT["negativeReadPos"],
    )
    F = F | ref_bits(
        next_ref_idx, next_ref_pos,
        BIT["negativeNextReadIdx"], BIT["tooLargeNextReadIdx"],
        BIT["negativeNextReadPos"],
    )

    t = seq_len + _I32(1)
    half = lax.div(t, _I32(2))
    rhs = _I32(32) + name_len + _I32(4) * n_cigar + half + seq_len
    F = F | jnp.where(
        remaining < rhs, _I32(BIT["tooFewRemainingBytesImplied"]), _I32(0)
    )
    F = F | jnp.where(name_len == 0, _I32(BIT["noReadName"]), _I32(0))
    F = F | jnp.where(name_len == 1, _I32(BIT["emptyReadName"]), _I32(0))

    out_ref[...] = F


@functools.partial(jax.jit, static_argnames=("interpret",))
def field_check_flags(
    padded: jnp.ndarray,   # (W + HALO,) uint8, W a multiple of TILE
    lengths: jnp.ndarray,  # (Cmax,) int32
    num_contigs: jnp.ndarray,  # (1,) int32
    interpret: bool = False,
):
    w = padded.shape[0] - HALO
    assert w % TILE == 0, "window must be a multiple of the tile size"
    grid = (w // TILE,)
    return pl.pallas_call(
        _field_check_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),     # bytes stay in HBM; DMA'd
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((TILE,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((w,), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((TILE + HALO,), jnp.uint8),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(padded, lengths, num_contigs)
