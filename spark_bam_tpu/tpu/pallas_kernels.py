"""Pallas TPU kernels for the checker hot path.

Two kernels, verified bit-exact against the engines they mirror:

``prefilter_flags_kernel`` — stage 0 of the candidate funnel: only the
flag bits derivable from the fixed 36-byte block (``remaining`` bounds,
refID/pos range, name-length sanity), no name-byte scans and no cigar
scans, so the slab halo shrinks from ``PAD`` to one DMA tile and the
254-way unroll disappears entirely.  Positions it cannot reject go on to
the deep pass in tpu/checker.py.

``full_flags_kernel`` — ALL 19 flag bits of the checker error model
(check/flags.py; reference full/Checker.scala:17-198) computed in-kernel,
**gather-free** — Mosaic does not lower 1-D dynamic gathers, so every
data-dependent lookup is restructured:

- contig-length lookup (tooLarge*Pos): a scalar ``fori_loop`` over the
  SMEM contig table, selecting each length into the lanes that reference
  it — O(C) vector selects instead of a gather;
- read-name byte/charset checks: name lengths are one *byte* (≤255), so
  the per-lane variable-length reads unroll into 254 statically-shifted
  slices with masked selects, and the charset count is a running sum that
  grows by one shifted slice per iteration;
- cigar-op validity: a stride-4 suffix-min scan over the slab yields, for
  every offset, the first bad-op position at int-stride in its class —
  membership in ``[cig_start, cig_end)`` becomes one compare, and the
  ``cig_start`` lookup rides the same 254-way unrolled select (cig_end,
  which can lie 256 KiB ahead, never needs a lookup at all).

The slab halo equals the checker's ``PAD`` (≥ 36 + 255 + 4·65535), so even
a worst-case cigar array resolves in-slab. Wired into the product behind
``spark.bam.backend=pallas`` (tpu/checker.py swaps its flag pass for this
kernel; the chain walk is unchanged). On non-TPU backends it runs in
interpret mode — the parity artifact (tests/test_pallas.py) pins it
against both the XLA flag pass and the NumPy engine.

``lz77_resolve_pallas`` — the fused device half of the two-phase inflate
(tpu/inflate.py): one grid row per BGZF block, token rows in VMEM,
pointer-doubling with an **in-kernel early exit** the moment every chain
has reached its root literal (``lax.while_loop``; worst case
log2(64 Ki) = 16 rounds, typical BAM blocks converge in a handful).
Unlike the flag kernels this one keeps the per-row ``take_along_axis`` —
the indices stay inside the 64 Ki block row, but Mosaic may still refuse
the gather on some TPU generations, so the inflate dispatcher treats any
lowering failure as a demotion to the (identical-math, also early-exit)
XLA resolve and logs once. Parity is pinned in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spark_bam_tpu.check.flags import BIT

TILE = 32 * 1024

_I32 = jnp.int32

def _i32_at(tile: jnp.ndarray, off: int, n: int) -> jnp.ndarray:
    u = (
        tile[off: off + n].astype(jnp.uint32)
        | (tile[off + 1: off + n + 1].astype(jnp.uint32) << 8)
        | (tile[off + 2: off + n + 2].astype(jnp.uint32) << 16)
        | (tile[off + 3: off + n + 3].astype(jnp.uint32) << 24)
    )
    return lax.bitcast_convert_type(u, jnp.int32)


# ----------------------------------------------------- full 19-bit kernel

# Slab halo for the full kernel: the checker's PAD (a multiple of 1024 for
# Mosaic's DMA tiling, ≥ 36 + 255 + 4*65535 so cigar scans resolve in-slab;
# import-cycle-safe — checker.py only imports this module lazily).
from spark_bam_tpu.tpu.checker import PAD as FULL_HALO  # noqa: E402
_INF = 1 << 28  # beyond any slab-relative cig_end; selected lanes stay int32


def _iota(n: int) -> jnp.ndarray:
    # TPU requires ≥2-D iota; squeeze back to the lane vector.
    return lax.broadcasted_iota(jnp.int32, (n, 1), 0).squeeze(-1)


def _full_flags_kernel(p_hbm, lengths_ref, nc_ref, n_ref, out_ref, slab, sem):
    i = pl.program_id(0)
    copy = pltpu.make_async_copy(
        p_hbm.at[pl.ds(i * TILE, TILE + FULL_HALO)], slab, sem
    )
    copy.start()
    copy.wait()
    tile = slab[...]
    slab_len = TILE + FULL_HALO
    t = TILE
    base = i * TILE
    nval = n_ref[0]
    c = nc_ref[0]

    # --- fixed-field extraction (lane l ↔ candidate offset base+l) -------
    remaining = _i32_at(tile, 0, t)
    ref_idx = _i32_at(tile, 4, t)
    ref_pos = _i32_at(tile, 8, t)
    name_len = tile[12: t + 12].astype(_I32)
    fnc = _i32_at(tile, 16, t)
    n_cigar = fnc & 0xFFFF
    mapped = ((fnc >> 18) & 1) == 0
    seq_len = _i32_at(tile, 20, t)
    next_ref_idx = _i32_at(tile, 24, t)
    next_ref_pos = _i32_at(tile, 28, t)

    rel = _iota(t)
    abs_i = base + rel

    # --- contig-length lookup without gather: scalar loop over SMEM ------
    def contig_body(j, carry):
        len_r, len_n = carry
        lj = lengths_ref[j]
        len_r = jnp.where(ref_idx == j, lj, len_r)
        len_n = jnp.where(next_ref_idx == j, lj, len_n)
        return len_r, len_n

    len_r, len_n = lax.fori_loop(
        0, c, contig_body,
        (jnp.zeros(t, dtype=_I32), jnp.zeros(t, dtype=_I32)),
    )

    def ref_bits(idx, pos, len_at, b_neg_idx, b_large_idx, b_neg_pos, b_large_pos):
        neg_idx = idx < -1
        large_idx = (~neg_idx) & (idx >= c)
        neg_pos = pos < -1
        idx_ok = (~neg_idx) & (~large_idx)
        large_pos = idx_ok & (~neg_pos) & (idx >= 0) & (pos > len_at)
        return (
            jnp.where(neg_idx, _I32(b_neg_idx), _I32(0))
            | jnp.where(large_idx, _I32(b_large_idx), _I32(0))
            | jnp.where(neg_pos, _I32(b_neg_pos), _I32(0))
            | jnp.where(large_pos, _I32(b_large_pos), _I32(0))
        )

    F = ref_bits(
        ref_idx, ref_pos, len_r,
        BIT["negativeReadIdx"], BIT["tooLargeReadIdx"],
        BIT["negativeReadPos"], BIT["tooLargeReadPos"],
    )
    F = F | ref_bits(
        next_ref_idx, next_ref_pos, len_n,
        BIT["negativeNextReadIdx"], BIT["tooLargeNextReadIdx"],
        BIT["negativeNextReadPos"], BIT["tooLargeNextReadPos"],
    )

    # --- implied size (JVM int32 wrap + truncating division) -------------
    tt = seq_len + _I32(1)
    half = lax.div(tt, _I32(2))
    rhs = _I32(32) + name_len + _I32(4) * n_cigar + half + seq_len
    F = F | jnp.where(
        remaining < rhs, _I32(BIT["tooFewRemainingBytesImplied"]), _I32(0)
    )
    F = F | jnp.where(name_len == 0, _I32(BIT["noReadName"]), _I32(0))
    F = F | jnp.where(name_len == 1, _I32(BIT["emptyReadName"]), _I32(0))

    # --- cigar suffix-min scan: first bad-op position per stride class ---
    j_slab = _iota(slab_len)
    bad_op = ((tile & 0xF) > 8) & (base + j_slab + 4 <= nval)
    V = jnp.where(bad_op, j_slab, _I32(_INF)).reshape(slab_len // 4, 4)
    D = jnp.flip(lax.cummin(jnp.flip(V, 0), axis=0), 0).reshape(slab_len)

    # --- per-lane variable-length lookups: 254-way static unroll ---------
    allowed = ((tile >= 0x21) & (tile <= 0x7E) & (tile != 0x40)).astype(_I32)
    run_sum = jnp.zeros(t, dtype=_I32)
    last_byte = jnp.zeros(t, dtype=jnp.uint8)
    good = jnp.zeros(t, dtype=_I32)
    d_cig = D[36: 36 + t]  # cig_start = l+36 for nameless lanes
    for L in range(2, 256):
        m = name_len == L
        # window [l+36, l+36+L-1) grows by the byte at offset 36+L-2
        run_sum = run_sum + allowed[36 + L - 2: 36 + L - 2 + t]
        last_byte = jnp.where(m, tile[36 + L - 1: 36 + L - 1 + t], last_byte)
        good = jnp.where(m, run_sum, good)
        d_cig = jnp.where(m, D[36 + L: 36 + L + t], d_cig)

    has_name = name_len >= 2
    name_eof = has_name & (abs_i + 36 + name_len > nval)
    F = F | jnp.where(name_eof, _I32(BIT["tooFewBytesForReadName"]), _I32(0))
    name_in = has_name & (~name_eof)
    non_null = name_in & (last_byte != 0)
    F = F | jnp.where(non_null, _I32(BIT["nonNullTerminatedReadName"]), _I32(0))
    bad_chars = name_in & (~non_null) & (good != name_len - 1)
    F = F | jnp.where(bad_chars, _I32(BIT["nonASCIIReadName"]), _I32(0))

    # --- cigar bits: membership via the suffix-min, no cig_end lookup ----
    cig_start = rel + 36 + jnp.where(name_in, name_len, _I32(0))
    cig_end = cig_start + _I32(4) * n_cigar
    cig_considered = ~name_eof
    has_bad = cig_considered & (d_cig < cig_end)
    F = F | jnp.where(has_bad, _I32(BIT["invalidCigarOp"]), _I32(0))
    cig_eof = cig_considered & (~has_bad) & (base + cig_end > nval)
    F = F | jnp.where(cig_eof, _I32(BIT["tooFewBytesForCigarOps"]), _I32(0))
    empty_ok = cig_considered & (~has_bad) & (~cig_eof) & mapped
    empty_seq = empty_ok & (seq_len == 0)
    empty_cig = empty_ok & (n_cigar == 0)
    some_empty = empty_seq | empty_cig
    # Swapped on purpose: reference quirk (check/vectorized.py).
    F = F | jnp.where(some_empty & empty_seq, _I32(BIT["emptyMappedCigar"]), _I32(0))
    F = F | jnp.where(some_empty & empty_cig, _I32(BIT["emptyMappedSeq"]), _I32(0))

    # --- the only flag when the fixed 36-byte read itself fails ----------
    few_fixed = abs_i > nval - 36
    F = jnp.where(few_fixed, _I32(BIT["tooFewFixedBlockBytes"]), F)

    out_ref[...] = F


# ----------------------------------------------------- fused LZ77 kernel

# Token-row width: one BGZF block inflates to ≤ 64 KiB (bgzf/block.py
# MAX_BLOCK_SIZE); keep the constant local to avoid a tpu/inflate.py cycle.
from spark_bam_tpu.bgzf.block import MAX_BLOCK_SIZE as _LZ_STRIDE  # noqa: E402

_LZ_ROUNDS = (_LZ_STRIDE - 1).bit_length()


def _lz77_kernel(lit_ref, dist_ref, out_ref, rounds_ref):
    dist = dist_ref[...].astype(_I32)                       # (1, S)
    iota = lax.broadcasted_iota(_I32, dist.shape, 1)
    parent = iota - dist                                    # dist=0 ⇒ self

    def cond(state):
        _, r, done = state
        return jnp.logical_and(~done, r < _LZ_ROUNDS)

    def body(state):
        p, r, _ = state
        nxt = jnp.take_along_axis(p, p, axis=1)
        # Fixed point ⇔ every pointer already names a root (the only
        # self-parents); one extra gather is the convergence test itself.
        return nxt, r + _I32(1), jnp.all(nxt == p)

    roots, r, _ = lax.while_loop(
        cond, body, (parent, _I32(0), jnp.bool_(False))
    )
    out_ref[...] = jnp.take_along_axis(lit_ref[...], roots, axis=1)
    rounds_ref[0, 0] = r


@functools.partial(jax.jit, static_argnames=("interpret",))
def lz77_resolve_pallas(
    lit: jnp.ndarray,   # (B, 64 Ki) uint8 literal plane
    dist: jnp.ndarray,  # (B, 64 Ki) uint16 back-reference distances (0 = literal)
    interpret: bool = False,
):
    """Resolve LZ77 chains for a batch of tokenized BGZF blocks in one
    launch, early-exiting per block row. Returns ``(resolved (B, S) u8,
    rounds () i32)`` — rounds is the batch max, comparable to the XLA
    resolve's global round count."""
    b, s = lit.shape
    out, rounds = pl.pallas_call(
        _lz77_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, s), lambda i: (i, 0)),
            pl.BlockSpec((1, s), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, s), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, s), jnp.uint8),
            jax.ShapeDtypeStruct((b, 1), jnp.int32),
        ],
        interpret=interpret,
    )(lit, dist)
    return out, jnp.max(rounds)


# --------------------------------------------------- tokenize bit-reader


def _tokenize_kernel(comp_ref, clen_ref, *refs):
    # refs = 9 table refs (tokenize_device.TABLES order) + 4 output refs.
    # pallas_call refuses captured array constants, so the RFC tables
    # arrive as operands and thread back in through ``tabs``.
    from spark_bam_tpu.tpu.tokenize_device import _tokenize_row

    tabs = tuple(r[...] for r in refs[:9])
    lit_ref, dist_ref, olen_ref, ok_ref = refs[9:]
    lit, dist, o, ok = _tokenize_row(comp_ref[0, :], clen_ref[0, 0], tabs)
    lit_ref[0, :] = lit
    dist_ref[0, :] = dist
    olen_ref[0, 0] = o
    ok_ref[0, 0] = ok.astype(_I32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def tokenize_pallas(
    staged: jnp.ndarray,  # (B, C_pad) uint8 zero-padded raw-DEFLATE payloads
    clens: jnp.ndarray,   # (B,) int32 real payload byte lengths
    interpret: bool = False,
):
    """The device entropy phase as a Pallas grid: one lane per BGZF
    block walking its raw-DEFLATE bitstream in VMEM — Huffman table
    decode, run expansion, symbol emission — producing the same packed
    lit/dist token planes the host tokenizer does (see
    tpu/tokenize_device.py for the row math and its error model).

    Returns ``(lit (B, S) u8, dist (B, S) u16, out_lens (B,) i32,
    ok (B,) bool)``. Bit-serial control flow leans hard on Mosaic
    (nested ``while_loop``, dynamic 1-D slices); any lowering refusal is
    a *demotion*, not an error — the inflate dispatcher falls back to
    the identical-math XLA vmap (``tokenize_device.tokenize_planes``)
    and logs once, mirroring ``lz77_resolve_pallas``. Parity is pinned
    in interpret mode by tests/test_tokenize_device.py."""
    from spark_bam_tpu.tpu.tokenize_device import STRIDE as _TOK_S
    from spark_bam_tpu.tpu.tokenize_device import TABLES

    b, c_pad = staged.shape
    lit, dist, olens, ok = pl.pallas_call(
        _tokenize_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, c_pad), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
        ] + [
            # Broadcast tables: every grid lane reads block 0 whole.
            pl.BlockSpec(t.shape, lambda i: (0,)) for t in TABLES
        ],
        out_specs=[
            pl.BlockSpec((1, _TOK_S), lambda i: (i, 0)),
            pl.BlockSpec((1, _TOK_S), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, _TOK_S), jnp.uint8),
            jax.ShapeDtypeStruct((b, _TOK_S), jnp.uint16),
            jax.ShapeDtypeStruct((b, 1), jnp.int32),
            jax.ShapeDtypeStruct((b, 1), jnp.int32),
        ],
        interpret=interpret,
    )(staged, clens.reshape(b, 1), *TABLES)
    return lit, dist, olens[:, 0], ok[:, 0] != 0


# --------------------------------------------------- funnel stage-0 kernel

# The prefilter only reads the fixed block (bytes [l, l+36)); one 1 KiB
# halo tile keeps the DMA length a multiple of Mosaic's tiling like PAD.
PRE_HALO = 1024


def _prefilter_flags_kernel(p_hbm, lengths_ref, nc_ref, n_ref, out_ref, slab, sem):
    i = pl.program_id(0)
    copy = pltpu.make_async_copy(
        p_hbm.at[pl.ds(i * TILE, TILE + PRE_HALO)], slab, sem
    )
    copy.start()
    copy.wait()
    tile = slab[...]
    t = TILE
    base = i * TILE
    nval = n_ref[0]
    c = nc_ref[0]

    # --- fixed-field extraction (lane l ↔ candidate offset base+l) -------
    remaining = _i32_at(tile, 0, t)
    ref_idx = _i32_at(tile, 4, t)
    ref_pos = _i32_at(tile, 8, t)
    name_len = tile[12: t + 12].astype(_I32)
    fnc = _i32_at(tile, 16, t)
    n_cigar = fnc & 0xFFFF
    seq_len = _i32_at(tile, 20, t)
    next_ref_idx = _i32_at(tile, 24, t)
    next_ref_pos = _i32_at(tile, 28, t)

    abs_i = base + _iota(t)

    # --- contig-length lookup without gather: scalar loop over SMEM ------
    def contig_body(j, carry):
        len_r, len_n = carry
        lj = lengths_ref[j]
        len_r = jnp.where(ref_idx == j, lj, len_r)
        len_n = jnp.where(next_ref_idx == j, lj, len_n)
        return len_r, len_n

    len_r, len_n = lax.fori_loop(
        0, c, contig_body,
        (jnp.zeros(t, dtype=_I32), jnp.zeros(t, dtype=_I32)),
    )

    def ref_bits(idx, pos, len_at, b_neg_idx, b_large_idx, b_neg_pos, b_large_pos):
        neg_idx = idx < -1
        large_idx = (~neg_idx) & (idx >= c)
        neg_pos = pos < -1
        idx_ok = (~neg_idx) & (~large_idx)
        large_pos = idx_ok & (~neg_pos) & (idx >= 0) & (pos > len_at)
        return (
            jnp.where(neg_idx, _I32(b_neg_idx), _I32(0))
            | jnp.where(large_idx, _I32(b_large_idx), _I32(0))
            | jnp.where(neg_pos, _I32(b_neg_pos), _I32(0))
            | jnp.where(large_pos, _I32(b_large_pos), _I32(0))
        )

    F = ref_bits(
        ref_idx, ref_pos, len_r,
        BIT["negativeReadIdx"], BIT["tooLargeReadIdx"],
        BIT["negativeReadPos"], BIT["tooLargeReadPos"],
    )
    F = F | ref_bits(
        next_ref_idx, next_ref_pos, len_n,
        BIT["negativeNextReadIdx"], BIT["tooLargeNextReadIdx"],
        BIT["negativeNextReadPos"], BIT["tooLargeNextReadPos"],
    )

    # --- implied size (JVM int32 wrap + truncating division) -------------
    tt = seq_len + _I32(1)
    half = lax.div(tt, _I32(2))
    rhs = _I32(32) + name_len + _I32(4) * n_cigar + half + seq_len
    F = F | jnp.where(
        remaining < rhs, _I32(BIT["tooFewRemainingBytesImplied"]), _I32(0)
    )
    F = F | jnp.where(name_len == 0, _I32(BIT["noReadName"]), _I32(0))
    F = F | jnp.where(name_len == 1, _I32(BIT["emptyReadName"]), _I32(0))

    # --- the only flag when the fixed 36-byte read itself fails ----------
    few_fixed = abs_i > nval - 36
    F = jnp.where(few_fixed, _I32(BIT["tooFewFixedBlockBytes"]), F)

    out_ref[...] = F


@functools.partial(jax.jit, static_argnames=("interpret",))
def prefilter_check_flags(
    padded: jnp.ndarray,       # (W + FULL_HALO,) uint8, W a multiple of TILE
    lengths: jnp.ndarray,      # (Cmax,) int32
    num_contigs: jnp.ndarray,  # (1,) int32
    n: jnp.ndarray,            # (1,) int32: valid byte count
    interpret: bool = False,
):
    """Stage-0 funnel bits at every offset of the window: the fixed-block
    subset of the 19-flag model, a guaranteed superset of full-pass
    rejections among those bits (positions it clears still face the deep
    pass)."""
    w = padded.shape[0] - FULL_HALO
    assert w % TILE == 0, "window must be a multiple of the tile size"
    grid = (w // TILE,)
    return pl.pallas_call(
        _prefilter_flags_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),     # bytes stay in HBM; DMA'd
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((TILE,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((w,), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((TILE + PRE_HALO,), jnp.uint8),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(padded, lengths, num_contigs, n)


@functools.partial(jax.jit, static_argnames=("interpret",))
def full_check_flags(
    padded: jnp.ndarray,       # (W + FULL_HALO,) uint8, W a multiple of TILE
    lengths: jnp.ndarray,      # (Cmax,) int32
    num_contigs: jnp.ndarray,  # (1,) int32
    n: jnp.ndarray,            # (1,) int32: valid byte count
    interpret: bool = False,
):
    """All 19 flag bits at every offset of the window (the Pallas flag
    pass behind ``spark.bam.backend=pallas``)."""
    w = padded.shape[0] - FULL_HALO
    assert w % TILE == 0, "window must be a multiple of the tile size"
    grid = (w // TILE,)
    return pl.pallas_call(
        _full_flags_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),     # bytes stay in HBM; DMA'd
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((TILE,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((w,), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((TILE + FULL_HALO,), jnp.uint8),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(padded, lengths, num_contigs, n)
