"""BGZF inflate feeding the device: host-parallel path + Pallas plan.

Today's production path inflates on host (zlib releases the GIL; a thread
pool saturates cores — bgzf/flat.py) and ships flat windows to HBM. That is
already off the critical path for the checker speedup: SURVEY.md §7 "the
checker/parser speedup does not depend on it [device DEFLATE]".

``InflatePipeline`` overlaps the three stages per window —
read+inflate (host threads) → H2D transfer → device kernel — double-buffered
so the device never waits on the host for steady-state streams.

Pallas DEFLATE design (the round-2+ kernel, SURVEY §7 hard-part #1):
bit-serial Huffman decoding with data-dependent back-references resists
lane-parallelism, so the plan is block-parallel, not bit-parallel:

1. one BGZF block (≤64 KiB uncompressed) per grid step; many blocks in
   flight across grid steps — throughput from pipelining, not SIMD;
2. per block, a two-phase decode in VMEM:
   a. Huffman phase: build the code tables from the dynamic header in SMEM,
      then decode symbols with a 12-bit lookup table (fits VMEM); emit
      (literal | (dist, len)) tuples to a VMEM staging buffer;
   b. copy phase: resolve LZ77 back-references with `lax.while_loop` over
      the staging buffer — references reach ≤32 KiB back, inside the block's
      own VMEM scratch, so no HBM round-trips;
3. CRC32 validation on device (slice-by-8 table in VMEM) so corrupt blocks
   are flagged without host involvement.

Keeping host zlib as the correctness fallback is permanent policy: the
checker consumes identical flat windows from either producer.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Iterator

import numpy as np

from spark_bam_tpu.bgzf.block import Metadata
from spark_bam_tpu.bgzf.flat import FlatView, inflate_blocks
from spark_bam_tpu.core.channel import open_channel


def window_plan(metas: list[Metadata], window_uncompressed: int) -> list[list[Metadata]]:
    """Group consecutive blocks into ≈window-sized uncompressed runs."""
    groups: list[list[Metadata]] = []
    cur: list[Metadata] = []
    size = 0
    for m in metas:
        if cur and size + m.uncompressed_size > window_uncompressed:
            groups.append(cur)
            cur, size = [], 0
        cur.append(m)
        size += m.uncompressed_size
    if cur:
        groups.append(cur)
    return groups


class InflatePipeline:
    """Double-buffered host-inflate → device-window stream."""

    def __init__(self, path, window_uncompressed: int = 64 << 20, threads: int = 8):
        from spark_bam_tpu.bgzf.index_blocks import blocks_metadata

        self.path = path
        self.metas = list(blocks_metadata(path))
        self.total = sum(m.uncompressed_size for m in self.metas)
        self.groups = window_plan(self.metas, window_uncompressed)
        self.threads = threads

    def __iter__(self) -> Iterator[FlatView]:
        ch = open_channel(self.path)
        pool = ThreadPoolExecutor(max_workers=1)  # pipeline stage, not fan-out

        def produce(group):
            return inflate_blocks(
                ch, group, file_total=self.total, threads=self.threads
            )

        try:
            nxt = pool.submit(produce, self.groups[0]) if self.groups else None
            for i, group in enumerate(self.groups):
                view = nxt.result()
                if i + 1 < len(self.groups):
                    nxt = pool.submit(produce, self.groups[i + 1])
                if i == len(self.groups) - 1:
                    view.at_eof = True
                yield view
        finally:
            pool.shutdown(wait=False)
            ch.close()
