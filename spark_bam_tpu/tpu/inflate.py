"""BGZF inflate feeding the device: host-parallel path + two-phase device path.

Production path A inflates on host (zlib releases the GIL; a thread pool
saturates cores — bgzf/flat.py) and ships flat windows to HBM. That is
already off the critical path for the checker speedup: SURVEY.md §7 "the
checker/parser speedup does not depend on it [device DEFLATE]".

Path B is the **batched two-phase device inflate** (SURVEY §7 hard-part
#1). Bit-serial Huffman decoding resists lane-parallelism, so the split is:

1. *Host entropy phase* (`sbt_tokenize_deflate`, native/): decode the
   DEFLATE bitstream into per-output-byte tokens — ``lit[i]`` (the byte, if
   position ``i`` was emitted by a literal) and ``dist[i]`` (0 for
   literals; the back-reference distance otherwise, u16 — DEFLATE's max is
   32768). The LZ77 "copy" half of inflate — the memory-bandwidth half —
   is deferred entirely. Token rows for a whole window's worth of blocks
   are **packed into one contiguous u8 buffer** (lit plane then dist
   plane) so the H2D hop is a single 3-bytes-per-output-byte transfer,
   unpacked on device by a bitcast inside the same XLA program as the
   resolve kernel.
2. *Device copy phase* (`resolve_lz77`): every output byte's value is the
   byte at its pointer chain's root literal; parents materialize as
   ``i - dist`` from an iota. Chains collapse with lock-step
   pointer-doubling — ``parent = parent[parent]`` per round — which
   **early-exits as soon as every chain has reached its root**
   (``lax.while_loop`` convergence test; the same loop shape as the fused
   Pallas kernel in tpu/pallas_kernels.py, ``lz77_resolve_pallas``).
   ``log2(64 KiB) = 16`` rounds bound the worst case (a block-spanning
   distance-1 RLE run); typical BAM blocks converge in a handful, and the
   per-call round count feeds the ``inflate.rounds`` histogram.

Batching: ALL blocks of a window group go through one tokenize call, one
packed H2D transfer, and one resolve dispatch — (blocks, 64 Ki) lanes per
launch, batch dim padded to a power of two so jit shape churn is bounded.

``InflatePipeline`` overlaps the stages: worker threads run read +
tokenize + pack + **async device dispatch** for up to ``depth`` window
groups while the consumer materializes the previous window's resolved
bytes — real double-buffering, so the device never idles on the host
entropy phase and the host never idles on the device copy phase.

The fully device-resident consumer (``checker.count_window_tokens``) goes
one step further: it takes the packed tokens directly, resolves + windows
+ counts inside ONE program, and only scalars (and the halo carry) ever
leave HBM — see stream_check.StreamChecker.count_reads.

Keeping host zlib as the correctness fallback is permanent policy: the
checker consumes identical flat windows from either producer.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator

import numpy as np

from spark_bam_tpu import obs

log = logging.getLogger(__name__)

import jax
import jax.numpy as jnp
from jax import lax

from spark_bam_tpu.bgzf.block import MAX_BLOCK_SIZE, Metadata
from spark_bam_tpu.bgzf.flat import (
    FlatView, inflate_blocks, read_run_payloads, stage_run_payloads,
)
from spark_bam_tpu.core.channel import open_channel

# Fixed token-row width: one BGZF block inflates to ≤ MAX_BLOCK_SIZE
# (reference Block.scala:49-51).
STRIDE = MAX_BLOCK_SIZE
_DOUBLING_ROUNDS = (STRIDE - 1).bit_length()  # collapses any chain in-range


def pack_tokens(lit: np.ndarray, dist: np.ndarray) -> np.ndarray:
    """Pack (B, STRIDE) u8/u16 token rows into ONE contiguous u8 buffer
    (lit plane, then the dist plane's little-endian bytes) — a single H2D
    transfer instead of two, and the layout `_unpack_tokens` bitcasts back
    for free on device."""
    return np.concatenate([
        np.ascontiguousarray(lit, dtype=np.uint8).reshape(-1),
        np.ascontiguousarray(dist, dtype="<u2").view(np.uint8).reshape(-1),
    ])


def _unpack_tokens(packed: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Device-side inverse of ``pack_tokens`` (shape-derived batch dim)."""
    plane = packed.shape[0] // 3
    b = plane // STRIDE
    lit = packed[:plane].reshape(b, STRIDE)
    dist = lax.bitcast_convert_type(
        packed[plane:].reshape(b, STRIDE, 2), jnp.uint16
    )
    return lit, dist


def _resolve_body(lit: jnp.ndarray, dist: jnp.ndarray):
    """The traced LZ77 resolve: early-exit pointer doubling.

    Returns ``(resolved (B, STRIDE) u8, rounds () i32)``. Convergence test:
    ``parent[parent] == parent`` everywhere ⇔ every pointer reached a root
    (roots are the only fixed points — dist=0 ⇒ parent=i), after which
    further doubling is the identity. Worst case ``_DOUBLING_ROUNDS``; a
    literal-only batch costs exactly one gather (the test itself)."""
    iota = jnp.arange(lit.shape[1], dtype=jnp.int32)[None, :]
    parent = iota - dist.astype(jnp.int32)

    def cond(state):
        _, r, done = state
        return jnp.logical_and(~done, r < _DOUBLING_ROUNDS)

    def body(state):
        p, r, _ = state
        nxt = jnp.take_along_axis(p, p, axis=1)
        return nxt, r + jnp.int32(1), jnp.all(nxt == p)

    roots, rounds, _ = lax.while_loop(
        cond, body, (parent, jnp.int32(0), jnp.bool_(False))
    )
    return jnp.take_along_axis(lit, roots, axis=1), rounds


@jax.jit
def resolve_lz77(lit: jnp.ndarray, dist: jnp.ndarray):
    """Device phase 2: resolve all LZ77 back-references in parallel.

    ``lit``/``dist`` are (B, STRIDE) u8/u16 token rows from the host
    entropy phase (dist=0 ⇒ literal). Returns ``(resolved, rounds)`` —
    the output bytes plus the number of pointer-doubling rounds the batch
    actually needed (early exit on convergence; see ``_resolve_body``).
    Padded tails are dist=0 identities, so they resolve to themselves
    harmlessly.
    """
    return _resolve_body(lit, dist)


@jax.jit
def _resolve_packed(packed: jnp.ndarray):
    """Unpack + resolve in ONE XLA program: the packed token buffer is the
    only H2D operand, the bitcast unpack fuses with the first gather."""
    lit, dist = _unpack_tokens(packed)
    return _resolve_body(lit, dist)


# Resolve straight from unpacked token planes (the device-tokenizer path:
# the planes were BORN on device, there is nothing to unpack). The donated
# variant aliases the lit plane into the resolved output — same (B, STRIDE)
# u8 shape — so the window ring's steady state reuses HBM instead of
# allocating a fresh output plane per window (``Config.inflate`` donate=off
# is the debugging escape hatch; tests/test_tokenize_device.py pins the
# flat-allocation regression).
_resolve_planes = jax.jit(_resolve_body)
_resolve_planes_donated = jax.jit(_resolve_body, donate_argnums=(0,))


# Fused-Pallas LZ77 engine selection. "auto" uses the Pallas kernel on the
# TPU backend (per-block VMEM rows, in-kernel early exit) and the XLA
# while_loop elsewhere; a Mosaic lowering/compile failure demotes to XLA
# permanently for the process (logged once). SPARK_BAM_LZ77=xla|pallas pins.
_lz77_engine: str | None = None


def _lz77_impl() -> str:
    global _lz77_engine
    if _lz77_engine is None:
        env = os.environ.get("SPARK_BAM_LZ77", "").lower()
        if env in ("xla", "pallas"):
            _lz77_engine = env
        else:
            _lz77_engine = (
                "pallas" if jax.default_backend() == "tpu" else "xla"
            )
    return _lz77_engine


def _dispatch_resolve(packed: np.ndarray):
    """H2D + resolve dispatch (async; nothing is synced here). Returns
    ``(resolved_dev (B, STRIDE) u8, rounds_dev () i32)``."""
    global _lz77_engine
    if _lz77_impl() == "pallas":
        try:
            from spark_bam_tpu.tpu.pallas_kernels import lz77_resolve_pallas

            dev = jnp.asarray(packed)
            lit, dist = _unpack_tokens(dev)
            return lz77_resolve_pallas(lit, dist)
        except Exception:
            _lz77_engine = "xla"
            log.warning(
                "Pallas LZ77 kernel unavailable; using the XLA resolve "
                "(reported once per process)", exc_info=True,
            )
    return _resolve_packed(jnp.asarray(packed))


# Device-tokenizer engine selection: same demote policy as the LZ77 engine
# above — "auto" tries the Pallas bit-reader on the TPU backend and falls
# back to the XLA vmap form permanently for the process on Mosaic refusal.
# ``Config.inflate``'s kernel= knob pins either engine explicitly.
_tok_engine: str | None = None


def _tok_impl(kernel: str = "auto") -> str:
    global _tok_engine
    if kernel in ("xla", "pallas"):
        return kernel
    if _tok_engine is None:
        _tok_engine = "pallas" if jax.default_backend() == "tpu" else "xla"
    return _tok_engine


def _dispatch_tokenize(staged_dev, clens_dev, kernel: str = "auto"):
    """Device entropy phase dispatch (async; nothing synced). Takes the
    staged raw-payload matrix + per-row compressed lengths already on
    device; returns ``(lit, dist, out_lens_dev, ok_dev)`` token planes plus
    the per-row produced length and well-formedness flag the materialize
    sync validates against the block footers."""
    global _tok_engine
    if _tok_impl(kernel) == "pallas":
        try:
            from spark_bam_tpu.tpu.pallas_kernels import tokenize_pallas

            return tokenize_pallas(staged_dev, clens_dev)
        except Exception:
            _tok_engine = "xla"
            log.warning(
                "Pallas tokenize kernel unavailable; using the XLA "
                "bit-reader (reported once per process)", exc_info=True,
            )
    from spark_bam_tpu.tpu.tokenize_device import tokenize_planes

    return tokenize_planes(staged_dev, clens_dev)


def _inflate_cfg(spec: str | None = None):
    """The effective ``InflateConfig``: an explicit spec (``Config.inflate``
    threaded down by callers that hold a Config) or the ``SPARK_BAM_INFLATE``
    env var (bench children, ad-hoc scripts)."""
    from spark_bam_tpu.core.inflate_config import InflateConfig

    if spec is None:
        spec = os.environ.get("SPARK_BAM_INFLATE", "")
    return InflateConfig.parse(spec)


def tokenize_pack(
    comp: np.ndarray,
    offsets: np.ndarray,
    lengths: np.ndarray,
    out_lengths: np.ndarray,
):
    """Host entropy phase for a batch of raw-DEFLATE payloads: tokenize,
    verify sizes against the block footers, pow2-pad the batch dim, pack.

    Returns ``(packed u8, out_lens i64 (B,), b)`` — ``b`` the real (un-
    padded) block count — or None when the native tokenizer is missing.
    Raises IOError when the tokenizer disagrees with the footers.
    """
    from spark_bam_tpu.native.build import tokenize_deflate_native

    t_host = time.perf_counter()
    with obs.span("inflate.tokenize", blocks=len(offsets)):
        toks = tokenize_deflate_native(comp, offsets, lengths, stride=STRIDE)
    if toks is None:
        return None
    lit, dist, out_lens = toks
    out_lengths = np.asarray(out_lengths, dtype=np.int64)
    if not np.array_equal(out_lens, out_lengths):
        raise IOError("tokenized output sizes disagree with block footers")
    # Pad the batch dim to a power of two so jit shape churn is bounded to
    # log2(max blocks) compiles, not one per distinct window block count.
    b = len(out_lens)
    b_pad = max(1 << max(b - 1, 0).bit_length(), 1)
    if b_pad != b:
        lit = np.concatenate([lit, np.zeros((b_pad - b, STRIDE), dtype=np.uint8)])
        # dist=0 rows are identity chains — the pad resolves to itself.
        dist = np.concatenate(
            [dist, np.zeros((b_pad - b, STRIDE), dtype=np.uint16)]
        )
    with obs.span("inflate.pack", blocks=b, bytes=lit.nbytes + dist.nbytes):
        packed = pack_tokens(lit, dist)
    # The host entropy phase IS tokenize+pack — both device-inflate
    # consumers (two-phase resolve and the fused count kernel) route
    # through here. Attributed under its own name so the device-tokenizer
    # A/B compares like with like; ``inflate.host_ms`` is only the residual
    # read/boundary-scan work either mode must do on host.
    attribute_ms(tokenize_host_ms=(time.perf_counter() - t_host) * 1e3)
    return packed, out_lens, b


def _record_rounds(rounds_dev) -> None:
    """Feed the rounds-to-convergence histogram (costs one scalar sync —
    only under a live registry)."""
    if obs.enabled():
        try:
            obs.observe("inflate.rounds", int(rounds_dev), unit="rounds")
        except Exception:
            pass


def attribute_ms(host_ms=None, h2d_ms=None, device_ms=None,
                 tokenize_host_ms=None, tokenize_device_ms=None) -> None:
    """Per-window host-vs-device attribution (ROADMAP item 1's missing
    evidence): each phase lands as BOTH a gauge (last window + peak, the
    ``top``/Prometheus view) and an ms-unit histogram (the stage digest
    bench attaches to BENCH_HISTORY rows). No-op without a live registry.

    ``host_ms`` is ONLY the residual host work every mode shares (bulk
    read + boundary scan + staging); the entropy phase reports under the
    tokenize_* names so the host-vs-device tokenizer A/B reads directly
    off the attribution split.
    """
    r = obs.registry()
    if r is None:
        return
    for name, v in (("inflate.host_ms", host_ms),
                    ("inflate.h2d_ms", h2d_ms),
                    ("inflate.device_ms", device_ms),
                    ("inflate.tokenize_host_ms", tokenize_host_ms),
                    ("inflate.tokenize_device_ms", tokenize_device_ms)):
        if v is not None:
            r.gauge(name).set(round(v, 3))
            r.histogram(name, unit="ms").observe(v)


PROFILE_ENV = "SPARK_BAM_PROFILE"
_profiled = False


@contextlib.contextmanager
def maybe_profile_window(label: str = "inflate_window"):
    """One-shot ``jax.profiler.trace`` around the FIRST window of the
    process when ``SPARK_BAM_PROFILE`` names a dump directory (the CLI's
    ``--profile`` flag sets it). Exactly one window is captured — the
    profiler's own overhead would poison every later window's host/device
    attribution. The dump path lands in the flight ring (and the log) so
    ``top``/postmortems can point an operator at the TensorBoard trace.
    Never raises: a missing/failed profiler degrades to a plain window."""
    global _profiled
    out = os.environ.get(PROFILE_ENV)
    if not out or _profiled:
        yield None
        return
    _profiled = True
    path = os.path.join(out, f"profile-{os.getpid()}-{label}")
    try:
        os.makedirs(path, exist_ok=True)
        prof = jax.profiler.trace(path)
        prof.__enter__()
    except Exception:
        log.warning("jax.profiler.trace unavailable; --profile window "
                    "skipped", exc_info=True)
        yield None
        return
    try:
        yield path
    finally:
        try:
            prof.__exit__(None, None, None)
        except Exception:
            log.warning("profiler dump failed", exc_info=True)
        else:
            from spark_bam_tpu.obs import flight

            flight.record("profile_dump", path=path, label=label)
            log.info("profiler trace for one %s written to %s", label, path)


def inflate_blocks_device(
    comp: np.ndarray,
    offsets: np.ndarray,
    lengths: np.ndarray,
    out_lengths: np.ndarray,
) -> np.ndarray | None:
    """Two-phase inflate of raw-DEFLATE payloads: host tokenize + packed
    H2D + device LZ77 resolution, all blocks in ONE kernel launch. Returns
    the concatenated output bytes, or None when the native tokenizer is
    unavailable (callers fall back to zlib)."""
    tp = tokenize_pack(comp, offsets, lengths, out_lengths)
    if tp is None:
        return None
    packed, out_lens, b = tp
    if obs.enabled():
        # Phase-split timing: H2D transfer (one packed buffer) vs the LZ77
        # kernel + D2H. The explicit sync between phases exists only under
        # a live registry — the production path keeps the async dispatch.
        t0 = time.perf_counter()
        with obs.span("inflate.h2d", blocks=b, bytes=packed.nbytes):
            packed_dev = jnp.asarray(packed)
            packed_dev.block_until_ready()
        t1 = time.perf_counter()
        obs.count("inflate.h2d_bytes", int(packed.nbytes))
        with obs.span("inflate.device_kernel", blocks=b):
            resolved_dev, rounds_dev = _resolve_packed(packed_dev)
            resolved = np.asarray(resolved_dev)[:b]
        attribute_ms(h2d_ms=(t1 - t0) * 1e3,
                     device_ms=(time.perf_counter() - t1) * 1e3)
        _record_rounds(rounds_dev)
        obs.count("inflate.device_windows")
    else:
        resolved_dev, rounds_dev = _dispatch_resolve(packed)
        resolved = np.asarray(resolved_dev)[:b]
    return np.concatenate(
        [resolved[i, :n] for i, n in enumerate(out_lens.tolist())]
    ) if len(out_lens) else np.empty(0, dtype=np.uint8)


def _read_group_payloads(ch, metas: list[Metadata]):
    """A group's payload buffer + per-block (offset, length) — one bulk
    positioned read for contiguous runs (host read phase)."""
    return read_run_payloads(ch, metas)


def tokenize_group(ch, metas: list[Metadata]):
    """Read + tokenize + pack one window group of blocks. Returns
    ``(packed, out_lens, b)`` or None (tokenizer unavailable); raises
    IOError on footer disagreement. This is the host half the fully
    device-resident count path feeds to ``checker.count_window_tokens``."""
    t0 = time.perf_counter()
    comp, offs, lens = _read_group_payloads(ch, metas)
    # Residual host work (read + boundary slices) — the part that stays on
    # host no matter where the entropy phase runs.
    attribute_ms(host_ms=(time.perf_counter() - t0) * 1e3)
    usizes = np.array([m.uncompressed_size for m in metas], dtype=np.int64)
    return tokenize_pack(comp, offs, lens, usizes)


def stage_group_device(ch, metas: list[Metadata]):
    """Read + stage + H2D one window group's RAW payloads — the worker-
    thread half of the device-tokenize path. Because this runs on the
    pipeline's producer threads (and the fused count's prefetch pool),
    window k+1's H2D overlaps window k's kernel: ``inflate.h2d_ms`` comes
    off the critical path entirely. Returns
    ``(staged_dev (B_pad, C_pad) u8, clens_dev (B_pad,) i32, usizes)``."""
    t0 = time.perf_counter()
    staged, clens = stage_run_payloads(ch, metas)
    attribute_ms(host_ms=(time.perf_counter() - t0) * 1e3)
    usizes = np.array([m.uncompressed_size for m in metas], dtype=np.int64)
    if obs.enabled():
        t0 = time.perf_counter()
        with obs.span("inflate.h2d", blocks=len(metas), bytes=staged.nbytes):
            staged_dev = jnp.asarray(staged)
            clens_dev = jnp.asarray(clens)
            staged_dev.block_until_ready()
        attribute_ms(h2d_ms=(time.perf_counter() - t0) * 1e3)
        obs.count("inflate.h2d_bytes", int(staged.nbytes))
    else:
        staged_dev = jnp.asarray(staged)
        clens_dev = jnp.asarray(clens)
    return staged_dev, clens_dev, usizes


class _PendingDeviceView:
    """A window group whose resolve dispatch is in flight: the device
    arrays plus everything needed to materialize a FlatView later (the
    double-buffering seam — workers dispatch, the consumer materializes).

    In device-tokenize mode ``tok_ok``/``tok_lens`` carry the bit-reader's
    per-row well-formedness flags and produced lengths; ``materialize``
    validates them against the block footers and raises IOError on any
    disagreement, so a malformed member demotes that window to host zlib —
    the device tokenizer can refuse bytes but never deliver wrong ones."""

    __slots__ = ("resolved_dev", "rounds_dev", "out_lens", "b", "metas",
                 "file_total", "at_eof", "tok_ok", "tok_lens")

    def __init__(self, resolved_dev, rounds_dev, out_lens, b, metas,
                 file_total, at_eof, tok_ok=None, tok_lens=None):
        self.resolved_dev = resolved_dev
        self.rounds_dev = rounds_dev
        self.out_lens = out_lens
        self.b = b
        self.metas = metas
        self.file_total = file_total
        self.at_eof = at_eof
        self.tok_ok = tok_ok
        self.tok_lens = tok_lens

    def materialize(self) -> FlatView:
        t0 = time.perf_counter()
        with obs.span("inflate.device_kernel", blocks=self.b):
            resolved = np.asarray(self.resolved_dev)[: self.b]
        # Async dispatch means the kernel+D2H wait is only observable at
        # the materialize sync — that wait is the window's device_ms.
        if obs.enabled():
            attribute_ms(device_ms=(time.perf_counter() - t0) * 1e3)
        if self.tok_ok is not None:
            ok = np.asarray(self.tok_ok)[: self.b]
            lens = np.asarray(self.tok_lens)[: self.b]
            expected = np.asarray(self.out_lens, dtype=np.int64)
            if not (ok.all() and np.array_equal(lens.astype(np.int64),
                                                expected)):
                obs.count("inflate.tokenize_demotions")
                bad = int(np.argmax(~ok | (lens.astype(np.int64) != expected)))
                raise IOError(
                    f"device tokenizer disagreed with block footers "
                    f"(first bad row {bad}: ok={bool(ok[bad])}, "
                    f"produced={int(lens[bad])}, footer={int(expected[bad])})"
                )
        _record_rounds(self.rounds_dev)
        obs.count("inflate.device_windows")
        data = np.concatenate(
            [resolved[i, :n] for i, n in enumerate(self.out_lens.tolist())]
        ) if len(self.out_lens) else np.empty(0, dtype=np.uint8)
        return _group_view(data, self.metas, self.file_total, self.at_eof)


def _group_view(
    data: np.ndarray, metas: list[Metadata], file_total, at_eof
) -> FlatView:
    usizes = np.array([m.uncompressed_size for m in metas], dtype=np.int64)
    block_flat = np.zeros(len(metas), dtype=np.int64)
    if len(metas):
        np.cumsum(usizes[:-1], out=block_flat[1:])
    total = int(usizes.sum())
    return FlatView(
        data,
        np.array([m.start for m in metas], dtype=np.int64),
        block_flat,
        file_total,
        at_eof or (file_total is not None and total == file_total),
    )


def dispatch_group_device(
    ch,
    metas: list[Metadata],
    file_total: int | None = None,
    at_eof: bool = False,
    inflate_spec: str | None = None,
) -> _PendingDeviceView | None:
    """Host phases + async device dispatch for one group; no sync. Returns
    None when the entropy phase is unavailable (host mode without the
    native tokenizer). ``inflate_spec`` is ``Config.inflate`` — its
    tokenize= knob routes the entropy phase (host tokenize+pack vs the
    device bit-reader over raw payload bytes)."""
    icfg = _inflate_cfg(inflate_spec)
    if icfg.resolve_tokenize() == "device":
        return _dispatch_group_raw(ch, metas, file_total, at_eof, icfg)
    t0 = time.perf_counter()
    comp, offs, lens = _read_group_payloads(ch, metas)
    attribute_ms(host_ms=(time.perf_counter() - t0) * 1e3)
    usizes = np.array([m.uncompressed_size for m in metas], dtype=np.int64)
    tp = tokenize_pack(comp, offs, lens, usizes)
    if tp is None:
        return None
    packed, out_lens, b = tp
    if obs.enabled():
        t0 = time.perf_counter()
        with obs.span("inflate.h2d", blocks=b, bytes=packed.nbytes):
            packed_dev = jnp.asarray(packed)
            packed_dev.block_until_ready()
        attribute_ms(h2d_ms=(time.perf_counter() - t0) * 1e3)
        obs.count("inflate.h2d_bytes", int(packed.nbytes))
        resolved_dev, rounds_dev = _resolve_packed(packed_dev)
    else:
        resolved_dev, rounds_dev = _dispatch_resolve(packed)
    return _PendingDeviceView(
        resolved_dev, rounds_dev, out_lens, b, metas, file_total, at_eof
    )


def _dispatch_group_raw(
    ch, metas, file_total, at_eof, icfg
) -> _PendingDeviceView:
    """Device-tokenize dispatch: raw payload bytes ship (≈1/3 the H2D
    traffic of packed token planes), the bit-reader kernel runs the entropy
    phase, and the LZ77 resolve consumes its planes in place — with
    donation on, the lit plane's HBM is reused as the resolved output, so
    steady state holds one staged matrix + two planes per in-flight window
    instead of growing per window. All dispatches are async; the footer
    validation happens at the materialize sync (never wrong bytes)."""
    staged_dev, clens_dev, usizes = stage_group_device(ch, metas)
    b = len(metas)
    if obs.enabled():
        t0 = time.perf_counter()
        with obs.span("inflate.tokenize_device", blocks=b):
            lit, dist, lens_dev, ok_dev = _dispatch_tokenize(
                staged_dev, clens_dev, icfg.kernel
            )
            ok_dev.block_until_ready()
        attribute_ms(tokenize_device_ms=(time.perf_counter() - t0) * 1e3)
    else:
        lit, dist, lens_dev, ok_dev = _dispatch_tokenize(
            staged_dev, clens_dev, icfg.kernel
        )
    obs.count("inflate.tokenize_blocks", b)
    resolve = _resolve_planes_donated if icfg.donate_enabled else _resolve_planes
    resolved_dev, rounds_dev = resolve(lit, dist)
    return _PendingDeviceView(
        resolved_dev, rounds_dev, usizes, b, metas, file_total, at_eof,
        tok_ok=ok_dev, tok_lens=lens_dev,
    )


def inflate_group_device(
    ch,
    metas: list[Metadata],
    file_total: int | None = None,
    at_eof: bool = False,
    inflate_spec: str | None = None,
) -> FlatView | None:
    """Two-phase device inflate of a run of blocks → FlatView (the device
    producer counterpart of bgzf/flat.py inflate_blocks; synchronous)."""
    pending = dispatch_group_device(
        ch, metas, file_total, at_eof, inflate_spec
    )
    if pending is None:
        return None
    return pending.materialize()


def inflate_file_device(path) -> FlatView | None:
    """Whole-file two-phase device inflate → FlatView (mirrors
    bgzf/flat.py flatten_file, with the device doing the copy phase)."""
    from spark_bam_tpu.bgzf.index_blocks import blocks_metadata

    metas = list(blocks_metadata(path))
    with open_channel(path) as ch:
        view = inflate_group_device(
            ch,
            metas,
            file_total=sum(m.uncompressed_size for m in metas),
            at_eof=True,
        )
    return view


def resolve_device_inflate(config, use_device: bool = True) -> bool:
    """Resolve ``Config.device_inflate``'s auto (``None``) state: True only
    on the TPU backend with the native tokenizer built — the production
    default per the measured A/B (bench.py's device_inflate probe); False
    for host-only consumers (never initializes a JAX backend for them) and
    wherever the tokenizer is missing (the pipeline would demote every
    window to host zlib anyway, with a warning)."""
    if config.device_inflate is not None:
        return config.device_inflate
    if not use_device:
        return False
    import jax

    if jax.default_backend() != "tpu":
        return False
    from spark_bam_tpu.native.build import load_native

    lib = load_native()
    return lib is not None and hasattr(lib, "sbt_tokenize_deflate")


def window_plan(metas: list[Metadata], window_uncompressed: int) -> list[list[Metadata]]:
    """Group consecutive blocks into ≈window-sized uncompressed runs."""
    groups: list[list[Metadata]] = []
    cur: list[Metadata] = []
    size = 0
    for m in metas:
        if cur and size + m.uncompressed_size > window_uncompressed:
            groups.append(cur)
            cur, size = [], 0
        cur.append(m)
        size += m.uncompressed_size
    if cur:
        groups.append(cur)
    return groups


class InflatePipeline:
    """Double-buffered host-inflate → device-window stream.

    With ``device_copy``, worker threads run the host phases (read +
    tokenize + pack) and the *async* device dispatch for up to ``depth``
    groups ahead; the consumer thread materializes resolved windows one at
    a time. Tokenize of window k+1 therefore overlaps the device resolve
    and D2H of window k — the device never idles on the host entropy
    phase."""

    def __init__(
        self,
        path,
        window_uncompressed: int = 64 << 20,
        threads: int = 8,
        device_copy: bool = False,
        depth: int = 2,
        metas: list | None = None,
        inflate_spec: str | None = None,
    ):
        from spark_bam_tpu.bgzf.index_blocks import blocks_metadata

        self.path = path
        # ``Config.inflate`` spec (tokenize=/kernel=/donate=); None reads
        # SPARK_BAM_INFLATE at dispatch time.
        self.inflate_spec = inflate_spec
        # ``metas``: reuse a prior metadata scan (whole-file header walk)
        # when the caller already has one.
        if metas is None:
            with obs.span("bgzf.read", kind="metadata_scan", path=str(path)):
                metas = list(blocks_metadata(path))
        self.metas = metas
        self.total = sum(m.uncompressed_size for m in self.metas)
        self.groups = window_plan(self.metas, window_uncompressed)
        self.threads = threads
        self.device_copy = device_copy
        # Window groups in flight at once: >1 fans the produce stage out
        # across groups (on top of each group's internal block-slice
        # parallelism), keeping every host core busy while the device runs.
        self.depth = max(1, depth)
        self._warned_device_demote = False

    def _demote_warn(self):
        if not self._warned_device_demote:
            self._warned_device_demote = True
            log.warning(
                "device inflate failed; demoting window(s) to host zlib "
                "(reported once per stream)", exc_info=True,
            )

    def __iter__(self) -> Iterator[FlatView]:
        ch = open_channel(self.path)
        if hasattr(ch, "set_plan"):
            # Remote data plane (core/remote_plan.py): the block table IS
            # the exact byte plan — hand it over so the channel coalesces
            # ranged GETs and prefetches in plan order instead of blindly
            # reading ahead of the cursor.
            ch.set_plan(
                (m.start, m.start + m.compressed_size) for m in self.metas
            )
        pool = ThreadPoolExecutor(max_workers=self.depth)

        def produce(group):
            if self.device_copy:
                # Host zlib is the permanent correctness fallback: a stream
                # the tokenizer can't take (or a size disagreement) demotes
                # the window, never kills the pipeline.
                try:
                    pending = dispatch_group_device(
                        ch, group, file_total=self.total,
                        inflate_spec=self.inflate_spec,
                    )
                except Exception:
                    self._demote_warn()
                    pending = None
                if pending is not None:
                    return pending
            return inflate_blocks(
                ch, group, file_total=self.total, threads=self.threads
            )

        try:
            pending = [
                pool.submit(produce, g) for g in self.groups[: self.depth]
            ]
            for i in range(len(self.groups)):
                fut = pending.pop(0)
                with contextlib.ExitStack() as stack:
                    if i == 0:
                        # --profile: the trace spans the first window's
                        # produce overlap AND its materialize sync, and is
                        # closed before the window is yielded so consumer
                        # work stays out of the capture.
                        stack.enter_context(maybe_profile_window())
                    # Double-buffer health: time spent blocked on the host
                    # producer is exactly the stall the ``depth`` knob
                    # exists to hide. >1ms of wait counts as a stall.
                    t0 = time.perf_counter()
                    view = fut.result()
                    wait_ms = (time.perf_counter() - t0) * 1e3
                    obs.observe("inflate.stall_ms", wait_ms, unit="ms")
                    if wait_ms > 1.0:
                        obs.count("inflate.stalls")
                    nxt = i + self.depth
                    if nxt < len(self.groups):
                        pending.append(
                            pool.submit(produce, self.groups[nxt])
                        )
                    if isinstance(view, _PendingDeviceView):
                        # Materialize on the consumer thread: workers are
                        # already tokenizing the NEXT groups while this D2H
                        # syncs (the double-buffering overlap point). An
                        # async dispatch error surfaces here — demote just
                        # this window to host zlib.
                        try:
                            view = view.materialize()
                        except Exception:
                            self._demote_warn()
                            view = inflate_blocks(
                                ch, self.groups[i], file_total=self.total,
                                threads=self.threads,
                            )
                if i == len(self.groups) - 1:
                    view.at_eof = True
                yield view
        finally:
            # Wait for in-flight produce calls: they hold zero-copy views of
            # the mmap, and closing it under them raises BufferError (or
            # worse). Queued-but-unstarted work is cancelled.
            pool.shutdown(wait=True, cancel_futures=True)
            ch.close()
