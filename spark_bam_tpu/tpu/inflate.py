"""BGZF inflate feeding the device: host-parallel path + two-phase device path.

Production path A inflates on host (zlib releases the GIL; a thread pool
saturates cores — bgzf/flat.py) and ships flat windows to HBM. That is
already off the critical path for the checker speedup: SURVEY.md §7 "the
checker/parser speedup does not depend on it [device DEFLATE]".

Path B is the **two-phase device inflate** (SURVEY §7 hard-part #1).
Bit-serial Huffman decoding resists lane-parallelism, so the split is:

1. *Host entropy phase* (`sbt_tokenize_deflate`, native/): decode the
   DEFLATE bitstream into per-output-byte tokens — ``lit[i]`` (the byte, if
   position ``i`` was emitted by a literal) and ``dist[i]`` (0 for
   literals; the back-reference distance otherwise, which fits u16 —
   DEFLATE's max is 32768). Tokens cost 3 wire bytes per output byte on
   the H2D hop; the implied parent pointer ``i - dist[i]`` is
   reconstructed on device from an iota. No byte copying happens on host:
   the LZ77 "copy" half of inflate — the memory-bandwidth half — is
   deferred entirely.
2. *Device copy phase* (`resolve_lz77`): every output byte's value is the
   byte at its pointer chain's root literal. Chains collapse in
   ``log2(64 KiB) = 16`` lock-step pointer-doubling rounds — pure gathers
   over a (blocks, 64 Ki) batch, fully lane-parallel, the same shape the
   checker's chain walk uses. Overlapping copies (RLE runs) are just deep
   chains; correctness is depth-independent.

``InflatePipeline`` overlaps the stages per window — read+tokenize/inflate
(host threads) → H2D transfer → device kernel — double-buffered so the
device never waits on the host for steady-state streams.

Keeping host zlib as the correctness fallback is permanent policy: the
checker consumes identical flat windows from either producer.
"""

from __future__ import annotations

import logging
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator

import numpy as np

from spark_bam_tpu import obs

log = logging.getLogger(__name__)

import jax
import jax.numpy as jnp
from jax import lax

from spark_bam_tpu.bgzf.block import MAX_BLOCK_SIZE, Metadata
from spark_bam_tpu.bgzf.flat import FlatView, inflate_blocks, read_block_payload
from spark_bam_tpu.core.channel import open_channel

# Fixed token-row width: one BGZF block inflates to ≤ MAX_BLOCK_SIZE
# (reference Block.scala:49-51).
STRIDE = MAX_BLOCK_SIZE
_DOUBLING_ROUNDS = (STRIDE - 1).bit_length()  # collapses any chain in-range


@jax.jit
def resolve_lz77(lit: jnp.ndarray, dist: jnp.ndarray) -> jnp.ndarray:
    """Device phase 2: resolve all LZ77 back-references in parallel.

    ``lit``/``dist`` are (B, STRIDE) u8/u16 token rows from the host
    entropy phase (dist=0 ⇒ literal). Parents materialize on device as
    ``i - dist`` (an iota minus the shipped distances — u16 on the wire,
    i32 only in HBM), then pointer chains (copy → … → root literal)
    collapse with log-step doubling — ``parent = parent[parent]`` per
    round — and one final gather reads each root's literal byte. 16
    rounds cover any chain that fits a 64 KiB block; padded tails are
    dist=0 identities, so they resolve to themselves harmlessly.
    """
    iota = jnp.arange(lit.shape[1], dtype=jnp.int32)[None, :]
    parent = iota - dist.astype(jnp.int32)

    def round_(p, _):
        return jnp.take_along_axis(p, p, axis=1), None

    roots, _ = lax.scan(round_, parent, None, length=_DOUBLING_ROUNDS)
    return jnp.take_along_axis(lit, roots, axis=1)


def inflate_blocks_device(
    comp: np.ndarray,
    offsets: np.ndarray,
    lengths: np.ndarray,
    out_lengths: np.ndarray,
) -> np.ndarray | None:
    """Two-phase inflate of raw-DEFLATE payloads: host tokenize + device
    LZ77 resolution. Returns the concatenated output bytes, or None when
    the native tokenizer is unavailable (callers fall back to zlib)."""
    from spark_bam_tpu.native.build import tokenize_deflate_native

    with obs.span("inflate.tokenize", blocks=len(offsets)):
        toks = tokenize_deflate_native(comp, offsets, lengths, stride=STRIDE)
    if toks is None:
        return None
    lit, dist, out_lens = toks
    out_lengths = np.asarray(out_lengths, dtype=np.int64)
    if not np.array_equal(out_lens, out_lengths):
        raise IOError("tokenized output sizes disagree with block footers")
    # Pad the batch dim to a power of two so jit shape churn is bounded to
    # log2(max blocks) compiles, not one per distinct window block count.
    b = len(out_lens)
    b_pad = max(1 << max(b - 1, 0).bit_length(), 1)
    if b_pad != b:
        lit = np.concatenate([lit, np.zeros((b_pad - b, STRIDE), dtype=np.uint8)])
        # dist=0 rows are identity chains — the pad resolves to itself.
        dist = np.concatenate(
            [dist, np.zeros((b_pad - b, STRIDE), dtype=np.uint16)]
        )
    if obs.enabled():
        # Phase-split timing: H2D transfer (jnp.asarray materializes the
        # tokens on device) vs the LZ77 kernel + D2H. The explicit sync
        # between phases exists only under a live registry — the
        # production path keeps the async single-expression dispatch.
        with obs.span("inflate.h2d", blocks=b, bytes=lit.nbytes + dist.nbytes):
            lit_d = jnp.asarray(lit)
            dist_d = jnp.asarray(dist)
            lit_d.block_until_ready()
            dist_d.block_until_ready()
        with obs.span("inflate.device_kernel", blocks=b):
            resolved = np.asarray(resolve_lz77(lit_d, dist_d))[:b]
        obs.count("inflate.device_windows")
    else:
        resolved = np.asarray(
            resolve_lz77(jnp.asarray(lit), jnp.asarray(dist))
        )[:b]
    return np.concatenate(
        [resolved[i, :n] for i, n in enumerate(out_lens.tolist())]
    ) if len(out_lens) else np.empty(0, dtype=np.uint8)


def inflate_group_device(
    ch,
    metas: list[Metadata],
    file_total: int | None = None,
    at_eof: bool = False,
) -> FlatView | None:
    """Two-phase device inflate of a run of blocks → FlatView (the device
    producer counterpart of bgzf/flat.py inflate_blocks)."""
    comp_parts, offs, lens = [], [], []
    off = 0
    for m in metas:
        payload = np.frombuffer(read_block_payload(ch, m), dtype=np.uint8)
        comp_parts.append(payload)
        offs.append(off)
        lens.append(len(payload))
        off += len(payload)
    comp = (
        np.concatenate(comp_parts) if comp_parts else np.empty(0, dtype=np.uint8)
    )
    usizes = np.array([m.uncompressed_size for m in metas], dtype=np.int64)
    data = inflate_blocks_device(
        comp, np.array(offs, dtype=np.int64), np.array(lens, dtype=np.int64), usizes
    )
    if data is None:
        return None
    block_flat = np.zeros(len(metas), dtype=np.int64)
    if len(metas):
        np.cumsum(usizes[:-1], out=block_flat[1:])
    total = int(usizes.sum())
    return FlatView(
        data,
        np.array([m.start for m in metas], dtype=np.int64),
        block_flat,
        file_total,
        at_eof or (file_total is not None and total == file_total),
    )


def inflate_file_device(path) -> FlatView | None:
    """Whole-file two-phase device inflate → FlatView (mirrors
    bgzf/flat.py flatten_file, with the device doing the copy phase)."""
    from spark_bam_tpu.bgzf.index_blocks import blocks_metadata

    metas = list(blocks_metadata(path))
    with open_channel(path) as ch:
        view = inflate_group_device(
            ch,
            metas,
            file_total=sum(m.uncompressed_size for m in metas),
            at_eof=True,
        )
    return view


def resolve_device_inflate(config, use_device: bool = True) -> bool:
    """Resolve ``Config.device_inflate``'s auto (``None``) state: True only
    on the TPU backend with the native tokenizer built — the production
    default per the measured A/B (bench.py's device_inflate probe); False
    for host-only consumers (never initializes a JAX backend for them) and
    wherever the tokenizer is missing (the pipeline would demote every
    window to host zlib anyway, with a warning)."""
    if config.device_inflate is not None:
        return config.device_inflate
    if not use_device:
        return False
    import jax

    if jax.default_backend() != "tpu":
        return False
    from spark_bam_tpu.native.build import load_native

    lib = load_native()
    return lib is not None and hasattr(lib, "sbt_tokenize_deflate")


def window_plan(metas: list[Metadata], window_uncompressed: int) -> list[list[Metadata]]:
    """Group consecutive blocks into ≈window-sized uncompressed runs."""
    groups: list[list[Metadata]] = []
    cur: list[Metadata] = []
    size = 0
    for m in metas:
        if cur and size + m.uncompressed_size > window_uncompressed:
            groups.append(cur)
            cur, size = [], 0
        cur.append(m)
        size += m.uncompressed_size
    if cur:
        groups.append(cur)
    return groups


class InflatePipeline:
    """Double-buffered host-inflate → device-window stream."""

    def __init__(
        self,
        path,
        window_uncompressed: int = 64 << 20,
        threads: int = 8,
        device_copy: bool = False,
        depth: int = 2,
        metas: list | None = None,
    ):
        from spark_bam_tpu.bgzf.index_blocks import blocks_metadata

        self.path = path
        # ``metas``: reuse a prior metadata scan (whole-file header walk)
        # when the caller already has one.
        if metas is None:
            with obs.span("bgzf.read", kind="metadata_scan", path=str(path)):
                metas = list(blocks_metadata(path))
        self.metas = metas
        self.total = sum(m.uncompressed_size for m in self.metas)
        self.groups = window_plan(self.metas, window_uncompressed)
        self.threads = threads
        self.device_copy = device_copy
        # Window groups in flight at once: >1 fans the produce stage out
        # across groups (on top of each group's internal block-slice
        # parallelism), keeping every host core busy while the device runs.
        self.depth = max(1, depth)
        self._warned_device_demote = False

    def __iter__(self) -> Iterator[FlatView]:
        ch = open_channel(self.path)
        pool = ThreadPoolExecutor(max_workers=self.depth)

        def produce(group):
            if self.device_copy:
                # Host zlib is the permanent correctness fallback: a stream
                # the tokenizer can't take (or a size disagreement) demotes
                # the window, never kills the pipeline.
                try:
                    view = inflate_group_device(ch, group, file_total=self.total)
                except Exception:
                    if not self._warned_device_demote:
                        self._warned_device_demote = True
                        log.warning(
                            "device inflate failed; demoting window(s) to "
                            "host zlib (reported once per stream)",
                            exc_info=True,
                        )
                    view = None
                if view is not None:
                    return view
            return inflate_blocks(
                ch, group, file_total=self.total, threads=self.threads
            )

        try:
            pending = [
                pool.submit(produce, g) for g in self.groups[: self.depth]
            ]
            for i in range(len(self.groups)):
                fut = pending.pop(0)
                # Double-buffer health: time spent blocked on the host
                # producer is exactly the stall the ``depth`` knob exists
                # to hide. >1ms of wait counts as a stall.
                t0 = time.perf_counter()
                view = fut.result()
                wait_ms = (time.perf_counter() - t0) * 1e3
                obs.observe("inflate.stall_ms", wait_ms, unit="ms")
                if wait_ms > 1.0:
                    obs.count("inflate.stalls")
                nxt = i + self.depth
                if nxt < len(self.groups):
                    pending.append(pool.submit(produce, self.groups[nxt]))
                if i == len(self.groups) - 1:
                    view.at_eof = True
                yield view
        finally:
            # Wait for in-flight produce calls: they hold zero-copy views of
            # the mmap, and closing it under them raises BufferError (or
            # worse). Queued-but-unstarted work is cancelled.
            pool.shutdown(wait=True, cancel_futures=True)
            ch.close()
