"""Device-resident DEFLATE tokenization: the bit-reader that replaces
the host entropy phase.

The two-phase device inflate (tpu/inflate.py) splits DEFLATE into an
entropy phase (bitstream → per-output-byte lit/dist tokens) and a copy
phase (LZ77 pointer-chain resolution). Until now the entropy phase ran
on host (``sbt_tokenize_deflate``) and every window shipped 3 bytes of
tokens per output byte over the bus. This module moves the entropy
phase onto the device: ``_tokenize_row`` walks ONE raw-DEFLATE
bitstream — dynamic/fixed Huffman table decode (canonical-code build
from the HLIT/HDIST/HCLEN header, code-length run expansion 16/17/18),
stored blocks, and symbol emission — producing token planes
**bit-identical** to the native tokenizer's, so the downstream resolve/
count kernels are unchanged. vmapped over a window's blocks, only the
*compressed* payload bytes cross the bus (~3-6x less H2D traffic than
token planes, and none of the host tokenize wall time).

Decoding untrusted bytes in fixed-shape SIMD code means every error is
a flag, not an exception: each row carries an ``ok`` lane that goes
False on any malformation the native tokenizer rejects (oversubscribed
code, bad stored-block LEN/~NLEN, distance beyond output, truncated
stream, symbol 286/287, missing end-of-block code). The driver
(tpu/inflate.py) checks ``ok`` and the produced lengths against the
BGZF footers at materialize time and demotes failing windows to host —
**never wrong bytes**.

Loop shape: the symbol loop is bit-serial by nature (each code's length
is only known after decoding it), so one row is a ``while_loop`` whose
trip count is bounded by the payload bit length. Parallelism comes from
the batch dim — one lane per BGZF block — which is exactly the Pallas
grid mapping in ``pallas_kernels.tokenize_pallas``; this module's XLA
``vmap`` form is the portable fallback the dispatch demotes to.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_bam_tpu.bgzf.block import MAX_BLOCK_SIZE

#: Token-row width (one BGZF block inflates to ≤ 64 KiB) — must match
#: the resolve kernels' STRIDE.
STRIDE = MAX_BLOCK_SIZE
_S = STRIDE
#: Windowed-write width: ≥ 258 (DEFLATE's max match) so any single
#: symbol lands in one masked write; 512 keeps stored-block copies to
#: a few iterations per block.
_WIN = 512
#: Plane slack so windowed writes at o near STRIDE never clamp.
_SP = _S + _WIN
#: Code-length scratch width: 286+30 lens + 144 run-write slack
#: (a 138-max run written 144 wide can start at index tot-1).
_LENS_W = 464

# RFC 1951 3.2.5 length/distance base+extra tables. Built under
# ensure_compile_time_eval: this module's first import may happen INSIDE a
# jit trace (the fused count kernel defers the import), and a device_put
# under tracing would bake tracers into module globals.
with jax.ensure_compile_time_eval():
    _LEN_BASE = jnp.array(
        [3, 4, 5, 6, 7, 8, 9, 10, 11, 13, 15, 17, 19, 23, 27, 31, 35, 43,
         51, 59, 67, 83, 99, 115, 131, 163, 195, 227, 258], jnp.int32)
    _LEN_EXTRA = jnp.array(
        [0, 0, 0, 0, 0, 0, 0, 0, 1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 3, 4, 4,
         4, 4, 5, 5, 5, 5, 0], jnp.int32)
    _DIST_BASE = jnp.array(
        [1, 2, 3, 4, 5, 7, 9, 13, 17, 25, 33, 49, 65, 97, 129, 193, 257,
         385, 513, 769, 1025, 1537, 2049, 3073, 4097, 6145, 8193, 12289,
         16385, 24577], jnp.int32)
    _DIST_EXTRA = jnp.array(
        [0, 0, 0, 0, 1, 1, 2, 2, 3, 3, 4, 4, 5, 5, 6, 6, 7, 7, 8, 8, 9, 9,
         10, 10, 11, 11, 12, 12, 13, 13], jnp.int32)
    # RFC 1951 3.2.7: the order code-length-code lengths appear in.
    _CL_ORDER = jnp.array(
        [16, 17, 18, 0, 8, 7, 9, 6, 10, 5, 11, 4, 12, 3, 13, 2, 14, 1, 15],
        jnp.int32)


def _bits(comp, clen8, bp, n, ok):
    """Read ``n`` (traced, ≤ 16) LSB-first bits at bit offset ``bp``.

    One aligned-enough 4-byte dynamic_slice covers any 16-bit read at
    any bit phase; ``ok`` goes False when the read runs past the
    payload's ``clen8`` bit length (truncated stream)."""
    byte = bp >> 3
    w = lax.dynamic_slice(comp, (byte,), (4,)).astype(jnp.uint32)
    v = w[0] | (w[1] << 8) | (w[2] << 16) | (w[3] << 24)
    v = v >> (bp & 7).astype(jnp.uint32)
    nn = n.astype(jnp.uint32) if hasattr(n, "astype") else jnp.uint32(n)
    v = jnp.where(nn >= 32, v, v & ((jnp.uint32(1) << nn) - 1))
    return v.astype(jnp.int32), bp + n, ok & (bp + n <= clen8)


def _huff_build(lens, nc, valid_n):
    """Canonical-code table build (RFC 1951 3.2.2): per-length counts
    plus the (length, symbol)-ordered symbol list — the same two arrays
    the native decoder peels codes against. ``lens`` is a fixed-width
    i32 vector; entries at index ≥ ``nc`` are masked out. Returns
    ``(count (16,), symbol (N,), ok)``; ok False on over-subscription
    (the all-zero table is legal — decode then fails on first use)."""
    n = lens.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    active = idx < nc
    l = jnp.where(active, jnp.clip(lens, 0, 15), 0)
    nz = active & (l > 0)
    count = jnp.zeros(16, jnp.int32).at[l].add(nz.astype(jnp.int32))

    def left_body(ln, st):
        left, bad = st
        left = left * 2 - count[ln]
        return left, bad | (left < 0)

    _, oversub = lax.fori_loop(
        1, 16, left_body, (jnp.int32(1), jnp.bool_(False))
    )
    ok = (count[1:].sum() == 0) | ~oversub
    # Stable (length, index) sort via one integer key; zero-length
    # symbols sink past every real code so ``symbol[index + code -
    # first]`` only ever reads coded symbols.
    key = jnp.where(nz, l, 16) * jnp.int32(valid_n) + idx
    symbol = jnp.argsort(key).astype(jnp.int32)
    return count, symbol, ok


def _huff_decode(comp, clen8, bp, ok, count, symbol):
    """Decode one canonical-Huffman symbol, peeling bits LSB-first
    against the running first-code-of-length (the native decoder's
    exact loop). Returns ``(sym or -1, bp, ok)`` — no code of length
    ≤ 15 matching means a corrupt stream."""
    n = symbol.shape[0]

    def body(ln, st):
        code, first, index, bpos, res, found, okk = st
        bit, bp_n, ok_n = _bits(comp, clen8, bpos, jnp.int32(1), okk)
        take = ~found & okk
        valid = take & ok_n
        code = code | jnp.where(valid, bit, 0)
        cnt = count[ln]
        hit = valid & (code - cnt < first)
        res = jnp.where(
            hit, symbol[jnp.clip(index + code - first, 0, n - 1)], res
        )
        adv = valid & ~hit
        return (
            jnp.where(adv, code << 1, code),
            jnp.where(adv, (first + cnt) << 1, first),
            jnp.where(adv, index + cnt, index),
            jnp.where(take, bp_n, bpos),
            res,
            found | hit,
            jnp.where(take, ok_n, okk),
        )

    code, first, index, bp, res, found, ok = lax.fori_loop(
        1, 16, body,
        (jnp.int32(0), jnp.int32(0), jnp.int32(0), bp, jnp.int32(-1),
         jnp.bool_(False), ok),
    )
    return jnp.where(found & ok, res, -1), bp, ok & found


def _fixed_tables_np():
    """The BTYPE=01 fixed litlen/dist tables (RFC 1951 3.2.6), built
    once in numpy so tracing only sees constants."""
    lens = np.zeros(288, np.int32)
    lens[:144] = 8
    lens[144:256] = 9
    lens[256:280] = 7
    lens[280:] = 8
    dlens = np.full(30, 5, np.int32)

    def build(ls, valid_n):
        count = np.zeros(16, np.int64)
        for v in ls:
            count[v] += 1
        key = np.where(ls > 0, ls, 16) * valid_n + np.arange(len(ls))
        return count.astype(np.int32), np.argsort(key).astype(np.int32)

    lc, lsym = build(lens, 288)
    dc, dsym = build(dlens, 30)
    return lc, lsym, dc, dsym


with jax.ensure_compile_time_eval():
    _F_LC, _F_LSYM, _F_DC, _F_DSYM = (
        jnp.asarray(a) for a in _fixed_tables_np()
    )

#: Every bitstream-constant table, in the Pallas operand order. The XLA
#: vmap form closes over these as compile-time constants, but
#: ``pallas_call`` refuses captured array constants — its kernel gets
#: them as explicit inputs (pallas_kernels.tokenize_pallas) and threads
#: them back in through ``_tokenize_row``'s ``tabs`` parameter.
TABLES = (_CL_ORDER, _LEN_BASE, _LEN_EXTRA, _DIST_BASE, _DIST_EXTRA,
          _F_LC, _F_LSYM, _F_DC, _F_DSYM)


def _window_write(buf, start, values, mask):
    """Masked windowed write: ``buf[start + k] = values[k]`` where
    ``mask[k]`` — a read-modify-write slice pair, the fixed-shape form
    of a variable-length emit."""
    win = lax.dynamic_slice(buf, (start,), (values.shape[0],))
    return lax.dynamic_update_slice(
        buf, jnp.where(mask, values, win), (start,)
    )


def _dynamic_tables(comp, clen8, bp, ok, cl_order):
    """Decode a BTYPE=10 header: HLIT/HDIST/HCLEN, the code-length code,
    then the run-expanded (16=repeat-prev, 17/18=zero-run) code lengths;
    build both canonical tables. Mirrors the native decoder's checks:
    HLIT ≤ 286, HDIST ≤ 30, no repeat-prev at index 0, runs may not
    overflow HLIT+HDIST, and the litlen table must code symbol 256."""
    hlit, bp, ok = _bits(comp, clen8, bp, jnp.int32(5), ok)
    hlit = hlit + 257
    hdist, bp, ok = _bits(comp, clen8, bp, jnp.int32(5), ok)
    hdist = hdist + 1
    hclen, bp, ok = _bits(comp, clen8, bp, jnp.int32(4), ok)
    hclen = hclen + 4
    ok = ok & (hlit <= 286) & (hdist <= 30)

    def cl_body(i, st):
        cl_lens, bpos, okk = st
        v, bp_n, ok_n = _bits(comp, clen8, bpos, jnp.int32(3), okk)
        use = i < hclen
        cl_lens = cl_lens.at[cl_order[i]].set(jnp.where(use, v, 0))
        return (
            cl_lens,
            jnp.where(use, bp_n, bpos),
            jnp.where(use, ok_n, okk),
        )

    cl_lens, bp, ok = lax.fori_loop(
        0, 19, cl_body, (jnp.zeros(19, jnp.int32), bp, ok)
    )
    cl_count, cl_sym, cl_ok = _huff_build(cl_lens, jnp.int32(19), 19)
    ok = ok & cl_ok

    tot = hlit + hdist
    lens0 = jnp.zeros(_LENS_W, jnp.int32)
    run_iota = jnp.arange(144, dtype=jnp.int32)

    def run_cond(st):
        _, cl_i, _, okk = st
        return okk & (cl_i < tot)

    def run_body(st):
        lens, cl_i, bpos, okk = st
        sym, bp1, ok1 = _huff_decode(comp, clen8, bpos, okk, cl_count, cl_sym)
        ok1 = ok1 & (sym >= 0)
        # Decode all three extra-bit widths from bp1 and select — cheaper
        # than a branch, and the unused reads can't fail harder than the
        # selected one.
        v2, bp2, ok2 = _bits(comp, clen8, bp1, jnp.int32(2), ok1)
        v3, bp3, ok3 = _bits(comp, clen8, bp1, jnp.int32(3), ok1)
        v7, bp7, ok7 = _bits(comp, clen8, bp1, jnp.int32(7), ok1)
        prev = lens[jnp.clip(cl_i - 1, 0, _LENS_W - 1)]
        is16 = sym == 16
        is17 = sym == 17
        is18 = sym == 18
        lit_sym = (sym >= 0) & (sym < 16)
        repeat = jnp.where(
            lit_sym, 1,
            jnp.where(is16, 3 + v2, jnp.where(is17, 3 + v3, 11 + v7)),
        )
        value = jnp.where(lit_sym, sym, jnp.where(is16, prev, 0))
        bp_n = jnp.where(
            lit_sym, bp1, jnp.where(is16, bp2, jnp.where(is17, bp3, bp7))
        )
        ok_n = jnp.where(
            lit_sym, ok1, jnp.where(is16, ok2, jnp.where(is17, ok3, ok7))
        )
        ok_n = ok_n & ~(is16 & (cl_i == 0))
        ok_n = ok_n & (cl_i + repeat <= tot)
        rep_eff = jnp.where(ok_n, repeat, 0)
        lens = _window_write(
            lens, cl_i, jnp.full(144, 1, jnp.int32) * value,
            run_iota < rep_eff,
        )
        return lens, cl_i + rep_eff, bp_n, ok_n

    lens, cl_i, bp, ok = lax.while_loop(
        run_cond, run_body, (lens0, jnp.int32(0), bp, ok)
    )
    ok = ok & (lens[256] > 0)
    lit_count, lit_sym, lok = _huff_build(lens[:288], hlit, 288)
    didx = jnp.arange(30, dtype=jnp.int32)
    dlens = lens[jnp.clip(hlit + didx, 0, _LENS_W - 1)]
    dist_count, dist_sym, dok = _huff_build(dlens, hdist, 30)
    return lit_count, lit_sym, dist_count, dist_sym, bp, ok & lok & dok


def _tokenize_row(comp, clen, tabs=None):
    """Tokenize ONE raw-DEFLATE stream.

    ``comp`` is the zero-padded (C_pad,) u8 payload (``bgzf.flat.
    stage_run_payloads`` staging convention: C_pad ≥ clen + 8 so the
    4-byte bit reads never leave the row), ``clen`` its real byte
    length. Returns ``(lit (S,) u8, dist (S,) u16, out_len i32, ok
    bool)`` — token planes bit-identical to native ``tokenize_one``:
    ``lit[i]`` is the byte where position ``i`` came from a literal
    (dist 0), else ``dist[i]`` the back-reference distance; tails
    beyond ``out_len`` are zero. ``ok`` False ⇔ the native tokenizer
    would reject the stream (callers demote those rows to host).
    ``tabs`` overrides the module ``TABLES`` (the Pallas kernel passes
    its VMEM copies; everyone else closes over the constants)."""
    (cl_order, len_base, len_extra, dist_base, dist_extra,
     f_lc, f_lsym, f_dc, f_dsym) = TABLES if tabs is None else tabs
    clen8 = clen * 8
    c_pad = comp.shape[0]
    win_iota = jnp.arange(_WIN, dtype=jnp.int32)

    def stored_block(bp, o, ok, lit_buf, dist_buf):
        bp = (bp + 7) & ~7
        ln, bp, ok = _bits(comp, clen8, bp, jnp.int32(16), ok)
        nln, bp, ok = _bits(comp, clen8, bp, jnp.int32(16), ok)
        ok = ok & ((ln ^ 0xFFFF) == nln)

        def cond(st):
            left, _, _, okk, _, _ = st
            return okk & (left > 0)

        def body(st):
            left, bpos, oo, okk, lbuf, dbuf = st
            src = bpos >> 3
            chunk = jnp.minimum(left, _WIN)
            okk = okk & (src + chunk <= clen) & (oo + chunk <= _S)
            chunk = jnp.where(okk, chunk, 0)
            # Element-clipped gather, NOT a dynamic_slice: a 512-wide
            # slice near the row's end would clamp its *start* and
            # silently misread; per-element clipping only pins the
            # masked-out tail lanes.
            vals = comp[jnp.clip(src + win_iota, 0, c_pad - 1)]
            mask = win_iota < chunk
            lbuf = _window_write(lbuf, oo, vals, mask)
            dbuf = _window_write(dbuf, oo, jnp.zeros(_WIN, jnp.uint16), mask)
            return left - chunk, bpos + chunk * 8, oo + chunk, okk, lbuf, dbuf

        left0 = jnp.where(ok, ln, 0)
        _, bp, o, ok, lit_buf, dist_buf = lax.while_loop(
            cond, body, (left0, bp, o, ok, lit_buf, dist_buf)
        )
        return bp, o, ok, lit_buf, dist_buf

    def huff_block(btype, bp, ok, o, lit_buf, dist_buf):
        dyn = _dynamic_tables(comp, clen8, bp, ok & (btype == 2), cl_order)
        is_dyn = btype == 2
        lit_count = jnp.where(is_dyn, dyn[0], f_lc)
        lit_sym = jnp.where(is_dyn, dyn[1], f_lsym)
        dist_count = jnp.where(is_dyn, dyn[2], f_dc)
        dist_sym = jnp.where(is_dyn, dyn[3], f_dsym)
        bp = jnp.where(is_dyn, dyn[4], bp)
        ok = jnp.where(is_dyn, dyn[5], ok)
        # A symbol consumes ≥ 1 bit, so clen8 + slack bounds the trip
        # count — the backstop that keeps a corrupt stream from looping.
        cap_steps = clen8 + 64

        def cond(st):
            _, _, okk, fin, _, _, steps = st
            return okk & ~fin & (steps < cap_steps)

        def body(st):
            bpos, oo, okk, fin, lbuf, dbuf, steps = st
            sym, bp1, ok1 = _huff_decode(
                comp, clen8, bpos, okk, lit_count, lit_sym
            )
            is_lit = (sym >= 0) & (sym < 256)
            is_eob = sym == 256
            is_match = sym > 256
            ok1 = ok1 & (sym >= 0)
            sym2 = jnp.clip(sym - 257, 0, 28)
            # 286/287 are coded-but-invalid litlen symbols.
            okm = ok1 & ~(is_match & (sym - 257 >= 29))
            lext = len_extra[sym2]
            vl, bp2, okm = _bits(comp, clen8, bp1, lext, okm)
            mlen = len_base[sym2] + vl
            dsym, bp3, okm = _huff_decode(
                comp, clen8, bp2, okm, dist_count, dist_sym
            )
            okm = okm & (dsym >= 0) & (dsym < 30)
            dext = dist_extra[jnp.clip(dsym, 0, 29)]
            vd, bp4, okm = _bits(comp, clen8, bp3, dext, okm)
            mdist = dist_base[jnp.clip(dsym, 0, 29)] + vd
            # Distance may not reach before the stream; output may not
            # overflow the 64 KiB row (BGZF guarantees it fits).
            okm = okm & (mdist <= oo) & (oo + mlen <= _S)
            okl = ok1 & (oo < _S)
            step_ok = jnp.where(is_lit, okl, jnp.where(is_match, okm, ok1))
            count = jnp.where(
                step_ok & is_lit, 1, jnp.where(step_ok & is_match, mlen, 0)
            )
            lval = jnp.where(is_lit, sym, 0).astype(jnp.uint8)
            dval = jnp.where(is_match, mdist, 0).astype(jnp.uint16)
            mask = win_iota < count
            lbuf = _window_write(
                lbuf, oo, jnp.full(_WIN, 1, jnp.uint8) * lval, mask
            )
            dbuf = _window_write(
                dbuf, oo, jnp.full(_WIN, 1, jnp.uint16) * dval, mask
            )
            bp_n = jnp.where(is_lit | is_eob, bp1, bp4)
            return (
                bp_n, oo + count, step_ok, fin | (is_eob & ok1),
                lbuf, dbuf, steps + 1,
            )

        bp, o, ok, fin, lit_buf, dist_buf, _ = lax.while_loop(
            cond, body,
            (bp, o, ok, jnp.bool_(False), lit_buf, dist_buf, jnp.int32(0)),
        )
        # No end-of-block code before the bits ran out ⇒ corrupt.
        ok = ok & fin
        return bp, o, ok, lit_buf, dist_buf

    def outer_cond(st):
        _, _, ok, done, _, _ = st
        return ~done

    def outer_body(st):
        bp, o, ok, _, lit_buf, dist_buf = st
        bfinal, bp, ok = _bits(comp, clen8, bp, jnp.int32(1), ok)
        btype, bp, ok = _bits(comp, clen8, bp, jnp.int32(2), ok)
        ok = ok & (btype != 3)
        is_stored = ok & (btype == 0)
        s_bp, s_o, s_ok, s_lit, s_dist = stored_block(
            bp, o, ok & is_stored, lit_buf, dist_buf
        )
        h_bp, h_o, h_ok, h_lit, h_dist = huff_block(
            btype, bp, ok & ~is_stored, o, lit_buf, dist_buf
        )
        bp = jnp.where(is_stored, s_bp, h_bp)
        o = jnp.where(is_stored, s_o, h_o)
        ok = ok & jnp.where(is_stored, s_ok, h_ok)
        lit_buf = jnp.where(is_stored, s_lit, h_lit)
        dist_buf = jnp.where(is_stored, s_dist, h_dist)
        done = ~ok | (bfinal == 1)
        return bp, o, ok, done, lit_buf, dist_buf

    bp, o, ok, _, lit_buf, dist_buf = lax.while_loop(
        outer_cond, outer_body,
        (jnp.int32(0), jnp.int32(0), jnp.bool_(True), jnp.bool_(False),
         jnp.zeros(_SP, jnp.uint8), jnp.zeros(_SP, jnp.uint16)),
    )
    return lit_buf[:_S], dist_buf[:_S], o, ok


@jax.jit
def tokenize_planes(staged, clens):
    """XLA form of the device tokenizer: one lane per staged payload row.

    ``staged`` is (B, C_pad) u8 (``stage_run_payloads`` convention),
    ``clens`` (B,) i32. Returns ``(lit (B, S) u8, dist (B, S) u16,
    out_lens (B,) i32, ok (B,) bool)``. Zero-length rows (batch pad)
    come back ``ok=False`` with ``out_len=0`` — callers treat
    ``clen == 0`` rows as vacuously fine."""
    return jax.vmap(_tokenize_row)(staged, clens)
