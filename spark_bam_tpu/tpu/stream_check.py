"""Streaming whole-file checking: larger-than-memory BAMs.

Stitches the InflatePipeline's block-aligned windows with a carried tail so
every chain can complete, and runs the window kernel over each stitched
buffer. Ownership tiles the uncompressed stream exactly; candidates whose
chains outrun even the stitched buffer stay *pending* and resolve against
later windows (the carry grows to keep every pending position in view), so
results equal the in-memory whole-file run byte-for-byte.

This is the scale path of BASELINE.json's NA12878/WGS configs: memory use
is O(window + carry), not O(file).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.tpu.checker import TpuChecker
from spark_bam_tpu.tpu.inflate import InflatePipeline


def stream_verdicts(
    path,
    config: Config = Config(),
    window_uncompressed: int | None = None,
    halo: int | None = None,
    use_device: bool = True,
) -> Iterator[tuple[int, np.ndarray]]:
    """Yield (absolute flat base, verdict array) spans tiling the file."""
    header = read_header(path)
    lengths = np.array(header.contig_lengths.lengths_list(), dtype=np.int32)
    window_uncompressed = window_uncompressed or config.window_size
    halo = halo or config.halo_size

    pipeline = InflatePipeline(path, window_uncompressed=window_uncompressed)

    checker: TpuChecker | None = None

    def check(buf: np.ndarray, at_eof: bool):
        nonlocal checker
        if use_device:
            want = max(len(buf), 1)
            kernel_window = 1 << max(20, (want - 1).bit_length())
            if checker is None or checker.window < kernel_window:
                checker = TpuChecker(
                    lengths,
                    window=kernel_window,
                    halo=min(halo, kernel_window // 4),
                    reads_to_check=config.reads_to_check,
                )
            return checker.check_buffer(buf, at_eof=at_eof)
        from spark_bam_tpu.check.vectorized import check_flat

        return check_flat(buf, lengths, at_eof=at_eof,
                          reads_to_check=config.reads_to_check)

    carry = np.empty(0, dtype=np.uint8)
    carry_abs = 0          # absolute flat offset of carry[0] (0 before start)
    owned_until = 0        # absolute: spans emitted so far tile [0, owned_until)
    pending_abs: list[int] = []  # owned positions still unresolved

    for view in pipeline:
        buf = np.concatenate([carry, view.data]) if len(carry) else view.data
        base = carry_abs
        at_eof = view.at_eof

        res = check(buf, at_eof)

        # Resolve pendings that now have more lookahead.
        if pending_abs:
            idxs = np.array(pending_abs, dtype=np.int64) - base
            assert (idxs >= 0).all(), "carry must retain pending positions"
            for abs_pos, rel in zip(list(pending_abs), idxs):
                if at_eof or not res.escaped[rel]:
                    yield abs_pos, res.verdict[rel: rel + 1]
                    pending_abs.remove(abs_pos)

        # This window's newly-owned span (the carry may reach back into
        # territory earlier windows already emitted).
        own_end = len(buf) if at_eof else max(len(buf) - halo, 0)
        lo = owned_until - base
        if own_end > lo:
            verdict = res.verdict[lo:own_end].copy()
            if not at_eof:
                esc = np.flatnonzero(res.escaped[lo:own_end])
                for i in esc:
                    pending_abs.append(base + lo + int(i))
                verdict[esc] = False  # reported via the pending path instead
            yield base + lo, verdict
            owned_until = base + own_end

        if at_eof:
            break
        # Carry enough tail to keep halo AND all pending positions in view.
        carry_from = own_end
        if pending_abs:
            carry_from = min(carry_from, min(pending_abs) - base)
        carry = buf[carry_from:].copy()
        carry_abs = base + carry_from

    assert not pending_abs, "pendings must resolve by EOF"


def count_reads_streaming(
    path, config: Config = Config(), window_uncompressed: int | None = None,
    halo: int | None = None, use_device: bool = True,
) -> int:
    """Record count via streaming verdicts (the count-reads scale path)."""
    header = read_header(path)
    total = 0
    # Header occupies the leading uncompressed bytes; its end in flat terms:
    from spark_bam_tpu.bgzf.index_blocks import blocks_metadata

    metas = list(blocks_metadata(path))
    flat_of_block = {}
    acc = 0
    for m in metas:
        flat_of_block[m.start] = acc
        acc += m.uncompressed_size
    header_end_abs = (
        flat_of_block[header.end_pos.block_pos] + header.end_pos.offset
    )

    for base, verdict in stream_verdicts(
        path, config, window_uncompressed, halo, use_device
    ):
        if len(verdict) == 1:  # a resolved pending position
            if base >= header_end_abs:
                total += int(verdict[0])
            continue
        lo = max(header_end_abs - base, 0)
        total += int(verdict[lo:].sum())
    return total
