"""Streaming whole-file checking: larger-than-memory BAMs.

This is the production scale path of BASELINE.json's NA12878/WGS configs
*and* the path bench.py measures — one code path, O(window) host memory.

Design (the double-buffered halo-carry loop):

- The ``InflatePipeline`` produces block-aligned uncompressed windows
  (host-parallel inflate, two windows in flight). Each kernel buffer is
  ``carry + window`` where ``carry`` is the previous buffer's trailing
  ``halo`` bytes, so every owned position has ≥ ``halo`` bytes of
  lookahead for its ``reads_to_check`` chain.
- Ownership tiles the uncompressed stream exactly: a non-final buffer
  owns everything but its halo tail; the halo positions are owned (and
  re-evaluated with full lookahead) by the next buffer.
- Two windows are in flight: window *k+1* is dispatched to the device
  before window *k*'s results are materialized, so host inflate, H2D
  transfer, and the kernel overlap.
- Candidates whose chains outrun even the halo (ultra-long reads — the
  reference bounds a boundary scan by ``maxReadSize`` = 10 MB,
  check/.../package.scala:49-57) *escape*; escaped owned positions are
  deferred into a side buffer of raw bytes that grows until their chains
  can complete, then resolve through the native tri-state walk (verdict
  projections) or the NumPy engine (flag projections). Deferred
  positions are reported ``False`` in their covering span and re-emitted
  as contiguous-run spans once resolved; every resolution is vectorized
  — O(pending) per window, never O(pending²).

The span contract: ``spans()`` yields ``(base, verdict)`` pairs whose
``True`` positions are exactly the record starts of the file. Window
spans tile ``[0, total)`` in order; deferred candidates (``False`` in
their covering span) re-emit later as spans whose ``base`` lies strictly
*behind* the tiling frontier — that, not span length, is how to tell a
re-emission from a window span. The same
window loop also projects ``full_spans()`` (all-19-flag masks — the
full-check workload) and ``read_batches()`` (columnar parses with exact
spill decode — the load workload).

``count_reads()`` never materializes per-position arrays on host: each
window runs one fused kernel whose owned-span count reduces on-chip, the
scalars accumulate on device, and a handful of integers cross the wire
per ~2^30 positions (reference workload: count-reads,
docs/benchmarks.md:53-59).

When the device inflate is live (``Config.device_inflate`` /
``fused_count``), ``count_reads`` goes one step further and runs the
**fully device-resident** loop: the host ships only the packed LZ77
token planes per window, and ``checker.count_window_tokens`` resolves +
assembles + funnels + walks inside one XLA program — the inflated bytes
never exist on host, the halo carry stays in HBM between windows, and
only the count scalars cross back. Host tokenize of the next windows
overlaps the device's current one via the same prefetch pool.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Iterator

import numpy as np

import jax
import jax.numpy as jnp

from spark_bam_tpu import obs
from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.check.vectorized import check_flat
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.tpu.inflate import InflatePipeline, resolve_device_inflate


def _next_pow2(n: int) -> int:
    return 1 << max(0, (max(n, 1) - 1).bit_length())


def pad_contig_lengths(lengths: np.ndarray, cmax: int = 1024) -> np.ndarray:
    """Contig lengths zero-padded to a static kernel shape."""
    lens = np.zeros(max(cmax, len(lengths)), dtype=np.int32)
    lens[: len(lengths)] = lengths
    return lens


def halo_windows(pipeline, halo: int, header_end: int):
    """Yield ``(buf, base, own_end, lo, at_eof)`` rows with the halo-carry
    ownership discipline — the single source of truth for seam semantics,
    shared by ``StreamChecker`` (single device) and
    ``parallel.stream_mesh.count_reads_sharded`` (whole mesh):

    - each buffer is ``carry + window`` where carry is the previous
      buffer's trailing ``halo`` bytes, so every owned position has
      ≥ halo bytes of chain lookahead;
    - a non-final buffer owns everything but its halo tail (the next
      buffer re-evaluates those positions with full lookahead); the final
      buffer owns through EOF;
    - ``lo`` clamps the owned span's start past the BAM header, so header
      bytes are never counted as record starts.
    """
    carry = np.empty(0, dtype=np.uint8)
    base_next = 0
    for view in pipeline:
        base = base_next
        buf = np.concatenate([carry, view.data]) if len(carry) else view.data
        n = len(buf)
        at_eof = view.at_eof
        own_end = n if at_eof else max(n - halo, 0)
        lo = min(max(header_end - base, 0), own_end)
        yield buf, base, own_end, lo, at_eof
        carry = buf[own_end:]
        base_next = base + own_end


@jax.jit
def _reduce_span(verdict, escaped, lo, hi):
    """Device-side reduction of one window's owned span → two scalars."""
    i = jnp.arange(verdict.shape[0], dtype=jnp.int32)
    m = (i >= lo) & (i < hi)
    return jnp.sum(m & verdict), jnp.sum(m & escaped)


class StreamChecker:
    """Whole-file streaming checker over a fixed device kernel window.

    Parameters mirror the ``spark.bam.*`` config surface: ``window``/``halo``
    from ``Config`` unless overridden; ``use_device=False`` runs the NumPy
    engine (the differential oracle) through the identical control flow.
    ``progress(windows_done, positions_done, total_positions)`` is invoked
    after each window resolves (the bench's per-window stage markers).
    """

    def __init__(
        self,
        path,
        config: Config = Config(),
        window_uncompressed: int | None = None,
        halo: int | None = None,
        use_device: bool = True,
        progress: Callable[[int, int, int], None] | None = None,
        pipeline_threads: int | None = None,
        pipeline_depth: int | None = None,
        metas: list | None = None,
    ):
        self.path = path
        self.config = config
        self.use_device = use_device
        self.progress = progress
        self.header = read_header(path)
        self.lengths = np.array(
            self.header.contig_lengths.lengths_list(), dtype=np.int32
        )
        fresh = window_uncompressed or config.window_size
        halo = config.halo_size if halo is None else halo
        # The halo must leave room to advance; chains needing more lookahead
        # than the halo escape to the deferral path and still resolve exactly.
        self.halo = min(halo, fresh // 2)
        pipe_kw = {}
        if pipeline_threads is not None:
            pipe_kw["threads"] = pipeline_threads
        if pipeline_depth is not None:
            pipe_kw["depth"] = pipeline_depth
        # ``metas``: reuse a caller's whole-file block-metadata scan (a
        # header walk over every BGZF block — seconds on multi-GB files).
        self.pipeline = InflatePipeline(
            path, window_uncompressed=fresh,
            device_copy=resolve_device_inflate(config, use_device),
            metas=metas, inflate_spec=config.inflate, **pipe_kw,
        )
        self.total = self.pipeline.total
        # Kernel shape: one power of two covering carry + window, clamped to
        # the file so small inputs compile a small kernel.
        self.kernel_window = _next_pow2(
            min(fresh + self.halo, max(self.total, 1 << 16))
        )
        # Absolute flat offset of the first record: the header's size in
        # uncompressed bytes IS that offset (bam/header.py measures it by
        # position after the contig dictionary).
        self.header_end_abs = self.header.uncompressed_size
        # Flush the device count accumulators to host ints often enough
        # that the int32 sums cannot overflow: ≤ 2^30 positions per chunk
        # (Config.flush_every overrides within that cap).
        self.flush_every = config.flush_every_for(self.kernel_window)
        # Pacing depth of the fused count ring (Config.ring_depth).
        self.ring_depth = max(1, config.ring_depth)
        # Funnel totals across the consuming projection (positions
        # screened by stage 0 / stage-0 survivors); None until a funnelled
        # window lands — the CLI's ``funnel:`` summary line reads this.
        self.funnel_stats: dict | None = None

    # ------------------------------------------------------------ the loop
    def _windows(self, launch):
        """Yield ``(buf, base, own_end, at_eof, launched)`` one window behind
        the device: window *k+1* is dispatched before *k* is yielded, so the
        consumer's host work overlaps the device. Seam semantics live in
        ``halo_windows`` (shared with the mesh streaming path)."""
        prev = None
        for buf, base, own_end, lo, at_eof in halo_windows(
            self.pipeline, self.halo, self.header_end_abs
        ):
            out = launch(buf, len(buf), at_eof, lo, own_end)
            if prev is not None:
                yield prev
            prev = (buf, base, own_end, at_eof, out)
        if prev is not None:
            yield prev

    def _device_inputs(self):
        lens = pad_contig_lengths(self.lengths)
        lens_dev = jax.device_put(jnp.asarray(lens))
        return lens_dev, jnp.int32(len(self.lengths))

    def _flags_impl(self) -> str:
        return self.config.flags_impl

    def _funnel_add(self, screened: int, survivors: int):
        """Fold one window's (or chunk's) funnel totals into the stats
        surface and the ``funnel.*`` observability counters."""
        if self.funnel_stats is None:
            self.funnel_stats = {"screened": 0, "survivors": 0}
        self.funnel_stats["screened"] += screened
        self.funnel_stats["survivors"] += survivors
        if obs.enabled():
            obs.count("funnel.positions", screened)
            obs.count("funnel.survivors", survivors)
            obs.observe("funnel.window_survivors", survivors)
            obs.observe("funnel.reduction", screened / max(survivors, 1))

    def _launcher(self, full_masks: bool = False):
        """Full-output launch (the spans path)."""
        if not self.use_device:
            return lambda buf, n, at_eof, lo, own_end: None  # host-lazy
        from spark_bam_tpu.tpu.checker import PAD, make_check_window

        kernel = make_check_window(
            self.kernel_window, self.config.reads_to_check,
            flags_impl=self._flags_impl(),
            funnel=self.config.funnel_enabled(full_masks),
        )
        lens_dev, nc = self._device_inputs()
        w = self.kernel_window

        def launch(buf, n, at_eof, lo, own_end):
            padded = np.zeros(w + PAD, dtype=np.uint8)
            padded[:n] = buf
            # Fresh buffer per window (never mutated after dispatch): safe
            # under async dispatch even when jnp.asarray aliases zero-copy
            # on the CPU backend.
            return kernel(
                jnp.asarray(padded), lens_dev, nc, jnp.int32(n),
                jnp.bool_(at_eof),
            )

        return launch

    def _count_launcher(self):
        """Fused count launch: one dispatch per window, scatters DCE'd."""
        from spark_bam_tpu.tpu.checker import PAD, make_count_window

        kernel = make_count_window(
            self.kernel_window, self.config.reads_to_check,
            flags_impl=self._flags_impl(),
            funnel=self.config.funnel_enabled(),
        )
        lens_dev, nc = self._device_inputs()
        w = self.kernel_window

        def launch(buf, n, at_eof, lo, own_end):
            padded = np.zeros(w + PAD, dtype=np.uint8)
            padded[:n] = buf
            return kernel(
                jnp.asarray(padded), lens_dev, nc, jnp.int32(n),
                jnp.bool_(at_eof), jnp.int32(lo), jnp.int32(own_end),
            )

        return launch

    def _materialize(self, buf, at_eof, out) -> dict:
        """One window's per-position results as host arrays."""
        if out is None:
            res = check_flat(
                buf, self.lengths, at_eof=at_eof,
                reads_to_check=self.config.reads_to_check,
            )
            return {
                "verdict": res.verdict, "escaped": res.escaped,
                "exact": res.exact, "fail_mask": res.fail_mask,
                "reads_before": res.reads_before,
            }
        return {k: np.asarray(v) for k, v in out.items()}

    # --------------------------------------------------- deferred candidates
    class _Deferred:
        """Escaped owned positions + the byte stream that will resolve them.

        ``buf`` holds raw bytes from ``base`` (the earliest pending
        position) through the newest window's end; it extends as windows
        arrive and trims as pendings resolve. All operations are
        vectorized over the pending set.
        """

        def __init__(self, lengths: np.ndarray, reads_to_check: int):
            self.lengths = lengths
            self.rtc = reads_to_check
            self.pending = np.empty(0, dtype=np.int64)
            self.base = 0
            self.buf = np.empty(0, dtype=np.uint8)
            # Absolute stream tip at the last whole-buffer chains attempt
            # (the flags-projection resolver); gates re-attempts so the
            # O(retained-span) flag recompute runs only after meaningful
            # growth, not every window.
            self._gate_tip = 0

        def __len__(self):
            return len(self.pending)

        def extend(self, win_buf: np.ndarray, win_base: int):
            """Grow the byte stream with a window's newly-seen bytes."""
            if not len(self.pending):
                return
            tip = self.base + len(self.buf)
            if win_base + len(win_buf) > tip:
                self.buf = np.concatenate(
                    [self.buf, win_buf[max(tip - win_base, 0):]]
                )

        def add(self, positions: np.ndarray, win_buf: np.ndarray, win_base: int):
            if not len(positions):
                return
            if not len(self.pending):
                self.base = int(positions.min())
                self.buf = win_buf[self.base - win_base:].copy()
            self.pending = np.concatenate([self.pending, positions])

        def _retire(self, done: np.ndarray) -> np.ndarray:
            """Drop resolved pendings; trim the buffer to the earliest
            survivor. Returns the retired positions."""
            positions = self.pending[done]
            self.pending = self.pending[~done]
            if not len(self.pending):
                self.buf = np.empty(0, dtype=np.uint8)
            else:
                lo = int(self.pending.min())
                self.buf = self.buf[lo - self.base:]
                self.base = lo
            return positions

        def _resolve_chains(self, at_eof: bool):
            """One sequential-exact pass over pendings; returns (positions
            resolved, their ChainResult rows) and retires them.

            Retirement requires full exactness (``~escaped & exact``) — an
            inexact lane's flags may still change once the buffer grows past
            its chain, so it stays pending (it always converges: with the
            chain span fully in-buffer the re-check is exact, and at EOF
            everything is definitive)."""
            res = check_flat(
                self.buf, self.lengths,
                candidates=self.pending - self.base,
                at_eof=at_eof, reads_to_check=self.rtc,
            )
            done = (~res.escaped) & res.exact
            return self._retire(done), res, done

        @staticmethod
        def _emit_runs(positions: np.ndarray, rows: tuple):
            """Group ascending resolved positions into contiguous runs and
            yield span-style ``(run_start, per-field arrays)`` tuples —
            one emission per run instead of one per position (sub-record
            windows defer whole windows at a time; per-position tuples
            were the re-emission half of the long-read perf cliff)."""
            if not len(positions):
                return
            breaks = np.flatnonzero(np.diff(positions) != 1) + 1
            for seg in np.split(np.arange(len(positions)), breaks):
                yield int(positions[seg[0]]), tuple(r[seg] for r in rows)

        def resolve(self, at_eof: bool, fields: tuple[str, ...]):
            """Re-check pendings against the grown stream; yield
            ``(pos, row)`` — ``row`` holds one array per projected field
            covering a contiguous run of positions from ``pos`` — for
            each pending run now resolved with certainty.

            The verdict-only projection (spans/count) resolves through the
            native tri-state chain walk when built: it touches only the
            ~``reads_to_check`` records each chain actually visits. The
            flag projections need a whole-buffer flag pass per attempt
            (their masks come from the full pass), so attempts are gated:
            only at EOF or once the stream grew by ≥¼ of the retained
            span since the last attempt. Ungated, sub-record windows
            (ultra-long reads) recompute the span every window —
            O(span²) per record."""
            if not len(self.pending):
                return
            obs.count("check.defer_retries")
            if fields == ("verdict",):
                from spark_bam_tpu.native.build import eager_check_window_native

                tri = eager_check_window_native(
                    self.buf, self.pending - self.base, self.lengths,
                    reads_to_check=self.rtc, exact_eof=at_eof,
                )
                if tri is not None:
                    verdicts = tri[tri != 2] == 1
                    positions = self._retire(tri != 2)
                    obs.count("check.defer_resolved", len(positions))
                    yield from self._emit_runs(positions, (verdicts,))
                    return
            tip = self.base + len(self.buf)
            if not at_eof and tip - self._gate_tip < (tip - self.base) // 4:
                return
            self._gate_tip = tip
            positions, res, done = self._resolve_chains(at_eof)
            obs.count("check.defer_resolved", len(positions))
            rows = tuple(np.asarray(getattr(res, f))[done] for f in fields)
            yield from self._emit_runs(positions, rows)

    # ------------------------------------------------------------- consumers
    def _stream(
        self,
        fields: tuple[str, ...],
        defer_inexact: bool,
        with_buf: bool = False,
    ):
        """The shared window loop behind ``spans``/``full_spans``/
        ``read_batches``: project ``fields`` from each window's results,
        defer unresolved owned lanes (escaped chains; plus inexact ones when
        the projection includes flags), and re-emit them as contiguous-run
        spans once exact. ``with_buf`` appends the window's byte buffer to
        each window tuple (``None`` on deferred re-emissions)."""
        deferred = self._Deferred(self.lengths, self.config.reads_to_check)
        windows = 0
        funnel = self.use_device and self.config.funnel_enabled(defer_inexact)
        for buf, base, own_end, at_eof, out in self._windows(
            self._launcher(full_masks=defer_inexact)
        ):
            with obs.span("check.window", base=base, own=own_end):
                res = self._materialize(buf, at_eof, out)
                if funnel:
                    self._funnel_add(len(buf), int(res["survivors"]))
                spans = [res[f][:own_end].copy() for f in fields]
                bad = res["escaped"][:own_end]
                if defer_inexact:
                    bad = bad | ~res["exact"][:own_end]
                deferred.extend(buf, base)
                bad_idx = np.flatnonzero(bad)
                if len(bad_idx):
                    for s in spans:
                        s[bad_idx] = 0  # re-emitted by the deferral path
                    deferred.add(base + bad_idx, buf, base)
            if obs.enabled():
                obs.count("check.windows")
                obs.count("check.positions", own_end)
                obs.count("check.deferred", len(bad_idx))
                # The escaped sum is an O(own_end) pass — only pay it
                # under a live registry.
                obs.count(
                    "check.escaped", int(res["escaped"][:own_end].sum())
                )
            yield (base, *spans, buf) if with_buf else (base, *spans)
            for pos, row in deferred.resolve(at_eof, fields):
                yield (pos, *row, None) if with_buf else (pos, *row)
            windows += 1
            if self.progress is not None:
                self.progress(windows, base + own_end, self.total)
        assert not len(deferred), "pendings must resolve by EOF"

    def spans(self) -> Iterator[tuple[int, np.ndarray]]:
        """Yield ``(base, verdict)`` spans; see the module contract."""
        yield from self._stream(("verdict",), defer_inexact=False)

    def count_reads(self) -> int:
        """Record count (the count-reads workload).

        On device, each window runs ONE fused kernel whose owned-span count
        reduces on-chip, and the per-window scalars accumulate *on device* —
        nothing crosses the wire until EOF (device→host round-trips per
        window are the latency tax on remote/tunnelled devices). A pacing
        sync on a two-windows-old scalar bounds in-flight windows (and HBM)
        without a transfer. If any owned candidate escaped (chains beyond
        the halo — ultra-long reads), the exact spans() path re-runs the
        file with full deferral; on real data with the default halo this
        never triggers.
        """
        if not self.use_device:
            return self._count_via_spans()
        fused = self.config.fused_count
        if fused is None:
            fused = self.pipeline.device_copy
        if fused:
            res = self._count_reads_fused()
            if res is not None:
                return res
        total = 0
        dev_total = None
        dev_esc = None
        dev_surv = None
        windows = 0
        chunk = 0
        screened = 0
        flush_every = self.flush_every
        funnel = self.config.funnel_enabled()
        escaped = False
        # pacing: keep ≤ ring_depth windows' scalars un-synced
        ring: list = []
        for buf, base, own_end, at_eof, out in self._windows(
            self._count_launcher()
        ):
            dev_total = (
                out["count"] if dev_total is None else dev_total + out["count"]
            )
            dev_esc = (
                out["esc_count"] if dev_esc is None
                else dev_esc + out["esc_count"]
            )
            dev_surv = (
                out["survivors"] if dev_surv is None
                else dev_surv + out["survivors"]
            )
            screened += len(buf)
            ring.append(out["count"])
            if len(ring) > self.ring_depth:
                ring.pop(0).block_until_ready()
            windows += 1
            chunk += 1
            obs.count("check.windows")
            obs.count("check.positions", own_end)
            if self.progress is not None:
                self.progress(windows, base + own_end, self.total)
            # One early escape checkpoint (window 4): escape-prone inputs
            # (ultra-long reads vs this halo) abort to the exact path after
            # ~4 windows instead of after a whole flush interval (up to
            # 2^30 positions of doomed device work). Costs a single extra
            # device sync per file; the steady-state policy stays
            # flush-aligned so tunnelled devices aren't synced per window.
            if windows == 4 and int(dev_esc):
                escaped = True
                break
            if chunk >= flush_every:
                # Escape checkpoint rides the flush: abort to the exact
                # path early instead of finishing a doomed device pass.
                if int(dev_esc):
                    escaped = True
                    break
                total += int(dev_total)
                if funnel:
                    self._funnel_add(screened, int(dev_surv))
                dev_total = dev_esc = dev_surv = None
                chunk = 0
                screened = 0
        if not escaped and dev_total is not None:
            if int(dev_esc):
                escaped = True
            else:
                total += int(dev_total)
                if funnel:
                    self._funnel_add(screened, int(dev_surv))
        if escaped:
            # Rare exact path (chains outran the halo — ultra-long reads):
            # the spans path resolves every deferral bit-exactly. Suppress
            # progress so consumers don't see the counters restart.
            obs.count("check.count_escape_retries")
            saved, self.progress = self.progress, None
            try:
                return self._count_via_spans()
            finally:
                self.progress = saved
        return total

    def _count_reads_fused(self) -> int | None:
        """The fully device-resident count loop: packed tokens in, scalars
        out, carry chained in HBM.

        Per window group, the host runs only the entropy phase
        (read + tokenize + pack, prefetched ``pipeline.depth`` groups
        ahead on worker threads) and ships ONE packed u8 buffer;
        ``checker.count_window_tokens`` does LZ77 resolve → window
        assembly → funnel/deep check → chain walk in one XLA program, with
        the (halo,) carry fed device-to-device between windows — the
        serial carry dependency chains the kernels in the device stream
        while the host tokenizes ahead, so neither side idles. Pacing,
        flush, and escape checkpoints mirror ``count_reads``.

        Returns None to demote to the classic (host-inflate) streaming
        loop: tokenizer unavailable, a stream it rejects, or a window
        group that cannot fit the kernel geometry. Nothing is consumed
        from ``self.pipeline`` before demotion — the classic path restarts
        cleanly. Escapes (chains beyond the halo) go to the exact spans
        path, as everywhere.
        """
        from concurrent.futures import ThreadPoolExecutor

        from spark_bam_tpu.native.build import load_native
        from spark_bam_tpu.core.channel import open_channel
        from spark_bam_tpu.tpu.checker import (
            make_count_window_raw, make_count_window_tokens,
        )
        from spark_bam_tpu.tpu.inflate import (
            _tok_impl, attribute_ms, maybe_profile_window,
            stage_group_device, tokenize_group,
        )

        icfg = self.config.inflate_config
        device_tok = icfg.resolve_tokenize() == "device"
        if not device_tok:
            lib = load_native()
            if lib is None or not hasattr(lib, "sbt_tokenize_deflate"):
                return None
        groups = self.pipeline.groups
        if not groups:
            return None
        w = self.kernel_window
        halo = self.halo
        # Every window must fit the kernel: carry (≤ halo) + group bytes.
        if max(
            sum(m.uncompressed_size for m in g) for g in groups
        ) + halo > w:
            return None

        funnel = self.config.funnel_enabled()
        if device_tok:
            # tokenize=device: workers stage + H2D the RAW payload matrix
            # (overlapping the kernel), and the entropy phase runs inside
            # the fused program. Any row the bit-reader rejects — or whose
            # produced length disagrees with its footer — flips the
            # kernel's tok_ok scalar and demotes the whole count to the
            # host-tokenize path; bad decodes never reach the total.
            kernel = make_count_window_raw(
                w, halo, self.config.reads_to_check,
                flags_impl=self._flags_impl(), funnel=funnel,
                tok_impl=_tok_impl(icfg.kernel),
                donate=icfg.donate_enabled,
            )
        else:
            kernel = make_count_window_tokens(
                w, halo, self.config.reads_to_check,
                flags_impl=self._flags_impl(), funnel=funnel,
            )
        lens_dev, nc = self._device_inputs()

        total = 0
        dev_total = dev_esc = dev_surv = None
        windows = 0
        chunk = 0
        screened = 0
        flush_every = self.flush_every
        escaped = False
        demoted = False
        ring: list = []
        ok_ring: list = []
        carry_dev = jnp.zeros(halo, dtype=jnp.uint8)
        carry_len = 0
        base = 0
        produce = stage_group_device if device_tok else tokenize_group

        ch = open_channel(self.path)
        pool = ThreadPoolExecutor(max_workers=self.pipeline.depth)
        try:
            pending = [
                pool.submit(produce, ch, g)
                for g in groups[: self.pipeline.depth]
            ]
            for gi in range(len(groups)):
                fut = pending.pop(0)
                t0 = time.perf_counter()
                try:
                    tp = fut.result()
                except Exception:
                    # A stream the tokenizer rejects (or a footer
                    # disagreement): demote the whole count to the host-
                    # inflate loop — correctness never depends on phase 1.
                    demoted = True
                    break
                wait_ms = (time.perf_counter() - t0) * 1e3
                obs.observe("inflate.stall_ms", wait_ms, unit="ms")
                if wait_ms > 1.0:
                    obs.count("inflate.stalls")
                if tp is None:
                    demoted = True
                    break
                nxt = gi + self.pipeline.depth
                if nxt < len(groups):
                    pending.append(
                        pool.submit(produce, ch, groups[nxt])
                    )
                if device_tok:
                    staged_dev, clens_dev, usizes = tp
                    n = carry_len + int(usizes.sum())
                else:
                    packed, out_lens, _b = tp
                    n = carry_len + int(out_lens.sum())
                at_eof = gi == len(groups) - 1
                own_end = n if at_eof else max(n - halo, 0)
                lo = min(max(self.header_end_abs - base, 0), own_end)
                with contextlib.ExitStack() as stack:
                    if gi == 0:
                        # --profile: one-shot capture of the first fused
                        # window (H2D + count kernel + the rounds sync).
                        stack.enter_context(maybe_profile_window(
                            label="count_window"))
                    if device_tok:
                        # H2D happened on the producer thread
                        # (stage_group_device) — off this critical path.
                        exp = np.zeros(staged_dev.shape[0], dtype=np.int32)
                        exp[: len(usizes)] = usizes
                        out = kernel(
                            staged_dev, clens_dev, jnp.asarray(exp),
                            carry_dev, lens_dev, nc,
                            jnp.int32(carry_len), jnp.int32(n),
                            jnp.bool_(at_eof), jnp.int32(lo),
                            jnp.int32(own_end),
                        )
                        ok_ring.append(out["tok_ok"])
                        obs.count("inflate.tokenize_blocks", len(usizes))
                    else:
                        obs.count("inflate.h2d_bytes", int(packed.nbytes))
                        if obs.enabled():
                            # H2D split: sync the packed transfer alone
                            # before the kernel dispatch. Only under a live
                            # registry — the production path stays fully
                            # async.
                            t_h2d = time.perf_counter()
                            packed_dev = jnp.asarray(packed)
                            packed_dev.block_until_ready()
                            attribute_ms(
                                h2d_ms=(time.perf_counter() - t_h2d) * 1e3
                            )
                        else:
                            packed_dev = jnp.asarray(packed)
                        out = kernel(
                            packed_dev,
                            jnp.asarray(out_lens.astype(np.int32)),
                            carry_dev, lens_dev, nc,
                            jnp.int32(carry_len), jnp.int32(n),
                            jnp.bool_(at_eof), jnp.int32(lo),
                            jnp.int32(own_end),
                        )
                    carry_dev = out["carry"]
                    carry_len = n - own_end
                    base += own_end
                    if obs.enabled():
                        # The rounds sync below is the first wait on the
                        # dispatch — its wall time IS the window's device
                        # phase (kernel + scalar D2H).
                        t_dev = time.perf_counter()
                        rounds = int(out["rounds"])
                        attribute_ms(
                            device_ms=(time.perf_counter() - t_dev) * 1e3
                        )
                        obs.observe("inflate.rounds", rounds, unit="rounds")
                        obs.count("inflate.device_windows")
                dev_total = (
                    out["count"] if dev_total is None
                    else dev_total + out["count"]
                )
                dev_esc = (
                    out["esc_count"] if dev_esc is None
                    else dev_esc + out["esc_count"]
                )
                dev_surv = (
                    out["survivors"] if dev_surv is None
                    else dev_surv + out["survivors"]
                )
                screened += n
                ring.append(out["count"])
                if len(ring) > self.ring_depth:
                    ring.pop(0).block_until_ready()
                    # Validate the bit-reader verdicts lazily, at the same
                    # pacing sync: a rejected row anywhere demotes the
                    # whole count (the classic loop restarts from scratch;
                    # nothing was consumed from self.pipeline).
                    if ok_ring and not bool(ok_ring.pop(0)):
                        obs.count("inflate.tokenize_demotions")
                        demoted = True
                        break
                windows += 1
                chunk += 1
                obs.count("check.windows")
                obs.count("check.positions", own_end)
                if self.progress is not None:
                    self.progress(windows, base, self.total)
                # Same escape-checkpoint policy as count_reads: one early
                # sync at window 4, then flush-aligned.
                if windows == 4 and int(dev_esc):
                    escaped = True
                    break
                if chunk >= flush_every:
                    if int(dev_esc):
                        escaped = True
                        break
                    total += int(dev_total)
                    if funnel:
                        self._funnel_add(screened, int(dev_surv))
                    dev_total = dev_esc = dev_surv = None
                    chunk = 0
                    screened = 0
        finally:
            pool.shutdown(wait=True, cancel_futures=True)
            ch.close()
        if not demoted and ok_ring and not all(bool(ok) for ok in ok_ring):
            obs.count("inflate.tokenize_demotions")
            demoted = True
        if demoted:
            return None
        if not escaped and dev_total is not None:
            if int(dev_esc):
                escaped = True
            else:
                total += int(dev_total)
                if funnel:
                    self._funnel_add(screened, int(dev_surv))
        if escaped:
            obs.count("check.count_escape_retries")
            saved, self.progress = self.progress, None
            try:
                return self._count_via_spans()
            finally:
                self.progress = saved
        return total

    def count_reads_resident(
        self, chunk_windows: int | None = None,
        first_chunk_windows: int = 4,
    ) -> int:
        """Record count with ONE device dispatch per resident chunk.

        ``count_reads`` dispatches the fused kernel once per window; a
        remote/tunnelled device charges a multi-second round-trip per
        dispatch, which caps streaming throughput far below the chip's
        kernel rate (measured: ~4.9 s/dispatch vs ~400 µs of compute).
        Here windows are packed into HBM-resident chunks and
        ``checker.count_scan`` walks all of a chunk's windows inside one
        XLA program — the round-trip is paid once per ~``chunk_windows``
        windows. The first chunk is small (``first_chunk_windows``) so
        escape-prone inputs (ultra-long reads vs this halo) abort to the
        exact path early, mirroring ``count_reads``'s window-4 checkpoint.

        Chunk device buffers are K·w+PAD bytes with K bucketed to a power
        of two (dummy rows own nothing), bounding recompiles to one per
        bucket; per-chunk positions stay < 2^31 so the on-device int32
        sums cannot overflow. Falls back to the exact spans path on any
        escape, and to the streaming loop if a pipeline row ever exceeds
        the kernel window (cannot happen with the block-aligned pipeline,
        but exactness must not depend on that).
        """
        if not self.use_device:
            return self._count_via_spans()
        from spark_bam_tpu.tpu.checker import PAD, make_count_scan

        w = self.kernel_window
        # Chunk bytes at the PACKED stride (w+PAD) are capped by
        # ``Config.resident_chunk_bytes`` (≤ 1 GiB): the 1 GiB ceiling keeps
        # the int32 ``starts`` offsets < 2^30 even after pow2 bucketing (the
        # bucket can double a non-pow2 row count) and per-chunk positions
        # < 2^31 for the on-device sums; the config default (256 MiB) also
        # leaves HBM headroom for the scan body's intermediates — BENCH_r05's
        # resident leg OOM-crashed the TPU worker with 1 GiB chunks in
        # flight ×2 plus the window intermediates. Floor-pow2 so the bucket
        # never exceeds the cap.
        cap_bytes = min(
            1 << 30, max(self.config.resident_chunk_bytes, w + PAD)
        )
        max_windows = max(1, cap_bytes // (w + PAD))
        max_windows = 1 << (max_windows.bit_length() - 1)
        if chunk_windows is None:
            chunk_windows = max_windows
        else:
            chunk_windows = min(chunk_windows, max_windows)
        kernel = make_count_scan(
            w, self.config.reads_to_check, flags_impl=self._flags_impl(),
            funnel=self.config.funnel_enabled(),
        )
        funnel = self.config.funnel_enabled()
        lens_dev, nc = self._device_inputs()

        total = 0
        # Per-chunk (count, esc) device scalars, folded to host ints one
        # chunk behind (keeps ≤ 2 chunks in flight; folding per chunk also
        # keeps every int32 sum within one chunk's < 2^31 positions — the
        # cross-chunk accumulator lives on host).
        pend: list = []
        windows_done = 0
        escaped = False

        def flush(rows):
            """Pack rows into a bucketed chunk and dispatch once.

            Row stride is w+PAD, not w: each window's slice is
            ``chunk[s : s+w+PAD]`` and ``check_window`` requires zeros
            beyond the row's valid bytes — at stride w the PAD lookahead
            would read the NEXT row (a halo-rewound, wrong-offset view of
            the stream), corrupting flags near the row end for chains
            that sample there (long-read regime). The per-row zero gap
            costs PAD/w ≈ 0.8% extra HBM."""
            k = len(rows)
            kp = _next_pow2(k)
            stride = w + PAD
            chunk = np.zeros(kp * stride, dtype=np.uint8)
            starts = np.arange(kp, dtype=np.int32) * stride
            ns = np.zeros(kp, dtype=np.int32)
            aes = np.zeros(kp, dtype=bool)
            los = np.zeros(kp, dtype=np.int32)
            owns = np.zeros(kp, dtype=np.int32)
            for j, (buf, ae, lo, own) in enumerate(rows):
                chunk[j * stride: j * stride + len(buf)] = buf
                ns[j], aes[j], los[j], owns[j] = len(buf), ae, lo, own
            return kernel(
                jnp.asarray(chunk), lens_dev, nc, jnp.asarray(starts),
                jnp.asarray(ns), jnp.asarray(aes), jnp.asarray(los),
                jnp.asarray(owns),
            )

        rows: list = []
        chunks = 0
        cap = first_chunk_windows
        pos_flushed = 0
        gen = halo_windows(self.pipeline, self.halo, self.header_end_abs)
        try:
            for buf, base, own_end, lo, at_eof in gen:
                if len(buf) > w:  # impossible with the block-aligned pipeline
                    return self.count_reads()
                rows.append((buf, at_eof, lo, own_end))
                windows_done += 1
                pos_flushed = base + own_end
                obs.count("check.windows")
                obs.count("check.positions", own_end)
                if len(rows) >= cap:
                    out = flush(rows)
                    scr = sum(len(r[0]) for r in rows)
                    rows = []
                    chunks += 1
                    cap = chunk_windows
                    pend.append(
                        (out["count"], out["esc_count"], out["survivors"], scr)
                    )
                    # Sync the first (small) chunk's scalars immediately;
                    # after that, one chunk behind.
                    if chunks == 1 or len(pend) > 1:
                        cnt, esc, surv, scr = pend.pop(0)
                        if int(esc):
                            escaped = True
                            break
                        total += int(cnt)
                        if funnel:
                            self._funnel_add(scr, int(surv))
                    # Progress at dispatch points only: buffered-but-unsent
                    # windows must not inflate the forensics position.
                    if self.progress is not None:
                        self.progress(windows_done, pos_flushed, self.total)
        finally:
            gen.close()
        if not escaped:
            if rows:
                out = flush(rows)
                scr = sum(len(r[0]) for r in rows)
                pend.append(
                    (out["count"], out["esc_count"], out["survivors"], scr)
                )
            for cnt, esc, surv, scr in pend:
                if int(esc):
                    escaped = True
                    break
                total += int(cnt)
                if funnel:
                    self._funnel_add(scr, int(surv))
            if not escaped and self.progress is not None and windows_done:
                self.progress(windows_done, pos_flushed, self.total)
        if escaped:
            obs.count("check.count_escape_retries")
            saved, self.progress = self.progress, None
            try:
                return self._count_via_spans()
            finally:
                self.progress = saved
        return total

    def _count_via_spans(self) -> int:
        he = self.header_end_abs
        return sum(
            int(v[max(he - b, 0):].sum()) for b, v in self.spans()
        )

    def full_spans(self) -> Iterator[tuple[int, "np.ndarray", "np.ndarray"]]:
        """Yield ``(base, fail_mask, reads_before)`` spans tiling the file —
        the streaming face of the *full* checker (all 19 flags per position;
        reference full/Checker.scala:17-198) in O(window) memory.

        Exactness discipline: owned lanes whose masks may be incomplete
        (escaped chains or buffer-edge-inexact failures) defer through the
        same side buffer as ``spans()`` — and stay deferred until a re-check
        is fully *exact* — then re-emit as contiguous-run spans (their
        slots in the covering span carry mask 0 / reads_before 0).
        """
        yield from self._stream(
            ("fail_mask", "reads_before"), defer_inexact=True
        )

    def read_batches(self) -> Iterator[tuple[int, "object"]]:
        """Columnar ``ReadBatch``es per streaming window — the load path at
        WGS scale (O(window) host memory; reference CanLoadBam.scala:173-243
        loads per split, here per device window).

        Yields ``(abs_base, batch)``; batch ``starts`` are window-relative.
        Records that start in an owned span but extend past the window's
        lookahead (longer than the halo), plus record starts whose verdicts
        resolved through the deferral path, are decoded exactly from a
        seekable stream and yielded as one final batch with ``abs_base=-1``
        (its ``starts`` index its own buffer).
        """
        from spark_bam_tpu.tpu.parser import parse_flat_records

        he = self.header_end_abs
        spill_abs: list[int] = []
        for base, verdict, buf in self._stream(
            ("verdict",), defer_inexact=False, with_buf=True
        ):
            if buf is None:  # a deferred contiguous-run re-emission
                idx = base + np.flatnonzero(verdict)
                spill_abs.extend(idx[idx >= he].tolist())
            else:
                starts = np.flatnonzero(verdict)
                starts = starts[base + starts >= he]
                if len(starts):
                    # A record must fit the buffer to parse in-window;
                    # spills (size beyond the halo lookahead) decode
                    # exactly from the stream.
                    sizes = (
                        buf[starts].astype(np.int64)
                        | (buf[starts + 1].astype(np.int64) << 8)
                        | (buf[starts + 2].astype(np.int64) << 16)
                        | (buf[starts + 3].astype(np.int64) << 24)
                    )
                    fits = starts + 4 + sizes <= len(buf)
                    spill_abs.extend((base + starts[~fits]).tolist())
                    starts = starts[fits]
                    if len(starts):
                        yield base, parse_flat_records(buf, starts)
            # Bound spill memory: flush in chunks during the stream, never
            # one unbounded EOF batch (ultra-long-read files spill often).
            if len(spill_abs) >= 4096:
                for batch in self._decode_spills(sorted(spill_abs)):
                    yield -1, batch
                spill_abs = []
        if spill_abs:
            for batch in self._decode_spills(sorted(spill_abs)):
                yield -1, batch

    def _decode_spills(self, positions: list[int], chunk_bytes: int = 64 << 20):
        """Exact single-record decode for starts whose bytes outran their
        window: read each record via the seekable stream and batch-parse in
        ≤``chunk_bytes`` buffers (bounded memory; offsets stay far inside
        the parser's int32 range)."""
        from spark_bam_tpu.bgzf.flat import metas_block_table, pos_of_flat_tables
        from spark_bam_tpu.bgzf.stream import (
            SeekableBlockStream,
            SeekableUncompressedBytes,
        )
        from spark_bam_tpu.core.channel import open_channel
        from spark_bam_tpu.core.pos import Pos
        from spark_bam_tpu.tpu.parser import parse_flat_records

        block_starts, block_flat = metas_block_table(self.pipeline.metas)
        stream = SeekableUncompressedBytes(
            SeekableBlockStream(open_channel(self.path))
        )
        try:
            parts: list[bytes] = []
            starts: list[int] = []
            off = 0
            for pos in positions:
                stream.seek(
                    Pos(*pos_of_flat_tables(block_starts, block_flat, pos))
                )
                size_bytes = stream.read(4)
                size = int.from_bytes(size_bytes, "little")
                parts.append(size_bytes + stream.read(size))
                starts.append(off)
                off += 4 + size
                if off >= chunk_bytes:
                    buf = np.frombuffer(b"".join(parts), dtype=np.uint8)
                    yield parse_flat_records(
                        buf, np.array(starts, dtype=np.int64)
                    )
                    parts, starts, off = [], [], 0
            if parts:
                buf = np.frombuffer(b"".join(parts), dtype=np.uint8)
                yield parse_flat_records(buf, np.array(starts, dtype=np.int64))
        finally:
            stream.close()

    def record_starts(self) -> Iterator[np.ndarray]:
        """Absolute flat offsets of record starts, one array per span, in
        stream order (deferred resolutions may append out of order)."""
        he = self.header_end_abs
        for base, verdict in self.spans():
            idx = base + np.flatnonzero(verdict)
            idx = idx[idx >= he]
            if len(idx):
                yield idx


def full_check_summary_streaming(
    path,
    config: Config = Config(),
    window_uncompressed: int | None = None,
    halo: int | None = None,
    use_device: bool = True,
    progress: Callable[[int, int, int], None] | None = None,
    metas: list | None = None,
) -> dict:
    """The full-check workload's aggregations at arbitrary scale: per-flag
    totals, considered-position count, and the critical (exactly one check
    failed) / two-check positions with their masks — computed from
    ``full_spans`` in O(window) memory (reference FullCheck.scala:112-417;
    BASELINE.json config "full-check split-point scan … all candidate
    offsets"). The CLI's in-memory path keeps the golden-output report for
    fixture-sized files; this is the WGS-scale library face.
    """
    from spark_bam_tpu.check.flags import (
        FLAG_NAMES,
        considered_mask,
        num_failing_fields,
    )

    checker = StreamChecker(
        path, config, window_uncompressed, halo, use_device, progress,
        metas=metas,
    )
    per_flag = np.zeros(len(FLAG_NAMES), dtype=np.int64)
    considered_total = 0
    crit_pos: list[np.ndarray] = []
    crit_mask: list[np.ndarray] = []
    two_pos: list[np.ndarray] = []
    two_mask: list[np.ndarray] = []
    for base, fm, rb in checker.full_spans():
        considered = considered_mask(fm, rb)
        considered_total += int(considered.sum())
        masked = fm[considered]
        for i in range(len(FLAG_NAMES)):
            per_flag[i] += int(((masked >> i) & 1).sum())
        nf = num_failing_fields(fm, rb)
        ones = np.flatnonzero(considered & (nf == 1))
        twos = np.flatnonzero(considered & (nf == 2))
        if len(ones):
            crit_pos.append(base + ones)
            crit_mask.append(fm[ones])
        if len(twos):
            two_pos.append(base + twos)
            two_mask.append(fm[twos])

    def cat_sorted(pos_parts, mask_parts):
        """Concatenate site arrays and restore ascending position order.

        Deferred re-emissions land *behind* the tiling frontier (the span
        contract above), so emission order is not ascending whenever any
        position resolved through the deferral path — sort here so the
        streaming summary's site order matches the in-memory path's.
        """
        pos = (
            np.concatenate(pos_parts) if pos_parts
            else np.empty(0, dtype=np.int64)
        )
        mask = (
            np.concatenate(mask_parts) if mask_parts
            else np.empty(0, dtype=np.int32)
        )
        if len(pos) > 1 and np.any(np.diff(pos) < 0):
            order = np.argsort(pos, kind="stable")
            pos, mask = pos[order], mask[order]
        return pos, mask

    if obs.enabled():
        # Distinct name from check_flat's ``check.flag_refutations.*``:
        # these totals are restricted to *considered* sites (and the device
        # path never passes through check_flat), so the two would
        # double-count under one name on the NumPy engine.
        for i, name in enumerate(FLAG_NAMES):
            # lint: allow[obs-contract] suffix bounded by FLAG_NAMES
            obs.count(f"check.flag_fail_sites.{name}", int(per_flag[i]))

    crit_pos_a, crit_mask_a = cat_sorted(crit_pos, crit_mask)
    two_pos_a, two_mask_a = cat_sorted(two_pos, two_mask)
    return {
        "per_flag": {
            name: int(per_flag[i]) for i, name in enumerate(FLAG_NAMES)
        },
        "considered": considered_total,
        "critical_positions": crit_pos_a,
        "critical_masks": crit_mask_a,
        "two_check_positions": two_pos_a,
        "two_check_masks": two_mask_a,
        "positions": checker.total,
    }


# ----------------------------------------------------------- module wrappers

def stream_verdicts(
    path,
    config: Config = Config(),
    window_uncompressed: int | None = None,
    halo: int | None = None,
    use_device: bool = True,
    progress: Callable[[int, int, int], None] | None = None,
) -> Iterator[tuple[int, np.ndarray]]:
    """Yield (base, verdict) spans tiling the file (see ``StreamChecker``)."""
    yield from StreamChecker(
        path, config, window_uncompressed, halo, use_device, progress
    ).spans()


def count_reads_streaming(
    path,
    config: Config = Config(),
    window_uncompressed: int | None = None,
    halo: int | None = None,
    use_device: bool = True,
    progress: Callable[[int, int, int], None] | None = None,
) -> int:
    """Record count via the streaming checker (the count-reads scale path)."""
    return StreamChecker(
        path, config, window_uncompressed, halo, use_device, progress
    ).count_reads()
