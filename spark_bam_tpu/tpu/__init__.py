"""TPU execution engines (JAX/XLA; Pallas kernels where profitable).

- ``checker``  — the vectorized boundary checker as a jittable window kernel
- ``parser``   — batched record-field extraction + on-device interval filter
- ``inflate``  — host-parallel BGZF inflate feeding device windows (the
  Pallas in-device DEFLATE design lives here too)
"""

from spark_bam_tpu.tpu.checker import TpuChecker, check_window, make_check_window

__all__ = ["TpuChecker", "check_window", "make_check_window"]
