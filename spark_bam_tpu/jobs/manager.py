"""Job admission + lifecycle: submit / status / cancel, pause on
exhaustion, resume from the journal.

Job identity is deterministic — ``blake2b(canonical spec JSON)`` — so
resubmitting the same spec is idempotent: if the job is running you get
its status; if a previous attempt died (worker SIGKILL, ENOSPC pause)
the resubmit *resumes* from the journal instead of restarting. That is
what makes the serve ops safe to retry and the fabric router's orphan
rescue safe to re-dispatch (``IDEMPOTENT_OPS``).

Admission is guarded twice before a byte is written:

- **capacity** — at most ``max_active`` running jobs, and no admission
  while host memory use is past ``mem_watermark`` (the job-plane mirror
  of PR 17's brownout shedding). Both defer with a typed, retryable
  verdict (``jobs.deferred``), never queue unboundedly.
- **space** — ``core/guard.py preflight_space`` against the output
  filesystem, sized from the input artifact (``jobs.preflight_rejects``).

A running job that hits ``ResourceExhausted`` mid-write (real ENOSPC or
the disk-chaos seam) *pauses*: journal + committed segments stay on
disk, the state flips to ``paused``, and an out-of-band SLO-ledger
alert fires (``obs/slo.py note_event``) so operators see it where burn
alerts land. Any other exception fails the job with the error recorded.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
import time
from dataclasses import dataclass, field

from spark_bam_tpu import obs
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.guard import ResourceExhausted, preflight_space
from spark_bam_tpu.jobs.runner import RUNNERS, JobCancelled

#: job states; terminal ones keep their result/error forever.
STATES = ("running", "done", "paused", "failed", "cancelled")


def default_jobs_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "spark-bam-jobs")


@dataclass(frozen=True)
class JobsConfig:
    """Parsed ``SPARK_BAM_JOBS`` spec (``dir=...,checkpoint=...,
    frames=...,mem=0.92,max=2``) — the job plane's knob surface,
    following the compact-spec convention of every other config."""

    dir: str = ""               # journal/segment root ("" ⇒ tmpdir)
    checkpoint: int = 5000      # rewrite/transcode: records per checkpoint
    frames: int = 8             # export: frames per checkpoint
    mem_watermark: float = 0.92  # defer admission past this used-fraction
    max_active: int = 2         # concurrent running jobs

    def __post_init__(self):
        if self.checkpoint < 1 or self.frames < 1 or self.max_active < 1:
            raise ValueError("jobs checkpoint/frames/max must be >= 1")
        if not (0.0 < self.mem_watermark <= 1.0):
            raise ValueError(
                f"jobs mem watermark must be in (0,1]: {self.mem_watermark}"
            )

    def root(self) -> str:
        return self.dir or default_jobs_dir()

    @staticmethod
    def parse(spec: str) -> "JobsConfig":
        kw: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"Bad jobs entry {part!r} in {spec!r}")
            key, value = (t.strip() for t in part.split("=", 1))
            key = key.replace("-", "_")
            if key == "dir":
                kw["dir"] = value
            elif key in ("checkpoint", "ckpt"):
                kw["checkpoint"] = int(value)
            elif key == "frames":
                kw["frames"] = int(value)
            elif key in ("mem", "mem_watermark"):
                kw["mem_watermark"] = float(value)
            elif key in ("max", "max_active"):
                kw["max_active"] = int(value)
            else:
                raise ValueError(
                    f"Unknown jobs knob {key!r}: expected "
                    "dir/checkpoint/frames/mem/max"
                )
        return JobsConfig(**kw)

    @staticmethod
    def from_env(env=None) -> "JobsConfig":
        return JobsConfig.parse(
            (env or os.environ).get("SPARK_BAM_JOBS", "")
        )


def job_id_of(spec: dict) -> str:
    """Deterministic job identity: the hash of the canonical spec."""
    canon = json.dumps(spec, separators=(",", ":"), sort_keys=True)
    return hashlib.blake2b(canon.encode(), digest_size=8).hexdigest()


def memory_used_fraction() -> "float | None":
    """Host memory used fraction from ``/proc/meminfo``; ``None`` where
    unavailable (the watermark check is then skipped)."""
    try:
        with open("/proc/meminfo") as f:
            info = {}
            for line in f:
                key, _, rest = line.partition(":")
                info[key.strip()] = rest
        total = int(info["MemTotal"].split()[0])
        avail = int(info["MemAvailable"].split()[0])
    except (OSError, KeyError, ValueError, IndexError):
        return None
    if total <= 0:
        return None
    return 1.0 - (avail / total)


@dataclass
class _Job:
    job_id: str
    spec: dict
    state: str = "running"
    result: "dict | None" = None
    error: str = ""
    submitted: float = 0.0
    finished: float = 0.0
    cancel: threading.Event = field(default_factory=threading.Event)
    thread: "threading.Thread | None" = None

    def status(self) -> dict:
        out = {
            "job_id": self.job_id,
            "op": self.spec.get("op"),
            "state": self.state,
            "submitted": self.submitted,
        }
        if self.finished:
            out["finished"] = self.finished
        if self.result is not None:
            out["result"] = self.result
        if self.error:
            out["error"] = self.error
        return out


class JobManager:
    """Owns the job table + one daemon thread per running job."""

    def __init__(self, jcfg: "JobsConfig | None" = None,
                 config: Config = Config(), alert_fn=None,
                 mem_fn=memory_used_fraction):
        # Spec precedence: explicit jcfg > the config's ``jobs`` knob
        # (which Config.from_env fills from SPARK_BAM_JOBS).
        self.jcfg = jcfg if jcfg is not None else config.jobs_config
        self.config = config
        self.alert_fn = alert_fn      # (name, **fields) → SLO ledger
        self.mem_fn = mem_fn
        self._jobs: "dict[str, _Job]" = {}
        self._lock = threading.Lock()

    # ---------------------------------------------------------- admission
    def _defer(self, why: str, **extra) -> ResourceExhausted:
        obs.count("jobs.deferred")
        exc = ResourceExhausted(f"job deferred: {why}")
        exc.retry_after_ms = 1000.0
        exc.extra = extra
        return exc

    def _admit(self) -> None:
        with self._lock:
            active = sum(1 for j in self._jobs.values()
                         if j.state == "running")
        if active >= self.jcfg.max_active:
            raise self._defer(
                f"{active} jobs running (max {self.jcfg.max_active})",
                active=active,
            )
        used = self.mem_fn() if self.mem_fn else None
        if used is not None and used >= self.jcfg.mem_watermark:
            raise self._defer(
                f"host memory at {used:.0%} "
                f"(watermark {self.jcfg.mem_watermark:.0%})",
                mem_used=round(used, 3),
            )

    def _preflight(self, spec: dict) -> None:
        try:
            need = os.path.getsize(spec["path"])
        except OSError:
            return  # missing input fails in the runner with NotFound
        try:
            preflight_space(spec["out"], need)
        except ResourceExhausted:
            obs.count("jobs.preflight_rejects")
            raise

    # ------------------------------------------------------------ surface
    def submit(self, spec: dict) -> dict:
        """Admit (or idempotently re-attach to) the job for ``spec``.
        Raises :class:`ResourceExhausted` on deferral/preflight; returns
        the job's status dict."""
        op = spec.get("op")
        if op not in RUNNERS:
            raise ValueError(
                f"unknown job op {op!r}: expected one of "
                f"{', '.join(sorted(RUNNERS))}"
            )
        if not spec.get("path") or not spec.get("out"):
            raise ValueError("job spec needs 'path' and 'out'")
        spec = {k: v for k, v in sorted(spec.items()) if v is not None}
        jid = job_id_of(spec)
        with self._lock:
            job = self._jobs.get(jid)
            if job is not None and job.state in ("running", "done"):
                return job.status()  # idempotent resubmit
        # paused/failed/cancelled (or unknown): (re)start — the runner
        # resumes from whatever the journal holds.
        self._admit()
        self._preflight(spec)
        with self._lock:
            job = self._jobs.get(jid)
            if job is not None and job.state in ("running", "done"):
                return job.status()
            job = _Job(jid, spec, submitted=round(time.time(), 3))
            self._jobs[jid] = job
            job.thread = threading.Thread(
                target=self._run, args=(job,),
                name=f"job-{jid}", daemon=True,
            )
            job.thread.start()
        obs.count("jobs.submitted")
        return job.status()

    def status(self, job_id: str) -> "dict | None":
        with self._lock:
            job = self._jobs.get(job_id)
            return job.status() if job is not None else None

    def cancel(self, job_id: str) -> "dict | None":
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None:
            return None
        if job.state == "running":
            job.cancel.set()
        return job.status()

    def jobs(self) -> "list[dict]":
        with self._lock:
            return [j.status() for j in self._jobs.values()]

    def close(self, timeout: float = 5.0) -> None:
        with self._lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            job.cancel.set()
        for job in jobs:
            if job.thread is not None:
                job.thread.join(timeout)

    # ------------------------------------------------------------- worker
    def job_dir(self, job_id: str) -> str:
        return os.path.join(self.jcfg.root(), job_id)

    def _run(self, job: _Job) -> None:
        runner = RUNNERS[job.spec["op"]]
        checkpoint = (self.jcfg.frames if job.spec["op"] == "export"
                      else self.jcfg.checkpoint)
        try:
            result = runner(
                job.spec, self.job_dir(job.job_id),
                config=self.config, checkpoint=checkpoint,
                cancel=job.cancel,
            )
            job.result = result
            job.state = "done"
            obs.count("jobs.completed")
        except JobCancelled as exc:
            job.error = str(exc)
            job.state = "cancelled"
            obs.count("jobs.cancelled")
        except ResourceExhausted as exc:
            # Paused, not failed: the journal + committed segments are
            # durable; a resubmit resumes. Surface where burn-rate
            # alerts land so a stuck fleet job pages like an SLO breach.
            job.error = str(exc)
            job.state = "paused"
            obs.count("jobs.paused")
            if self.alert_fn is not None:
                try:
                    self.alert_fn(
                        "jobs.paused", job_id=job.job_id,
                        op=job.spec.get("op"), error=str(exc),
                    )
                except Exception:
                    pass
        except Exception as exc:
            job.error = f"{type(exc).__name__}: {exc}"
            job.state = "failed"
            obs.count("jobs.failed")
        finally:
            job.finished = round(time.time(), 3)
