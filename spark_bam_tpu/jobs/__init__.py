"""Durable job plane: crash-resumable rewrite/export/transcode.

Long-running mutations (re-compress a BAM, export a flowcell to
columnar) get a write-ahead journal (``journal.py``), checkpointed
segment output, a manager with serve-op admission (``manager.py``) and
an end-to-end integrity scrubber (``scrub.py``). A job killed at any
point — SIGKILL, ENOSPC, a yanked disk — resumes from its last durable
checkpoint and produces a final artifact byte-identical to an
uninterrupted run (docs/robustness.md, "Durable jobs & scrubbing").
"""

from spark_bam_tpu.jobs.journal import (  # noqa: F401
    Journal,
    JournalError,
    SegmentedOutput,
)
from spark_bam_tpu.jobs.manager import JobManager, JobsConfig  # noqa: F401
