"""Job runners: checkpointed rewrite / export / transcode.

Each runner drives its producer into a :class:`SegmentedOutput`,
journaling a checkpoint at every durable segment boundary. Checkpoints
sit on boundaries the producer can re-enter exactly:

- **rewrite/transcode** — BGZF member boundaries. The codec pipeline is
  force-flushed (every complete payload becomes a member on disk), and
  the checkpoint records the writer's residual buffer (the <1-block
  tail that has not been carved into a payload yet), flat/compressed
  offsets, and the per-segment block/record-start deltas. Resume skips
  the already-written records on the input side and seeds a fresh
  ``BgzfWriter`` with the recorded residue — payloads are carved and
  compressed independently, so the remaining members come out
  byte-identical to an uninterrupted run (host zlib and fixed device
  modes; ``mode=auto`` demotion can differ per run and is documented
  as non-reproducible in docs/robustness.md).
- **export** — native-container frame boundaries. Frames are a pure
  function of (query, columnar config) (columnar/export.py), so resume
  recomputes the stream and skips the first N frames without
  re-encoding them.

A mid-run ``ResourceExhausted`` (ENOSPC/EIO, real or injected) leaves
the journal and committed segments intact — the manager pauses the job;
a later run of the same spec resumes instead of restarting.
"""

from __future__ import annotations

import base64
import itertools
import os

from spark_bam_tpu import obs
from spark_bam_tpu.bam.writer import BgzfWriter, WriteResult, encode_bam_header
from spark_bam_tpu.bgzf.block import Metadata
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.jobs.journal import Journal, SegmentedOutput


class JobCancelled(RuntimeError):
    """The manager's cancel flag was set; the job stopped at the next
    record/frame boundary. Committed checkpoints survive — a resubmit
    resumes, it does not restart."""


class _SegSink:
    """File-object facade over :class:`SegmentedOutput` for writers that
    expect ``.write()``/``.flush()`` (BgzfWriter, frame emitters)."""

    def __init__(self, segout: SegmentedOutput):
        self._segout = segout

    def write(self, data: bytes) -> int:
        self._segout.write(data)
        return len(data)

    def flush(self) -> None:
        pass


def _flush_members(w: BgzfWriter) -> None:
    """Force every complete payload through the codec and onto disk,
    leaving only the residual (<1 block) tail in ``w.buf`` — the state a
    checkpoint can serialize."""
    w._dispatch_batch()
    while w._pending:
        w._write_oldest()


def _drop_uncovered_segments(segout: SegmentedOutput, first: int) -> int:
    """Delete committed segments the journal does not cover (a crash
    between segment commit and checkpoint append); returns bytes
    discarded. The re-run regenerates them byte-identically anyway —
    deleting keeps 'segments on disk' == 'checkpoints in journal'."""
    lost = 0
    i = first
    while True:
        path = os.path.join(segout.dir, f"seg-{i:05d}")
        if not os.path.exists(path):
            return lost
        try:
            lost += os.path.getsize(path)
            os.unlink(path)
        except OSError:
            pass
        i += 1


def _open_job(job_dir: str, spec: dict) -> "tuple[Journal, SegmentedOutput, dict | None, int]":
    """Recover the journal + segment directory for ``spec``; returns
    (journal, segout, last checkpoint or None, redone bytes)."""
    os.makedirs(job_dir, exist_ok=True)
    journal = Journal.open(os.path.join(job_dir, "journal.sbj"))
    if journal.last("spec") is None:
        journal.append({"t": "spec", "spec": spec})
    segout = SegmentedOutput(os.path.join(job_dir, "segments"))
    redone = segout.discard_parts()
    ck = journal.last("ckpt")
    redone += _drop_uncovered_segments(
        segout, (ck["seq"] + 1) if ck is not None else 0
    )
    if redone:
        obs.count("jobs.redone_bytes", redone)
    if ck is not None:
        obs.count("jobs.resumed")
    return journal, segout, ck, redone


def _note_checkpoint(nbytes: int) -> None:
    obs.count("jobs.checkpoints")
    obs.count("jobs.checkpoint_bytes", nbytes)


# ----------------------------------------------------------------- rewrite

def run_rewrite_job(
    spec: dict,
    job_dir: str,
    config: Config = Config(),
    checkpoint: int = 5000,
    cancel=None,
) -> dict:
    """Checkpointed ``htsjdk-rewrite``: re-block + re-compress
    ``spec["path"]`` into ``spec["out"]``, journaled every
    ``checkpoint`` records. ``spec`` keys mirror the serve ``rewrite``
    op: ``path``, ``out``, ``block_payload``, ``level``, ``deflate``,
    ``index``. Returns the result dict (also journaled in the ``done``
    record); raises :class:`JobCancelled` if ``cancel`` fires."""
    from spark_bam_tpu.bam.iterators import RecordStream
    from spark_bam_tpu.cli.rewrite import emit_sidecars
    from spark_bam_tpu.compress.codec import make_codec
    from spark_bam_tpu.core.channel import open_channel

    journal, segout, ck, redone = _open_job(job_dir, spec)
    done = journal.last("done")
    if done is not None:
        journal.close()
        return dict(done["result"], resumed=True, redone_bytes=0)

    block_payload = int(spec.get("block_payload") or 0xFF00)
    level = int(spec.get("level") or 6)
    dspec = spec.get("deflate")
    if dspec is None:
        dspec = config.deflate
    codec = make_codec(dspec, level=level)

    blocks: "list[Metadata]" = []
    flats: "list[int]" = []
    flats_new: "list[int]" = []
    skip = 0
    seg_next = 0
    header_len = 0
    checkpoints = 0
    if ck is not None:
        skip = int(ck["records"])
        seg_next = int(ck["seq"]) + 1
        header_len = int(ck["header_len"])
        for record in journal.records:
            if record.get("t") == "ckpt":
                blocks.extend(Metadata(*b) for b in record["blocks"])
                flats.extend(record["flats"])
                checkpoints += 1

    sink = _SegSink(segout)
    w = BgzfWriter(sink, block_payload, level, codec=codec)
    if ck is not None:
        w.buf = bytearray(base64.b64decode(ck["buf"]))
        w._flat = int(ck["flat"])
        w._offset = int(ck["offset"])
    mark = 0
    count = skip
    segout.begin(seg_next)
    try:
        with obs.span("jobs.rewrite", path=str(spec["path"]), resumed=skip):
            with open_channel(spec["path"]) as channel:
                stream = RecordStream.open(channel)
                if ck is None:
                    w.write(encode_bam_header(stream.header))
                    header_len = w.flat_tell
                for rec in itertools.islice(stream, skip, None):
                    rec = rec[1] if isinstance(rec, tuple) else rec
                    flats_new.append(w.flat_tell)
                    w.write(rec.encode())
                    count += 1
                    if count % checkpoint == 0:
                        _flush_members(w)
                        _, nbytes = segout.commit()
                        delta = w.blocks[mark:]
                        journal.append({
                            "t": "ckpt", "seq": seg_next, "records": count,
                            "flat": w._flat, "offset": w._offset,
                            "buf": base64.b64encode(bytes(w.buf)).decode(),
                            "header_len": header_len, "seg_bytes": nbytes,
                            "blocks": [
                                [m.start, m.compressed_size,
                                 m.uncompressed_size]
                                for m in delta
                            ],
                            "flats": flats_new,
                        })
                        _note_checkpoint(nbytes)
                        checkpoints += 1
                        blocks.extend(delta)
                        flats.extend(flats_new)
                        mark = len(w.blocks)
                        flats_new = []
                        seg_next += 1
                        segout.begin(seg_next)
                    if cancel is not None and cancel.is_set():
                        raise JobCancelled(f"job cancelled at {count} records")
            w.close()
            _, nbytes = segout.commit()
            blocks.extend(w.blocks[mark:])
            flats.extend(flats_new)
            total = segout.assemble(spec["out"])
            result = WriteResult(
                count=count, header_len=header_len, blocks=blocks,
                record_flats=flats, bytes_out=w._offset,
            )
            sidecars = (
                emit_sidecars(spec["out"], result, config)
                if spec.get("index") else {}
            )
    except BaseException:
        segout.abort()
        journal.close()
        raise
    res = {
        "path": str(spec["path"]), "out": str(spec["out"]),
        "count": count, "n_blocks": len(blocks), "bytes_out": total,
        "sidecars": dict(sidecars), "checkpoints": checkpoints,
        "redone_bytes": redone, "resumed": bool(ck is not None),
    }
    journal.append({"t": "done", "result": res})
    segout.remove()
    journal.close()
    return res


# ------------------------------------------------------------------ export

def run_export_job(
    spec: dict,
    job_dir: str,
    config: Config = Config(),
    checkpoint: int = 8,
    cancel=None,
    parallel=None,
) -> dict:
    """Checkpointed BAM → native-container export, journaled every
    ``checkpoint`` frames. The frame stream is a pure function of
    (path, columns, columnar config) so resume recomputes and skips.
    ``spec``: ``path``, ``out``, optional ``columns`` (list) and
    ``batch_rows``."""
    from spark_bam_tpu.bam.header import read_header
    from spark_bam_tpu.columnar.export import _partition_batch_stream
    from spark_bam_tpu.columnar.native import (
        batch_frame,
        container_head,
        container_meta,
        end_frame,
    )
    from spark_bam_tpu.columnar.schema import Rebatcher, normalize_columns
    from spark_bam_tpu.load.api import load_bam
    from spark_bam_tpu.parallel.executor import ParallelConfig

    journal, segout, ck, redone = _open_job(job_dir, spec)
    done = journal.last("done")
    if done is not None:
        journal.close()
        return dict(done["result"], resumed=True, redone_bytes=0)

    ccfg = config.columnar_config
    if spec.get("batch_rows"):
        from dataclasses import replace

        ccfg = replace(ccfg, batch_rows=int(spec["batch_rows"]))
    columns = normalize_columns(spec.get("columns") or ccfg.columns)
    header = read_header(spec["path"])
    contigs = [
        (name, length)
        for _, (name, length) in sorted(header.contig_lengths.items())
    ]
    meta = container_meta(
        columns, codec=ccfg.codec, level=ccfg.level, contigs=contigs
    )

    skip = int(ck["frames"]) if ck is not None else 0
    seg_next = int(ck["seq"]) + 1 if ck is not None else 0
    rows = int(ck["rows"]) if ck is not None else 0
    offset = int(ck["offset"]) if ck is not None else 0
    frames = 0
    checkpoints = sum(1 for r in journal.records if r.get("t") == "ckpt")

    parallel = parallel if parallel is not None else ParallelConfig()
    ds = load_bam(spec["path"], config=config, parallel=parallel)
    reports: list = []
    rebatcher = Rebatcher(ccfg.batch_rows)

    def frame_stream():
        for batch in _partition_batch_stream(
            ds, ccfg.batch_rows, columns, reports
        ):
            yield from rebatcher.feed(batch)
        yield from rebatcher.flush()

    segout.begin(seg_next)
    try:
        with obs.span("jobs.export", path=str(spec["path"]), resumed=skip):
            if ck is None:
                head = container_head(meta)
                segout.write(head)
                offset += len(head)
            for frame in frame_stream():
                frames += 1
                if frames <= skip:
                    # Already durable (rows restored from the checkpoint);
                    # recompute-and-skip without re-encoding.
                    continue
                encoded = batch_frame(frame, meta)
                segout.write(encoded)
                rows += frame.num_rows
                offset += len(encoded)
                if (frames - skip) % checkpoint == 0:
                    _, nbytes = segout.commit()
                    journal.append({
                        "t": "ckpt", "seq": seg_next, "frames": frames,
                        "rows": rows, "offset": offset, "seg_bytes": nbytes,
                    })
                    _note_checkpoint(nbytes)
                    checkpoints += 1
                    seg_next += 1
                    segout.begin(seg_next)
                if cancel is not None and cancel.is_set():
                    raise JobCancelled(
                        f"job cancelled at {frames} frames"
                    )
            tail = end_frame(rows, frames)
            segout.write(tail)
            offset += len(tail)
            _, nbytes = segout.commit()
            total = segout.assemble(spec["out"])
    except BaseException:
        segout.abort()
        journal.close()
        raise
    res = {
        "path": str(spec["path"]), "out": str(spec["out"]),
        "format": "native", "columns": list(columns), "rows": rows,
        "batches": frames, "bytes_out": total,
        "checkpoints": checkpoints, "redone_bytes": redone,
        "resumed": bool(ck is not None),
    }
    journal.append({"t": "done", "result": res})
    segout.remove()
    journal.close()
    return res


# --------------------------------------------------------------- transcode

def run_transcode_job(
    spec: dict,
    job_dir: str,
    config: Config = Config(),
    checkpoint: int = 5000,
    cancel=None,
) -> dict:
    """Fleet re-compression: a rewrite job with sidecar emission forced
    on, so the transcoded output serves warm loads immediately."""
    return run_rewrite_job(
        dict(spec, index=True), job_dir,
        config=config, checkpoint=checkpoint, cancel=cancel,
    )


RUNNERS = {
    "rewrite": run_rewrite_job,
    "export": run_export_job,
    "transcode": run_transcode_job,
}
