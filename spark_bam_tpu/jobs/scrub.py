"""End-to-end integrity scrubber for rewritten/exported artifacts.

Validates what the durable job plane writes (and anything else shaped
like it): BAM outputs member-by-member (BGZF header structure, raw
deflate round-trip, per-member CRC32 + ISIZE, the EOF sentinel),
``.blocks``/``.records``/``.sbi`` sidecars against the BAM they
describe, and SBCR native containers via the validating reader
(columnar/native.py — frame CRCs, schema, end-frame counts). With a
``--source`` BAM it additionally runs record parity: lock-step decode
of source and output, comparing encoded record bytes on a stride (and
total counts always) — the cheap end-to-end "did the transform preserve
the data" check.

Damaged artifacts can be quarantined (renamed ``<path>.quarantined``)
so a warm-cache load can never trust them again; every artifact gets a
verdict in a :class:`ScrubReport`, whose ``job_report()`` view reuses
the executor's ``JobReport``/``PartitionReport`` ledger shape — the
``scrub`` CLI prints it the way ``report`` prints a load's.
"""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass, field

from spark_bam_tpu import obs
from spark_bam_tpu.bam.writer import BGZF_EOF
from spark_bam_tpu.bgzf.block import Metadata
from spark_bam_tpu.parallel.executor import JobReport, PartitionReport

_MEMBER_MAGIC = b"\x1f\x8b\x08\x04"


@dataclass
class Finding:
    path: str
    kind: str     # bam | blocks | records | sbi | native | parity | io
    error: str

    def as_dict(self) -> dict:
        return {"path": self.path, "kind": self.kind, "error": self.error}


@dataclass
class ScrubReport:
    artifacts: "list[str]" = field(default_factory=list)
    findings: "list[Finding]" = field(default_factory=list)
    quarantined: "list[str]" = field(default_factory=list)
    records_checked: int = 0
    records_compared: int = 0

    @property
    def clean(self) -> bool:
        return not self.findings

    def job_report(self) -> JobReport:
        """The scrub as an executor-style ledger: one partition per
        artifact, quarantined where findings landed."""
        bad = {f.path for f in self.findings}
        parts = []
        for i, path in enumerate(self.artifacts):
            errors = "; ".join(
                f.error for f in self.findings if f.path == path
            )
            parts.append(PartitionReport(
                index=i,
                status="quarantined" if path in bad else "ok",
                error=errors or None,
            ))
        return JobReport(partitions=parts)

    def summary(self) -> dict:
        return {
            "artifacts": len(self.artifacts),
            "findings": [f.as_dict() for f in self.findings],
            "quarantined": list(self.quarantined),
            "records_checked": self.records_checked,
            "records_compared": self.records_compared,
            "clean": self.clean,
        }


def scan_bgzf_members(data: bytes, path: str) -> "tuple[list[Metadata], list[Finding]]":
    """Structural walk of a BGZF byte string: every member's header,
    BSIZE extra field, raw-deflate payload, CRC32 and ISIZE are checked;
    the file must end with the 28-byte EOF sentinel. Returns the member
    table (for sidecar cross-checks) and any findings."""
    members: "list[Metadata]" = []
    findings: "list[Finding]" = []

    def bad(msg: str) -> "tuple[list[Metadata], list[Finding]]":
        findings.append(Finding(path, "bam", msg))
        return members, findings

    p = 0
    n = len(data)
    while p < n:
        if n - p < 18:
            return bad(f"trailing {n - p} bytes at {p}: no room for a member")
        if data[p: p + 4] != _MEMBER_MAGIC:
            return bad(f"bad BGZF member magic at offset {p}")
        xlen = struct.unpack_from("<H", data, p + 10)[0]
        extra = data[p + 12: p + 12 + xlen]
        if len(extra) != xlen:
            return bad(f"member at {p}: extra field truncated")
        bsize = None
        q = 0
        while q + 4 <= len(extra):
            si1, si2, slen = extra[q], extra[q + 1], struct.unpack_from(
                "<H", extra, q + 2)[0]
            if si1 == 0x42 and si2 == 0x43 and slen == 2:
                bsize = struct.unpack_from("<H", extra, q + 4)[0]
            q += 4 + slen
        if bsize is None:
            return bad(f"member at {p}: no BC (BSIZE) subfield")
        size = bsize + 1
        if p + size > n:
            return bad(
                f"member at {p}: declares {size} bytes, file has {n - p}"
            )
        payload = data[p + 12 + xlen: p + size - 8]
        crc, isize = struct.unpack_from("<II", data, p + size - 8)
        try:
            inflated = zlib.decompress(bytes(payload), -15)
        except zlib.error as exc:
            return bad(f"member at {p}: deflate payload corrupt ({exc})")
        if len(inflated) != isize:
            return bad(
                f"member at {p}: ISIZE {isize} != inflated {len(inflated)}"
            )
        if (zlib.crc32(inflated) & 0xFFFFFFFF) != crc:
            return bad(f"member at {p}: payload CRC32 mismatch")
        members.append(Metadata(p, size, isize))
        p += size
    if not data.endswith(BGZF_EOF):
        findings.append(Finding(path, "bam", "missing BGZF EOF sentinel"))
    elif members and members[-1].uncompressed_size == 0:
        members.pop()  # the sentinel itself is not a data member
    return members, findings


def _scrub_blocks_sidecar(path: str, members: "list[Metadata]") -> "list[Finding]":
    from spark_bam_tpu.bgzf.index_blocks import read_blocks_index

    try:
        rows = read_blocks_index(path)
    except (OSError, ValueError) as exc:
        return [Finding(path, "blocks", f"unreadable: {exc}")]
    if rows != members:
        n = min(len(rows), len(members))
        at = next(
            (i for i in range(n) if rows[i] != members[i]), n
        )
        return [Finding(
            path, "blocks",
            f"{len(rows)} rows vs {len(members)} members on disk; "
            f"first divergence at row {at}",
        )]
    return []


def _scrub_records_sidecar(path: str, members: "list[Metadata]") -> "list[Finding]":
    from spark_bam_tpu.bam.index_records import read_records_index

    try:
        rows = read_records_index(path)
    except (OSError, ValueError) as exc:
        return [Finding(path, "records", f"unreadable: {exc}")]
    usize = {m.start: m.uncompressed_size for m in members}
    for i, pos in enumerate(rows):
        if pos.block_pos not in usize:
            return [Finding(
                path, "records",
                f"row {i}: {pos} does not start on a member boundary",
            )]
        if not (0 <= pos.offset < max(usize[pos.block_pos], 1)):
            return [Finding(
                path, "records",
                f"row {i}: {pos} offset outside its member's "
                f"{usize[pos.block_pos]} uncompressed bytes",
            )]
    return []


def _scrub_sbi(path: str, members: "list[Metadata]") -> "list[Finding]":
    from spark_bam_tpu.sbi.format import SbiFormatError, decode_sbi

    try:
        with open(path, "rb") as f:
            index = decode_sbi(f.read())
    except (OSError, SbiFormatError, ValueError) as exc:
        return [Finding(path, "sbi", f"undecodable: {exc}")]
    if members and list(index.blocks) != members:
        return [Finding(
            path, "sbi",
            f"{len(index.blocks)} indexed blocks disagree with "
            f"{len(members)} members on disk",
        )]
    return []


def _scrub_native(path: str) -> "tuple[int, list[Finding]]":
    from spark_bam_tpu.columnar.native import ColumnarFormatError, NativeReader

    try:
        reader = NativeReader(path)
        rows = sum(b.num_rows for b in reader.iter_batches())
    except (OSError, ColumnarFormatError, ValueError) as exc:
        return 0, [Finding(path, "native", f"container invalid: {exc}")]
    return rows, []


def _record_parity(out_path: str, source: str, stride: int) -> "tuple[int, int, list[Finding]]":
    """Lock-step decode of source and output; every ``stride``-th record's
    encoded bytes must match, and the totals must match. Returns
    (records checked, records byte-compared, findings)."""
    from spark_bam_tpu.bam.iterators import RecordStream
    from spark_bam_tpu.core.channel import open_channel
    from spark_bam_tpu.core.guard import MalformedInputError

    checked = compared = 0
    try:
        with open_channel(source) as sch, open_channel(out_path) as och:
            src = iter(RecordStream.open(sch))
            out = iter(RecordStream.open(och))
            i = 0
            while True:
                a = next(src, None)
                b = next(out, None)
                if a is None and b is None:
                    break
                if a is None or b is None:
                    return checked, compared, [Finding(
                        out_path, "parity",
                        f"record count diverges at index {i} "
                        f"(source {'ended' if a is None else 'continues'})",
                    )]
                checked += 1
                if i % max(stride, 1) == 0:
                    ra = a[1] if isinstance(a, tuple) else a
                    rb = b[1] if isinstance(b, tuple) else b
                    compared += 1
                    if ra.encode() != rb.encode():
                        return checked, compared, [Finding(
                            out_path, "parity",
                            f"record {i} bytes differ from source",
                        )]
                i += 1
    except (OSError, MalformedInputError, ValueError, EOFError) as exc:
        return checked, compared, [Finding(
            out_path, "parity", f"parity scan failed: {exc}"
        )]
    return checked, compared, []


def _sniff(path: str) -> str:
    """Artifact kind by extension, falling back to magic bytes."""
    from spark_bam_tpu.columnar.native import MAGIC as SBCR_MAGIC

    lower = path.lower()
    for ext in ("blocks", "records", "sbi"):
        if lower.endswith("." + ext):
            return ext
    try:
        with open(path, "rb") as f:
            head = f.read(4)
    except OSError:
        return "io"
    if head[:2] == b"\x1f\x8b":
        return "bam"
    if head == SBCR_MAGIC:
        return "native"
    return "bam" if lower.endswith(".bam") else "native"


def scrub_paths(
    paths,
    source: "str | None" = None,
    quarantine: bool = False,
    stride: int = 16,
) -> ScrubReport:
    """Scrub each artifact in ``paths``. A ``.bam`` automatically pulls
    in its existing sidecars; ``source`` enables record parity against
    the input the artifact was derived from."""
    report = ScrubReport()
    todo: "list[str]" = []
    for p in (str(p) for p in paths):
        todo.append(p)
        if p.lower().endswith(".bam"):
            for ext in (".blocks", ".records", ".sbi"):
                if os.path.exists(p + ext) and p + ext not in todo:
                    todo.append(p + ext)
    members_of: "dict[str, list[Metadata]]" = {}
    with obs.span("jobs.scrub", artifacts=len(todo)):
        # BAMs first: sidecar checks need the member tables.
        for path in sorted(todo, key=lambda p: _sniff(p) != "bam"):
            kind = _sniff(path)
            report.artifacts.append(path)
            obs.count("scrub.artifacts")
            findings: "list[Finding]" = []
            if kind == "io":
                findings = [Finding(path, "io", "unreadable artifact")]
            elif kind == "bam":
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError as exc:
                    findings = [Finding(path, "io", str(exc))]
                else:
                    members, findings = scan_bgzf_members(data, path)
                    members_of[path] = members
                    if not findings and source:
                        checked, compared, parity = _record_parity(
                            path, source, stride
                        )
                        report.records_checked += checked
                        report.records_compared += compared
                        findings.extend(parity)
            elif kind == "native":
                rows, findings = _scrub_native(path)
                report.records_checked += rows
            else:
                base = path[: path.rfind(".")]
                members = members_of.get(base, [])
                if kind == "blocks":
                    findings = _scrub_blocks_sidecar(path, members)
                elif kind == "records":
                    findings = _scrub_records_sidecar(path, members)
                else:
                    findings = _scrub_sbi(path, members)
            report.findings.extend(findings)
            if findings:
                obs.count("scrub.findings", len(findings))
                if quarantine:
                    try:
                        os.replace(path, path + ".quarantined")
                        report.quarantined.append(path + ".quarantined")
                        obs.count("scrub.quarantined")
                    except OSError:
                        pass
    obs.count("scrub.records_checked", report.records_checked)
    return report
