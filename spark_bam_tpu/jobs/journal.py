"""CRC32-framed append-only write-ahead journal + segmented output.

The journal is line-oriented JSONL with a per-line checksum frame::

    SBJ1 <crc32:08x> <json>\\n

- Every record is a JSON object carrying a ``"t"`` tag ("spec",
  "ckpt", "seg", "done", ...). Readers skip records whose tag they do
  not recognize — the same unknown-tag forward-compat discipline as the
  ``.sbi`` container — so an old scrubber can walk a new journal.
- Recovery truncates the torn tail: appends land with fsync, but a
  crash (or injected torn write, core/faults.py) can leave a partial
  final line. The first line that fails its frame (bad magic, bad CRC,
  bad JSON, no newline) ends the valid prefix; everything after it is
  discarded and the file is truncated back to the durable prefix.
- A non-empty file that does not *start* with the magic is not a
  journal at all — that is a clean reject (:class:`JournalError`),
  never a truncate-to-zero of somebody else's file.

Output never goes to the final artifact path directly: it lands as
committed segment files (``seg-00000``, ``seg-00001``, ...) via
:class:`SegmentedOutput`, each renamed into place only after an
fsync + size check, with a journal checkpoint recorded *after* the
segment is durable. Resume replays the journal, keeps every committed
segment, deletes orphaned ``.part`` files (work after the last
checkpoint, counted as ``jobs.redone_bytes``) and restarts the
producer from the checkpointed state — so the assembled artifact is
byte-identical to an uninterrupted run.
"""

from __future__ import annotations

import json
import os
import zlib

from spark_bam_tpu import obs
from spark_bam_tpu.core import faults as _faults
from spark_bam_tpu.core.atomic import AtomicFile, fsync_dir
from spark_bam_tpu.core.faults import Unrecoverable
from spark_bam_tpu.core.guard import map_write_error

MAGIC = "SBJ1"
#: tags this version understands; anything else is skipped on read.
KNOWN_TAGS = frozenset({"spec", "ckpt", "seg", "done", "note"})


class JournalError(ValueError, Unrecoverable):
    """The file at the journal path is not a journal (wrong magic at
    offset 0) or a record violates the format in a way recovery must
    not paper over. Deterministic damage — never retried, never
    auto-truncated."""


def _frame(record: dict) -> bytes:
    payload = json.dumps(
        record, separators=(",", ":"), sort_keys=True
    ).encode("utf-8")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return b"%s %08x %s\n" % (MAGIC.encode(), crc, payload)


def _parse_line(line: bytes) -> "dict | None":
    """One framed line → record, or ``None`` when the frame is invalid
    (torn tail / flipped bytes). Caller decides whether ``None`` means
    truncate-here (tail) or reject (head)."""
    if not line.endswith(b"\n"):
        return None
    body = line[:-1]
    parts = body.split(b" ", 2)
    if len(parts) != 3 or parts[0] != MAGIC.encode():
        return None
    try:
        crc = int(parts[1], 16)
    except ValueError:
        return None
    if len(parts[1]) != 8 or (zlib.crc32(parts[2]) & 0xFFFFFFFF) != crc:
        return None
    try:
        record = json.loads(parts[2])
    except ValueError:
        return None
    return record if isinstance(record, dict) else None


def read_journal(path) -> "list[dict]":
    """Parse the durable prefix of a journal without modifying the file.
    Returns the known-tag records in order; unknown tags are counted
    (``jobs.journal_skipped``) and dropped. Raises :class:`JournalError`
    if the file exists, is non-empty, and does not start with the
    magic."""
    records, _ = _scan(path)
    return records


def _scan(path) -> "tuple[list[dict], int]":
    """(known-tag records of the valid prefix, byte length of that
    prefix)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return [], 0
    if raw and not raw.startswith(MAGIC.encode() + b" "):
        raise JournalError(
            f"{path} is not a job journal (missing {MAGIC!r} magic); "
            "refusing to recover over a foreign file"
        )
    records: "list[dict]" = []
    good = 0
    pos = 0
    while pos < len(raw):
        nl = raw.find(b"\n", pos)
        line = raw[pos: nl + 1] if nl >= 0 else raw[pos:]
        record = _parse_line(line)
        if record is None:
            break  # torn tail (or flipped byte): durable prefix ends here
        pos = nl + 1
        good = pos
        tag = record.get("t")
        if tag in KNOWN_TAGS:
            records.append(record)
        else:
            obs.count("jobs.journal_skipped")
    return records, good


class Journal:
    """Append-only, fsync-per-record journal with torn-tail recovery.

    ``Journal.open`` recovers: it truncates any torn tail back to the
    last valid line (counting ``jobs.journal_truncated``) and exposes
    the surviving records as ``.records``. Appends go through the
    disk-chaos seam so the fault-injection tests can tear them."""

    def __init__(self, path, records: "list[dict]", f):
        self.path = str(path)
        self.records = records
        self._f = f

    @classmethod
    def open(cls, path) -> "Journal":
        records, good = _scan(path)
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        if size > good:
            # Torn tail: cut back to the durable prefix. The magic check
            # in _scan already guaranteed this is our file.
            with open(path, "r+b") as f:
                f.truncate(good)
                f.flush()
                os.fsync(f.fileno())
            obs.count("jobs.journal_truncated")
        f = _faults.wrap_disk(open(path, "ab"))
        return cls(path, records, f)

    def append(self, record: dict) -> None:
        """Durably append one record: write + flush + fsync, mapped into
        the guard taxonomy on failure (a full disk pauses the job, it
        does not corrupt the journal — the torn frame is cut on the
        next recovery)."""
        data = _frame(record)
        try:
            self._f.write(data)
            self._f.flush()
            os.fsync(self._f.fileno())
        except OSError as exc:
            raise map_write_error(
                exc, "journal append", path=self.path
            ) from exc
        self.records.append(record)
        obs.count("jobs.journal_appends")

    def last(self, tag: str) -> "dict | None":
        for record in reversed(self.records):
            if record.get("t") == tag:
                return record
        return None

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


class SegmentedOutput:
    """Checkpointed output: bytes land in ``seg-NNNNN`` files, each
    committed (fsync + size check + rename + dir fsync) before the
    journal records the checkpoint that covers it."""

    def __init__(self, directory):
        self.dir = str(directory)
        os.makedirs(self.dir, exist_ok=True)
        self._f = None
        self._index = -1
        self._written = 0

    def _name(self, index: int) -> str:
        return os.path.join(self.dir, f"seg-{index:05d}")

    def committed(self) -> "list[str]":
        """Committed segment paths in order, stopping at the first gap
        (a gap means the journal checkpoint sequence ends there too)."""
        out = []
        i = 0
        while os.path.exists(self._name(i)):
            out.append(self._name(i))
            i += 1
        return out

    def discard_parts(self) -> int:
        """Delete orphaned ``.part`` files (work lost past the last
        durable checkpoint); returns the byte count discarded — the
        resume's ``redone_bytes``."""
        lost = 0
        try:
            entries = os.listdir(self.dir)
        except OSError:
            return 0
        for name in entries:
            if name.endswith(".part"):
                full = os.path.join(self.dir, name)
                try:
                    lost += os.path.getsize(full)
                    os.unlink(full)
                except OSError:
                    pass
        return lost

    def begin(self, index: int):
        """Open ``seg-<index>.part`` for writing; returns the chaos-
        wrapped file object."""
        assert self._f is None, "previous segment not committed/aborted"
        self._index = index
        self._written = 0
        path = self._name(index) + ".part"
        try:
            self._f = _faults.wrap_disk(open(path, "wb"))
        except OSError as exc:
            raise map_write_error(
                exc, "segment open", path=path
            ) from exc
        return self._f

    def write(self, data: bytes) -> None:
        try:
            self._f.write(data)
        except OSError as exc:
            raise map_write_error(
                exc, "segment write", path=self._name(self._index) + ".part"
            ) from exc
        self._written += len(data)

    def commit(self) -> "tuple[str, int]":
        """Durably commit the open segment: flush + fsync, verify the
        on-disk size matches the bytes handed to :meth:`write` (catches
        silently-torn writes), rename ``.part`` → final, fsync the
        directory. Returns (path, bytes)."""
        part = self._name(self._index) + ".part"
        final = self._name(self._index)
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
            size = os.fstat(self._f.fileno()).st_size
            self._f.close()
            if size != self._written:
                raise OSError(
                    5,  # EIO: the device lied about a write
                    f"segment {part}: wrote {self._written} bytes, "
                    f"disk holds {size}",
                )
            _faults.disk_replace(part, final)
            fsync_dir(final)
        except OSError as exc:
            self.abort()
            raise map_write_error(exc, "segment commit", path=part) from exc
        self._f = None
        n, self._written = self._written, 0
        return final, n

    def abort(self) -> None:
        if self._f is None:
            return
        try:
            self._f.close()
        except OSError:
            pass
        try:
            os.unlink(self._name(self._index) + ".part")
        except OSError:
            pass
        self._f = None

    def assemble(self, out_path) -> int:
        """Concatenate the committed segments into the final artifact,
        atomically (core/atomic.py). Returns total bytes."""
        total = 0
        out = AtomicFile(out_path)
        try:
            for seg in self.committed():
                with open(seg, "rb") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        out.f.write(chunk)
                        total += len(chunk)
            out.commit()
        except OSError as exc:
            out.abort()
            raise map_write_error(
                exc, "artifact assembly", path=out_path
            ) from exc
        except BaseException:
            out.abort()
            raise
        return total

    def remove(self) -> None:
        """Delete the segment files (after a successful assembly)."""
        for seg in self.committed():
            try:
                os.unlink(seg)
            except OSError:
                pass
