"""The split service: warm state + handlers behind an admission gate.

Long-running counterpart of the one-shot CLI paths. Three resident
tiers do the work the one-shot paths rebuild per invocation:

- ``MeshSteps`` (parallel/mesh.py): jit'd ``shard_map`` steps compiled
  once at warm-up, reused for every dispatch — no per-request re-trace.
- ``_FileState`` LRU: flat views + contig dictionaries + lazy record
  starts per file, bounded by ``ServeConfig.flat_cache`` bytes.
- The shared ``.sbi`` ``CacheStore`` (sbi/store.shared_store): repeat
  plan requests resolve entirely from the sidecar index — zero
  ``load.split_resolutions``.

Scan-class requests are cut into window rows and answered through the
:class:`~spark_bam_tpu.serve.batcher.Batcher`; plan-class requests run
on a small worker pool against the index tier. Admission, deadlines and
shedding are described in docs/serving.md.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout

import numpy as np

from spark_bam_tpu import obs
from spark_bam_tpu.bam.header import read_header
from spark_bam_tpu.obs import account as obs_account
from spark_bam_tpu.obs import flight
from spark_bam_tpu.obs import trace as obs_trace
from spark_bam_tpu.obs.sampler import TailSampler
from spark_bam_tpu.obs.slo import SloEngine
from spark_bam_tpu.obs.timeseries import RingStore
from spark_bam_tpu.bgzf.flat import flatten_file
from spark_bam_tpu.core.config import Config
from spark_bam_tpu.core.faults import LatencyTracker
from spark_bam_tpu.core.guard import ResourceExhausted
from spark_bam_tpu.parallel.mesh import make_mesh, mesh_steps
from spark_bam_tpu.serve.admission import CLASS_OF, AdmissionGate
from spark_bam_tpu.serve.batcher import Batcher, RowTask
from spark_bam_tpu.serve.config import MAX_CONTIGS, ServeConfig
from spark_bam_tpu.serve.protocol import encode, error_response, ok_response
from spark_bam_tpu.tpu.checker import PAD
from spark_bam_tpu.tpu.stream_check import pad_contig_lengths

#: Retry-After fallback before the latency tracker has enough samples.
_RETRY_AFTER_DEFAULT_MS = 50.0

#: Per-op latency window behind the ``stats`` percentiles (p50/p99) —
#: the numbers the fabric autoscaler and operators both read.
_LATENCY_WINDOW = 512


def _percentile(samples, q: float) -> "float | None":
    """Nearest-rank percentile over a small sample window."""
    if not samples:
        return None
    s = sorted(samples)
    i = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return round(s[i], 3)


class ServiceError(Exception):
    """Handler failure with a stable wire ``error`` type (docs/serving.md)."""

    def __init__(self, error: str, message: str, **extra):
        self.error = error
        self.extra = extra
        super().__init__(message)


def _norm_tags(raw) -> "tuple[str, ...]":
    """Normalize a request's ``tags_required`` (string or list) into the
    tuple of two-char tag names ``_apply_filter`` takes. Raises
    ``ValueError`` on malformed names so callers map it to a
    ProtocolError before any work happens."""
    if not raw:
        return ()
    if isinstance(raw, str):
        raw = [t for t in raw.replace(";", ",").split(",") if t]
    tags = tuple(str(t).strip() for t in raw)
    for t in tags:
        if len(t) != 2:
            raise ValueError(f"tag names are exactly two chars: {t!r}")
    return tags


class _FileState:
    """Warm per-file tier: flat view, contig dictionary, lazy starts."""

    def __init__(self, path: str, config: Config):
        self.path = str(path)
        st = os.stat(self.path)
        self.stamp = (st.st_size, st.st_mtime_ns)
        header = read_header(self.path)
        self.header = header
        self.contigs = [
            (name, length)
            for _, (name, length) in sorted(header.contig_lengths.items())
        ]
        lens_list = header.contig_lengths.lengths_list()
        if len(lens_list) > MAX_CONTIGS:
            raise ServiceError(
                "Unsupported",
                f"{self.path}: {len(lens_list)} contigs exceeds the serve "
                f"step's fixed dictionary ({MAX_CONTIGS}); use the one-shot "
                "CLI path",
            )
        self.lengths = pad_contig_lengths(
            np.asarray(lens_list, dtype=np.int32), cmax=MAX_CONTIGS
        )
        self.nc = len(lens_list)
        self.header_end = header.uncompressed_size
        self.flat = flatten_file(self.path)
        self.nbytes = int(self.flat.data.nbytes)
        self._starts: "np.ndarray | None" = None
        self._starts_lock = threading.Lock()
        self._read_batch = None
        self._read_batch_lock = threading.Lock()
        # Encoded-frame cache: query shape → (frames tuple, rows). Valid
        # by the SAME determinism invariant the resume token rests on —
        # an unchanged file + query always encodes the same frame list
        # (file changes evict the whole _FileState via ``fresh()``).
        self._frame_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._frame_cache_lock = threading.Lock()

    #: distinct query shapes kept hot per file.
    _FRAME_CACHE_SLOTS = 8

    def frame_cache_get(self, key: tuple):
        with self._frame_cache_lock:
            hit = self._frame_cache.get(key)
            if hit is not None:
                self._frame_cache.move_to_end(key)
            return hit

    def frame_cache_put(self, key: tuple, chunks: tuple, rows: int) -> None:
        with self._frame_cache_lock:
            self._frame_cache[key] = (chunks, rows)
            self._frame_cache.move_to_end(key)
            while len(self._frame_cache) > self._FRAME_CACHE_SLOTS:
                self._frame_cache.popitem(last=False)

    def fresh(self) -> bool:
        try:
            st = os.stat(self.path)
        except OSError:
            return False
        return (st.st_size, st.st_mtime_ns) == self.stamp

    def starts(self, config: Config) -> np.ndarray:
        """Exact whole-file record starts (cache-aware; the escape /
        plan-exactness fallback). Computed once, kept warm."""
        with self._starts_lock:
            if self._starts is None:
                from spark_bam_tpu.load.tpu_load import record_starts

                self._starts = np.asarray(
                    record_starts(self.path, config).starts, dtype=np.int64
                )
            return self._starts

    def read_batch(self, config: Config):
        """Warm parsed ``ReadBatch`` over the flat view (the ``batch``
        op's third resident tier: repeat region queries re-filter the
        cached planes — zero re-parse, zero split resolutions)."""
        with self._read_batch_lock:
            if self._read_batch is None:
                from spark_bam_tpu.tpu.parser import parse_flat_records

                starts = self.starts(config)
                with obs.span("serve.parse", records=len(starts)):
                    self._read_batch = parse_flat_records(
                        self.flat.data, starts
                    )
            return self._read_batch


class SplitService:
    """Handlers + warm tiers; see module docstring. Thread-safe."""

    def __init__(self, config: Config = Config(), mesh=None):
        self.config = config
        self.serve_cfg: ServeConfig = config.serve_config
        self.policy = config.fault_policy
        # Zero-copy transport knobs the ACCEPT LOOP reads when answering
        # ``hello`` (serve/server.py) — the service only carries them.
        self.shm_enabled = bool(self.serve_cfg.shm)
        self.shm_bytes = int(self.serve_cfg.shm_bytes)
        self.shm_wait_ms = float(self.serve_cfg.shm_wait_ms)
        self.shm_chaos = self._build_shm_chaos(config)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.steps = mesh_steps(self.mesh)
        self.batcher = Batcher(
            self.steps,
            width=self.serve_cfg.window + PAD,
            batch_rows=self.serve_cfg.batch_rows,
            tick_ms=self.serve_cfg.tick_ms,
            reads_to_check=config.reads_to_check,
            flags_impl=config.flags_impl,
            funnel=config.funnel_enabled(),
        )
        self.gate = AdmissionGate({
            "plan": self.serve_cfg.plan_queue,
            "scan": self.serve_cfg.scan_queue,
            # Durable-job control ops: cheap table lookups + thread
            # spawns; real capacity gating lives in the JobManager.
            "control": 8,
        })
        from spark_bam_tpu.jobs.manager import JobManager

        self.jobs = JobManager(config=config, alert_fn=self._job_alert)
        self.pool = ThreadPoolExecutor(
            max_workers=self.serve_cfg.workers, thread_name_prefix="serve-worker"
        )
        # Split resolution fans out beneath a plan handler; a separate pool
        # keeps that nesting from deadlocking the request workers.
        self.resolve_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="serve-resolve"
        )
        self.latency = LatencyTracker()
        self._files: "OrderedDict[str, _FileState]" = OrderedDict()
        self._files_lock = threading.Lock()
        self.served = 0
        # op → [requests, rows, bytes, ms] — the per-op throughput ledger
        # ``stats`` reports (docs/serving.md "Observability").
        self._op_stats: "dict[str, list]" = {}
        # op → recent latencies (ms) behind the stats p50/p99.
        self._op_lat: "dict[str, deque]" = {}
        self._op_lock = threading.Lock()
        self._closed = False
        self.draining = False
        # Observability stage 2 (docs/observability.md): cost accounting
        # always runs (pure Python, no registry needed); the ring scraper,
        # SLO engine and tail sampler start when obs is configured.
        self.accountant = obs_account.Accountant()
        self.rings: "RingStore | None" = None
        self.slo_engine: "SloEngine | None" = None
        self.sampler: "TailSampler | None" = None
        self.start_observability()

    @staticmethod
    def _build_shm_chaos(config: Config):
        """Seeded shm-seam fault source (fabric/chaos.py) when the fabric
        ``chaos=`` spec carries any ``shm_*`` rate — the serve accept
        loop rolls it per frame record. Lazy import so an unconfigured
        service never pulls the fabric stack."""
        arg = config.fabric_config.chaos
        if not arg:
            return None
        from spark_bam_tpu.fabric.chaos import FabricChaos, parse_fabric_chaos

        seed, spec = parse_fabric_chaos(arg)
        if not (spec.shm_crc or spec.shm_trunc or spec.shm_unlink):
            return None
        return FabricChaos(seed, spec)

    def start_observability(self) -> bool:
        """Idempotently start the time-series ring scraper, SLO engine
        and tail sampler. Needs a configured registry — called at init
        and again by harnesses that ``obs.configure()`` after building
        the service (the bench A/B legs). Returns whether the stack is
        live."""
        if self.rings is not None:
            return True
        reg = obs.registry()
        if reg is None:
            return False
        scfg = self.config.slo_config
        rings = RingStore(reg, cadence_ms=scfg.every_ms)
        engine = SloEngine(scfg, lambda: self.rings) if scfg.enabled else None
        # Tail sampling only when an ``--slo`` spec opted in (even a
        # knob-only ``"sample=0.5"`` counts): a bare ``--metrics-out``
        # run must keep every trace, not a default 10% of them.
        sampler = None
        if self.config.slo:
            sampler = TailSampler(
                fraction=scfg.sample, seed=scfg.seed,
                slow_ms=scfg.sampler_slow_ms(),
                alerting=(
                    (lambda: self.slo_engine and self.slo_engine.alerting)
                    if engine is not None else None
                ),
            )
        with self._files_lock:
            self.rings = rings
            self.slo_engine = engine
            self.sampler = sampler
        rings.start(
            on_scrape=engine.evaluate if engine is not None else None
        )
        return True

    def stop_observability(self) -> None:
        """Tear the ring/engine/sampler stack down so a later
        :meth:`start_observability` rebinds to the CURRENT registry —
        the bench telemetry A/B flips obs off and on around a live
        service, and a stale RingStore would keep scraping the dead
        registry from before the flip."""
        with self._files_lock:
            rings, self.rings = self.rings, None
            self.slo_engine = None
            self.sampler = None
        if rings is not None:
            rings.stop()

    def _job_alert(self, name: str, **fields) -> None:
        """A paused job pages where burn-rate alerts land: the SLO
        ledger when the engine is live, the flight recorder always."""
        engine = self.slo_engine
        if engine is not None:
            engine.note_event(name, **fields)
        else:
            flight.record("slo_alert", objective=name, state="firing",
                          **fields)

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        self._closed = True
        self.jobs.close(timeout=1.0)
        if self.rings is not None:
            self.rings.stop()
        self.batcher.close()
        self.pool.shutdown(wait=False, cancel_futures=True)
        self.resolve_pool.shutdown(wait=False, cancel_futures=True)

    # ------------------------------------------------------------ admission
    def retry_after_ms(self) -> float:
        med = self.latency.median()
        return med if med is not None else _RETRY_AFTER_DEFAULT_MS

    def submit(self, req: dict, conn=None) -> "Future[dict]":
        """Admit ``req`` and return a future resolving to the full response
        dict. Raises :class:`Overloaded` synchronously when the request
        class is at its inflight limit; every other failure becomes a typed
        error *response* on the future. ``conn`` is the accept loop's
        per-connection transport state — unused here (the loop itself
        answers ``hello`` and encodes frame records), accepted so the
        loop can pass it to any service uniformly."""
        fut: "Future[dict]" = Future()
        op = req.get("op")
        if op == "ping":
            fut.set_result(ok_response(req, pong=True,
                                       devices=int(self.mesh.devices.size)))
            return fut
        if op == "stats":
            fut.set_result(ok_response(req, **self.stats()))
            return fut
        if op == "drain":
            fut.set_result(ok_response(req, **self.drain()))
            return fut
        if op == "tune":
            try:
                fut.set_result(ok_response(req, **self.tune(req)))
            except (KeyError, TypeError, ValueError) as exc:
                fut.set_result(error_response(req, "ProtocolError", str(exc)))
            return fut
        if op == "telemetry":
            fut.set_result(ok_response(req, **self.telemetry(req)))
            return fut
        if op == "alerts":
            fut.set_result(ok_response(req, **self.alerts()))
            return fut
        klass = CLASS_OF[op]
        if self._closed:
            raise RuntimeError("service is closed")
        if self.draining:
            # Graceful drain: in-flight work finishes unshed, new work is
            # refused with a typed error the fabric router reroutes on.
            fut.set_result(error_response(
                req, "Draining", "service is draining; route elsewhere",
            ))
            return fut
        self.gate.admit(klass, self.retry_after_ms())  # may raise Overloaded
        obs.count("serve.requests")
        deadline_ms = req.get("deadline_ms")
        if deadline_ms is not None:
            deadline_ts = time.monotonic() + float(deadline_ms) / 1000.0
        elif self.policy.deadline is not None:
            deadline_ts = time.monotonic() + self.policy.deadline
        else:
            deadline_ts = None
        t0 = time.monotonic()
        self.pool.submit(self._run, op, req, fut, klass, deadline_ts, t0)
        return fut

    def _run(self, op, req, fut, klass, deadline_ts, t0) -> None:
        handler = getattr(self, f"_handle_{op}")
        # Rebind the caller's trace context (if the request carried one)
        # around the request span, so every span this handler opens —
        # including the batcher rows it fans out — joins the same
        # cross-process trace (docs/observability.md).
        ctx = obs_trace.from_carrier(req.get("trace"))
        token = obs_trace.set_current(ctx) if ctx is not None else None
        flight.record("request", op=op, id=req.get("id"),
                      trace=ctx.trace_id if ctx else None)
        # The cost accumulator travels by contextvar exactly like the
        # trace: RowTask captures it at creation, the batcher attributes
        # per-row queue/device/h2d costs at dispatch (obs/account.py).
        cost = self.accountant.begin(op, req.get("tenant"))
        cost_token = obs_account.bind(cost)
        try:
            with obs.span("serve.request", op=op):
                if deadline_ts is not None and time.monotonic() > deadline_ts:
                    obs.count("serve.shed")
                    raise ServiceError(
                        "DeadlineExceeded",
                        f"{op} deadline expired before service started",
                    )
                resp = ok_response(req, **handler(req, deadline_ts))
        except ServiceError as exc:
            resp = error_response(req, exc.error, str(exc), **exc.extra)
        except TimeoutError as exc:
            obs.count("serve.shed")
            resp = error_response(req, "DeadlineExceeded", str(exc))
        except ResourceExhausted as exc:
            # Retryable environment exhaustion (disk/memory), typed so
            # clients and the fabric router can pace a retry instead of
            # treating it as an Internal failure.
            resp = error_response(
                req, "ResourceExhausted", str(exc),
                retry_after_ms=round(getattr(
                    exc, "retry_after_ms", self.retry_after_ms()
                ), 3),
            )
        except FileNotFoundError as exc:
            resp = error_response(req, "NotFound", str(exc))
        except Exception as exc:
            resp = error_response(
                req, "Internal", f"{type(exc).__name__}: {exc}"
            )
        finally:
            self.gate.release(klass)
            obs_account.reset(cost_token)
            if token is not None:
                obs_trace.reset(token)
        ms = (time.monotonic() - t0) * 1000.0
        ok = bool(resp.get("ok"))
        self.latency.record(ms)
        obs.observe("serve.latency_ms", ms)
        nbytes = self._note_op(op, ms, resp)
        self.accountant.finish(cost, ms, nbytes, ok=ok)
        if not ok:
            obs.count("serve.errors")
            flight.record("error", op=op, id=req.get("id"),
                          error=resp.get("error"),
                          message=resp.get("message"))
        if self.sampler is not None:
            # Tail decision at completion: prune dropped traces, pin
            # slow/errored exemplars on the latency histogram.
            self.sampler.note(ctx.trace_id if ctx else None, ms,
                              error=not ok)
        # Under the op lock: ``+=`` from concurrent pool threads loses
        # updates, and ``served`` feeds the autoscaler's served-changed
        # hysteresis — a stuck count reads as "no fresh samples" and
        # holds tuning moves forever.
        with self._op_lock:
            self.served += 1
        fut.set_result(resp)

    def _note_op(self, op: str, ms: float, resp: dict) -> int:
        """Per-op request/row/byte accounting. Rows come from whichever
        cardinality the op reports (``rows``/``count``/``total``); bytes
        are the encoded JSON line plus any binary frames (returned, so
        the cost accountant bills the same number)."""
        rows = 0
        if resp.get("ok"):
            for key in ("rows", "count", "total"):
                if isinstance(resp.get(key), int):
                    rows = resp[key]
                    break
        chunks = resp.get("_binary") or ()
        nbytes = sum(len(c) for c in chunks)
        nbytes += len(encode(
            {k: v for k, v in resp.items() if k != "_binary"}
        ))
        with self._op_lock:
            acc = self._op_stats.setdefault(op, [0, 0, 0, 0.0])
            acc[0] += 1
            acc[1] += rows
            acc[2] += nbytes
            acc[3] += ms
            lat = self._op_lat.get(op)
            if lat is None:
                lat = self._op_lat[op] = deque(maxlen=_LATENCY_WINDOW)
            lat.append(ms)
        return nbytes

    # -------------------------------------------------------------- admin ops
    def drain(self) -> dict:
        """Stop admitting work ops; in-flight requests and queued batcher
        ticks complete unshed. ping/stats/tune keep answering so the
        control plane can watch inflight drop to zero before detaching."""
        self.draining = True
        return {"draining": True, "inflight": self.gate.inflight()}

    def tune(self, req: dict) -> dict:
        """Runtime retargeting of the batching/admission knobs — the
        fabric autoscaler's actuator (bounded by ITS floors/ceilings;
        the service applies whatever it is told). Returns the applied
        values (batch_rows after mesh rounding)."""
        applied: dict = {}
        if req.get("batch_rows") is not None:
            applied["batch_rows"] = self.batcher.set_batch_rows(
                int(req["batch_rows"])
            )
        if req.get("tick_ms") is not None:
            applied["tick_ms"] = self.batcher.set_tick_ms(
                float(req["tick_ms"])
            )
        for key, klass in (("plan_queue", "plan"), ("scan_queue", "scan")):
            if req.get(key) is not None:
                applied[key] = self.gate.set_limit(klass, int(req[key]))
        if not applied:
            raise ValueError(
                "tune needs at least one of batch_rows/tick_ms/"
                "plan_queue/scan_queue"
            )
        obs.count("serve.tuned")
        return {"applied": applied, **self._knobs()}

    def alerts(self) -> dict:
        """The SLO engine's full status — per-objective burn rates, the
        firing set, and the bounded alert ledger. ``{"enabled": False}``
        when no objectives are configured (``--slo``/``SPARK_BAM_SLO``)."""
        if self.slo_engine is None:
            return {"slo": {"enabled": False, "objectives": [],
                            "firing": [], "ledger": []}}
        return {"slo": self.slo_engine.status()}

    def telemetry(self, req: "dict | None" = None) -> dict:
        """One scrape's worth of worker observability: the live obs
        snapshot (None when metrics are disabled), a tail of recent span
        events, the time-series ring snapshot, the SLO status, the
        accounting rollups, the flight-recorder ring, and the same stats
        dict the ``stats`` op serves — everything the router's fleet
        collector and the ``top`` CLI need in a single round-trip."""
        req = req or {}
        max_spans = int(req.get("max_spans") or 256)
        reg = obs.registry()
        spans: list = []
        snap = None
        if reg is not None:
            snap = reg.snapshot()
            spans = reg.events()[-max_spans:]
        return {
            "pid": os.getpid(),
            "telemetry_enabled": reg is not None,
            "snapshot": snap,
            "spans": spans,
            "series": self.rings.snapshot() if self.rings else None,
            "slo": (self.slo_engine.status()
                    if self.slo_engine is not None else None),
            "accounting": self.accountant.snapshot(),
            "flight": flight.recorder().events(),
            "stats": self.stats(),
        }

    def _knobs(self) -> dict:
        return {
            "batch_rows": int(self.batcher.batch_rows),
            "tick_ms": round(self.batcher.tick_s * 1000.0, 3),
            "limits": dict(self.gate.limits),
        }

    # ------------------------------------------------------------ warm tier
    def file_state(self, path) -> _FileState:
        path = str(path)
        with self._files_lock:
            fs = self._files.get(path)
            if fs is not None and fs.fresh():
                self._files.move_to_end(path)
                return fs
            if fs is not None:
                del self._files[path]
        fs = _FileState(path, self.config)
        with self._files_lock:
            self._files[path] = fs
            self._files.move_to_end(path)
            total = sum(f.nbytes for f in self._files.values())
            while total > self.serve_cfg.flat_cache and len(self._files) > 1:
                _, evicted = self._files.popitem(last=False)
                total -= evicted.nbytes
        return fs

    # ------------------------------------------------------------- handlers
    def _handle_plan(self, req: dict, deadline_ts) -> dict:
        from spark_bam_tpu.load.api import split_starts

        path = req["path"]
        size = req.get("split_size")
        splits = split_starts(
            path, split_size=size, config=self.config, pool=self.resolve_pool
        )
        return {
            "path": str(path),
            "splits": [
                {
                    "start": s.start,
                    "end": s.end,
                    "pos": None if p is None else [p.block_pos, p.offset],
                    "vpos": None if p is None else p.to_htsjdk(),
                }
                for s, p in splits
            ],
        }

    def _handle_record_starts(self, req: dict, deadline_ts) -> dict:
        fs = self.file_state(req["path"])
        starts = fs.starts(self.config)
        limit = int(req.get("limit", 0))
        blocks, offs = fs.flat.pos_of_flat_many(starts[:limit] if limit else
                                                starts[:0])
        return {
            "path": fs.path,
            "count": int(len(starts)),
            "vpos": [
                (int(b) << 16) | int(o) for b, o in zip(blocks, offs)
            ],
        }

    def _handle_count(self, req: dict, deadline_ts) -> dict:
        fs = self.file_state(req["path"])
        lo, hi = self._flat_range(fs, req)
        tasks = self._scan_rows(fs, lo, hi, deadline_ts)
        count, escaped = self._gather(tasks, deadline_ts)
        exact_fallback = False
        if escaped:
            count = self._exact_count(fs, lo, hi)
            exact_fallback = True
        return {
            "path": fs.path,
            "count": int(count),
            "escaped": int(escaped),
            "exact_fallback": exact_fallback,
        }

    def _handle_fleet(self, req: dict, deadline_ts) -> dict:
        paths = req["paths"]
        if not isinstance(paths, list) or not paths:
            raise ServiceError("ProtocolError", "fleet needs a non-empty 'paths' list")
        # Submit every file's rows before waiting on any: rows from the
        # whole fleet coalesce into shared batcher ticks.
        per_path = []
        for p in paths:
            fs = self.file_state(p)
            lo, hi = fs.header_end, fs.flat.size
            per_path.append((fs, lo, hi, self._scan_rows(fs, lo, hi, deadline_ts)))
        counts = {}
        total = 0
        for fs, lo, hi, tasks in per_path:
            count, escaped = self._gather(tasks, deadline_ts)
            if escaped:
                count = self._exact_count(fs, lo, hi)
            counts[fs.path] = int(count)
            total += int(count)
        return {"paths": counts, "total": total}

    def _handle_rewrite(self, req: dict, deadline_ts) -> dict:
        """Re-block + re-compress ``path`` into ``out`` through the write
        path (cli/rewrite.py): the device compressor when the service
        config (or the request's ``deflate`` spec) enables it, sidecars
        emitted during the write when ``index`` is set. Scan-class: the
        compressor competes with count/fleet for the device, so it shares
        their inflight cap."""
        from spark_bam_tpu.cli.rewrite import rewrite_bam
        from spark_bam_tpu.compress.config import DeflateConfig

        path = req["path"]
        out = req.get("out")
        if not out:
            raise ServiceError("ProtocolError", "rewrite needs an 'out' path")
        deflate = req.get("deflate")
        if deflate is not None:
            try:
                DeflateConfig.parse(deflate)
            except ValueError as exc:
                raise ServiceError("ProtocolError", str(exc)) from exc
        # ``resume_from`` (the streaming-failover token) is accepted and
        # ignored here: rewrite emits no frames — its idempotency is the
        # atomic output commit, so a failover simply re-runs the rewrite
        # and overwrites, never interleaves.
        try:
            block_payload = int(req.get("block_payload") or 0xFF00)
            level = int(req.get("level") or 6)
        except (TypeError, ValueError) as exc:
            raise ServiceError("ProtocolError", str(exc)) from exc
        with obs.span("serve.rewrite", path=str(path)):
            res = rewrite_bam(
                path, out,
                block_payload=block_payload, level=level, deflate=deflate,
                index=bool(req.get("index")), config=self.config,
            )
        return {
            "path": str(path),
            "out": str(out),
            "count": res.count,
            "n_blocks": res.n_blocks,
            "bytes_out": res.bytes_out,
            "sidecars": dict(res.sidecars),
        }

    # ----------------------------------------------------------- job plane
    #: request fields forwarded into a job spec, per job op.
    _JOB_FIELDS = ("path", "out", "block_payload", "level", "deflate",
                   "index", "columns", "batch_rows")

    def _handle_submit(self, req: dict, deadline_ts) -> dict:
        """Admit a durable job (jobs/manager.py). ``job`` selects the
        runner (rewrite/export/transcode); the spec fields mirror the
        one-shot ops. Deterministic job ids make retries idempotent —
        resubmitting a spec whose journal survives RESUMES it."""
        from spark_bam_tpu.jobs.runner import RUNNERS

        job = req.get("job")
        if job not in RUNNERS:
            raise ServiceError(
                "ProtocolError",
                f"submit needs job ∈ {{{', '.join(sorted(RUNNERS))}}}, "
                f"got {job!r}",
            )
        spec = {"op": job}
        spec.update(
            (k, req[k]) for k in self._JOB_FIELDS
            if req.get(k) is not None
        )
        try:
            status = self.jobs.submit(spec)
        except ValueError as exc:
            raise ServiceError("ProtocolError", str(exc)) from exc
        return status

    def _job_or_404(self, req: dict) -> str:
        jid = req.get("job_id")
        if not jid:
            raise ServiceError("ProtocolError", "missing 'job_id'")
        return str(jid)

    def _handle_job_status(self, req: dict, deadline_ts) -> dict:
        status = self.jobs.status(self._job_or_404(req))
        if status is None:
            raise ServiceError(
                "NotFound", f"no job {req.get('job_id')!r} on this worker"
            )
        return status

    def _handle_job_cancel(self, req: dict, deadline_ts) -> dict:
        status = self.jobs.cancel(self._job_or_404(req))
        if status is None:
            raise ServiceError(
                "NotFound", f"no job {req.get('job_id')!r} on this worker"
            )
        return status

    def _handle_batch(self, req: dict, deadline_ts) -> dict:
        """Columnar record batches for a (possibly interval/flag-filtered)
        file, staged as native-container frames (columnar/native.py) for
        the server to stream length-prefixed. Reuses the warm flat view
        and parsed planes, so a repeat region query does zero split
        resolutions and zero re-parses; the frame stream is byte-identical
        to ``load.api.export(fmt="native")`` for the same query
        (docs/analytics.md)."""
        from spark_bam_tpu.columnar.from_parser import (
            read_batch_to_record_batches,
        )
        from spark_bam_tpu.columnar.native import (
            batch_frame,
            container_head,
            container_meta,
            end_frame,
        )
        from spark_bam_tpu.columnar.schema import normalize_columns
        from spark_bam_tpu.load.tpu_load import _apply_filter
        from spark_bam_tpu.tpu.parser import ReadBatch

        fs = self.file_state(req["path"])
        ccfg = self.config.columnar_config
        try:
            columns = normalize_columns(req.get("columns") or ccfg.columns)
        except ValueError as exc:
            raise ServiceError("ProtocolError", str(exc)) from exc
        batch_rows = int(req.get("batch_rows") or ccfg.batch_rows)
        if batch_rows <= 0:
            raise ServiceError("ProtocolError", "batch_rows must be positive")
        wire = str(req.get("wire") or "sbcr")
        if wire not in ("sbcr", "arrow"):
            raise ServiceError(
                "ProtocolError",
                f"wire must be 'sbcr' or 'arrow', got {wire!r}",
            )
        if wire == "arrow":
            from spark_bam_tpu.columnar.arrow_ipc import arrow_available

            if not arrow_available():
                raise ServiceError(
                    "Unsupported",
                    "wire=arrow needs pyarrow (the [arrow] extra); "
                    "the default sbcr wire has no dependencies",
                )
        loci = req.get("intervals") or None
        flags_required = int(req.get("flags_required") or 0)
        flags_forbidden = int(req.get("flags_forbidden") or 0)
        tags_required = _norm_tags(req.get("tags_required"))
        # Encoded frames are a pure function of (file, query) — the same
        # determinism invariant resume rests on — so repeat queries skip
        # filter + encode entirely and the transport is the only cost.
        cache_key = (wire, columns, batch_rows, repr(loci), flags_required,
                     flags_forbidden, tags_required, ccfg.codec, ccfg.level)
        cached = fs.frame_cache_get(cache_key)
        if cached is not None:
            obs.count("serve.frame_cache_hits")
            chunks, rows = list(cached[0]), cached[1]
        else:
            obs.count("serve.frame_cache_misses")
            warm = fs.read_batch(self.config)
            if deadline_ts is not None and time.monotonic() > deadline_ts:
                obs.count("serve.shed")
                raise ServiceError(
                    "DeadlineExceeded", "batch deadline expired during parse"
                )
            # _apply_filter narrows ``valid`` in place: work on a copy so
            # the warm tier keeps the unfiltered mask for the next request.
            batch = ReadBatch(dict(warm.columns), warm.starts, buf=warm.buf)
            batch.columns["valid"] = np.array(
                warm.columns["valid"], copy=True
            )
            if loci or flags_required or flags_forbidden or tags_required:
                _apply_filter(
                    batch, fs.header, loci, flags_required, flags_forbidden,
                    tags_required=tags_required,
                )
            if wire == "arrow":
                from spark_bam_tpu.columnar.arrow_ipc import stream_frames

                with obs.span("serve.batch_encode", path=fs.path):
                    chunks, rows = stream_frames(batch, batch_rows, columns)
            else:
                meta = container_meta(
                    columns, codec=ccfg.codec, level=ccfg.level,
                    contigs=fs.contigs,
                )
                chunks = [container_head(meta)]
                rows = 0
                with obs.span("serve.batch_encode", path=fs.path):
                    for rb in read_batch_to_record_batches(
                        batch, batch_rows, columns
                    ):
                        chunks.append(batch_frame(rb, meta))
                        rows += rb.num_rows
                chunks.append(end_frame(rows, len(chunks) - 1))
            fs.frame_cache_put(cache_key, tuple(chunks), rows)
        total_frames = len(chunks)
        # Frame-sequence resume token (docs/robustness.md): the chunk
        # list is deterministic for an unchanged file + query, so a
        # replacement worker re-encodes and serves only the tail — the
        # delivered sequence is byte-identical to an undisturbed run.
        resume_from = int(req.get("resume_from") or 0)
        out = {}
        if resume_from:
            if not 0 <= resume_from < total_frames:
                raise ServiceError(
                    "ProtocolError",
                    f"resume_from={resume_from} out of range "
                    f"(0..{total_frames - 1})",
                )
            chunks = chunks[resume_from:]
            out["resume_from"] = resume_from
            out["total_frames"] = total_frames
        nbytes = sum(len(c) for c in chunks)
        obs.count("columnar.rows", rows)
        obs.count("columnar.bytes_out", nbytes)
        if wire == "arrow":
            # Only the non-default wire is echoed: sbcr responses stay
            # byte-identical to every earlier release.
            out["wire"] = wire
        out.update({
            "path": fs.path,
            "rows": int(rows),
            "columns": list(columns),
            "batch_rows": int(batch_rows),
            "binary_frames": len(chunks),
            "binary_bytes": int(nbytes),
            "_binary": chunks,
        })
        return out

    def _handle_aggregate(self, req: dict, deadline_ts) -> dict:
        """Fused on-device aggregation over the warm parsed planes
        (agg/kernels.py): the same predicate pushdown as ``batch``
        (intervals / flag masks / tag presence) narrows ``valid``, then
        the whole plan reduces inside the compiled mesh tick and only
        the int64 result vectors come back — kilobytes instead of a
        record stream, byte-equal to the host oracle
        (docs/analytics.md "Aggregation"). Scan-class: the reduction
        holds the device like count/batch do."""
        from spark_bam_tpu.agg.host import host_aggregate
        from spark_bam_tpu.agg.kernels import aggregate_planes
        from spark_bam_tpu.agg.plan import AggConfig, encode_result
        from spark_bam_tpu.load.tpu_load import _apply_filter
        from spark_bam_tpu.tpu.parser import ReadBatch

        fs = self.file_state(req["path"])
        try:
            plan = AggConfig.parse(req.get("agg") or self.config.agg)
            tags_required = _norm_tags(req.get("tags_required"))
            chunk = req.get("chunk")
            if chunk is not None:
                chunk = int(chunk)
                if chunk < 1:
                    raise ValueError(f"agg chunk must be >= 1: {chunk}")
        except (TypeError, ValueError) as exc:
            raise ServiceError("ProtocolError", str(exc)) from exc
        loci = req.get("intervals") or None
        flags_required = int(req.get("flags_required") or 0)
        flags_forbidden = int(req.get("flags_forbidden") or 0)
        warm = fs.read_batch(self.config)
        if deadline_ts is not None and time.monotonic() > deadline_ts:
            obs.count("serve.shed")
            raise ServiceError(
                "DeadlineExceeded", "aggregate deadline expired during parse"
            )
        batch = ReadBatch(dict(warm.columns), warm.starts, buf=warm.buf)
        batch.columns["valid"] = np.array(warm.columns["valid"], copy=True)
        if loci or flags_required or flags_forbidden or tags_required:
            _apply_filter(
                batch, fs.header, loci, flags_required, flags_forbidden,
                tags_required=tags_required,
            )
        rows = int(np.count_nonzero(batch.columns["valid"]))
        with obs.span("agg.reduce", path=fs.path):
            try:
                vectors = aggregate_planes(
                    batch.columns, plan, fs.nc,
                    steps=self.steps, chunk=chunk,
                )
            except Exception:
                # Device path down (no mesh step for this shape, XLA
                # failure): the numpy oracle answers identically, just
                # slower — availability over speed, counted so the
                # dashboard surfaces the regression.
                obs.count("agg.host_fallbacks")
                vectors = host_aggregate(batch.columns, plan, fs.nc)
        with obs.span("agg.encode", path=fs.path):
            meta, payload = encode_result(plan, fs.nc, fs.contigs, vectors)
        chunks = [payload]
        total_frames = len(chunks)
        # Same frame-sequence resume token as ``batch``: a single
        # deterministic frame, so a failover either re-serves it or
        # serves nothing (the client already holds it).
        resume_from = int(req.get("resume_from") or 0)
        out = {}
        if resume_from:
            if not 0 <= resume_from < total_frames:
                raise ServiceError(
                    "ProtocolError",
                    f"resume_from={resume_from} out of range "
                    f"(0..{total_frames - 1})",
                )
            chunks = chunks[resume_from:]
            out["resume_from"] = resume_from
            out["total_frames"] = total_frames
        nbytes = sum(len(c) for c in chunks)
        obs.count("agg.requests")
        obs.count("agg.rows", rows)
        obs.count("agg.bytes_out", nbytes)
        out.update({
            "path": fs.path,
            "rows": rows,
            "agg": plan.canonical(),
            "result": meta,
            "binary_frames": len(chunks),
            "binary_bytes": int(nbytes),
            "_binary": chunks,
        })
        return out

    # ------------------------------------------------------------- scanning
    def _flat_range(self, fs: _FileState, req: dict) -> "tuple[int, int]":
        """Flat [lo, hi) for a request: whole file, or the blocks whose
        compressed starts land in the request's compressed [start, end)."""
        start, end = req.get("start"), req.get("end")
        if start is None and end is None:
            return fs.header_end, fs.flat.size
        bs, bf = fs.flat.block_starts, fs.flat.block_flat
        lo = fs.header_end
        hi = fs.flat.size
        if start is not None:
            i = int(np.searchsorted(bs, int(start), side="left"))
            lo = max(fs.header_end, int(bf[i]) if i < len(bf) else fs.flat.size)
        if end is not None:
            i = int(np.searchsorted(bs, int(end), side="left"))
            hi = int(bf[i]) if i < len(bf) else fs.flat.size
        return lo, max(lo, hi)

    def _scan_rows(self, fs: _FileState, lo: int, hi: int,
                   deadline_ts) -> "list[RowTask]":
        """Cut [lo, hi) into batcher rows with ``batch_windows``'s exact
        tiling (same step/ownership arithmetic ⇒ byte-identical verdicts
        vs the one-shot path)."""
        window = self.serve_cfg.window
        halo = self.serve_cfg.halo
        step = max(window - halo, 1)
        n_total = fs.flat.size
        buf = fs.flat.data
        tasks: "list[RowTask]" = []
        if lo >= hi:
            return tasks
        for s in range(0, n_total, step):
            e = min(s + window, n_total)
            own_end = e if e == n_total else min(s + step, n_total)
            if own_end <= lo:
                if e == n_total:
                    break
                continue
            if s >= hi:
                break
            row_lo = max(lo, s) - s
            row_own = min(hi, own_end) - s
            if row_lo >= row_own:
                if e == n_total:
                    break
                continue
            t = RowTask(
                window=buf[s:e],
                n=e - s,
                at_eof=(e == n_total),
                lo=row_lo,
                own=row_own,
                lengths=fs.lengths,
                nc=fs.nc,
                deadline_ts=deadline_ts,
            )
            self.batcher.submit(t)
            tasks.append(t)
            if e == n_total:
                break
        return tasks

    def _gather(self, tasks: "list[RowTask]",
                deadline_ts) -> "tuple[int, int]":
        count = escaped = 0
        for t in tasks:
            left = None
            if deadline_ts is not None:
                left = max(deadline_ts - time.monotonic(), 0.001)
            try:
                c, esc = t.future.result(timeout=left)
            except FutureTimeout:
                # concurrent.futures.TimeoutError is NOT the builtin
                # TimeoutError before 3.11; normalize so the deadline
                # maps to DeadlineExceeded, not Internal.
                raise TimeoutError(
                    "deadline expired waiting for device verdict"
                ) from None
            count += c
            escaped += esc
        return count, escaped

    def _exact_count(self, fs: _FileState, lo: int, hi: int) -> int:
        starts = fs.starts(self.config)
        return int(np.searchsorted(starts, hi, side="left")
                   - np.searchsorted(starts, lo, side="left"))

    # ---------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._op_lock:
            ops = {
                op: {
                    "requests": int(n),
                    "rows": int(rows),
                    "bytes": int(nbytes),
                    "ms": round(ms, 3),
                    "rows_per_s": round(rows / (ms / 1000.0), 1) if ms else 0.0,
                    "bytes_per_s": round(nbytes / (ms / 1000.0), 1) if ms else 0.0,
                    "p50_ms": _percentile(self._op_lat.get(op), 0.50),
                    "p99_ms": _percentile(self._op_lat.get(op), 0.99),
                }
                for op, (n, rows, nbytes, ms) in sorted(self._op_stats.items())
            }
            all_lat = [v for d in self._op_lat.values() for v in d]
        inflight = self.gate.inflight()
        # The warm-tier proof read per-WORKER, so the fabric router's
        # spill-to-cold-worker behavior doesn't poison a global counter
        # (bench serve/fabric legs assert on this). None when obs is off.
        reg = obs.registry()
        resolutions = (
            int(reg.counter("load.split_resolutions").value)
            if reg is not None else None
        )
        return {
            "served": int(self.served),
            "inflight": inflight,
            "queue_depth": int(sum(inflight.values())),
            "backlog": int(self.batcher.backlog()),
            "draining": bool(self.draining),
            "files_resident": len(self._files),
            "batch_sizes": {
                str(k): int(v)
                for k, v in sorted(self.batcher.batch_sizes.items())
            },
            "devices": int(self.mesh.devices.size),
            "latency_p50_ms": _percentile(all_lat, 0.50),
            "latency_p99_ms": _percentile(all_lat, 0.99),
            "split_resolutions": resolutions,
            "ops": ops,
            # Durable-job table: id → state (full detail via job_status).
            "jobs": {
                j["job_id"]: j["state"] for j in self.jobs.jobs()
            },
            "accounting": self.accountant.snapshot(),
            # The compact SLO block the fabric autoscaler steers on
            # (max_burn_fast + firing objective names); None without
            # configured objectives.
            "slo": (self.slo_engine.summary()
                    if self.slo_engine is not None else None),
            **self._knobs(),
        }
