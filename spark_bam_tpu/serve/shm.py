"""Shared-memory frame transport: ring segments + descriptor records.

The zero-copy data plane (docs/serving.md "Transport"). A connection
that negotiates ``transport=shm`` in the hello exchange gets a
file-backed ring segment (``/dev/shm`` when present) created by the
server and mapped read-write by the client. Data frames are written
once into the ring; only tiny *descriptor records* cross the socket.
The socket stays the ordering/control channel — a descriptor is sent
only after its frame bytes are fully in the ring, and the send syscall
is the memory barrier — so the client may map the referenced range the
moment the descriptor arrives.

**Record grammar** (replaces the bare u64-framed stream on negotiated
connections; one ``kind`` byte then a kind-specific body):

- ``kind 0`` (inline):   ``u64 length`` + that many frame bytes — the
  per-frame fallback (ring full past the ack wait, frame larger than
  the ring, or a severed segment). Always available; byte content is
  identical to the socket path's frames.
- ``kind 1`` (shm ref):  ``<u32 seg_id, u64 offset, u64 length,
  u32 crc>`` — the frame lives at monotone ring ``offset`` (physical
  position = ``offset % capacity``) in segment ``seg_id``. ``crc`` is a
  *guard* crc32 over the frame's length + first/last ``GUARD_WINDOW``
  bytes — enough to catch reclaim races and stale reads without paying
  a full-frame checksum on the memcpy-speed path (SBCR frames carry
  their own full crc32s internally; byte-identity tests cover the rest).
- ``kind 2`` (segment announce): ``<u32 seg_id, u16 path_len>`` + the
  segment's utf-8 path. Introduces a segment mid-stream — the fabric
  router relays a same-host worker's descriptors under router-assigned
  ids, and a streaming failover announces the replacement worker's
  segment this way. Announces do not count toward ``binary_frames``.

**Reclaim protocol** (consumer-ack): the segment header holds two
monotone u64 cursors — ``head`` (server-owned write position) and
``tail`` (client-owned consumed-through position). The client advances
``tail`` to ``offset + length`` after consuming a frame; the server
treats ``head - tail`` as bytes in flight and waits (bounded by the
``shm_wait`` knob) for the ring to drain before reusing space, falling
back to an inline record if the consumer stalls. No extra socket
round-trips: the ack IS the shared cursor.

**Orphan cleanup**: segment filenames embed the creating pid
(``sbt-shm-<pid>-<id>-<nonce>``). The server unlinks on connection
close; :func:`sweep_orphans` (run at worker start) unlinks segments
whose creator is dead, so a SIGKILL'd worker can't leak ``/dev/shm``.
An unlink never invalidates an existing mapping, so a client that
already mapped a segment keeps reading safely.
"""

from __future__ import annotations

import mmap
import os
import struct
import tempfile
import zlib

from spark_bam_tpu import obs

#: record kinds (first byte of every transport record).
REC_INLINE = 0
REC_SHM = 1
REC_SEGMENT = 2

#: shm-ref descriptor body: seg_id u32, offset u64, length u64, crc u32.
DESC = struct.Struct("<IQQI")
#: segment-announce body prefix: seg_id u32, path_len u16.
SEG = struct.Struct("<IH")
#: inline body prefix (same u64 as classic socket framing).
U64 = struct.Struct("<Q")

#: segment header: magic, version, seg_id, capacity, head, tail.
#: head/tail are 8-byte aligned (offsets 24/32) — single-word cursors
#: the two sides update without locks.
_HDR = struct.Struct("<8sIIQQQ")
_MAGIC = b"SBTSHM1\0"
_VERSION = 1
#: data region starts one page in, leaving the header its own page.
DATA_OFF = 4096
_HEAD_OFF = 24
_TAIL_OFF = 32

#: guard-crc window: first/last N bytes + the length, not the whole
#: frame — the transport check stays O(1) per frame (module docstring).
GUARD_WINDOW = 4096

_PREFIX = "sbt-shm-"


class ShmError(ConnectionError):
    """Client-side shm fault (stale/corrupt descriptor, dead segment).

    A ``ConnectionError`` on purpose: the serve client's reconnect +
    ``resume_from`` loop already knows how to survive those, so a
    severed shm stream resumes on a fresh segment (or the socket path
    after repeated strikes) transparently."""


class ChaosTruncation(Exception):
    """Seeded ``shm_trunc`` injection: carry the half-written descriptor
    so the server can put exactly those bytes on the wire, then abort."""

    def __init__(self, partial: bytes):
        self.partial = partial
        super().__init__("chaos: descriptor truncated mid-record")


def guard_crc(frame) -> int:
    """crc32 over ``len`` + the frame's first/last :data:`GUARD_WINDOW`
    bytes (the whole frame when small)."""
    view = memoryview(frame)
    n = len(view)
    crc = zlib.crc32(U64.pack(n))
    if n <= 2 * GUARD_WINDOW:
        crc = zlib.crc32(view, crc)
    else:
        crc = zlib.crc32(view[:GUARD_WINDOW], crc)
        crc = zlib.crc32(view[n - GUARD_WINDOW:], crc)
    return crc & 0xFFFFFFFF


def pack_inline(frame) -> bytes:
    return b"".join([bytes([REC_INLINE]), U64.pack(len(frame)), bytes(frame)])


def pack_desc(seg_id: int, offset: int, length: int, crc: int) -> bytes:
    return bytes([REC_SHM]) + DESC.pack(seg_id, offset, length, crc)


def pack_segment(seg_id: int, path: str) -> bytes:
    raw = str(path).encode()
    return bytes([REC_SEGMENT]) + SEG.pack(seg_id, len(raw)) + raw


def segment_dir() -> str:
    """Where ring segments live: ``SPARK_BAM_SHM_DIR`` override, else
    ``/dev/shm`` (a real tmpfs — the point), else the temp dir."""
    override = os.environ.get("SPARK_BAM_SHM_DIR")
    if override:
        return override
    if os.path.isdir("/dev/shm"):
        return "/dev/shm"
    return tempfile.gettempdir()


class SegmentWriter:
    """Server-side ring segment: one per negotiated connection.

    Contiguous allocation with wrap-skip (a frame never straddles the
    ring boundary — the allocator skips the tail fragment instead), so
    every descriptor maps to one contiguous range. ``try_write`` is
    non-blocking: the caller owns the wait-for-ack pacing and the
    inline fallback."""

    def __init__(self, capacity: int, seg_id: int = 1,
                 directory: "str | None" = None):
        self.capacity = max(int(capacity), DATA_OFF)
        self.seg_id = int(seg_id)
        self.head = 0
        self.alive = True
        d = directory or segment_dir()
        nonce = os.urandom(4).hex()
        self.path = os.path.join(
            d, f"{_PREFIX}{os.getpid()}-{self.seg_id}-{nonce}"
        )
        fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, DATA_OFF + self.capacity)
            self._mm = mmap.mmap(fd, DATA_OFF + self.capacity)
        finally:
            os.close(fd)
        _HDR.pack_into(self._mm, 0, _MAGIC, _VERSION, self.seg_id,
                       self.capacity, 0, 0)
        obs.count("serve.shm_segments")

    def _tail(self) -> int:
        (tail,) = U64.unpack_from(self._mm, _TAIL_OFF)
        return tail

    def free_bytes(self) -> int:
        return self.capacity - (self.head - self._tail())

    def try_write(self, frame) -> "tuple[int, int, int, int] | None":
        """Copy ``frame`` into the ring and return its descriptor tuple
        ``(seg_id, offset, length, crc)``, or None when it doesn't fit
        right now (ring backlog) or ever (frame > capacity / segment
        severed) — the caller waits or falls back to an inline record."""
        if not self.alive:
            return None
        length = len(frame)
        if length > self.capacity:
            return None
        pos = self.head % self.capacity
        skip = self.capacity - pos if pos + length > self.capacity else 0
        if (self.head - self._tail()) + skip + length > self.capacity:
            return None
        if skip:
            self.head += skip
            pos = 0
        self._mm[DATA_OFF + pos:DATA_OFF + pos + length] = bytes(frame)
        offset = self.head
        self.head += length
        U64.pack_into(self._mm, _HEAD_OFF, self.head)
        return (self.seg_id, offset, length, guard_crc(frame))

    def drained(self) -> bool:
        """True once the consumer's ack cursor has caught up with every
        byte written — the signal that the segment may be unlinked
        without racing a reader that has seen descriptors but not yet
        mapped the file (the relay teardown seam)."""
        return self._tail() >= self.head

    def sever(self) -> None:
        """Kill the segment mid-stream (the ``shm_unlink`` chaos seam):
        unlink the file and stop allocating — frames already described
        stay readable through the client's existing mapping; everything
        after falls back to inline records."""
        self.alive = False
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def close(self) -> None:
        self.alive = False
        try:
            self._mm.close()
        except Exception:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class SegmentReader:
    """Client-side mapping of a server's ring segment (read frames,
    write the ``tail`` ack cursor)."""

    def __init__(self, path: str, seg_id: int):
        self.path = str(path)
        self.seg_id = int(seg_id)
        fd = os.open(self.path, os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, 0)
        finally:
            os.close(fd)
        magic, version, sid, capacity, _, _ = _HDR.unpack_from(self._mm, 0)
        if magic != _MAGIC or version != _VERSION:
            self._mm.close()
            raise ShmError(f"{self.path}: not a transport segment")
        # ``seg_id`` is the ANNOUNCED id — the key descriptors reference
        # on this hop. The header keeps the writer's own id, which is a
        # different number when a router relays a worker's segment under
        # a remapped id, so the two are deliberately not compared; the
        # magic plus every frame's guard crc catch a wrong-file map.
        self.writer_seg_id = sid
        self.capacity = capacity
        self._acked = 0

    def read(self, offset: int, length: int, crc: int) -> memoryview:
        """Map the described range (zero-copy). Raises :class:`ShmError`
        on a stale descriptor (already reclaimed) or guard-crc mismatch
        — both mean the stream is unsafe and must resume."""
        if length > self.capacity:
            raise ShmError(f"descriptor length {length} exceeds segment")
        if offset < self._acked:
            raise ShmError(
                f"stale descriptor: offset {offset} already acked "
                f"({self._acked})"
            )
        pos = offset % self.capacity
        view = memoryview(self._mm)[DATA_OFF + pos:DATA_OFF + pos + length]
        if guard_crc(view) != crc:
            obs.count("serve.shm_crc_errors")
            raise ShmError(
                f"guard crc mismatch at offset {offset} (+{length})"
            )
        return view

    def ack(self, offset: int, length: int) -> None:
        """Advance the consumed-through cursor — the reclaim signal the
        server's allocator waits on. Monotone; out-of-order acks are
        collapsed to the furthest point."""
        through = offset + length
        if through > self._acked:
            self._acked = through
            U64.pack_into(self._mm, _TAIL_OFF, through)

    def close(self) -> None:
        try:
            self._mm.close()
        except Exception:
            pass


def sweep_orphans(directory: "str | None" = None) -> int:
    """Unlink segments whose creating process is dead (worker start /
    ``serve_worker`` bring-up). Returns how many were removed."""
    d = directory or segment_dir()
    removed = 0
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    for name in names:
        if not name.startswith(_PREFIX):
            continue
        try:
            pid = int(name[len(_PREFIX):].split("-", 1)[0])
        except ValueError:
            continue
        try:
            os.kill(pid, 0)
            continue          # creator alive: not an orphan
        except ProcessLookupError:
            pass
        except OSError:
            continue          # EPERM etc: someone else's live process
        try:
            os.unlink(os.path.join(d, name))
            removed += 1
        except OSError:
            pass
    if removed:
        obs.count("serve.shm_orphans_cleaned", removed)
    return removed
