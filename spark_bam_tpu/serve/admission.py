"""Admission control: bounded per-class inflight limits with load shedding.

Three request classes share the daemon: *plan* (split plans,
record-start indexes — bursty, index-bound), *scan* (count verdicts,
fleet loads, rewrites — device-bound) and *control* (durable-job
submit/status/cancel — cheap bookkeeping whose real admission happens
in jobs/manager.py). Each has its own inflight cap so a flood of one
class cannot starve the other. Over-limit arrivals are rejected synchronously
with :class:`Overloaded` carrying a Retry-After hint derived from the
observed service-latency median (``FaultPolicy.LatencyTracker``).
"""

from __future__ import annotations

import threading

from spark_bam_tpu import obs

#: op → admission class. ping/stats bypass admission entirely.
CLASS_OF = {
    "plan": "plan",
    "record_starts": "plan",
    "count": "scan",
    "fleet": "scan",
    "batch": "scan",
    "aggregate": "scan",
    "rewrite": "scan",
    "submit": "control",
    "job_status": "control",
    "job_cancel": "control",
}


class Overloaded(Exception):
    """Request rejected at admission; retry after ``retry_after_ms``."""

    def __init__(self, klass: str, limit: int, retry_after_ms: float):
        self.klass = klass
        self.limit = limit
        self.retry_after_ms = float(retry_after_ms)
        super().__init__(
            f"{klass} queue full ({limit} inflight); "
            f"retry after {self.retry_after_ms:.0f} ms"
        )


class AdmissionGate:
    """Per-class inflight counters with hard limits.

    ``admit`` either reserves a slot or raises :class:`Overloaded`;
    ``release`` must be called exactly once per successful ``admit``
    (the service does so when the response future resolves).
    """

    def __init__(self, limits: "dict[str, int]"):
        self.limits = dict(limits)
        self._inflight = {k: 0 for k in limits}
        self._lock = threading.Lock()

    def admit(self, klass: str, retry_after_ms: float) -> None:
        with self._lock:
            if self._inflight[klass] >= self.limits[klass]:
                obs.count("serve.overloaded")
                raise Overloaded(klass, self.limits[klass], retry_after_ms)
            self._inflight[klass] += 1
            depth = sum(self._inflight.values())
        obs.gauge("serve.queue_depth").set(depth)

    def set_limit(self, klass: str, limit: int) -> int:
        """Retarget one class's inflight cap (the ``tune`` op / fabric
        autoscaler actuator). In-flight requests above a lowered cap
        drain naturally; only new admissions see the new limit."""
        limit = int(limit)
        if limit < 1:
            raise ValueError(f"admission limit must be >= 1: {limit}")
        with self._lock:
            if klass not in self.limits:
                raise KeyError(klass)
            self.limits[klass] = limit
        return limit

    def release(self, klass: str) -> None:
        with self._lock:
            self._inflight[klass] -= 1
            depth = sum(self._inflight.values())
        obs.gauge("serve.queue_depth").set(depth)

    def inflight(self) -> "dict[str, int]":
        with self._lock:
            return dict(self._inflight)
