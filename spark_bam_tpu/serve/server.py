"""Async accept loop: newline-JSON over a unix socket or localhost TCP.

The event loop only parses lines and shuttles futures — all real work
happens on the service's worker pool and the batcher thread, so a slow
request never stalls accepts. Each connection may pipeline requests;
responses carry the client's ``id`` and may complete out of order.

Transport negotiation lives HERE, not in the service: ``hello`` is
answered by the accept loop because transport is per-connection state
(docs/serving.md "Transport"). A connection that negotiates
``transport=shm`` gets a ring segment (serve/shm.py) and its binary
frames leave as descriptor records; everything else keeps the classic
u64-framed socket path, byte-for-byte as before.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import socket as _socket
import struct
import threading

from spark_bam_tpu import obs
from spark_bam_tpu.serve import shm
from spark_bam_tpu.serve.admission import Overloaded
from spark_bam_tpu.serve.protocol import (
    ProtocolError,
    decode_request,
    encode,
    error_response,
    ok_response,
)
from spark_bam_tpu.serve.service import SplitService

#: Longest accepted request line; beyond this the connection is dropped.
MAX_LINE = 4 << 20


class _Conn:
    """Per-connection transport state (hello-negotiated). Touched only
    on the event loop — no locks."""

    __slots__ = ("transport", "ring", "wait_s", "chaos", "_next_seg_id")

    def __init__(self):
        self.transport = "socket"
        self.ring: "shm.SegmentWriter | None" = None
        self.wait_s = 0.2
        self.chaos = None
        self._next_seg_id = 0

    def alloc_seg_id(self) -> int:
        """Connection-unique segment ids: the router's descriptor relay
        announces UPSTREAM segments on the same id space, so both its
        own ring and remapped worker segments draw from one counter."""
        self._next_seg_id += 1
        return self._next_seg_id

    def close_ring(self) -> None:
        ring, self.ring = self.ring, None
        if ring is not None:
            ring.close()

    def detach_ring(self) -> "shm.SegmentWriter | None":
        ring, self.ring = self.ring, None
        return ring


#: How long a closing connection's ring may wait for the consumer's ack
#: cursor before it is unlinked regardless (leak bound, not correctness:
#: a consumer that mapped the segment keeps its pages either way).
_RING_LINGER_S = 10.0


async def _drain_then_close(ring: "shm.SegmentWriter", loop) -> None:
    deadline = loop.time() + _RING_LINGER_S
    try:
        while not ring.drained() and loop.time() < deadline:
            await asyncio.sleep(0.02)
    finally:
        ring.close()


def _local_peer(writer) -> bool:
    """shm segments only work same-host: unix sockets always qualify,
    TCP only from loopback."""
    sock = writer.get_extra_info("socket")
    if sock is not None and sock.family == _socket.AF_UNIX:
        return True
    peer = writer.get_extra_info("peername")
    host = peer[0] if isinstance(peer, (tuple, list)) and peer else None
    if host is None:
        return False
    host = str(host)
    return host.startswith("127.") or host == "::1"


def _hello_response(service, conn: _Conn, req: dict, writer) -> dict:
    """Negotiate the connection's transport (protocol.py ``hello``).
    Every refusal is a DOWNGRADE to sockets, never an error — the
    fallback path must always be reachable."""
    want = str(req.get("transport") or "socket")
    conn.close_ring()           # re-negotiation tears down any prior ring
    conn.transport = "socket"
    if want != "shm":
        return ok_response(req, transport="socket")
    if not getattr(service, "shm_enabled", False):
        obs.count("transport.downgrades")
        return ok_response(req, transport="socket",
                           reason="server does not offer transport=shm")
    if not _local_peer(writer):
        obs.count("transport.downgrades")
        return ok_response(req, transport="socket",
                           reason="shm transport is same-host only")
    capacity = int(getattr(service, "shm_bytes", 64 << 20))
    asked = int(req.get("segment_bytes") or 0)
    if asked:
        capacity = min(capacity, asked)
    try:
        ring = shm.SegmentWriter(capacity, seg_id=conn.alloc_seg_id())
    except OSError as exc:
        obs.count("transport.downgrades")
        return ok_response(req, transport="socket",
                           reason=f"segment allocation failed: {exc}")
    conn.ring = ring
    conn.transport = "shm"
    conn.wait_s = float(getattr(service, "shm_wait_ms", 200.0)) / 1000.0
    conn.chaos = getattr(service, "shm_chaos", None)
    obs.count("transport.shm_connections")
    return ok_response(req, transport="shm", segment=ring.path,
                       segment_id=ring.seg_id, segment_bytes=ring.capacity)


async def _handle_connection(service: SplitService, reader, writer) -> None:
    obs.count("serve.connections")
    wlock = asyncio.Lock()
    conn = _Conn()
    loop = asyncio.get_running_loop()

    async def record_for(frame) -> bytes:
        """One frame → one transport record (shm connections only).
        Ring writes are memcpy-speed and bounded; a full ring waits
        briefly for the consumer's ack cursor, then goes inline — the
        transport degrades, it never deadlocks."""
        ring = conn.ring
        chaos = conn.chaos
        if ring is not None and ring.alive:
            if chaos is not None and chaos.roll("shm_unlink"):
                # lint: allow[obs-contract] literal name in obs/names.py
                obs.count("fabric.chaos.shm_unlinks")
                ring.sever()    # frames after this point go inline
            else:
                desc = ring.try_write(frame)
                if desc is None and len(frame) <= ring.capacity:
                    obs.count("transport.ring_full_waits")
                    deadline = loop.time() + conn.wait_s
                    while desc is None and loop.time() < deadline:
                        await asyncio.sleep(0.001)
                        desc = ring.try_write(frame)
                if desc is not None:
                    rec = shm.pack_desc(*desc)
                    if chaos is not None and chaos.roll("shm_crc"):
                        # lint: allow[obs-contract] name in obs/names.py
                        obs.count("fabric.chaos.shm_crcs")
                        # Stale-crc injection: the client must detect
                        # the mismatch and resume, never trust the frame.
                        rec = rec[:-1] + bytes([rec[-1] ^ 0xFF])
                    if chaos is not None and chaos.roll("shm_trunc"):
                        # lint: allow[obs-contract] name in obs/names.py
                        obs.count("fabric.chaos.shm_truncs")
                        raise shm.ChaosTruncation(rec[:len(rec) // 2])
                    obs.count("transport.shm_frames")
                    obs.count("transport.shm_bytes", len(frame))
                    return rec
        obs.count("transport.inline_frames")
        return shm.pack_inline(frame)

    async def write(resp: dict) -> None:
        # Binary record-batch frames (the batch op) ride after the JSON
        # line: classic connections get u64-length-prefixed bytes, shm
        # connections get transport records (serve/protocol.py).
        # ``_binary`` is a materialized list — the JSON line and EVERY
        # frame coalesce into one buffered write. ``_binary_iter`` (the
        # fabric router's streaming relay) is an async iterator drained
        # under the write lock with the head + first frame coalesced;
        # ``_records_iter`` carries pre-encoded transport records (the
        # router's descriptor relay) forwarded verbatim.
        chunks = resp.pop("_binary", None)
        frames_iter = resp.pop("_binary_iter", None)
        records_iter = resp.pop("_records_iter", None)
        head = encode(resp)
        poison = None
        if chunks:
            if conn.transport == "shm":
                parts = [head]
                try:
                    for c in chunks:
                        parts.append(await record_for(c))
                except shm.ChaosTruncation as exc:
                    parts.append(exc.partial)
                    poison = True
                data = b"".join(parts)
            else:
                data = b"".join(
                    [head, *(struct.pack("<Q", len(c)) + bytes(c)
                             for c in chunks)]
                )
        else:
            data = head
        if frames_iter is None and records_iter is None:
            async with wlock:
                writer.write(data)
                await writer.drain()
                if poison:
                    obs.count("serve.stream_aborts")
                    try:
                        writer.transport.abort()
                    except Exception:
                        pass
            return

        async def as_records(it):
            async for c in it:
                if conn.transport == "shm":
                    yield await record_for(c)
                else:
                    yield struct.pack("<Q", len(c)) + bytes(c)

        stream = records_iter if records_iter is not None \
            else as_records(frames_iter)
        async with wlock:
            # The JSON head is HELD until the first frame record is
            # ready, then both leave in a single buffered write — one
            # syscall, one packet for small responses (and the exact
            # same byte sequence as separate writes).
            pending = data
            try:
                async for rec in stream:
                    if pending is not None:
                        writer.write(pending + rec)
                        pending = None
                    else:
                        writer.write(rec)
                    await writer.drain()
                if pending is not None:
                    writer.write(pending)
                    await writer.drain()
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                # The JSON head promised binary_frames the stream can no
                # longer deliver (resume exhausted / chaos truncation):
                # put whatever must precede the cut on the wire, then
                # abort the transport so the client sees a hard
                # connection error, never a silently-short response.
                obs.count("serve.stream_aborts")
                tail = exc.partial if isinstance(exc, shm.ChaosTruncation) \
                    else b""
                if pending is not None or tail:
                    try:
                        writer.write((pending or b"") + tail)
                        await writer.drain()
                    except Exception:
                        pass
                try:
                    writer.transport.abort()
                except Exception:
                    pass

    async def one(req: dict) -> None:
        try:
            fut = service.submit(req, conn=conn)
        except Overloaded as exc:
            await write(error_response(
                req, "Overloaded", str(exc),
                retry_after_ms=exc.retry_after_ms,
            ))
            return
        # SplitService hands back thread-pool futures; the fabric Router
        # (which reuses this accept loop) hands back asyncio awaitables.
        if isinstance(fut, concurrent.futures.Future):
            await write(await asyncio.wrap_future(fut))
        else:
            await write(await fut)

    pending: "set[asyncio.Task]" = set()
    try:
        while True:
            try:
                line = await reader.readline()
            except (asyncio.LimitOverrunError, ValueError):
                await write(error_response(
                    {}, "ProtocolError", f"request line exceeds {MAX_LINE} bytes"
                ))
                break
            if not line:
                break
            if not line.strip():
                continue
            try:
                req = decode_request(line)
            except ProtocolError as exc:
                await write(error_response({}, "ProtocolError", str(exc)))
                continue
            if req.get("op") == "hello":
                # Answered inline on the loop: transport is connection
                # state and must be settled before later responses.
                await write(_hello_response(service, conn, req, writer))
                continue
            task = asyncio.ensure_future(one(req))
            pending.add(task)
            task.add_done_callback(pending.discard)
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
    finally:
        for task in pending:
            task.cancel()
        ring = conn.detach_ring()
        if ring is not None:
            if ring.drained() or not ring.alive:
                ring.close()
            else:
                # A relay peer closes its upstream connection as soon as
                # the last descriptor is forwarded — possibly before the
                # END client has mapped this segment. Hold the unlink
                # until the ack cursor catches up (bounded): mapped pages
                # survive the eventual unlink, an unmapped file does not.
                asyncio.ensure_future(_drain_then_close(ring, loop))
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass


class ServeAddress:
    """Where a server listens: ``unix:<path>`` or ``tcp:<host>:<port>``."""

    def __init__(self, spec: str):
        self.spec = spec
        if spec.startswith("unix:"):
            self.kind = "unix"
            self.path = spec[len("unix:"):]
            if not self.path:
                raise ValueError(f"empty unix socket path in {spec!r}")
        else:
            body = spec[len("tcp:"):] if spec.startswith("tcp:") else spec
            host, _, port = body.rpartition(":")
            self.kind = "tcp"
            self.host = host or "127.0.0.1"
            try:
                self.port = int(port)
            except ValueError:
                raise ValueError(
                    f"bad serve address {spec!r}: expected unix:<path> or "
                    "tcp:<host>:<port>"
                ) from None


async def start_server(service: SplitService, address: ServeAddress):
    """Start listening; returns the ``asyncio.AbstractServer``."""
    handler = lambda r, w: _handle_connection(service, r, w)
    if address.kind == "unix":
        return await asyncio.start_unix_server(
            handler, path=address.path, limit=MAX_LINE
        )
    return await asyncio.start_server(
        handler, host=address.host, port=address.port, limit=MAX_LINE
    )


class ServerThread:
    """In-process server with its own event loop (bench/tests/embedders).

    ``with ServerThread(service, "tcp:127.0.0.1:0") as srv:`` exposes
    ``srv.address`` (``(host, port)`` or unix path) while the calling
    thread stays free to act as a client.
    """

    def __init__(self, service: SplitService, spec: str = "tcp:127.0.0.1:0"):
        self.service = service
        self.addr = ServeAddress(spec)
        self.loop = asyncio.new_event_loop()
        self._server = None
        self._started = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="serve-loop", daemon=True
        )

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)

        async def boot():
            self._server = await start_server(self.service, self.addr)
            self._started.set()

        self.loop.run_until_complete(boot())
        self.loop.run_forever()
        leftovers = asyncio.all_tasks(self.loop)
        for task in leftovers:
            task.cancel()
        if leftovers:
            self.loop.run_until_complete(
                asyncio.gather(*leftovers, return_exceptions=True)
            )
        self.loop.run_until_complete(self.loop.shutdown_asyncgens())
        self.loop.close()

    def start(self) -> "ServerThread":
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("serve loop failed to start")
        return self

    @property
    def address(self):
        if self.addr.kind == "unix":
            return self.addr.path
        return self._server.sockets[0].getsockname()[:2]

    def stop(self) -> None:
        def _shutdown():
            if self._server is not None:
                self._server.close()
            self.loop.stop()

        self.loop.call_soon_threadsafe(_shutdown)
        self._thread.join(timeout=10)

    def __enter__(self) -> "ServerThread":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def serve_forever(service: SplitService, spec: str) -> None:
    """Blocking accept loop for the CLI ``serve`` subcommand."""

    async def main():
        server = await start_server(service, ServeAddress(spec))
        async with server:
            await server.serve_forever()

    asyncio.run(main())
