"""Async accept loop: newline-JSON over a unix socket or localhost TCP.

The event loop only parses lines and shuttles futures — all real work
happens on the service's worker pool and the batcher thread, so a slow
request never stalls accepts. Each connection may pipeline requests;
responses carry the client's ``id`` and may complete out of order.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import struct
import threading

from spark_bam_tpu import obs
from spark_bam_tpu.serve.admission import Overloaded
from spark_bam_tpu.serve.protocol import (
    ProtocolError,
    decode_request,
    encode,
    error_response,
)
from spark_bam_tpu.serve.service import SplitService

#: Longest accepted request line; beyond this the connection is dropped.
MAX_LINE = 4 << 20


async def _handle_connection(service: SplitService, reader, writer) -> None:
    obs.count("serve.connections")
    wlock = asyncio.Lock()

    async def write(resp: dict) -> None:
        # Binary record-batch frames (the batch op) ride after the JSON
        # line, each with a u64 length prefix; the JSON's binary_frames
        # field tells the client how many to read (serve/protocol.py).
        # ``_binary`` is a materialized list; ``_binary_iter`` (the
        # fabric router's streaming relay) is an async iterator drained
        # frame-by-frame under the write lock — the frames are relayed
        # as the upstream worker produces them, never buffered whole.
        chunks = resp.pop("_binary", None)
        frames_iter = resp.pop("_binary_iter", None)
        data = encode(resp)
        if chunks:
            data = b"".join(
                [data, *(struct.pack("<Q", len(c)) + bytes(c) for c in chunks)]
            )
        async with wlock:
            writer.write(data)
            await writer.drain()
            if frames_iter is not None:
                try:
                    async for c in frames_iter:
                        writer.write(struct.pack("<Q", len(c)) + bytes(c))
                        await writer.drain()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # The JSON head already promised binary_frames the
                    # stream can no longer deliver (resume exhausted):
                    # abort the transport so the client sees a hard
                    # connection error, never a silently-short response.
                    obs.count("serve.stream_aborts")
                    try:
                        writer.transport.abort()
                    except Exception:
                        pass

    async def one(req: dict) -> None:
        try:
            fut = service.submit(req)
        except Overloaded as exc:
            await write(error_response(
                req, "Overloaded", str(exc),
                retry_after_ms=exc.retry_after_ms,
            ))
            return
        # SplitService hands back thread-pool futures; the fabric Router
        # (which reuses this accept loop) hands back asyncio awaitables.
        if isinstance(fut, concurrent.futures.Future):
            await write(await asyncio.wrap_future(fut))
        else:
            await write(await fut)

    pending: "set[asyncio.Task]" = set()
    try:
        while True:
            try:
                line = await reader.readline()
            except (asyncio.LimitOverrunError, ValueError):
                await write(error_response(
                    {}, "ProtocolError", f"request line exceeds {MAX_LINE} bytes"
                ))
                break
            if not line:
                break
            if not line.strip():
                continue
            try:
                req = decode_request(line)
            except ProtocolError as exc:
                await write(error_response({}, "ProtocolError", str(exc)))
                continue
            task = asyncio.ensure_future(one(req))
            pending.add(task)
            task.add_done_callback(pending.discard)
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
    finally:
        for task in pending:
            task.cancel()
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass


class ServeAddress:
    """Where a server listens: ``unix:<path>`` or ``tcp:<host>:<port>``."""

    def __init__(self, spec: str):
        self.spec = spec
        if spec.startswith("unix:"):
            self.kind = "unix"
            self.path = spec[len("unix:"):]
            if not self.path:
                raise ValueError(f"empty unix socket path in {spec!r}")
        else:
            body = spec[len("tcp:"):] if spec.startswith("tcp:") else spec
            host, _, port = body.rpartition(":")
            self.kind = "tcp"
            self.host = host or "127.0.0.1"
            try:
                self.port = int(port)
            except ValueError:
                raise ValueError(
                    f"bad serve address {spec!r}: expected unix:<path> or "
                    "tcp:<host>:<port>"
                ) from None


async def start_server(service: SplitService, address: ServeAddress):
    """Start listening; returns the ``asyncio.AbstractServer``."""
    handler = lambda r, w: _handle_connection(service, r, w)
    if address.kind == "unix":
        return await asyncio.start_unix_server(
            handler, path=address.path, limit=MAX_LINE
        )
    return await asyncio.start_server(
        handler, host=address.host, port=address.port, limit=MAX_LINE
    )


class ServerThread:
    """In-process server with its own event loop (bench/tests/embedders).

    ``with ServerThread(service, "tcp:127.0.0.1:0") as srv:`` exposes
    ``srv.address`` (``(host, port)`` or unix path) while the calling
    thread stays free to act as a client.
    """

    def __init__(self, service: SplitService, spec: str = "tcp:127.0.0.1:0"):
        self.service = service
        self.addr = ServeAddress(spec)
        self.loop = asyncio.new_event_loop()
        self._server = None
        self._started = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="serve-loop", daemon=True
        )

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)

        async def boot():
            self._server = await start_server(self.service, self.addr)
            self._started.set()

        self.loop.run_until_complete(boot())
        self.loop.run_forever()
        leftovers = asyncio.all_tasks(self.loop)
        for task in leftovers:
            task.cancel()
        if leftovers:
            self.loop.run_until_complete(
                asyncio.gather(*leftovers, return_exceptions=True)
            )
        self.loop.run_until_complete(self.loop.shutdown_asyncgens())
        self.loop.close()

    def start(self) -> "ServerThread":
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("serve loop failed to start")
        return self

    @property
    def address(self):
        if self.addr.kind == "unix":
            return self.addr.path
        return self._server.sockets[0].getsockname()[:2]

    def stop(self) -> None:
        def _shutdown():
            if self._server is not None:
                self._server.close()
            self.loop.stop()

        self.loop.call_soon_threadsafe(_shutdown)
        self._thread.join(timeout=10)

    def __enter__(self) -> "ServerThread":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def serve_forever(service: SplitService, spec: str) -> None:
    """Blocking accept loop for the CLI ``serve`` subcommand."""

    async def main():
        server = await start_server(service, ServeAddress(spec))
        async with server:
            await server.serve_forever()

    asyncio.run(main())
