"""Serving-daemon knobs: batching, admission limits, resident budgets.

Parsed from the same compact ``k=v,...`` spec pattern as ``FaultPolicy``/
``RemoteConfig`` so it threads through ``Config.serve`` /
``SPARK_BAM_SERVE`` / ``--serve`` unchanged. Tuning notes in
docs/serving.md.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import lru_cache

from spark_bam_tpu.core.config import parse_bytes

#: Per-row contig-dictionary capacity of the serve step. Fixed so every
#: batch shares ONE compiled shape regardless of which files it mixes;
#: a file with more contigs is answered with a typed error (docs/serving.md).
MAX_CONTIGS = 1024


@dataclass(frozen=True)
class ServeConfig:
    """Knobs for the long-running split/record service (serve/)."""

    batch_rows: int = 8           # window rows per device dispatch (rounded
                                  # up to a mesh-size multiple at startup)
    tick_ms: float = 2.0          # batcher gather window after first arrival
    plan_queue: int = 64          # admission cap, plan class (plan/record_starts)
    scan_queue: int = 64          # admission cap, scan class (count/fleet)
    workers: int = 2              # plan-class handler / row-prep threads
    window: int = 1 << 20         # uncompressed bytes per row window
    halo: int = 1 << 16           # trailing lookahead per row
    flat_cache: int = 256 << 20   # resident flat-view byte budget (LRU)
    # --- zero-copy transport (serve/shm.py; docs/serving.md "Transport")
    shm: int = 1                  # offer transport=shm in the hello exchange
    shm_bytes: int = 64 << 20     # ring-segment capacity per connection
    shm_wait_ms: float = 200.0    # ack wait before a full ring goes inline

    def __post_init__(self):
        if self.batch_rows < 1 or self.workers < 1:
            raise ValueError(
                f"serve batch_rows/workers must be >= 1: "
                f"{self.batch_rows}/{self.workers}"
            )
        if self.tick_ms < 0:
            raise ValueError(f"serve tick must be >= 0 ms: {self.tick_ms}")
        if self.plan_queue < 1 or self.scan_queue < 1:
            raise ValueError(
                f"serve queue limits must be >= 1: "
                f"plan={self.plan_queue} scan={self.scan_queue}"
            )
        if self.halo < 1 or self.window <= self.halo:
            raise ValueError(
                f"serve window {self.window} must exceed halo {self.halo} "
                "(>= 1)"
            )
        if self.flat_cache < 1:
            raise ValueError(f"serve flat cache must be >= 1: {self.flat_cache}")
        if self.shm_bytes < 1 << 16:
            raise ValueError(
                f"serve shm_bytes must be >= 64KB: {self.shm_bytes}"
            )
        if self.shm_wait_ms < 0:
            raise ValueError(
                f"serve shm_wait must be >= 0 ms: {self.shm_wait_ms}"
            )

    _KEYS = {
        "batch": "batch_rows",
        "batch_rows": "batch_rows",
        "tick": "tick_ms",
        "tick_ms": "tick_ms",
        "plan_queue": "plan_queue",
        "planq": "plan_queue",
        "scan_queue": "scan_queue",
        "scanq": "scan_queue",
        "workers": "workers",
        "window": "window",
        "halo": "halo",
        "cache": "flat_cache",
        "flat_cache": "flat_cache",
        "shm": "shm",
        "shm_bytes": "shm_bytes",
        "shm_wait": "shm_wait_ms",
        "shm_wait_ms": "shm_wait_ms",
    }
    _BYTE_KEYS = ("window", "halo", "flat_cache", "shm_bytes")

    @staticmethod
    @lru_cache(maxsize=64)
    def parse(spec: str) -> "ServeConfig":
        """``"batch=16,tick=2,scan_queue=128,window=1MB,halo=64KB"`` (any
        subset; ``""`` ⇒ defaults). Byte-valued keys take size shorthand."""
        kw: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"Bad serve-config entry {part!r} in {spec!r}")
            key, value = (t.strip() for t in part.split("=", 1))
            field = ServeConfig._KEYS.get(key.replace("-", "_"))
            if field is None:
                raise ValueError(
                    f"Unknown serve-config key {key!r}: expected one of "
                    f"{', '.join(sorted(set(ServeConfig._KEYS)))}"
                )
            if field in ServeConfig._BYTE_KEYS:
                kw[field] = parse_bytes(value)
            elif field in ("tick_ms", "shm_wait_ms"):
                kw[field] = float(value)
            else:
                kw[field] = int(value)
        return ServeConfig(**kw)

    @staticmethod
    def from_env(env=None) -> "ServeConfig":
        return ServeConfig.parse((env or os.environ).get("SPARK_BAM_SERVE", ""))
