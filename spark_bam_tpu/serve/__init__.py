"""Sharded split service: a long-running daemon over the device mesh.

Promotes the one-shot CLI paths (plan/count/record-starts/fleet) into a
serving loop that keeps compiled mesh steps, flat views and the ``.sbi``
index tier warm across requests, coalesces concurrent requests into one
device dispatch per tick, and sheds load with typed responses when the
queue is full. See docs/serving.md.
"""

from spark_bam_tpu.serve.admission import AdmissionGate, Overloaded
from spark_bam_tpu.serve.batcher import Batcher, RowTask
from spark_bam_tpu.serve.client import ServeClient, ServeClientError
from spark_bam_tpu.serve.config import MAX_CONTIGS, ServeConfig
from spark_bam_tpu.serve.protocol import (
    OPS,
    ProtocolError,
    decode_request,
    encode,
    error_response,
    ok_response,
)
from spark_bam_tpu.serve.server import (
    ServeAddress,
    ServerThread,
    serve_forever,
    start_server,
)
from spark_bam_tpu.serve.service import ServiceError, SplitService
from spark_bam_tpu.serve.shm import (
    SegmentReader,
    SegmentWriter,
    ShmError,
    sweep_orphans,
)

__all__ = [
    "AdmissionGate",
    "Batcher",
    "MAX_CONTIGS",
    "OPS",
    "Overloaded",
    "ProtocolError",
    "RowTask",
    "SegmentReader",
    "SegmentWriter",
    "ServeAddress",
    "ServeClient",
    "ServeClientError",
    "ServeConfig",
    "ServerThread",
    "ServiceError",
    "ShmError",
    "SplitService",
    "decode_request",
    "encode",
    "error_response",
    "ok_response",
    "serve_forever",
    "start_server",
    "sweep_orphans",
]
