"""Request batcher: coalesce concurrent window rows into one device tick.

Scan-class requests (count/fleet) are expanded by the service into
window-row tasks; the batcher gathers rows arriving within ``tick_ms``
of the first, pads to the FIXED batch shape ``(batch_rows, window+PAD)``
and dispatches the mesh-cached serve step exactly once per tick. Fixed
shape + cached step ⇒ one trace at warm-up, zero re-traces in steady
state, which is the entire perf story of the daemon (docs/serving.md).

Rows from different files coalesce in one tick: the serve step takes
per-row contig dictionaries, so batching is purely shape-keyed.
"""

from __future__ import annotations

import threading
import time
from collections import Counter, deque
from concurrent.futures import Future

import numpy as np

from spark_bam_tpu import obs
from spark_bam_tpu.obs import account as obs_account
from spark_bam_tpu.obs import trace as obs_trace
from spark_bam_tpu.serve.config import MAX_CONTIGS


class RowTask:
    """One window row awaiting a device verdict.

    ``future`` resolves to ``(boundary_count, escaped_count)`` for the
    row's owned span, or to ``TimeoutError`` when the owning request's
    deadline passed while the row was still queued (load shedding).

    Rows capture the submitting thread's trace context at creation: a
    tick batches rows from many requests (many traces), so the dispatch
    emits one synthetic span event per row, parented under that row's
    request span rather than the shared tick. The request's cost
    accumulator (obs/account.py) rides along the same way — a shared
    tick bills each request its own rows.
    """

    __slots__ = ("window", "n", "at_eof", "lo", "own", "lengths", "nc",
                 "deadline_ts", "enqueued_ts", "future", "trace_id", "pspan",
                 "cost")

    def __init__(self, window, n, at_eof, lo, own, lengths, nc,
                 deadline_ts=None):
        self.window = window          # (W+PAD,) uint8, already padded
        self.n = int(n)
        self.at_eof = bool(at_eof)
        self.lo = int(lo)
        self.own = int(own)
        self.lengths = lengths        # (MAX_CONTIGS,) int32
        self.nc = int(nc)
        self.deadline_ts = deadline_ts  # monotonic seconds or None
        self.enqueued_ts = time.monotonic()
        self.future: Future = Future()
        ctx = obs_trace.current()
        self.trace_id = ctx.trace_id if ctx is not None else None
        self.pspan = ctx.span_id if ctx is not None else None
        self.cost = obs_account.current()


class Batcher:
    """Tick loop turning queued :class:`RowTask`s into serve-step calls."""

    def __init__(self, steps, width: int, batch_rows: int, tick_ms: float,
                 reads_to_check: int = 10, flags_impl: str = "xla",
                 funnel: bool = False):
        ndev = steps.mesh.devices.size
        self.steps = steps
        self.ndev = int(ndev)
        self.width = int(width)                      # window + PAD
        self.batch_rows = -(-int(batch_rows) // ndev) * ndev
        self.tick_s = float(tick_ms) / 1000.0
        self._step = steps.serve_step(
            reads_to_check=reads_to_check, flags_impl=flags_impl,
            funnel=funnel,
        )
        self._queue: "deque[RowTask]" = deque()
        self._cond = threading.Condition()
        self._running = threading.Event()
        self._running.set()
        self._closed = False
        self.batch_sizes: "Counter[int]" = Counter()
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True
        )
        self._thread.start()

    def submit(self, task: RowTask) -> Future:
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._queue.append(task)
            self._cond.notify()
        return task.future

    def backlog(self) -> int:
        """Rows queued but not yet dispatched — the ``stats`` op exposes
        this so operators (and brownout postmortems) can see queue
        pressure building BEFORE latency percentiles move."""
        with self._cond:
            return len(self._queue)

    def set_batch_rows(self, batch_rows: int) -> int:
        """Retarget rows-per-tick at runtime (the ``tune`` op / fabric
        autoscaler). Rounded up to a mesh-size multiple as at startup, so
        the set of dispatch shapes — hence compiled executables — stays
        small and mesh-aligned. Returns the applied (rounded) value."""
        rows = -(-max(1, int(batch_rows)) // self.ndev) * self.ndev
        with self._cond:
            self.batch_rows = rows
            self._cond.notify()
        return rows

    def set_tick_ms(self, tick_ms: float) -> float:
        """Retarget the gather window (host-side only — no recompile).
        Written under the condition so the batcher thread's in-progress
        ``_take_batch`` never reads a torn/stale tick mid-gather."""
        tick_ms = max(0.0, float(tick_ms))
        with self._cond:
            self.tick_s = tick_ms / 1000.0
            self._cond.notify()
        return tick_ms

    def pause(self) -> None:
        """Hold dispatch (tests use this to force a full-batch coalesce)."""
        self._running.clear()

    def resume(self) -> None:
        self._running.set()
        with self._cond:
            self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._running.set()
        self._thread.join(timeout=10)
        for t in list(self._queue):
            t.future.set_exception(RuntimeError("batcher closed"))
        self._queue.clear()

    # ------------------------------------------------------------------

    def _take_batch(self) -> "list[RowTask]":
        """Block for the first row, then gather up to ``batch_rows`` rows
        arriving within one tick. Returns [] only at close."""
        with self._cond:
            while not self._queue and not self._closed:
                self._cond.wait(0.05)
            if not self._queue:
                return []
            deadline = time.monotonic() + self.tick_s
            while len(self._queue) < self.batch_rows:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(left)
            batch = []
            while self._queue and len(batch) < self.batch_rows:
                batch.append(self._queue.popleft())
            return batch

    def _loop(self) -> None:
        while True:
            self._running.wait()
            batch = self._take_batch()
            if not batch and self._closed:
                return
            if not batch:
                continue
            # Shed rows whose request deadline already passed.
            now = time.monotonic()
            live = []
            for t in batch:
                if t.deadline_ts is not None and now > t.deadline_ts:
                    obs.count("serve.shed")
                    t.future.set_exception(
                        TimeoutError("deadline expired in serve queue")
                    )
                else:
                    live.append(t)
            if not live:
                continue
            try:
                self._dispatch(live)
            except BaseException as exc:  # scatter failure to every row
                for t in live:
                    if not t.future.done():
                        t.future.set_exception(exc)

    def _dispatch(self, batch: "list[RowTask]") -> None:
        # Pad to the CURRENT target, or up to the next mesh multiple of the
        # gathered rows when a ``tune`` shrank batch_rows after this batch
        # was taken — the dispatch shape must always cover the batch.
        B = max(self.batch_rows, -(-len(batch) // self.ndev) * self.ndev)
        width = self.width
        ws = np.zeros((B, width), dtype=np.uint8)
        ns = np.zeros(B, dtype=np.int32)
        eofs = np.zeros(B, dtype=bool)
        los = np.zeros(B, dtype=np.int32)
        owns = np.zeros(B, dtype=np.int32)
        lens = np.zeros((B, MAX_CONTIGS), dtype=np.int32)
        ncs = np.ones(B, dtype=np.int32)  # benign dict for padding rows
        now = time.monotonic()
        for i, t in enumerate(batch):
            ws[i, : len(t.window)] = t.window
            ns[i] = t.n
            eofs[i] = t.at_eof
            los[i] = t.lo
            owns[i] = t.own
            lens[i, : len(t.lengths)] = t.lengths
            ncs[i] = t.nc
            obs.observe("serve.queue_ms", (now - t.enqueued_ts) * 1000.0)
        # Padding rows keep lo == own == 0: empty owned span, zero counts.
        put = self.steps.put
        t_wall = time.time()
        t0 = time.perf_counter()
        with obs.span("serve.tick", rows=len(batch), shape=B):
            out = self._step(
                put(ws), put(ns), put(eofs), put(los), put(owns),
                put(lens), put(ncs),
            )
            res = np.asarray(out)
        tick_ms = (time.perf_counter() - t0) * 1000.0
        self.batch_sizes[len(batch)] += 1
        obs.count("serve.batches")
        obs.observe("serve.batch_rows", len(batch))
        obs.count("serve.h2d_bytes", sum(len(t.window) for t in batch))
        # Per-row cost attribution: the same queue_ms the histogram saw,
        # an even 1/rows share of the tick's device time, and the row's
        # own window bytes — shares sum back to serve.tick / the
        # serve.h2d_bytes counter exactly (the bench conservation gate).
        share_ms = tick_ms / len(batch)
        for t in batch:
            if t.cost is not None:
                t.cost.add(
                    queue_ms=(now - t.enqueued_ts) * 1000.0,
                    device_ms=share_ms,
                    h2d_bytes=len(t.window),
                    rows=1,
                )
        # One synthetic dispatch event per traced row: the tick is shared
        # across requests, so each row's event parents under ITS request
        # span — this is the cross-process hop that makes a serve request
        # read router → worker → tick → device dispatch as one tree.
        reg = obs.registry()
        if reg is not None:
            for t in batch:
                if t.trace_id is not None:
                    reg.emit_span_event(
                        "serve.device_dispatch", tick_ms,
                        trace_id=t.trace_id, parent_span_id=t.pspan,
                        t_wall=t_wall, rows=len(batch),
                        queue_ms=round((now - t.enqueued_ts) * 1000.0, 3),
                    )
        for i, t in enumerate(batch):
            if not t.future.done():
                t.future.set_result((int(res[i, 0]), int(res[i, 1])))
