"""Minimal blocking client for the split service (tests, bench, scripts).

One socket, one request at a time; the server supports pipelining but
this client keeps the common case trivial. Raises
:class:`ServeClientError` for non-ok responses so callers get typed
failures instead of dicts to inspect.

``Overloaded`` responses are retried in place: the server's
``retry_after_ms`` hint (floored by the policy's backoff schedule,
capped at ``backoff_max``, jittered) paces up to ``max_retries``
re-sends before the error surfaces — admission shedding reads as
latency, not failure, exactly like the partition executor's transient
handling (docs/serving.md "Backpressure"). Pass ``policy=None`` to
fail fast instead.

``batch`` and ``aggregate`` requests additionally survive MID-STREAM
connection loss: the client keeps every frame it has already read,
reconnects, and re-issues
the request with ``resume_from=<frames held>`` — the frame-sequence
resume token of docs/robustness.md. Against a streaming fabric router
the replacement worker serves only the missing tail; the reassembled
frame list is byte-identical to an undisturbed response.

With ``transport="auto"`` (the default) the client opens each
connection with a ``hello`` asking for the shared-memory frame
transport (docs/serving.md "Transport"); when granted it maps the
server's ring segment and reads frames by descriptor, zero socket
copies. Every failure on that path — segment won't map, stale
descriptor, guard-crc mismatch — raises :class:`~.shm.ShmError`, a
``ConnectionError``, so it rides the SAME reconnect + ``resume_from``
loop as a socket cut; after two shm strikes the client stops asking
and stays on sockets (``transport="socket"`` forces that from the
start). ``map_frames=True`` returns frames as memoryviews into the
mapped segment (acks deferred until the next request or
:meth:`ServeClient.release_frames`) — the ``wire=arrow`` zero-copy
read path.
"""

from __future__ import annotations

import json
import random
import socket
import struct
import time

from spark_bam_tpu import obs
from spark_bam_tpu.core.faults import FaultPolicy
from spark_bam_tpu.obs import trace as obs_trace
from spark_bam_tpu.serve import shm
from spark_bam_tpu.serve.server import MAX_LINE, ServeAddress


class ServeClientError(RuntimeError):
    """Server answered ``ok: false``; ``error``/``retry_after_ms`` attached."""

    def __init__(self, resp: dict):
        self.resp = resp
        self.error = resp.get("error", "Internal")
        self.retry_after_ms = resp.get("retry_after_ms")
        super().__init__(f"{self.error}: {resp.get('message', '')}")


class ServeClient:
    def __init__(self, address, timeout: float = 120.0,
                 policy: "FaultPolicy | None" = FaultPolicy(),
                 transport: str = "auto", map_frames: bool = False):
        """``address`` is a spec string (``tcp:host:port`` / ``unix:path``),
        a ``(host, port)`` tuple, or a unix socket path. ``policy`` paces
        Overloaded retries (None = raise immediately). ``transport`` is
        ``"auto"`` (hello for shm, fall back to sockets) or ``"socket"``
        (never ask); ``map_frames`` returns shm frames as memoryviews
        with deferred acks instead of copied bytes."""
        self.policy = policy
        self._address = address
        self._timeout = timeout
        self._want_transport = transport
        self._map_frames = bool(map_frames)
        self._transport = "socket"
        self._segments: "dict[int, shm.SegmentReader]" = {}
        self._graveyard: "list[shm.SegmentReader]" = []
        self._deferred: "list[tuple[shm.SegmentReader, int, int]]" = []
        self._shm_strikes = 0
        self._next_id = 0
        self._connect()

    def _connect(self) -> None:
        address, timeout = self._address, self._timeout
        if isinstance(address, tuple):
            self._sock = socket.create_connection(address, timeout=timeout)
        else:
            addr = ServeAddress(str(address) if str(address).startswith(("unix:", "tcp:"))
                                else ("unix:" + str(address) if "/" in str(address)
                                      else str(address)))
            if addr.kind == "unix":
                self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                self._sock.settimeout(timeout)
                self._sock.connect(addr.path)
            else:
                self._sock = socket.create_connection(
                    (addr.host, addr.port), timeout=timeout
                )
        self._rfile = self._sock.makefile("rb")
        self._handshake()

    def _reconnect(self) -> None:
        self.close(keep_segments=True)
        self._connect()

    # ----- transport negotiation -------------------------------------

    def _roundtrip(self, req: dict) -> dict:
        """One JSON line out, one in — control exchanges with no frames."""
        self._next_id += 1
        self._sock.sendall(
            (json.dumps({**req, "id": self._next_id}) + "\n").encode()
        )
        line = self._rfile.readline(MAX_LINE)
        if not line:
            raise ConnectionError("server closed the connection")
        return json.loads(line)

    def _handshake(self) -> None:
        """Ask for ``transport=shm`` unless told not to (or burned: two
        shm strikes pin the client to sockets — the universal fallback)."""
        self._transport = "socket"
        if self._want_transport == "socket" or self._shm_strikes >= 2:
            return
        resp = self._roundtrip({"op": "hello", "transport": "shm"})
        if not resp.get("ok") or resp.get("transport") != "shm":
            return
        try:
            self._open_segment(int(resp["segment_id"]), str(resp["segment"]))
        except (OSError, shm.ShmError, KeyError, ValueError):
            # Granted but unmappable (container boundary, permissions):
            # tell the server so it frees the ring and sends plain frames.
            obs.count("transport.downgrades")
            self._roundtrip({"op": "hello", "transport": "socket"})
            return
        self._transport = "shm"

    def _open_segment(self, seg_id: int, path: str) -> None:
        old = self._segments.pop(seg_id, None)
        if old is not None:
            # Frames already handed out may still view the old mapping
            # (map_frames / resume progress): keep it mapped until close.
            self._graveyard.append(old)
        try:
            self._segments[seg_id] = shm.SegmentReader(path, seg_id)
        except OSError as exc:
            raise shm.ShmError(f"cannot map segment {path}: {exc}") from exc

    @property
    def transport(self) -> str:
        """The negotiated transport of the CURRENT connection."""
        return self._transport

    def release_frames(self) -> None:
        """Ack every deferred (``map_frames``) range back to the server's
        reclaim cursor. Called automatically at the next request — by
        then the previous response's views must no longer be read."""
        deferred, self._deferred = self._deferred, []
        for reader, offset, length in deferred:
            reader.ack(offset, length)

    # ----- requests ---------------------------------------------------

    def request(self, op: str, **fields) -> dict:
        """Send one request and block for its response payload. Responses
        announcing ``binary_frames`` (``batch``/``aggregate``) have that many
        frames read off the transport and attached as a list of bytes
        under ``"_binary"`` — concatenated they are a native columnar
        container (columnar/native.py), or an Arrow IPC stream when the
        request said ``wire=arrow``. ``Overloaded`` responses honor their
        Retry-After hint under ``self.policy``; ``batch`` requests that
        lose the connection (or the shm stream) mid-read reconnect and
        resume from the frames already held (``resume_from``)."""
        self.release_frames()
        retries = self.policy.max_retries if self.policy is not None else 0
        # Frames survive across resume attempts: a mid-stream loss keeps
        # what arrived and asks only for the tail.
        progress: "list[bytes]" = (
            [] if op in ("batch", "aggregate") else None
        )
        for attempt in range(retries + 1):
            try:
                resp = self._request_once(op, fields, progress=progress)
                resp["_transport"] = self._transport
                return resp
            except ServeClientError as exc:
                if exc.error != "Overloaded" or attempt >= retries:
                    raise
                time.sleep(self._overload_delay(exc, attempt))
            except (ConnectionError, OSError, json.JSONDecodeError) as exc:
                # A death mid-JSON-line decodes as garbage; treat it the
                # same as a mid-frame cut — reconnect and resume. Shm
                # faults land here too (ShmError IS a ConnectionError);
                # repeated strikes downgrade the reconnect to sockets.
                if isinstance(exc, shm.ShmError):
                    self._shm_strikes += 1
                if progress is None or attempt >= retries:
                    raise
                self._reconnect()
        raise AssertionError("unreachable")

    def _overload_delay(self, exc: "ServeClientError", attempt: int) -> float:
        """Server hint floored by the policy's exponential schedule,
        capped at ``backoff_max``, jittered — so a fleet of rejected
        clients doesn't re-arrive in lockstep."""
        p = self.policy
        hint_s = float(exc.retry_after_ms or 0.0) / 1000.0
        d = min(p.backoff_max, max(hint_s, p.backoff_base * (2 ** attempt)))
        return d * (1 - p.jitter + p.jitter * random.random())

    def _request_once(self, op: str, fields: dict,
                      progress: "list | None" = None) -> dict:
        self._next_id += 1
        req = {"op": op, "id": self._next_id, **fields}
        # Frames held at ENTRY came from a prior severed attempt — only
        # then is this a resume (the list fills during a normal read too).
        resuming = bool(progress)
        if resuming:
            # Compose with any caller-supplied token: the server slices
            # its deterministic frame sequence at base + held frames.
            req["resume_from"] = (
                int(fields.get("resume_from") or 0) + len(progress)
            )
        if "trace" not in req and obs.enabled():
            # Join the caller's trace (e.g. the CLI root span) or mint a
            # fresh one per request; the server rebinds it so the whole
            # request reads as one cross-process span tree.
            ctx = obs_trace.current() or obs_trace.mint()
            req["trace"] = obs_trace.carrier(ctx)
        self._sock.sendall((json.dumps(req) + "\n").encode())
        line = self._rfile.readline(MAX_LINE)
        if not line:
            raise ConnectionError("server closed the connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise ServeClientError(resp)
        n_frames = int(resp.get("binary_frames") or 0)
        if n_frames:
            frames = progress if progress is not None else []
            if self._transport == "shm":
                self._read_records(n_frames, frames)
            else:
                for _ in range(n_frames):
                    (length,) = struct.unpack("<Q", self._read_exact(8))
                    frames.append(self._read_exact(length))
            resp["_binary"] = list(frames)
        elif resuming:
            # Resumed with zero frames left to serve (the loss hit after
            # the final frame): the held list IS the complete response.
            resp["_binary"] = list(progress)
        if resuming:
            # Present the reassembled response as the undisturbed one.
            resp["binary_frames"] = len(resp.get("_binary") or ())
            resp.pop("resume_from", None)
            resp.pop("total_frames", None)
        return resp

    def _read_records(self, n_frames: int, frames: list) -> None:
        """Drain ``n_frames`` transport records (serve/shm.py grammar).
        Segment announces (kind 2) may interleave and don't count."""
        got = 0
        while got < n_frames:
            kind = self._read_exact(1)[0]
            if kind == shm.REC_SEGMENT:
                seg_id, plen = shm.SEG.unpack(self._read_exact(shm.SEG.size))
                self._open_segment(seg_id, self._read_exact(plen).decode())
                continue
            if kind == shm.REC_INLINE:
                (length,) = struct.unpack("<Q", self._read_exact(8))
                frames.append(self._read_exact(length))
                got += 1
                continue
            if kind == shm.REC_SHM:
                seg_id, offset, length, crc = shm.DESC.unpack(
                    self._read_exact(shm.DESC.size)
                )
                reader = self._segments.get(seg_id)
                if reader is None:
                    raise shm.ShmError(
                        f"descriptor references unknown segment {seg_id}"
                    )
                view = reader.read(offset, length, crc)
                if self._map_frames:
                    frames.append(view)
                    self._deferred.append((reader, offset, length))
                else:
                    frames.append(bytes(view))
                    view.release()
                    reader.ack(offset, length)
                got += 1
                continue
            raise shm.ShmError(f"unknown transport record kind {kind}")

    def _read_exact(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            piece = self._rfile.read(n - len(out))
            if not piece:
                raise ConnectionError(
                    "server closed the connection mid-frame"
                )
            out.extend(piece)
        return bytes(out)

    def close(self, keep_segments: bool = False) -> None:
        try:
            self._rfile.close()
        finally:
            self._sock.close()
        if not keep_segments:
            self.release_frames()
            for reader in (*self._segments.values(), *self._graveyard):
                reader.close()
            self._segments.clear()
            self._graveyard.clear()

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
