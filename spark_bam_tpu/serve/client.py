"""Minimal blocking client for the split service (tests, bench, scripts).

One socket, one request at a time; the server supports pipelining but
this client keeps the common case trivial. Raises
:class:`ServeClientError` for non-ok responses so callers get typed
failures instead of dicts to inspect.

``Overloaded`` responses are retried in place: the server's
``retry_after_ms`` hint (floored by the policy's backoff schedule,
capped at ``backoff_max``, jittered) paces up to ``max_retries``
re-sends before the error surfaces — admission shedding reads as
latency, not failure, exactly like the partition executor's transient
handling (docs/serving.md "Backpressure"). Pass ``policy=None`` to
fail fast instead.
"""

from __future__ import annotations

import json
import random
import socket
import struct
import time

from spark_bam_tpu import obs
from spark_bam_tpu.core.faults import FaultPolicy
from spark_bam_tpu.obs import trace as obs_trace
from spark_bam_tpu.serve.server import MAX_LINE, ServeAddress


class ServeClientError(RuntimeError):
    """Server answered ``ok: false``; ``error``/``retry_after_ms`` attached."""

    def __init__(self, resp: dict):
        self.resp = resp
        self.error = resp.get("error", "Internal")
        self.retry_after_ms = resp.get("retry_after_ms")
        super().__init__(f"{self.error}: {resp.get('message', '')}")


class ServeClient:
    def __init__(self, address, timeout: float = 120.0,
                 policy: "FaultPolicy | None" = FaultPolicy()):
        """``address`` is a spec string (``tcp:host:port`` / ``unix:path``),
        a ``(host, port)`` tuple, or a unix socket path. ``policy`` paces
        Overloaded retries (None = raise immediately)."""
        self.policy = policy
        if isinstance(address, tuple):
            self._sock = socket.create_connection(address, timeout=timeout)
        else:
            addr = ServeAddress(str(address) if str(address).startswith(("unix:", "tcp:"))
                                else ("unix:" + str(address) if "/" in str(address)
                                      else str(address)))
            if addr.kind == "unix":
                self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                self._sock.settimeout(timeout)
                self._sock.connect(addr.path)
            else:
                self._sock = socket.create_connection(
                    (addr.host, addr.port), timeout=timeout
                )
        self._rfile = self._sock.makefile("rb")
        self._next_id = 0

    def request(self, op: str, **fields) -> dict:
        """Send one request and block for its response payload. Responses
        announcing ``binary_frames`` (the ``batch`` op) have that many
        u64-length-prefixed frames read off the socket and attached as a
        list of bytes under ``"_binary"`` — concatenated they are a
        native columnar container (columnar/native.py). ``Overloaded``
        responses honor their Retry-After hint under ``self.policy``."""
        retries = self.policy.max_retries if self.policy is not None else 0
        for attempt in range(retries + 1):
            try:
                return self._request_once(op, fields)
            except ServeClientError as exc:
                if exc.error != "Overloaded" or attempt >= retries:
                    raise
                time.sleep(self._overload_delay(exc, attempt))
        raise AssertionError("unreachable")

    def _overload_delay(self, exc: "ServeClientError", attempt: int) -> float:
        """Server hint floored by the policy's exponential schedule,
        capped at ``backoff_max``, jittered — so a fleet of rejected
        clients doesn't re-arrive in lockstep."""
        p = self.policy
        hint_s = float(exc.retry_after_ms or 0.0) / 1000.0
        d = min(p.backoff_max, max(hint_s, p.backoff_base * (2 ** attempt)))
        return d * (1 - p.jitter + p.jitter * random.random())

    def _request_once(self, op: str, fields: dict) -> dict:
        self._next_id += 1
        req = {"op": op, "id": self._next_id, **fields}
        if "trace" not in req and obs.enabled():
            # Join the caller's trace (e.g. the CLI root span) or mint a
            # fresh one per request; the server rebinds it so the whole
            # request reads as one cross-process span tree.
            ctx = obs_trace.current() or obs_trace.mint()
            req["trace"] = obs_trace.carrier(ctx)
        self._sock.sendall((json.dumps(req) + "\n").encode())
        line = self._rfile.readline(MAX_LINE)
        if not line:
            raise ConnectionError("server closed the connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise ServeClientError(resp)
        n_frames = int(resp.get("binary_frames") or 0)
        if n_frames:
            frames = []
            for _ in range(n_frames):
                (length,) = struct.unpack("<Q", self._read_exact(8))
                frames.append(self._read_exact(length))
            resp["_binary"] = frames
        return resp

    def _read_exact(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            piece = self._rfile.read(n - len(out))
            if not piece:
                raise ConnectionError(
                    "server closed the connection mid-frame"
                )
            out.extend(piece)
        return bytes(out)

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self._sock.close()

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
