"""Newline-delimited JSON wire protocol for the split service.

One request object per line, one response object per line. Requests carry
an ``op`` plus op-specific fields and an optional client-chosen ``id``
echoed back verbatim, so clients may pipeline. Responses are either

    {"id": ..., "ok": true, ...payload}
    {"id": ..., "ok": false, "error": "<Type>", "message": "...", ...}

The ``batch`` op additionally streams binary record-batch frames after
its JSON line: the payload's ``binary_frames`` counts the frames that
follow, each written as a little-endian u64 length prefix + that many
bytes. Concatenated, the frames are exactly a native columnar container
(columnar/native.py) — byte-identical to the file sink's output for the
same query. Handlers stage the chunks on the in-process response under
the ``"_binary"`` key; the server pops it before JSON encoding. The
fabric router's streaming relay stages an ASYNC ITERATOR under
``"_binary_iter"`` instead — same wire format, but the server writes
each frame as it arrives rather than joining a buffered list. A relay
that has already ENCODED transport records (the router's zero-copy
descriptor relay) stages them under ``"_records_iter"`` and the server
forwards the bytes verbatim.

``hello`` is the transport negotiation op, answered by the ACCEPT LOOP
itself (serve/server.py), never the service — transport is connection
state, not request state. ``{"op": "hello", "transport": "shm"}`` asks
for the shared-memory frame transport; a capable server answers
``{"transport": "shm", "segment": <path>, "segment_id": N,
"segment_bytes": M}`` and from then on that connection's binary frames
travel as transport RECORDS (serve/shm.py: inline / shm-descriptor /
segment-announce), not bare u64-framed bytes. Any other answer (or no
hello at all) keeps classic socket framing — the universal fallback and
the only remote path. A later ``hello`` with ``transport=socket``
downgrades the connection back (the client does this when it cannot map
the announced segment). ``wire=arrow`` on a ``batch`` request swaps the
frame payload from the SBCR container to Arrow IPC stream format
(columnar/arrow_ipc.py) — same framing, resume token and counts either
way.

``batch`` (and ``rewrite``, vacuously) accept an optional ``resume_from``
integer — the frame-sequence resume token (docs/robustness.md): the
frame list for an unchanged file + query is deterministic, so a request
with ``resume_from=N`` is answered with frames ``N..`` only, plus
``total_frames`` echoing the full count. Clients and the fabric router
use it to resume a response severed mid-stream on a replacement worker;
the reassembled sequence is byte-identical to an undisturbed one.

Admin ops (``drain``, ``tune``, ``telemetry``, ``alerts``) bypass
admission like ``ping``/``stats``: ``drain`` stops new work-op admission
(in-flight ticks finish unshed), ``tune`` retargets batching/admission
knobs at runtime — the fabric autoscaler's actuator (docs/fabric.md) —
``telemetry`` returns the worker's merged obs snapshot, recent span
events, time-series rings, and flight-recorder ring, and ``alerts``
returns the SLO engine's statuses, burn rates and alert ledger
(docs/observability.md).

Job ops (``submit``, ``job_status``, ``job_cancel``) are the durable
job plane's control surface (jobs/manager.py): ``submit`` admits (or
idempotently re-attaches to) a journaled rewrite/export/transcode and
answers immediately with the job's id + state; the other two poll and
cancel it. They ride a small ``control`` admission class so a burst of
job control can never displace plan/scan work. A deferred or paused job
answers with the typed ``ResourceExhausted`` error + ``retry_after_ms``.

Requests may carry an optional ``tenant`` string — a client-chosen
identity the per-request cost accountant (obs/account.py) rolls up by,
so ``stats``/``top`` can answer "who is spending the fleet". Absent
tenants bill to ``"-"``.

Requests may carry an optional ``trace`` field — ``{"id": <trace_id>,
"span": <parent span_id>}`` — minted by the client (or the fabric
router on behalf of bare clients) and rebound in the worker's serve
loop, so one request reads as one cross-process span tree
(docs/observability.md "Trace propagation"). Servers ignore unknown
carrier shapes rather than erroring.

Error types are stable strings (``Overloaded``, ``DeadlineExceeded``,
``ProtocolError``, ``NotFound``, ``Unsupported``, ``Internal``,
``Draining``, ``WorkerLost``, ``ResourceExhausted``) — docs/serving.md
tabulates them.
"""

from __future__ import annotations

import json

#: ops answered by the service; anything else is a ProtocolError.
OPS = ("ping", "stats", "plan", "record_starts", "count", "fleet", "batch",
       "aggregate", "rewrite", "drain", "tune", "telemetry", "alerts",
       "submit", "job_status", "job_cancel", "hello")


class ProtocolError(ValueError):
    """Malformed request line (bad JSON, missing/unknown fields)."""


def decode_request(line: "str | bytes") -> dict:
    try:
        req = json.loads(line)
    except Exception as exc:
        raise ProtocolError(f"request is not valid JSON: {exc}") from exc
    if not isinstance(req, dict):
        raise ProtocolError(f"request must be a JSON object, got {type(req).__name__}")
    op = req.get("op")
    if op not in OPS:
        raise ProtocolError(f"unknown op {op!r}: expected one of {', '.join(OPS)}")
    return req


def encode(obj: dict) -> bytes:
    return (json.dumps(obj, separators=(",", ":"), sort_keys=True) + "\n").encode()


def ok_response(req: dict, **payload) -> dict:
    return {"id": req.get("id"), "ok": True, **payload}


def error_response(req: dict, error: str, message: str, **extra) -> dict:
    return {"id": req.get("id"), "ok": False, "error": error,
            "message": message, **extra}
