"""Build + ctypes-load the native runtime (g++ → shared object, cached).

No pybind11 in this environment, so the binding is plain ctypes over an
``extern "C"`` surface. The build is lazy and cached next to the source;
everything degrades gracefully to the NumPy/Python paths when a compiler is
unavailable (``load_native()`` returns None).
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

log = logging.getLogger(__name__)

_SRC = Path(__file__).parent / "spark_bam_native.cpp"
_LIB_CACHE: list = []  # [lib or None], filled once
_LOAD_LOCK = threading.Lock()  # concurrent first-use (pipeline threads)


def _build(src: Path, out: Path) -> bool:
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17"]
    # Build to a temp name then atomically rename: a killed/concurrent build
    # can never leave a half-written .so that later loads would trip over.
    tmp = out.with_suffix(f".tmp{os.getpid()}")
    tail = [str(src), "-o", str(tmp), "-lz"]
    # -march=native helps the bit-twiddling hot loops measurably; the cache
    # key includes a host-CPU token, so a shared checkout never serves one
    # machine's tuned binary to a different machine. Retry generic in case
    # the toolchain rejects -march=native.
    last_err = None
    try:
        for flags in ([*base, "-march=native", *tail], [*base, *tail]):
            try:
                subprocess.run(flags, check=True, capture_output=True)
                os.replace(tmp, out)
                return True
            except FileNotFoundError as e:
                log.warning("native build failed (%s); using Python fallbacks", e)
                return False
            except subprocess.CalledProcessError as e:
                last_err = e
        log.warning(
            "native build failed (rc=%s): %s; using Python fallbacks",
            last_err.returncode,
            (last_err.stderr or b"").decode(errors="replace")[-500:],
        )
        return False
    finally:
        tmp.unlink(missing_ok=True)


def _host_token() -> str:
    """A short token identifying this host's CPU (for the .so cache key)."""
    import platform

    desc = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features", "model name")):
                    desc += line
                    break
    except OSError:
        pass
    return hashlib.sha256(desc.encode()).hexdigest()[:8]


def load_native():
    """The loaded shared library with argtypes set, or None."""
    if _LIB_CACHE:
        return _LIB_CACHE[0]
    with _LOAD_LOCK:
        if _LIB_CACHE:  # another thread finished while we waited
            return _LIB_CACHE[0]
        return _load_native_locked()


def _load_native_locked():
    digest = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    out = _SRC.parent / f"_spark_bam_native_{digest}_{_host_token()}.so"
    if not out.exists() and not _build(_SRC, out):
        _LIB_CACHE.append(None)
        return None
    try:
        lib = ctypes.CDLL(str(out))
    except OSError as e:
        log.warning("native load failed (%s); using Python fallbacks", e)
        _LIB_CACHE.append(None)
        return None

    c_u8p = ctypes.POINTER(ctypes.c_uint8)
    c_i64p = ctypes.POINTER(ctypes.c_int64)
    c_i32p = ctypes.POINTER(ctypes.c_int32)
    c_u16p = ctypes.POINTER(ctypes.c_uint16)

    lib.sbt_inflate_blocks.restype = ctypes.c_long
    lib.sbt_inflate_blocks.argtypes = [
        c_u8p, c_i64p, c_i64p, ctypes.c_int64, c_u8p, c_i64p, c_i64p,
    ]
    lib.sbt_eager_check.restype = None
    lib.sbt_eager_check.argtypes = [
        c_u8p, ctypes.c_int64, c_i64p, ctypes.c_int64,
        c_i32p, ctypes.c_int32, ctypes.c_int32, c_u8p,
    ]
    lib.sbt_find_record_start.restype = ctypes.c_int64
    lib.sbt_find_record_start.argtypes = [
        c_u8p, ctypes.c_int64, ctypes.c_int64,
        c_i32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
    ]
    lib.sbt_find_record_start_window.restype = ctypes.c_int64
    lib.sbt_find_record_start_window.argtypes = [
        c_u8p, ctypes.c_int64, ctypes.c_int64,
        c_i32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        ctypes.c_int32, c_i64p,
    ]
    lib.sbt_eager_check_window.restype = None
    lib.sbt_eager_check_window.argtypes = [
        c_u8p, ctypes.c_int64, c_i64p, ctypes.c_int64,
        c_i32p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, c_u8p,
    ]
    lib.sbt_tokenize_deflate.restype = ctypes.c_long
    lib.sbt_tokenize_deflate.argtypes = [
        c_u8p, c_i64p, c_i64p, ctypes.c_int64,
        c_u8p, c_u16p, ctypes.c_int64, c_i64p,
    ]
    lib.sbt_rans_decompress.restype = ctypes.c_int64
    lib.sbt_rans_decompress.argtypes = [
        c_u8p, ctypes.c_int64, c_u8p, ctypes.c_int64,
    ]
    lib.sbt_inflate_blocks_fast.restype = ctypes.c_long
    lib.sbt_inflate_blocks_fast.argtypes = [
        c_u8p, c_i64p, c_i64p, ctypes.c_int64, c_u8p, c_i64p, c_i64p,
        ctypes.c_int64,
    ]
    _LIB_CACHE.append(lib)
    return lib


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def eager_check_native(
    buf: np.ndarray,
    candidates: np.ndarray,
    contig_lengths: np.ndarray,
    reads_to_check: int = 10,
) -> np.ndarray | None:
    """Native eager verdicts for candidate offsets; None if unavailable."""
    lib = load_native()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    cand = np.ascontiguousarray(candidates, dtype=np.int64)
    lens = np.ascontiguousarray(contig_lengths, dtype=np.int32)
    out = np.zeros(len(cand), dtype=np.uint8)
    lib.sbt_eager_check(
        _ptr(buf, ctypes.c_uint8), len(buf),
        _ptr(cand, ctypes.c_int64), len(cand),
        _ptr(lens, ctypes.c_int32), len(lens),
        reads_to_check, _ptr(out, ctypes.c_uint8),
    )
    return out.astype(bool)


def find_record_start_native(
    buf: np.ndarray,
    start: int,
    contig_lengths: np.ndarray,
    reads_to_check: int = 10,
    max_read_size: int = 10_000_000,
) -> int | None:
    """First boundary at/after start (flat offset), -1 if none; None if the
    native library is unavailable."""
    lib = load_native()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    lens = np.ascontiguousarray(contig_lengths, dtype=np.int32)
    return int(
        lib.sbt_find_record_start(
            _ptr(buf, ctypes.c_uint8), len(buf), start,
            _ptr(lens, ctypes.c_int32), len(lens),
            reads_to_check, max_read_size,
        )
    )


def eager_check_window_native(
    buf: np.ndarray,
    candidates: np.ndarray,
    contig_lengths: np.ndarray,
    reads_to_check: int = 10,
    exact_eof: bool = False,
) -> np.ndarray | None:
    """Tri-state verdicts per candidate over a bounded window: 0/1 =
    certain fail/pass (chain resolved on in-window bytes), 2 = the verdict
    depended on the window edge (retry with more lookahead). ``None`` if
    the native library is unavailable."""
    lib = load_native()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    cand = np.ascontiguousarray(candidates, dtype=np.int64)
    lens = np.ascontiguousarray(contig_lengths, dtype=np.int32)
    out = np.zeros(len(cand), dtype=np.uint8)
    lib.sbt_eager_check_window(
        _ptr(buf, ctypes.c_uint8), len(buf),
        _ptr(cand, ctypes.c_int64), len(cand),
        _ptr(lens, ctypes.c_int32), len(lens),
        reads_to_check, 1 if exact_eof else 0,
        _ptr(out, ctypes.c_uint8),
    )
    return out


def find_record_start_window_native(
    buf: np.ndarray,
    start: int,
    contig_lengths: np.ndarray,
    reads_to_check: int = 10,
    max_read_size: int = 10_000_000,
    exact_eof: bool = False,
) -> tuple[int, int] | None:
    """Tri-state bounded-window scan: ``(found, uncertain_at)``.

    ``found`` ≥ 0 is the first position whose chain passed on in-window
    bytes alone (certain). ``found`` = -1 with ``uncertain_at`` ≥ 0 means
    scanning stopped where a verdict depended on the window edge — every
    earlier position is a certain fail; grow the window and resume there.
    ``(-1, -1)`` = certain fails throughout the scanned span. ``None`` if
    the native library is unavailable."""
    lib = load_native()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    lens = np.ascontiguousarray(contig_lengths, dtype=np.int32)
    uncertain = ctypes.c_int64(-1)
    found = int(
        lib.sbt_find_record_start_window(
            _ptr(buf, ctypes.c_uint8), len(buf), start,
            _ptr(lens, ctypes.c_int32), len(lens),
            reads_to_check, max_read_size,
            1 if exact_eof else 0, ctypes.byref(uncertain),
        )
    )
    return found, int(uncertain.value)


def tokenize_deflate_native(
    comp: np.ndarray,
    offsets: np.ndarray,
    lengths: np.ndarray,
    stride: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray] | None:
    """Phase 1 of the two-phase device inflate: entropy-decode raw-DEFLATE
    payloads into fixed-shape (lit, dist, out_lens) token rows for the
    device LZ77 resolver (tpu/inflate.py) — u8 lit + u16 dist, 3 wire
    bytes per output byte (dist=0 marks a literal; a back-reference's
    parent is i - dist). Returns None if the native library is
    unavailable."""
    lib = load_native()
    if lib is None:
        return None
    comp = np.ascontiguousarray(comp, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    count = len(offsets)
    lit = np.empty((count, stride), dtype=np.uint8)
    dist = np.empty((count, stride), dtype=np.uint16)
    out_lens = np.zeros(count, dtype=np.int64)
    rc = lib.sbt_tokenize_deflate(
        _ptr(comp, ctypes.c_uint8),
        _ptr(offsets, ctypes.c_int64),
        _ptr(lengths, ctypes.c_int64),
        count,
        _ptr(lit, ctypes.c_uint8),
        _ptr(dist, ctypes.c_uint16),
        stride,
        _ptr(out_lens, ctypes.c_int64),
    )
    if rc != 0:
        raise IOError(f"deflate tokenize failed at block {rc - 1}")
    return lit, dist, out_lens


def rans_decompress_native(blob: bytes, out_size: int) -> bytes | None:
    """Native rANS 4x8 decode (cram/rans.py is the fallback + encoder).
    Returns None when the library is unavailable; raises on bad input."""
    lib = load_native()
    if lib is None:
        return None
    data = np.frombuffer(blob, dtype=np.uint8)
    out = np.empty(out_size, dtype=np.uint8)
    produced = lib.sbt_rans_decompress(
        _ptr(np.ascontiguousarray(data), ctypes.c_uint8), len(data),
        _ptr(out, ctypes.c_uint8), out_size,
    )
    if produced != out_size:
        raise IOError(f"rANS decode produced {produced}, wanted {out_size}")
    return out.tobytes()


def inflate_blocks_fast_into(
    comp: np.ndarray,
    offsets: np.ndarray,
    lengths: np.ndarray,
    out: np.ndarray,
    out_offsets: np.ndarray,
    out_lengths: np.ndarray,
) -> bool:
    """Fast table-driven inflate of raw-DEFLATE payloads into ``out``.

    Word copies only engage where >=8 bytes of room remain before the end
    of ``out`` (they degrade to byte copies near it), so callers may pass
    exact-size buffers; +8 slack past the last block's end recovers full
    speed on the tail. Blocks the fast decoder rejects are re-run through
    zlib, so a True return always means exact output; returns False only
    when the native library is unavailable (caller falls back entirely).
    """
    lib = load_native()
    if lib is None:
        return False
    count = len(offsets)
    if count == 0:
        return True
    start = 0
    while start < count:
        rc = lib.sbt_inflate_blocks_fast(
            _ptr(comp, ctypes.c_uint8),
            _ptr(offsets[start:], ctypes.c_int64),
            _ptr(lengths[start:], ctypes.c_int64),
            count - start,
            _ptr(out, ctypes.c_uint8),
            _ptr(out_offsets[start:], ctypes.c_int64),
            _ptr(out_lengths[start:], ctypes.c_int64),
            len(out),
        )
        if rc == 0:
            return True
        # Block (start + rc - 1) was rejected: decode it with zlib (the
        # permanent correctness fallback) and resume after it.
        import zlib

        i = start + int(rc) - 1
        o, l = int(offsets[i]), int(lengths[i])
        data = zlib.decompress(comp[o: o + l].tobytes(), -15)
        if len(data) != int(out_lengths[i]):
            raise IOError(
                f"inflate produced {len(data)} bytes, footer says {int(out_lengths[i])}"
            )
        oo = int(out_offsets[i])
        out[oo: oo + len(data)] = np.frombuffer(data, dtype=np.uint8)
        start = i + 1
    return True


def inflate_blocks_native(
    comp: np.ndarray,
    offsets: np.ndarray,
    lengths: np.ndarray,
    out_lengths: np.ndarray,
) -> np.ndarray | None:
    """Batched raw-DEFLATE inflate; returns the flat output buffer or None."""
    lib = load_native()
    if lib is None:
        return None
    comp = np.ascontiguousarray(comp, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    out_lengths = np.ascontiguousarray(out_lengths, dtype=np.int64)
    out_offsets = np.zeros(len(out_lengths), dtype=np.int64)
    np.cumsum(out_lengths[:-1], out=out_offsets[1:])
    out = np.empty(int(out_lengths.sum()), dtype=np.uint8)
    rc = lib.sbt_inflate_blocks(
        _ptr(comp, ctypes.c_uint8),
        _ptr(offsets, ctypes.c_int64),
        _ptr(lengths, ctypes.c_int64),
        len(offsets),
        _ptr(out, ctypes.c_uint8),
        _ptr(out_offsets, ctypes.c_int64),
        _ptr(out_lengths, ctypes.c_int64),
    )
    if rc != 0:
        raise IOError(f"native inflate failed at block {rc - 1}")
    return out
