// Native runtime for spark-bam-tpu: the CPU hot loops that stay off-device.
//
// The reference's only native touchpoint is the JVM's zlib binding
// (SURVEY.md §2: bgzf Stream.scala:49-54); everything else is JVM bytecode.
// Here the host-side hot loops are real C++:
//
//   - sbt_inflate_blocks: batched raw-DEFLATE inflate of BGZF payloads
//     (zlib, thread-free: callers fan out with one call per thread)
//   - sbt_eager_check:    the sequential eager checker over a flat buffer —
//     byte-exact with check/eager.py, used for escaped-candidate re-checks
//     and split-point scans without Python-loop overhead
//   - sbt_find_record_start: byte-wise scan until a position passes
//   - sbt_tokenize_deflate: phase 1 of the two-phase device inflate
//     (u8 lit + u16 dist token rows — 3 wire bytes per output byte)
//     (SURVEY.md §7 hard-part #1): entropy-decode DEFLATE into per-output-
//     byte (literal, parent-pointer) token arrays, leaving all LZ77
//     back-reference byte motion to the device resolver (tpu/inflate.py)
//
// Build: spark_bam_tpu/native/build.py (g++ -O3 -shared; ctypes binding).

#include <cstdint>
#include <cstring>
#include <vector>
#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------- inflate
// Inflate `count` raw-deflate payloads; offsets/lengths index into `comp`,
// out_offsets into `out`. Returns 0 on success, 1-based index of the first
// failing block otherwise.
long sbt_inflate_blocks(
    const uint8_t* comp,
    const int64_t* offsets,
    const int64_t* lengths,
    int64_t count,
    uint8_t* out,
    const int64_t* out_offsets,
    const int64_t* out_lengths) {
  for (int64_t i = 0; i < count; ++i) {
    z_stream zs;
    std::memset(&zs, 0, sizeof(zs));
    if (inflateInit2(&zs, -15) != Z_OK) return i + 1;
    zs.next_in = const_cast<uint8_t*>(comp + offsets[i]);
    zs.avail_in = static_cast<uInt>(lengths[i]);
    zs.next_out = out + out_offsets[i];
    zs.avail_out = static_cast<uInt>(out_lengths[i]);
    int rc = inflate(&zs, Z_FINISH);
    int64_t produced = static_cast<int64_t>(zs.total_out);
    inflateEnd(&zs);
    if (rc != Z_STREAM_END || produced != out_lengths[i]) return i + 1;
  }
  return 0;
}

// ---------------------------------------------------------------- checker
// Exact port of the eager checker semantics (check/eager.py; reference
// eager/Checker.scala:18-177) over a flat uncompressed buffer of n bytes
// that ends at EOF. Returns 1 (boundary) / 0.
static inline int32_t rd_i32(const uint8_t* p) {
  uint32_t v = (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
               ((uint32_t)p[3] << 24);
  return (int32_t)v;
}

// Core chain walk. `touched` (when non-null) is set to 1 iff the verdict
// depended on the buffer edge `n` — the chain was cut mid-walk, so a
// caller whose buffer end is NOT the file's EOF must treat the result as
// uncertain in BOTH directions (a cut mid-record false-fails; a cut
// exactly at a record edge false-passes). Verdicts that return without
// touching `n` are exact regardless of what lies beyond the buffer.
static int eager_ok_ex(
    const uint8_t* buf, int64_t n, int64_t start,
    const int32_t* contig_lengths, int32_t num_contigs, int32_t reads_to_check,
    int* touched) {
  int64_t logical = start;   // the recursion's startPos bookkeeping
  int64_t physical = start;  // actual stream position
  for (int32_t successes = 0;; ++successes) {
    if (successes == reads_to_check) return 1;
    if (physical >= n) {
      // Zero bytes exactly at the expected record edge after >=1 success.
      if (touched) *touched = 1;
      return physical == logical && successes > 0;
    }
    if (physical + 36 > n) {
      if (touched) *touched = 1;
      return 0;
    }

    const uint8_t* p = buf + physical;
    int32_t remaining = rd_i32(p);
    int32_t ref_idx = rd_i32(p + 4);
    int32_t ref_pos = rd_i32(p + 8);
    if (ref_idx < -1 || ref_idx >= num_contigs || ref_pos < -1) return 0;
    if (ref_idx >= 0 && ref_pos > contig_lengths[ref_idx]) return 0;

    int32_t name_len = p[12];
    if (name_len == 0 || name_len == 1) return 0;

    uint32_t fnc = (uint32_t)rd_i32(p + 16);
    uint32_t flags = fnc >> 16;
    int32_t n_cigar = (int32_t)(fnc & 0xffff);
    int32_t seq_len = rd_i32(p + 20);
    if ((flags & 4) == 0 && (seq_len == 0 || n_cigar == 0)) return 0;

    // JVM int32 wrap + truncating division.
    int32_t t = seq_len + 1;
    int32_t half = t / 2;  // C++ division truncates toward zero, like the JVM
    int32_t rhs = (int32_t)(32 + name_len + 4 * n_cigar + half + seq_len);
    if (remaining < rhs) return 0;

    int32_t next_ref = rd_i32(p + 24);
    int32_t next_pos = rd_i32(p + 28);
    if (next_ref < -1 || next_ref >= num_contigs || next_pos < -1) return 0;
    if (next_ref >= 0 && next_pos > contig_lengths[next_ref]) return 0;

    int64_t name_end = physical + 36 + name_len;
    if (name_end > n) {
      if (touched) *touched = 1;
      return 0;
    }
    if (buf[name_end - 1] != 0) return 0;
    for (int64_t j = physical + 36; j < name_end - 1; ++j) {
      uint8_t b = buf[j];
      if (b < 0x21 || b > 0x7e || b == 0x40) return 0;
    }

    int64_t cig_end = name_end + 4 * (int64_t)n_cigar;
    if (cig_end > n) {
      if (touched) *touched = 1;
      return 0;
    }
    for (int64_t j = name_end; j < cig_end; j += 4)
      if ((buf[j] & 0xf) > 8) return 0;

    int64_t next_logical = logical + 4 + (int64_t)remaining;
    int64_t next_physical = cig_end > next_logical ? cig_end : next_logical;
    if (next_physical > n) next_physical = n;  // stream skip clamps at EOF
    logical = next_logical;
    physical = next_physical;
  }
}

static int eager_ok(
    const uint8_t* buf, int64_t n, int64_t start,
    const int32_t* contig_lengths, int32_t num_contigs, int32_t reads_to_check) {
  return eager_ok_ex(buf, n, start, contig_lengths, num_contigs,
                     reads_to_check, nullptr);
}

// Verdicts for `m` candidate offsets.
void sbt_eager_check(
    const uint8_t* buf, int64_t n,
    const int64_t* candidates, int64_t m,
    const int32_t* contig_lengths, int32_t num_contigs,
    int32_t reads_to_check, uint8_t* out) {
  for (int64_t i = 0; i < m; ++i)
    out[i] = (uint8_t)eager_ok(buf, n, candidates[i], contig_lengths,
                               num_contigs, reads_to_check);
}

// First boundary at/after `start`, scanning < max_read_size bytes; -1 if none.
int64_t sbt_find_record_start(
    const uint8_t* buf, int64_t n, int64_t start,
    const int32_t* contig_lengths, int32_t num_contigs,
    int32_t reads_to_check, int64_t max_read_size) {
  int64_t limit = start + max_read_size;
  for (int64_t pos = start; pos < limit && pos < n; ++pos)
    if (eager_ok(buf, n, pos, contig_lengths, num_contigs, reads_to_check))
      return pos;
  return -1;
}

// Tri-state verdicts for `m` candidates over a bounded window: out[i] is
// 0/1 when the chain resolved on in-window bytes alone (certain — exact
// regardless of what lies beyond), 2 when the verdict depended on the
// window edge (caller must retry with more lookahead). exact_eof nonzero
// = the window end IS the file end (classic semantics, never 2). The
// streaming deferral path resolves escaped candidates with this instead
// of re-running a whole-buffer flag pass per window.
void sbt_eager_check_window(
    const uint8_t* buf, int64_t n, const int64_t* candidates, int64_t m,
    const int32_t* contig_lengths, int32_t num_contigs,
    int32_t reads_to_check, int32_t exact_eof, uint8_t* out) {
  for (int64_t i = 0; i < m; ++i) {
    int touched = 0;
    int ok = eager_ok_ex(buf, n, candidates[i], contig_lengths, num_contigs,
                         reads_to_check, &touched);
    out[i] = (touched && !exact_eof) ? (uint8_t)2 : (uint8_t)ok;
  }
}

// Tri-state scan for bounded windows whose end is NOT the file's EOF
// (split-boundary resolution over a partial inflate — load/api.py).
// Returns the first position in [start, start+max_read_size) ∩ [0, n)
// whose chain passes using only in-window bytes (a *certain* pass).
// Scanning stops at the first position whose verdict depended on the
// window edge: its index goes to *uncertain_at (else -1) and -1 is
// returned — every position before it carries a certain verdict, so the
// caller can grow the window and resume exactly there. With exact_eof
// nonzero the window end IS the file end: classic semantics, never
// uncertain.
int64_t sbt_find_record_start_window(
    const uint8_t* buf, int64_t n, int64_t start,
    const int32_t* contig_lengths, int32_t num_contigs,
    int32_t reads_to_check, int64_t max_read_size,
    int32_t exact_eof, int64_t* uncertain_at) {
  *uncertain_at = -1;
  int64_t limit = start + max_read_size;
  for (int64_t pos = start; pos < limit && pos < n; ++pos) {
    int touched = 0;
    int ok = eager_ok_ex(buf, n, pos, contig_lengths, num_contigs,
                         reads_to_check, &touched);
    if (touched && !exact_eof) {
      *uncertain_at = pos;
      return -1;
    }
    if (ok) return pos;
  }
  return -1;
}

}  // extern "C"

// ------------------------------------------------------------- tokenizer
// RFC-1951 entropy decoder that emits tokens instead of bytes: for each
// uncompressed output position i it records
//   dist[i] = 0    and lit[i] = the byte, for literal/stored output
//   dist[i] = dist and lit[i] = 0,        for back-reference output
// so position i's implied parent is i - dist[i] (itself for literals) and
// its byte is the byte at its chain's root literal. DEFLATE distances fit
// 16 bits (max 32768), so the token stream is u8 lit + u16 dist = 3 bytes
// per output byte on the wire — the device reconstructs parents from an
// iota and resolves every chain in parallel with log-step pointer
// doubling (tpu/inflate.py resolve_lz77); this host phase does no byte
// copying.

namespace {

struct BitReader {
  const uint8_t* p;
  int64_t n;
  int64_t pos;     // next byte index
  uint32_t buf;    // bit buffer, LSB-first
  int cnt;         // valid bits in buf
  bool ok;
};

static inline uint32_t br_bits(BitReader& br, int need) {
  while (br.cnt < need) {
    if (br.pos >= br.n) {
      br.ok = false;
      return 0;
    }
    br.buf |= (uint32_t)br.p[br.pos++] << br.cnt;
    br.cnt += 8;
  }
  uint32_t v = br.buf & ((1u << need) - 1);
  br.buf >>= need;
  br.cnt -= need;
  return v;
}

// Canonical Huffman decoding from code lengths (RFC 1951 §3.2.2): count
// codes per length, then peel bits LSB-first comparing against the running
// first-code-of-length.
struct Huff {
  int16_t count[16];    // number of codes of each bit length
  int16_t symbol[288];  // symbols ordered by (length, symbol)
};

static bool huff_build(Huff& h, const uint8_t* lens, int n) {
  for (int i = 0; i < 16; ++i) h.count[i] = 0;
  for (int i = 0; i < n; ++i) h.count[lens[i]]++;
  // An all-zero table is legal (RFC 1951 §3.2.7: a stream with no matches
  // may declare no distance codes); huff_decode then fails only if a
  // symbol is actually requested from it.
  if (h.count[0] == n) return true;
  int left = 1;  // over-subscription check
  for (int len = 1; len < 16; ++len) {
    left <<= 1;
    left -= h.count[len];
    if (left < 0) return false;
  }
  int16_t offs[16];
  offs[1] = 0;
  for (int len = 1; len < 15; ++len) offs[len + 1] = offs[len] + h.count[len];
  for (int i = 0; i < n; ++i)
    if (lens[i]) h.symbol[offs[lens[i]]++] = (int16_t)i;
  return true;
}

static inline int huff_decode(BitReader& br, const Huff& h) {
  int code = 0, first = 0, index = 0;
  for (int len = 1; len < 16; ++len) {
    code |= (int)br_bits(br, 1);
    if (!br.ok) return -1;
    int cnt = h.count[len];
    if (code - cnt < first) return h.symbol[index + (code - first)];
    index += cnt;
    first += cnt;
    first <<= 1;
    code <<= 1;
  }
  return -1;
}

static const int16_t kLenBase[29] = {
    3,  4,  5,  6,  7,  8,  9,  10, 11,  13,  15,  17,  19,  23, 27,
    31, 35, 43, 51, 59, 67, 83, 99, 115, 131, 163, 195, 227, 258};
static const int16_t kLenExtra[29] = {0, 0, 0, 0, 0, 0, 0, 0, 1, 1,
                                      1, 1, 2, 2, 2, 2, 3, 3, 3, 3,
                                      4, 4, 4, 4, 5, 5, 5, 5, 0};
static const int16_t kDistBase[30] = {
    1,    2,    3,    4,    5,    7,     9,     13,    17,   25,
    33,   49,   65,   97,   129,  193,   257,   385,   513,  769,
    1025, 1537, 2049, 3073, 4097, 6145,  8193,  12289, 16385, 24577};
static const int16_t kDistExtra[30] = {0, 0, 0,  0,  1,  1,  2,  2,  3,  3,
                                       4, 4, 5,  5,  6,  6,  7,  7,  8,  8,
                                       9, 9, 10, 10, 11, 11, 12, 12, 13, 13};

static bool fixed_tables(Huff& lit, Huff& dist) {
  uint8_t lens[288];
  for (int i = 0; i < 144; ++i) lens[i] = 8;
  for (int i = 144; i < 256; ++i) lens[i] = 9;
  for (int i = 256; i < 280; ++i) lens[i] = 7;
  for (int i = 280; i < 288; ++i) lens[i] = 8;
  if (!huff_build(lit, lens, 288)) return false;
  for (int i = 0; i < 30; ++i) lens[i] = 5;
  return huff_build(dist, lens, 30);
}

static bool dynamic_tables(BitReader& br, Huff& lit, Huff& dist) {
  static const uint8_t kOrder[19] = {16, 17, 18, 0, 8,  7, 9,  6, 10, 5,
                                     11, 4,  12, 3, 13, 2, 14, 1, 15};
  int hlit = (int)br_bits(br, 5) + 257;
  int hdist = (int)br_bits(br, 5) + 1;
  int hclen = (int)br_bits(br, 4) + 4;
  if (!br.ok || hlit > 286 || hdist > 30) return false;
  uint8_t cl_lens[19] = {0};
  for (int i = 0; i < hclen; ++i) cl_lens[kOrder[i]] = (uint8_t)br_bits(br, 3);
  if (!br.ok) return false;
  Huff cl;
  if (!huff_build(cl, cl_lens, 19)) return false;
  uint8_t lens[288 + 30] = {0};
  int i = 0;
  while (i < hlit + hdist) {
    int sym = huff_decode(br, cl);
    if (sym < 0) return false;
    if (sym < 16) {
      lens[i++] = (uint8_t)sym;
    } else {
      int repeat, value = 0;
      if (sym == 16) {
        if (i == 0) return false;
        value = lens[i - 1];
        repeat = 3 + (int)br_bits(br, 2);
      } else if (sym == 17) {
        repeat = 3 + (int)br_bits(br, 3);
      } else {
        repeat = 11 + (int)br_bits(br, 7);
      }
      if (!br.ok || i + repeat > hlit + hdist) return false;
      while (repeat--) lens[i++] = (uint8_t)value;
    }
  }
  if (lens[256] == 0) return false;  // need an end-of-block code
  return huff_build(lit, lens, hlit) && huff_build(dist, lens + hlit, hdist);
}

// Tokenize one raw-DEFLATE stream. Returns bytes produced, or -1 on error.
static int64_t tokenize_one(const uint8_t* comp, int64_t clen, uint8_t* lit,
                            uint16_t* dist_out, int64_t cap) {
  BitReader br{comp, clen, 0, 0, 0, true};
  int64_t o = 0;
  for (;;) {
    uint32_t final_blk = br_bits(br, 1);
    uint32_t type = br_bits(br, 2);
    if (!br.ok) return -1;
    if (type == 0) {  // stored: byte-aligned len/~len then raw literals
      br.buf = 0;
      br.cnt = 0;
      if (br.pos + 4 > br.n) return -1;
      uint32_t len = (uint32_t)comp[br.pos] | ((uint32_t)comp[br.pos + 1] << 8);
      uint32_t nlen =
          (uint32_t)comp[br.pos + 2] | ((uint32_t)comp[br.pos + 3] << 8);
      if ((len ^ 0xffff) != nlen) return -1;
      br.pos += 4;
      if (br.pos + len > br.n || o + len > cap) return -1;
      for (uint32_t k = 0; k < len; ++k) {
        lit[o] = comp[br.pos + k];
        dist_out[o] = 0;
        ++o;
      }
      br.pos += len;
    } else if (type == 3) {
      return -1;
    } else {
      Huff hl, hd;
      bool built =
          type == 1 ? fixed_tables(hl, hd) : dynamic_tables(br, hl, hd);
      if (!built) return -1;
      for (;;) {
        int sym = huff_decode(br, hl);
        if (sym < 0) return -1;
        if (sym < 256) {
          if (o >= cap) return -1;
          lit[o] = (uint8_t)sym;
          dist_out[o] = 0;
          ++o;
        } else if (sym == 256) {
          break;
        } else {
          sym -= 257;
          if (sym >= 29) return -1;
          int len = kLenBase[sym] + (int)br_bits(br, kLenExtra[sym]);
          int dsym = huff_decode(br, hd);
          if (dsym < 0 || dsym >= 30) return -1;
          int dist = kDistBase[dsym] + (int)br_bits(br, kDistExtra[dsym]);
          if (!br.ok || dist > o || o + len > cap) return -1;
          for (int k = 0; k < len; ++k) {
            lit[o] = 0;
            dist_out[o] = (uint16_t)dist;
            ++o;
          }
        }
      }
    }
    if (final_blk) return o;
  }
}

}  // namespace

// ------------------------------------------------------------------ rANS
// rANS 4x8 decoder (CRAM 3.0 block method 4): 4 interleaved 32-bit
// states, byte renormalization, 12-bit frequencies; order-0 and order-1.
// Mirrors cram/rans.py (which stays as the pure-Python fallback and the
// encoder); the layout is u8 order, u32 comp size, u32 raw size, freq
// table(s), interleaved byte stream.

namespace rans {

constexpr int kTot = 4096;
constexpr uint32_t kLow = 1u << 23;

struct Rd {
  const uint8_t* p;
  int64_t n;
  int64_t pos;
  bool ok;
  inline uint8_t u8() {
    if (pos >= n) {
      ok = false;
      return 0;
    }
    return p[pos++];
  }
  inline uint32_t u32() {
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) v |= (uint32_t)u8() << (8 * i);
    return v;
  }
};

static bool read_freqs(Rd& r, uint16_t F[256]) {
  std::memset(F, 0, 256 * sizeof(uint16_t));
  int sym = r.u8();
  int rle = 0;
  while (r.ok) {
    int f = r.u8();
    if (f >= 0x80) f = ((f & 0x7F) << 8) | r.u8();
    F[sym] = (uint16_t)f;
    if (rle) {
      --rle;
      ++sym;
      // A run past symbol 255 is malformed (the Python fallback rejects
      // it too); wrapping would silently clobber low-symbol frequencies.
      if (sym > 255) return false;
    } else if (r.pos < r.n && sym + 1 == r.p[r.pos]) {
      sym = r.u8();
      rle = r.u8();
    } else {
      sym = r.u8();
      if (sym == 0) break;
    }
  }
  return r.ok;
}

struct Ctx {
  uint16_t freq[256];
  uint16_t cum[257];
  uint8_t lookup[kTot];
  // Validates the total BEFORE any lookup write: a malformed table (two-
  // byte freqs can claim up to 32767 each) must not index past lookup[].
  // Unclaimed slots stay 0, matching the Python fallback's zero-filled
  // table, so native and Python decode malformed slots identically.
  bool build() {
    cum[0] = 0;
    uint32_t total = 0;
    for (int s = 0; s < 256; ++s) {
      total += freq[s];
      if (total > (uint32_t)kTot) return false;
      cum[s + 1] = (uint16_t)total;
    }
    if (total == 0) return false;
    std::memset(lookup, 0, sizeof(lookup));
    for (int s = 0; s < 256; ++s)
      for (int k = cum[s]; k < cum[s + 1]; ++k) lookup[k] = (uint8_t)s;
    return true;
  }
};

static inline void renorm(uint32_t& st, Rd& r) {
  while (st < kLow && r.pos < r.n) st = (st << 8) | r.p[r.pos++];
}

static int64_t decode_o0(Rd& r, uint8_t* out, int64_t out_sz) {
  Ctx c;
  if (!read_freqs(r, c.freq)) return -1;
  if (!c.build()) return -1;
  uint32_t st[4];
  for (int j = 0; j < 4; ++j) st[j] = r.u32();
  if (!r.ok) return -1;
  for (int64_t i = 0; i < out_sz; ++i) {
    uint32_t& s = st[i & 3];
    uint32_t m = s & (kTot - 1);
    uint8_t sym = c.lookup[m];
    out[i] = sym;
    s = c.freq[sym] * (s >> 12) + m - c.cum[sym];
    renorm(s, r);
  }
  return out_sz;
}

static int64_t decode_o1(Rd& r, uint8_t* out, int64_t out_sz) {
  std::vector<Ctx> ctxs(256);
  std::vector<bool> present(256, false);
  int ctx = r.u8();
  int rle = 0;
  while (r.ok) {
    if (!read_freqs(r, ctxs[ctx].freq)) return -1;
    if (!ctxs[ctx].build()) return -1;
    present[ctx] = true;
    if (rle) {
      --rle;
      ++ctx;
      if (ctx > 255) return -1;  // context run past 255: malformed
    } else if (r.pos < r.n && ctx + 1 == r.p[r.pos]) {
      ctx = r.u8();
      rle = r.u8();
    } else {
      ctx = r.u8();
      if (ctx == 0) break;
    }
  }
  if (!r.ok) return -1;
  int64_t isz4 = out_sz >> 2;
  uint32_t st[4];
  for (int j = 0; j < 4; ++j) st[j] = r.u32();
  if (!r.ok) return -1;
  int last[4] = {0, 0, 0, 0};
  int64_t i4[4] = {0, isz4, 2 * isz4, 3 * isz4};
  for (int64_t i = 0; i < isz4; ++i) {
    for (int j = 0; j < 4; ++j) {
      if (!present[last[j]]) return -1;
      Ctx& c = ctxs[last[j]];
      uint32_t m = st[j] & (kTot - 1);
      uint8_t sym = c.lookup[m];
      out[i4[j] + i] = sym;
      st[j] = c.freq[sym] * (st[j] >> 12) + m - c.cum[sym];
      renorm(st[j], r);
      last[j] = sym;
    }
  }
  for (int64_t pos = 4 * isz4; pos < out_sz; ++pos) {
    if (!present[last[3]]) return -1;
    Ctx& c = ctxs[last[3]];
    uint32_t m = st[3] & (kTot - 1);
    uint8_t sym = c.lookup[m];
    out[pos] = sym;
    st[3] = c.freq[sym] * (st[3] >> 12) + m - c.cum[sym];
    renorm(st[3], r);
    last[3] = sym;
  }
  return out_sz;
}

}  // namespace rans

extern "C" {

// Decode one rANS 4x8 stream (header included). Returns bytes produced,
// or -1 on malformed input / capacity overflow.
int64_t sbt_rans_decompress(
    const uint8_t* in, int64_t in_len, uint8_t* out, int64_t out_cap) {
  rans::Rd r{in, in_len, 0, true};
  int order = r.u8();
  (void)r.u32();  // compressed size (informational)
  int64_t out_sz = (int64_t)r.u32();
  if (!r.ok || out_sz > out_cap) return -1;
  if (out_sz == 0) return 0;
  if (order == 0) return rans::decode_o0(r, out, out_sz);
  if (order == 1) return rans::decode_o1(r, out, out_sz);
  return -1;
}

// Tokenize `count` raw-DEFLATE payloads into (count, stride) lit/dist
// rows; pads each row's tail with dist=0 (identity) so the device resolver
// works on fixed shapes. Returns 0, or the 1-based index of the first
// failing block.
long sbt_tokenize_deflate(
    const uint8_t* comp,
    const int64_t* offsets,
    const int64_t* lengths,
    int64_t count,
    uint8_t* lit,
    uint16_t* dist,
    int64_t stride,
    int64_t* out_lens) {
  for (int64_t i = 0; i < count; ++i) {
    uint8_t* l = lit + i * stride;
    uint16_t* d = dist + i * stride;
    int64_t produced =
        tokenize_one(comp + offsets[i], lengths[i], l, d, stride);
    if (produced < 0) return i + 1;
    out_lens[i] = produced;
    for (int64_t k = produced; k < stride; ++k) {
      l[k] = 0;
      d[k] = 0;
    }
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------- fast inflate
// libdeflate-style raw-DEFLATE decoder specialized for BGZF blocks: 64-bit
// bit buffer refilled 8 bytes at a time, single-level 15-bit direct-indexed
// Huffman tables (15 = DEFLATE's max code length, so no subtables), and
// word-wise LZ77 copies under an 8-byte-slack contract against the whole
// output allocation. The host-inflate wall is THE end-to-end bottleneck on
// small hosts (the reference's hot loop is the JVM zlib binding,
// bgzf/.../block/Stream.scala:49-54); this decoder measures ~1.3-2x zlib
// depending on host/data (see bench history). Any block it rejects falls
// back to zlib (sbt_inflate_blocks) for identical results — it never
// guesses.

namespace fastinf {

struct FB {
  const uint8_t* p;
  const uint8_t* end;
  uint64_t buf;  // LSB-first bit buffer
  int cnt;       // valid bits in buf
};

static inline void refill(FB& b) {
  if (b.end - b.p >= 8) {
    uint64_t w;
    std::memcpy(&w, b.p, 8);  // little-endian hosts only (x86/arm64)
    b.buf |= w << b.cnt;
    int take = (63 - b.cnt) >> 3;
    b.p += take;
    b.cnt += take << 3;
  } else {
    while (b.cnt <= 56 && b.p < b.end) {
      b.buf |= (uint64_t)(*b.p++) << b.cnt;
      b.cnt += 8;
    }
  }
}

static inline uint32_t take_bits(FB& b, int n) {
  uint32_t v = (uint32_t)(b.buf & ((1ull << n) - 1));
  b.buf >>= n;
  b.cnt -= n;
  return v;
}

// Two-level decode tables (zlib/libdeflate scheme): an 11-bit primary
// table (8 KB, L1-resident; build cost ~2048 entries, not 32768) plus
// per-prefix subtables for the rare >11-bit codes.
//
// u32 entry:
//   direct : (symbol << 8) | total_code_length         (length 1..11)
//   subptr : 0x80000000 | (subtable_offset << 8) | sub_bits
//   0      : invalid
constexpr int kRootBits = 11;
constexpr uint32_t kRootSize = 1u << kRootBits;
// Root + generous subtable arena (legal complete codes need far less;
// the build errors out rather than overrun).
constexpr uint32_t kTabCap = kRootSize + 4096;

static inline uint32_t bitrev(uint32_t c, int len) {
  uint32_t r = 0;
  for (int i = 0; i < len; ++i) {
    r = (r << 1) | (c & 1);
    c >>= 1;
  }
  return r;
}

static bool build_table(uint32_t* tab, const uint8_t* lens, int n) {
  int count[16] = {0};
  for (int i = 0; i < n; ++i) count[lens[i]]++;
  count[0] = 0;  // zero-length = absent, excluded from the Kraft sum
  int left = 1;
  int maxlen = 0;
  for (int len = 1; len <= 15; ++len) {
    left <<= 1;
    left -= count[len];
    if (left < 0) return false;  // over-subscribed
    if (count[len]) maxlen = len;
  }
  // A complete code covers every root entry (short fills + long-prefix
  // subptrs); only incomplete codes (legal for degenerate distance
  // tables, RFC 1951 §3.2.7) need the invalid-fill.
  if (left != 0) std::memset(tab, 0, kRootSize * sizeof(uint32_t));
  uint32_t codes[288 + 30];
  {
    uint32_t code = 0;
    uint32_t next[16] = {0};
    for (int len = 1; len <= 15; ++len) {
      code = (code + (uint32_t)count[len - 1]) << 1;
      next[len] = code;
    }
    for (int sym = 0; sym < n; ++sym)
      if (lens[sym]) codes[sym] = next[lens[sym]]++;
  }

  // Short codes: direct root fill.
  for (int sym = 0; sym < n; ++sym) {
    int L = lens[sym];
    if (!L || L > kRootBits) continue;
    uint32_t e = ((uint32_t)sym << 8) | (uint32_t)L;
    for (uint32_t idx = bitrev(codes[sym], L); idx < kRootSize;
         idx += (1u << L))
      tab[idx] = e;
  }
  if (maxlen <= kRootBits) return true;

  // Long codes: size each used root prefix, then allocate + fill.
  uint8_t submax[kRootSize];
  std::memset(submax, 0, sizeof(submax));
  for (int sym = 0; sym < n; ++sym) {
    int L = lens[sym];
    if (L <= kRootBits) continue;
    uint32_t pfx = bitrev(codes[sym], L) & (kRootSize - 1);
    if (L - kRootBits > submax[pfx]) submax[pfx] = (uint8_t)(L - kRootBits);
  }
  uint32_t suboff[kRootSize];
  uint32_t alloc = kRootSize;
  for (uint32_t pfx = 0; pfx < kRootSize; ++pfx) {
    if (!submax[pfx]) continue;
    uint32_t size = 1u << submax[pfx];
    if (alloc + size > kTabCap) return false;
    suboff[pfx] = alloc;
    std::memset(tab + alloc, 0, size * sizeof(uint32_t));
    tab[pfx] = 0x80000000u | (alloc << 8) | submax[pfx];
    alloc += size;
  }
  for (int sym = 0; sym < n; ++sym) {
    int L = lens[sym];
    if (L <= kRootBits) continue;
    uint32_t r = bitrev(codes[sym], L);
    uint32_t pfx = r & (kRootSize - 1);
    uint32_t hi = r >> kRootBits;  // remaining L - kRootBits stream bits
    uint32_t e = ((uint32_t)sym << 8) | (uint32_t)L;
    for (uint32_t idx = hi; idx < (1u << submax[pfx]);
         idx += (1u << (L - kRootBits)))
      tab[suboff[pfx] + idx] = e;
  }
  return true;
}

// Decode one symbol's table entry from the low bits of `buf`; returns the
// final (direct) entry, 0 if invalid.
static inline uint32_t lookup(const uint32_t* tab, uint64_t buf) {
  uint32_t e = tab[(uint32_t)buf & (kRootSize - 1)];
  if (e & 0x80000000u) {
    uint32_t sb = e & 0xffu;
    e = tab[((e >> 8) & 0x3fffffu) +
            (((uint32_t)(buf >> kRootBits)) & ((1u << sb) - 1))];
  }
  return e;
}

static bool build_fixed(uint32_t* lit_tab, uint32_t* dist_tab) {
  uint8_t lens[288];
  for (int i = 0; i < 144; ++i) lens[i] = 8;
  for (int i = 144; i < 256; ++i) lens[i] = 9;
  for (int i = 256; i < 280; ++i) lens[i] = 7;
  for (int i = 280; i < 288; ++i) lens[i] = 8;
  if (!build_table(lit_tab, lens, 288)) return false;
  for (int i = 0; i < 30; ++i) lens[i] = 5;
  return build_table(dist_tab, lens, 30);
}

// Inflate one raw-DEFLATE stream. `hard_end` bounds the *whole* output
// allocation (8-byte word-copy slack may spill past this block's region
// into bytes that later blocks overwrite, never past hard_end). Returns
// bytes produced, or -1 on any error (caller falls back to zlib).
static int64_t inflate_one(const uint8_t* in, int64_t nin, uint8_t* out,
                           int64_t out_len, uint8_t* hard_end) {
  FB b{in, in + nin, 0, 0};
  uint8_t* dst = out;
  uint8_t* dst_end = out + out_len;
  thread_local static uint32_t lit_tab[kTabCap];
  thread_local static uint32_t dist_tab[kTabCap];
  thread_local static uint32_t fixed_lit[kTabCap];
  thread_local static uint32_t fixed_dist[kTabCap];
  thread_local static bool fixed_ready = false;

  for (;;) {
    refill(b);
    if (b.cnt < 3) return -1;
    uint32_t bfinal = take_bits(b, 1);
    uint32_t btype = take_bits(b, 2);
    if (btype == 3) return -1;
    if (btype == 0) {  // stored: byte-align, LEN/NLEN, raw copy
      take_bits(b, b.cnt & 7);
      const uint8_t* q = b.p - (b.cnt >> 3);
      b.buf = 0;
      b.cnt = 0;
      b.p = q;
      if (b.end - b.p < 4) return -1;
      uint32_t len = (uint32_t)b.p[0] | ((uint32_t)b.p[1] << 8);
      uint32_t nlen = (uint32_t)b.p[2] | ((uint32_t)b.p[3] << 8);
      if ((len ^ 0xffffu) != nlen) return -1;
      b.p += 4;
      if (b.end - b.p < (int64_t)len || dst + len > dst_end) return -1;
      std::memcpy(dst, b.p, len);
      dst += len;
      b.p += len;
    } else {
      const uint32_t* lt;
      const uint32_t* dt;
      if (btype == 1) {
        if (!fixed_ready) {
          if (!build_fixed(fixed_lit, fixed_dist)) return -1;
          fixed_ready = true;
        }
        lt = fixed_lit;
        dt = fixed_dist;
      } else {
        refill(b);
        if (b.cnt < 14) return -1;
        int hlit = (int)take_bits(b, 5) + 257;
        int hdist = (int)take_bits(b, 5) + 1;
        int hclen = (int)take_bits(b, 4) + 4;
        if (hlit > 286 || hdist > 30) return -1;
        static const uint8_t kOrder[19] = {16, 17, 18, 0, 8,  7, 9,  6, 10, 5,
                                           11, 4,  12, 3, 13, 2, 14, 1, 15};
        uint8_t cl_lens[19] = {0};
        for (int i = 0; i < hclen; ++i) {
          refill(b);
          if (b.cnt < 3) return -1;
          cl_lens[kOrder[i]] = (uint8_t)take_bits(b, 3);
        }
        // The code-length pre-table borrows dist_tab (rebuilt below).
        if (!build_table(dist_tab, cl_lens, 19)) return -1;
        uint8_t lens[288 + 30] = {0};
        int i = 0;
        while (i < hlit + hdist) {
          refill(b);
          uint32_t e = lookup(dist_tab, b.buf);
          int L = (int)(e & 0xff);
          if (!L || L > b.cnt) return -1;
          take_bits(b, L);
          int sym = (int)(e >> 8);
          if (sym < 16) {
            lens[i++] = (uint8_t)sym;
          } else if (sym == 16) {
            if (i == 0 || b.cnt < 2) return -1;
            int rep = 3 + (int)take_bits(b, 2);
            if (i + rep > hlit + hdist) return -1;
            uint8_t prev = lens[i - 1];
            while (rep--) lens[i++] = prev;
          } else if (sym == 17) {
            if (b.cnt < 3) return -1;
            int rep = 3 + (int)take_bits(b, 3);
            if (i + rep > hlit + hdist) return -1;
            i += rep;  // lens[] pre-zeroed
          } else {
            if (b.cnt < 7) return -1;
            int rep = 11 + (int)take_bits(b, 7);
            if (i + rep > hlit + hdist) return -1;
            i += rep;
          }
        }
        if (lens[256] == 0) return -1;  // need an end-of-block code
        if (!build_table(lit_tab, lens, hlit)) return -1;
        if (!build_table(dist_tab, lens + hlit, hdist)) return -1;
        lt = lit_tab;
        dt = dist_tab;
      }

      // One refill per iteration suffices: a full match consumes at most
      // 15 (litlen) + 5 (len extra) + 15 (dist) + 13 (dist extra) = 48
      // bits and refill leaves >= 57 mid-stream; the L > cnt checks only
      // fire at a (malformed) stream end.
      for (;;) {
        refill(b);
        uint32_t e = lookup(lt, b.buf);
        int L = (int)(e & 0xff);
        if (!L || L > b.cnt) return -1;
        b.buf >>= L;
        b.cnt -= L;
        uint32_t sym = e >> 8;
        if (sym < 256) {
          if (dst >= dst_end) return -1;
          *dst++ = (uint8_t)sym;
          // Literal run: keep decoding while the buffer holds a whole code.
          while (b.cnt >= 15) {
            e = lookup(lt, b.buf);
            L = (int)(e & 0xff);
            if (!L) return -1;
            sym = e >> 8;
            if (sym >= 256) break;
            b.buf >>= L;
            b.cnt -= L;
            if (dst >= dst_end) return -1;
            *dst++ = (uint8_t)sym;
          }
          continue;  // non-literal (bits unconsumed): outer loop re-decodes
        }
        if (sym == 256) break;
        int li = (int)sym - 257;
        if (li >= 29) return -1;
        int eb = kLenExtra[li];
        if (b.cnt < eb) return -1;
        uint32_t len = (uint32_t)kLenBase[li] + take_bits(b, eb);
        e = lookup(dt, b.buf);
        L = (int)(e & 0xff);
        if (!L || L > b.cnt) return -1;
        b.buf >>= L;
        b.cnt -= L;
        uint32_t dsym = e >> 8;
        if (dsym >= 30) return -1;
        int deb = kDistExtra[dsym];
        if (b.cnt < deb) return -1;
        uint32_t dist = (uint32_t)kDistBase[dsym] + take_bits(b, deb);
        if ((int64_t)dist > dst - out) return -1;  // BGZF: no prior history
        if (dst + len > dst_end) return -1;
        const uint8_t* src = dst - dist;
        if (dist == 1) {
          std::memset(dst, dst[-1], len);
          dst += len;
        } else if (dist >= 8 && dst + len + 8 <= hard_end) {
          uint8_t* d = dst;
          const uint8_t* s = src;
          int64_t l = (int64_t)len;
          do {
            std::memcpy(d, s, 8);
            d += 8;
            s += 8;
            l -= 8;
          } while (l > 0);
          dst += len;
        } else {
          for (uint32_t k = 0; k < len; ++k) dst[k] = src[k];
          dst += len;
        }
      }
    }
    if (bfinal) return dst - out;
  }
}

}  // namespace fastinf

extern "C" {

// Fast batched raw-DEFLATE inflate. Same contract as sbt_inflate_blocks
// plus `out_capacity`: the total bytes allocated at `out`, which must
// include >=8 bytes of slack beyond the last block's end (word-copy
// overrun room). Returns 0, or the 1-based index of the first failing
// block — the caller re-runs failures through zlib.
long sbt_inflate_blocks_fast(
    const uint8_t* comp,
    const int64_t* offsets,
    const int64_t* lengths,
    int64_t count,
    uint8_t* out,
    const int64_t* out_offsets,
    const int64_t* out_lengths,
    int64_t out_capacity) {
  uint8_t* hard_end = out + out_capacity;
  for (int64_t i = 0; i < count; ++i) {
    int64_t got = fastinf::inflate_one(
        comp + offsets[i], lengths[i], out + out_offsets[i], out_lengths[i],
        hard_end);
    if (got != out_lengths[i]) return i + 1;
  }
  return 0;
}

}  // extern "C"
