// Native runtime for spark-bam-tpu: the CPU hot loops that stay off-device.
//
// The reference's only native touchpoint is the JVM's zlib binding
// (SURVEY.md §2: bgzf Stream.scala:49-54); everything else is JVM bytecode.
// Here the host-side hot loops are real C++:
//
//   - sbt_inflate_blocks: batched raw-DEFLATE inflate of BGZF payloads
//     (zlib, thread-free: callers fan out with one call per thread)
//   - sbt_eager_check:    the sequential eager checker over a flat buffer —
//     byte-exact with check/eager.py, used for escaped-candidate re-checks
//     and split-point scans without Python-loop overhead
//   - sbt_find_record_start: byte-wise scan until a position passes
//
// Build: spark_bam_tpu/native/build.py (g++ -O3 -shared; ctypes binding).

#include <cstdint>
#include <cstring>
#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------- inflate
// Inflate `count` raw-deflate payloads; offsets/lengths index into `comp`,
// out_offsets into `out`. Returns 0 on success, 1-based index of the first
// failing block otherwise.
long sbt_inflate_blocks(
    const uint8_t* comp,
    const int64_t* offsets,
    const int64_t* lengths,
    int64_t count,
    uint8_t* out,
    const int64_t* out_offsets,
    const int64_t* out_lengths) {
  for (int64_t i = 0; i < count; ++i) {
    z_stream zs;
    std::memset(&zs, 0, sizeof(zs));
    if (inflateInit2(&zs, -15) != Z_OK) return i + 1;
    zs.next_in = const_cast<uint8_t*>(comp + offsets[i]);
    zs.avail_in = static_cast<uInt>(lengths[i]);
    zs.next_out = out + out_offsets[i];
    zs.avail_out = static_cast<uInt>(out_lengths[i]);
    int rc = inflate(&zs, Z_FINISH);
    int64_t produced = static_cast<int64_t>(zs.total_out);
    inflateEnd(&zs);
    if (rc != Z_STREAM_END || produced != out_lengths[i]) return i + 1;
  }
  return 0;
}

// ---------------------------------------------------------------- checker
// Exact port of the eager checker semantics (check/eager.py; reference
// eager/Checker.scala:18-177) over a flat uncompressed buffer of n bytes
// that ends at EOF. Returns 1 (boundary) / 0.
static inline int32_t rd_i32(const uint8_t* p) {
  uint32_t v = (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
               ((uint32_t)p[3] << 24);
  return (int32_t)v;
}

static int eager_ok(
    const uint8_t* buf, int64_t n, int64_t start,
    const int32_t* contig_lengths, int32_t num_contigs, int32_t reads_to_check) {
  int64_t logical = start;   // the recursion's startPos bookkeeping
  int64_t physical = start;  // actual stream position
  for (int32_t successes = 0;; ++successes) {
    if (successes == reads_to_check) return 1;
    if (physical >= n)
      // Zero bytes exactly at the expected record edge after >=1 success.
      return physical == logical && successes > 0;
    if (physical + 36 > n) return 0;

    const uint8_t* p = buf + physical;
    int32_t remaining = rd_i32(p);
    int32_t ref_idx = rd_i32(p + 4);
    int32_t ref_pos = rd_i32(p + 8);
    if (ref_idx < -1 || ref_idx >= num_contigs || ref_pos < -1) return 0;
    if (ref_idx >= 0 && ref_pos > contig_lengths[ref_idx]) return 0;

    int32_t name_len = p[12];
    if (name_len == 0 || name_len == 1) return 0;

    uint32_t fnc = (uint32_t)rd_i32(p + 16);
    uint32_t flags = fnc >> 16;
    int32_t n_cigar = (int32_t)(fnc & 0xffff);
    int32_t seq_len = rd_i32(p + 20);
    if ((flags & 4) == 0 && (seq_len == 0 || n_cigar == 0)) return 0;

    // JVM int32 wrap + truncating division.
    int32_t t = seq_len + 1;
    int32_t half = t / 2;  // C++ division truncates toward zero, like the JVM
    int32_t rhs = (int32_t)(32 + name_len + 4 * n_cigar + half + seq_len);
    if (remaining < rhs) return 0;

    int32_t next_ref = rd_i32(p + 24);
    int32_t next_pos = rd_i32(p + 28);
    if (next_ref < -1 || next_ref >= num_contigs || next_pos < -1) return 0;
    if (next_ref >= 0 && next_pos > contig_lengths[next_ref]) return 0;

    int64_t name_end = physical + 36 + name_len;
    if (name_end > n) return 0;
    if (buf[name_end - 1] != 0) return 0;
    for (int64_t j = physical + 36; j < name_end - 1; ++j) {
      uint8_t b = buf[j];
      if (b < 0x21 || b > 0x7e || b == 0x40) return 0;
    }

    int64_t cig_end = name_end + 4 * (int64_t)n_cigar;
    if (cig_end > n) return 0;
    for (int64_t j = name_end; j < cig_end; j += 4)
      if ((buf[j] & 0xf) > 8) return 0;

    int64_t next_logical = logical + 4 + (int64_t)remaining;
    int64_t next_physical = cig_end > next_logical ? cig_end : next_logical;
    if (next_physical > n) next_physical = n;  // stream skip clamps at EOF
    logical = next_logical;
    physical = next_physical;
  }
}

// Verdicts for `m` candidate offsets.
void sbt_eager_check(
    const uint8_t* buf, int64_t n,
    const int64_t* candidates, int64_t m,
    const int32_t* contig_lengths, int32_t num_contigs,
    int32_t reads_to_check, uint8_t* out) {
  for (int64_t i = 0; i < m; ++i)
    out[i] = (uint8_t)eager_ok(buf, n, candidates[i], contig_lengths,
                               num_contigs, reads_to_check);
}

// First boundary at/after `start`, scanning < max_read_size bytes; -1 if none.
int64_t sbt_find_record_start(
    const uint8_t* buf, int64_t n, int64_t start,
    const int32_t* contig_lengths, int32_t num_contigs,
    int32_t reads_to_check, int64_t max_read_size) {
  int64_t limit = start + max_read_size;
  for (int64_t pos = start; pos < limit && pos < n; ++pos)
    if (eager_ok(buf, n, pos, contig_lengths, num_contigs, reads_to_check))
      return pos;
  return -1;
}

}  // extern "C"
