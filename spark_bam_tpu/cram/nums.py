"""CRAM integer primitives: ITF8 / LTF8 varints and little-endian helpers.

ITF8 encodes an int32 in 1-5 bytes with a UTF8-like length prefix in the
first byte; LTF8 extends the scheme to int64 in 1-9 bytes. Negative values
occupy the maximal form (their unsigned two's-complement pattern).
"""

from __future__ import annotations

import struct

from spark_bam_tpu.core.guard import StructurallyInvalid, TruncatedInput


class Cursor:
    """A positioned view over bytes; every CRAM structure parses off one.

    Truncation raises ``TruncatedInput`` (an ``EOFError`` subclass, so
    legacy ``except EOFError`` handlers keep working); a negative read
    size — always a corrupt length field — raises ``StructurallyInvalid``.
    """

    __slots__ = ("buf", "pos")

    def __init__(self, buf, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def u8(self) -> int:
        try:
            v = self.buf[self.pos]
        except IndexError:
            raise TruncatedInput(f"truncated stream at byte {self.pos}") from None
        self.pos += 1
        return v

    def peek_u8(self) -> int:
        """Next byte without advancing; clean TruncatedInput when truncated."""
        try:
            return self.buf[self.pos]
        except IndexError:
            raise TruncatedInput(f"truncated stream at byte {self.pos}") from None

    def read(self, n: int) -> bytes:
        if n < 0:
            raise StructurallyInvalid(
                f"negative read of {n} bytes", pos=self.pos
            )
        v = bytes(self.buf[self.pos: self.pos + n])
        if len(v) != n:
            raise TruncatedInput(
                f"wanted {n} bytes at {self.pos}, got {len(v)}"
            )
        self.pos += n
        return v

    def i32(self) -> int:
        try:
            v = struct.unpack_from("<i", self.buf, self.pos)[0]
        except struct.error:
            raise TruncatedInput(f"truncated stream at byte {self.pos}") from None
        self.pos += 4
        return v

    def u32(self) -> int:
        try:
            v = struct.unpack_from("<I", self.buf, self.pos)[0]
        except struct.error:
            raise TruncatedInput(f"truncated stream at byte {self.pos}") from None
        self.pos += 4
        return v

    def itf8(self) -> int:
        b0 = self.u8()
        if b0 < 0x80:
            u = b0
        elif b0 < 0xC0:
            u = ((b0 << 8) | self.u8()) & 0x3FFF
        elif b0 < 0xE0:
            u = ((b0 << 16) | (self.u8() << 8) | self.u8()) & 0x1FFFFF
        elif b0 < 0xF0:
            u = (
                (b0 << 24) | (self.u8() << 16) | (self.u8() << 8) | self.u8()
            ) & 0x0FFFFFFF
        else:
            u = (
                ((b0 & 0x0F) << 28)
                | (self.u8() << 20)
                | (self.u8() << 12)
                | (self.u8() << 4)
                | (self.u8() & 0x0F)
            )
        return u - (1 << 32) if u >= 1 << 31 else u

    def ltf8(self) -> int:
        b0 = self.u8()
        if b0 < 0x80:
            return b0
        if b0 < 0xC0:
            u = ((b0 & 0x3F) << 8) | self.u8()
        elif b0 < 0xE0:
            u = ((b0 & 0x1F) << 16) | int.from_bytes(self.read(2), "big")
        elif b0 < 0xF0:
            u = ((b0 & 0x0F) << 24) | int.from_bytes(self.read(3), "big")
        elif b0 < 0xF8:
            u = ((b0 & 0x07) << 32) | int.from_bytes(self.read(4), "big")
        elif b0 < 0xFC:
            u = ((b0 & 0x03) << 40) | int.from_bytes(self.read(5), "big")
        elif b0 < 0xFE:
            u = ((b0 & 0x01) << 48) | int.from_bytes(self.read(6), "big")
        elif b0 < 0xFF:
            u = int.from_bytes(self.read(7), "big")
        else:
            u = int.from_bytes(self.read(8), "big")
        return u - (1 << 64) if u >= 1 << 63 else u

    def remaining(self) -> int:
        return len(self.buf) - self.pos

    def at_end(self) -> bool:
        return self.pos >= len(self.buf)


def itf8(v: int) -> bytes:
    u = v & 0xFFFFFFFF
    if u < 0x80:
        return bytes([u])
    if u < 0x4000:
        return bytes([0x80 | (u >> 8), u & 0xFF])
    if u < 0x200000:
        return bytes([0xC0 | (u >> 16), (u >> 8) & 0xFF, u & 0xFF])
    if u < 0x10000000:
        return bytes(
            [0xE0 | (u >> 24), (u >> 16) & 0xFF, (u >> 8) & 0xFF, u & 0xFF]
        )
    return bytes(
        [
            0xF0 | (u >> 28),
            (u >> 20) & 0xFF,
            (u >> 12) & 0xFF,
            (u >> 4) & 0xFF,
            u & 0x0F,
        ]
    )


def ltf8(v: int) -> bytes:
    u = v & 0xFFFFFFFFFFFFFFFF
    if u < 0x80:
        return bytes([u])
    if u < 0x4000:
        return bytes([0x80 | (u >> 8), u & 0xFF])
    if u < 0x200000:
        return bytes([0xC0 | (u >> 16)]) + (u & 0xFFFF).to_bytes(2, "big")
    if u < 0x10000000:
        return bytes([0xE0 | (u >> 24)]) + (u & 0xFFFFFF).to_bytes(3, "big")
    if u < 1 << 35:
        return bytes([0xF0 | (u >> 32)]) + (u & 0xFFFFFFFF).to_bytes(4, "big")
    if u < 1 << 42:
        return bytes([0xF8 | (u >> 40)]) + (u & ((1 << 40) - 1)).to_bytes(5, "big")
    if u < 1 << 49:
        return bytes([0xFC | (u >> 48)]) + (u & ((1 << 48) - 1)).to_bytes(6, "big")
    if u < 1 << 56:
        return b"\xfe" + u.to_bytes(7, "big")
    return b"\xff" + u.to_bytes(8, "big")


def i32le(v: int) -> bytes:
    return struct.pack("<i", v)


def u32le(v: int) -> bytes:
    return struct.pack("<I", v)
