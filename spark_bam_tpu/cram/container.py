"""CRAM 3.0 physical structure: file definition, blocks, containers.

File layout (CRAM 3.0 spec):

    file definition   "CRAM" major minor file-id[20]
    container*        header + blocks
    EOF container     fixed 38-byte sentinel

Container header: length (i32le, byte size of the blocks that follow),
ref seq id / start / span / n_records (itf8), record counter & bases
(ltf8), n_blocks (itf8), landmark array (itf8 count + offsets), crc32.

Block: method u8, content type u8, content id (itf8), compressed and raw
sizes (itf8), payload, crc32 over everything before the crc.
"""

from __future__ import annotations

import bz2
import lzma
import struct
import zlib
from dataclasses import dataclass, field

from spark_bam_tpu.core.guard import (
    LimitExceeded,
    MalformedInputError,
    StructurallyInvalid,
    TruncatedInput,
    check_count,
    current_limits,
)
from spark_bam_tpu.cram import rans
from spark_bam_tpu.cram.nums import Cursor, i32le, itf8, ltf8, u32le

MAGIC = b"CRAM"
VERSION = (3, 0)

# Block compression methods.
RAW = 0
GZIP = 1
BZIP2 = 2
LZMA = 3
RANS4x8 = 4

# Block content types.
FILE_HEADER = 0
COMPRESSION_HEADER = 1
MAPPED_SLICE = 2
EXTERNAL = 4
CORE = 5

EOF_START = 4542278  # "EOF" packed big-endian — the sentinel's start field


def file_definition(file_id: bytes = b"") -> bytes:
    fid = (file_id or b"spark-bam-tpu")[:20].ljust(20, b"\x00")
    return MAGIC + bytes(VERSION) + fid


def parse_file_definition(buf: bytes) -> tuple[int, int]:
    if buf[:4] != MAGIC:
        raise StructurallyInvalid(f"Not a CRAM: bad magic {buf[:4]!r}")
    if len(buf) < 6:
        raise TruncatedInput(
            f"CRAM file definition cut short: {len(buf)} of 6 bytes"
        )
    return buf[4], buf[5]


@dataclass
class Block:
    content_type: int
    content_id: int
    data: bytes           # uncompressed payload
    method: int = RAW     # requested/observed wire compression

    def serialize(self, method: int | None = None) -> bytes:
        method = self.method if method is None else method
        if method == GZIP:
            comp = zlib.compress(self.data, 6)
        elif method == RANS4x8:
            comp = rans.compress(self.data, order=1 if len(self.data) >= 4 else 0)
        elif method == BZIP2:
            comp = bz2.compress(self.data)
        elif method == LZMA:
            comp = lzma.compress(self.data)
        else:
            method, comp = RAW, self.data
        if len(comp) >= len(self.data):
            method, comp = RAW, self.data  # never pay to compress
        head = (
            bytes([method, self.content_type])
            + itf8(self.content_id)
            + itf8(len(comp))
            + itf8(len(self.data))
            + comp
        )
        return head + u32le(zlib.crc32(head))

    @staticmethod
    def parse(cur: Cursor) -> "Block":
        start = cur.pos
        method = cur.u8()
        content_type = cur.u8()
        content_id = cur.itf8()
        # Both size fields come from untrusted bytes: validate before they
        # size a read (comp_size) or a decompression buffer (raw_size).
        lim = current_limits()
        comp_size = check_count(
            cur.itf8(), "CRAM block comp_size", pos=start
        )
        raw_size = check_count(
            cur.itf8(), "CRAM block raw_size", lim.alloc_budget, pos=start
        )
        comp = cur.read(comp_size)
        crc = cur.u32()
        actual = zlib.crc32(bytes(cur.buf[start: cur.pos - 4]))
        if crc != actual:
            raise StructurallyInvalid(
                f"block crc mismatch: stored {crc:#x}, computed {actual:#x}",
                pos=start,
            )
        data = _decompress(method, comp, raw_size, start)
        if len(data) != raw_size:
            raise StructurallyInvalid(
                f"block inflated to {len(data)} bytes, header said {raw_size}",
                pos=start,
            )
        return Block(content_type, content_id, data, method)


def _decompress(method: int, comp: bytes, raw_size: int, start: int) -> bytes:
    """Inflate one block payload, never producing more than ``raw_size + 1``
    bytes regardless of what the compressed stream claims (a zip-bomb
    payload fails the post-inflate size check without the allocation)."""
    try:
        if method == RAW:
            return comp
        if method == GZIP:
            return zlib.decompressobj(zlib.MAX_WBITS | 32).decompress(
                comp, raw_size + 1
            )
        if method == RANS4x8:
            return rans.decompress(comp, max_out=raw_size)
        if method == BZIP2:
            return bz2.BZ2Decompressor().decompress(comp, raw_size + 1)
        if method == LZMA:
            return lzma.LZMADecompressor().decompress(comp, raw_size + 1)
    except (zlib.error, OSError, lzma.LZMAError, ValueError, IndexError, EOFError) as e:
        if isinstance(e, MalformedInputError):
            raise  # already typed (rans guards, cursor truncation)
        raise StructurallyInvalid(
            f"block decompress (method {method}) failed: {e}", pos=start
        ) from e
    raise StructurallyInvalid(
        f"unknown block compression method {method}", pos=start
    )


def gzip_maybe(data: bytes) -> int:
    """Pick GZIP for payloads long enough to plausibly win."""
    return GZIP if len(data) >= 64 else RAW


@dataclass
class ContainerHeader:
    length: int                 # byte size of the container's blocks
    ref_seq_id: int
    start: int
    span: int
    n_records: int
    record_counter: int
    bases: int
    n_blocks: int
    landmarks: list[int] = field(default_factory=list)

    def serialize(self) -> bytes:
        body = (
            i32le(self.length)
            + itf8(self.ref_seq_id)
            + itf8(self.start)
            + itf8(self.span)
            + itf8(self.n_records)
            + ltf8(self.record_counter)
            + ltf8(self.bases)
            + itf8(self.n_blocks)
            + itf8(len(self.landmarks))
            + b"".join(itf8(x) for x in self.landmarks)
        )
        return body + u32le(zlib.crc32(body))

    @staticmethod
    def parse(cur: Cursor) -> "ContainerHeader":
        start = cur.pos
        length = cur.i32()
        ref_seq_id = cur.itf8()
        align_start = cur.itf8()
        span = cur.itf8()
        n_records = cur.itf8()
        record_counter = cur.ltf8()
        bases = cur.ltf8()
        n_blocks = cur.itf8()
        # Landmarks are ≥ 1 byte each: a count past the remaining bytes is
        # provably corrupt before the loop runs (2³¹ itf8 reads otherwise).
        n_landmarks = check_count(
            cur.itf8(), "CRAM landmark count", cur.remaining(), pos=start
        )
        landmarks = [cur.itf8() for _ in range(n_landmarks)]
        crc = cur.u32()
        actual = zlib.crc32(bytes(cur.buf[start: cur.pos - 4]))
        if crc != actual:
            raise StructurallyInvalid(
                f"container crc mismatch: stored {crc:#x}, computed {actual:#x}",
                pos=start,
            )
        return ContainerHeader(
            length, ref_seq_id, align_start, span, n_records,
            record_counter, bases, n_blocks, landmarks,
        )

    @property
    def is_eof(self) -> bool:
        return self.ref_seq_id == -1 and self.start == EOF_START and self.n_records == 0


def eof_container() -> bytes:
    """The 38-byte v3 EOF sentinel: an empty compression-header container
    with the magic (-1, "EOF") coordinates."""
    block = Block(COMPRESSION_HEADER, 0, b"\x01\x00\x01\x00\x01\x00").serialize(RAW)
    header = ContainerHeader(
        length=len(block),
        ref_seq_id=-1,
        start=EOF_START,
        span=0,
        n_records=0,
        record_counter=0,
        bases=0,
        n_blocks=1,
        landmarks=[],
    )
    return header.serialize() + block


def sam_header_container(sam_text: str, pad: int = 1024) -> bytes:
    """The leading container holding the SAM header text, padded so tools
    can rewrite headers in place (the usual writer convention)."""
    payload = sam_text.encode("latin-1")
    data = struct.pack("<i", len(payload)) + payload + b"\x00" * pad
    block = Block(FILE_HEADER, 0, data).serialize(gzip_maybe(data))
    header = ContainerHeader(
        length=len(block),
        ref_seq_id=0,
        start=0,
        span=0,
        n_records=0,
        record_counter=0,
        bases=0,
        n_blocks=1,
        landmarks=[0],
    )
    return header.serialize() + block
