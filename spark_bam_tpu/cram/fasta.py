"""Minimal FASTA reading for reference-based CRAM decode.

Returns ``{sequence name: bytes}`` — whole sequences in memory, which is
the right trade for the decode path's random per-base access on test-scale
references. A ``.fai`` index, when present, is used only to size buffers.
"""

from __future__ import annotations


def read_fasta(path) -> dict[str, bytes]:
    seqs: dict[str, bytes] = {}
    name = None
    parts: list[bytes] = []
    with open(path, "rb") as f:
        for line in f:
            line = line.strip()
            if line.startswith(b">"):
                if name is not None:
                    seqs[name] = b"".join(parts)
                name = line[1:].split()[0].decode("latin-1")
                parts = []
            elif line:
                parts.append(line)
    if name is not None:
        seqs[name] = b"".join(parts)
    return seqs
