"""rANS 4x8 entropy codec (CRAM 3.0 block compression method 4).

Asymmetric-numeral-system coding with 4 interleaved 32-bit states, byte
renormalization, and 12-bit (4096-total) normalized frequencies; order-0
(context-free) and order-1 (previous-byte context) variants. The layout
follows the CRAM 3.0 specification:

    u8  order (0|1)
    u32 compressed size   (frequency table + rANS data)
    u32 uncompressed size
    frequency table, then interleaved rANS byte stream

Decode order-0: position i uses state i mod 4. Order-1: output is split in
four quarters (the last takes the remainder, continued by state 3); each
state walks its quarter with the previous byte as context.

Both directions are implemented: the writer uses encode for CRAM block
compression, and encode/decode round-trips are the codec's own test bed.
Pure Python — CRAM is a capability path, not the benchmark hot path.
"""

from __future__ import annotations

import struct

from spark_bam_tpu.core.guard import StructurallyInvalid
from spark_bam_tpu.cram.nums import Cursor

TOTFREQ = 4096
_BITS = 12
_LOW = 1 << 23  # renormalization threshold


# ------------------------------------------------------------ freq tables
def _normalize(counts: list[int], total: int = TOTFREQ) -> dict[int, int]:
    """Scale raw symbol counts to sum exactly ``total``, each survivor ≥ 1."""
    t = sum(counts)
    freqs: dict[int, int] = {}
    for s in range(256):
        if counts[s]:
            freqs[s] = max(1, counts[s] * total // t)
    excess = sum(freqs.values()) - total
    # Settle the rounding debt against the largest entries.
    for s in sorted(freqs, key=lambda k: -freqs[k]):
        if excess == 0:
            break
        adj = min(freqs[s] - 1, excess) if excess > 0 else excess
        freqs[s] -= adj
        excess -= adj
    if excess:
        raise ValueError("cannot normalize frequencies")
    return freqs


def _write_freqs(freqs: dict[int, int]) -> bytes:
    """Symbol/frequency list: ascending symbols, consecutive runs
    compressed (second member of a run is followed by the count of further
    members), 1- or 2-byte frequencies, 0-terminated."""
    out = bytearray()
    syms = sorted(freqs)
    rle = 0
    for i, s in enumerate(syms):
        if rle:
            rle -= 1
        else:
            out.append(s)
            if i > 0 and syms[i - 1] == s - 1:
                run = 0
                while i + run + 1 < len(syms) and syms[i + run + 1] == s + run + 1:
                    run += 1
                out.append(run)
                rle = run
        f = freqs[s]
        if f >= 128:
            out.append(0x80 | (f >> 8))
            out.append(f & 0xFF)
        else:
            out.append(f)
    out.append(0)
    return bytes(out)


def _read_freqs(cur: Cursor) -> list[int]:
    freqs = [0] * 256
    sym = cur.u8()
    rle = 0
    while True:
        f = cur.u8()
        if f >= 0x80:
            f = ((f & 0x7F) << 8) | cur.u8()
        freqs[sym] = f
        if rle:
            rle -= 1
            sym += 1
        elif sym + 1 == cur.peek_u8():
            sym = cur.u8()
            rle = cur.u8()
        else:
            sym = cur.u8()
            if sym == 0:
                break
    return freqs


def _tables(freqs: list[int]):
    """(cumulative starts, symbol-of-slot lookup) for one context."""
    cum = [0] * 257
    for s in range(256):
        cum[s + 1] = cum[s] + freqs[s]
    lookup = bytearray(TOTFREQ)
    for s in range(256):
        if freqs[s]:
            lookup[cum[s]: cum[s + 1]] = bytes([s]) * freqs[s]
    return cum, bytes(lookup)


# ---------------------------------------------------------------- order 0
def _enc_flush(states, out: bytearray) -> None:
    for r in (states[3], states[2], states[1], states[0]):
        out.extend(((r >> 24) & 0xFF, (r >> 16) & 0xFF, (r >> 8) & 0xFF, r & 0xFF))


def _enc_put(r: int, freq: int, start: int, out: bytearray) -> int:
    x_max = ((_LOW >> _BITS) << 8) * freq
    while r >= x_max:
        out.append(r & 0xFF)
        r >>= 8
    return ((r // freq) << _BITS) + (r % freq) + start


def _encode_o0(data: bytes) -> bytes:
    counts = [0] * 256
    for b in data:
        counts[b] += 1
    freqs = _normalize(counts)
    table = _write_freqs(freqs)
    cum = [0] * 257
    for s in range(256):
        cum[s + 1] = cum[s] + freqs.get(s, 0)
    states = [_LOW] * 4
    rev = bytearray()
    for i in range(len(data) - 1, -1, -1):
        j = i & 3
        s = data[i]
        states[j] = _enc_put(states[j], freqs[s], cum[s], rev)
    _enc_flush(states, rev)
    return table + bytes(reversed(rev))


def _decode_o0(cur: Cursor, out_sz: int) -> bytes:
    freqs = _read_freqs(cur)
    cum, lookup = _tables(freqs)
    states = [cur.u32() for _ in range(4)]
    buf = cur.buf
    p = cur.pos
    n = len(buf)
    out = bytearray(out_sz)
    for i in range(out_sz):
        j = i & 3
        r = states[j]
        m = r & (TOTFREQ - 1)
        s = lookup[m]
        out[i] = s
        r = freqs[s] * (r >> _BITS) + m - cum[s]
        while r < _LOW and p < n:
            r = (r << 8) | buf[p]
            p += 1
        states[j] = r
    cur.pos = p
    return bytes(out)


# ---------------------------------------------------------------- order 1
def _quarters(out_sz: int):
    isz4 = out_sz >> 2
    return isz4, [0, isz4, 2 * isz4, 3 * isz4]


def _encode_o1(data: bytes) -> bytes:
    out_sz = len(data)
    isz4, i4 = _quarters(out_sz)
    counts = [[0] * 256 for _ in range(256)]
    for j in range(4):
        lo = i4[j]
        hi = i4[j] + isz4 if j < 3 else out_sz
        last = 0
        for p in range(lo, hi):
            counts[last][data[p]] += 1
            last = data[p]
    freqs: dict[int, dict[int, int]] = {}
    for ctx in range(256):
        if any(counts[ctx]):
            freqs[ctx] = _normalize(counts[ctx])

    # Outer context list uses the same run compression as the symbol list.
    table = bytearray()
    ctxs = sorted(freqs)
    rle = 0
    for i, c in enumerate(ctxs):
        if rle:
            rle -= 1
        else:
            table.append(c)
            if i > 0 and ctxs[i - 1] == c - 1:
                run = 0
                while i + run + 1 < len(ctxs) and ctxs[i + run + 1] == c + run + 1:
                    run += 1
                table.append(run)
                rle = run
        table.extend(_write_freqs(freqs[c]))
    table.append(0)

    cums = {
        ctx: [0] * 257 for ctx in freqs
    }
    for ctx, f in freqs.items():
        cum = cums[ctx]
        for s in range(256):
            cum[s + 1] = cum[s] + f.get(s, 0)

    states = [_LOW] * 4
    rev = bytearray()
    # Reverse of the decode op sequence: remainder (state 3) first,
    # then the main loop back-to-front with states 3..0.
    for p in range(out_sz - 1, 4 * isz4 - 1, -1):
        # State 3 continues straight out of its quarter, so the context is
        # simply the previous byte.
        ctx = data[p - 1] if p > 0 else 0
        s = data[p]
        states[3] = _enc_put(states[3], freqs[ctx][s], cums[ctx][s], rev)
    for i in range(isz4 - 1, -1, -1):
        for j in (3, 2, 1, 0):
            p = i4[j] + i
            ctx = data[p - 1] if i > 0 else 0
            s = data[p]
            states[j] = _enc_put(states[j], freqs[ctx][s], cums[ctx][s], rev)
    _enc_flush(states, rev)
    return bytes(table) + bytes(reversed(rev))


def _decode_o1(cur: Cursor, out_sz: int) -> bytes:
    freqs = [None] * 256
    cums = [None] * 256
    lookups = [None] * 256
    ctx = cur.u8()
    rle = 0
    while True:
        f = _read_freqs(cur)
        cum, lookup = _tables(f)
        freqs[ctx] = f
        cums[ctx] = cum
        lookups[ctx] = lookup
        if rle:
            rle -= 1
            ctx += 1
        elif ctx + 1 == cur.peek_u8():
            ctx = cur.u8()
            rle = cur.u8()
        else:
            ctx = cur.u8()
            if ctx == 0:
                break
    isz4, i4 = _quarters(out_sz)
    states = [cur.u32() for _ in range(4)]
    last = [0, 0, 0, 0]
    buf = cur.buf
    p = cur.pos
    n = len(buf)
    out = bytearray(out_sz)
    for i in range(isz4):
        for j in range(4):
            r = states[j]
            m = r & (TOTFREQ - 1)
            s = lookups[last[j]][m]
            out[i4[j] + i] = s
            r = freqs[last[j]][s] * (r >> _BITS) + m - cums[last[j]][s]
            while r < _LOW and p < n:
                r = (r << 8) | buf[p]
                p += 1
            states[j] = r
            last[j] = s
    for pos in range(4 * isz4, out_sz):
        r = states[3]
        m = r & (TOTFREQ - 1)
        s = lookups[last[3]][m]
        out[pos] = s
        r = freqs[last[3]][s] * (r >> _BITS) + m - cums[last[3]][s]
        while r < _LOW and p < n:
            r = (r << 8) | buf[p]
            p += 1
        states[3] = r
        last[3] = s
    cur.pos = p
    return bytes(out)


# ------------------------------------------------------------- public API
def compress(data: bytes, order: int = 0) -> bytes:
    if len(data) == 0:
        body = b""
        order = 0
    elif order == 0 or len(data) < 4:
        order = 0
        body = _encode_o0(data)
    else:
        body = _encode_o1(data)
    return (
        bytes([order]) + struct.pack("<I", len(body)) + struct.pack("<I", len(data))
        + body
    )


def decompress(blob: bytes, max_out: int | None = None) -> bytes:
    cur = Cursor(blob)
    order = cur.u8()
    comp_sz = cur.u32()
    out_sz = cur.u32()
    del comp_sz
    if max_out is not None and out_sz > max_out:
        # The caller (cram/container.py) knows the block's declared raw
        # size; a larger embedded out_sz is corrupt — refuse before the
        # decode loop sizes itself on it.
        raise StructurallyInvalid(
            f"rANS output size {out_sz} exceeds declared block size {max_out}"
        )
    if out_sz == 0:
        return b""
    if order in (0, 1):
        from spark_bam_tpu.native.build import rans_decompress_native

        native = rans_decompress_native(bytes(blob), out_sz)
        if native is not None:
            return native
        return _decode_o0(cur, out_sz) if order == 0 else _decode_o1(cur, out_sz)
    raise StructurallyInvalid(f"unknown rANS order {order}")
