"""BAM↔CRAM record bridging: tag splitting, binning, cigar↔features.

The writer lowers ``BamRecord``s into CRAM data series without needing the
reference genome: every M/=/X cigar run is stored as an explicit-bases
``b`` feature, so the reader reconstructs sequence and cigar from the
stream alone (the htslib ``no_ref`` convention). ``=``/``X`` runs decode
back as ``M`` — the one lossy corner, inherent to reference-less features.
"""

from __future__ import annotations

import struct

from spark_bam_tpu.core.guard import StructurallyInvalid, TruncatedInput

# BAM tag value byte-lengths by type char (value excludes tag+type).
_FIXED_TAG = {"A": 1, "c": 1, "C": 1, "s": 2, "S": 2, "i": 4, "I": 4, "f": 4}
_SUB_SIZE = {"c": 1, "C": 1, "s": 2, "S": 2, "i": 4, "I": 4, "f": 4}


def split_tags(raw: bytes) -> list[tuple[bytes, int, bytes]]:
    """Split a BAM tag blob into (tag, type char, raw value bytes) triples.

    Z/H values keep their NUL terminator out of the value (re-added on
    rebuild); B values keep subtype+count+payload.

    The blob comes off disk (or a CRAM stream), so every length and
    offset is untrusted: damage raises the core/guard.py taxonomy —
    :class:`TruncatedInput` when a declared value runs past the blob,
    :class:`StructurallyInvalid` for unknown type/subtype codes or a
    negative B-array count — never a bare ``struct.error``/``ValueError``.
    """
    out = []
    p = 0
    n = len(raw)
    while p + 3 <= n:
        tag = bytes(raw[p: p + 2])
        typ = raw[p + 2]
        p += 3
        t = chr(typ)
        if t in _FIXED_TAG:
            size = _FIXED_TAG[t]
            if p + size > n:
                raise TruncatedInput(
                    f"tag {tag!r}:{t} value runs past blob end "
                    f"(need {size} bytes at {p}, have {n - p})"
                )
            out.append((tag, typ, bytes(raw[p: p + size])))
            p += size
        elif t in "ZH":
            end = raw.find(b"\x00", p)
            if end < 0:
                raise TruncatedInput(
                    f"tag {tag!r}:{t} string missing NUL terminator"
                )
            out.append((tag, typ, bytes(raw[p:end])))
            p = end + 1
        elif t == "B":
            if p + 5 > n:
                raise TruncatedInput(
                    f"tag {tag!r}:B header runs past blob end"
                )
            sub = chr(raw[p])
            if sub not in _SUB_SIZE:
                raise StructurallyInvalid(
                    f"tag {tag!r}:B has unknown subtype {sub!r}"
                )
            count = struct.unpack_from("<i", raw, p + 1)[0]
            if count < 0:
                raise StructurallyInvalid(
                    f"tag {tag!r}:B declares negative count {count}"
                )
            size = 5 + count * _SUB_SIZE[sub]
            if p + size > n:
                raise TruncatedInput(
                    f"tag {tag!r}:B[{sub}] x{count} runs past blob end "
                    f"(need {size} bytes at {p}, have {n - p})"
                )
            out.append((tag, typ, bytes(raw[p: p + size])))
            p += size
        else:
            raise StructurallyInvalid(f"unknown tag type {t!r}")
    return out


def join_tags(entries: list[tuple[bytes, int, bytes]]) -> bytes:
    out = bytearray()
    for tag, typ, value in entries:
        out += tag
        out.append(typ)
        out += value
        if chr(typ) in "ZH":
            out.append(0)
    return bytes(out)


def reg2bin(beg: int, end: int) -> int:
    """BAM bin for [beg, end) (SAM spec §4.2.1)."""
    end -= 1
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


# Cigar op codes (bam/record.py CIGAR_OPS = "MIDNSHP=X").
_OP_M, _OP_I, _OP_D, _OP_N, _OP_S, _OP_H, _OP_P, _OP_EQ, _OP_X = range(9)


def features_from_record(cigar, seq: str):
    """(feature code, 1-based read pos, payload) triples for a mapped read.

    Payloads: bases bytes for b/I/S, run length for D/N/H/P.
    """
    feats = []
    read_pos = 1
    for length, op in cigar:
        if op in (_OP_M, _OP_EQ, _OP_X):
            bases = seq[read_pos - 1: read_pos - 1 + length].encode("latin-1")
            feats.append((ord("b"), read_pos, bases))
            read_pos += length
        elif op == _OP_I:
            bases = seq[read_pos - 1: read_pos - 1 + length].encode("latin-1")
            feats.append((ord("I"), read_pos, bases))
            read_pos += length
        elif op == _OP_S:
            bases = seq[read_pos - 1: read_pos - 1 + length].encode("latin-1")
            feats.append((ord("S"), read_pos, bases))
            read_pos += length
        elif op == _OP_D:
            feats.append((ord("D"), read_pos, length))
        elif op == _OP_N:
            feats.append((ord("N"), read_pos, length))
        elif op == _OP_H:
            feats.append((ord("H"), read_pos, length))
        elif op == _OP_P:
            feats.append((ord("P"), read_pos, length))
        else:
            raise ValueError(f"cigar op {op} out of range")
    return feats


def subst_tables(sm: bytes):
    """Decode the 5-byte substitution matrix: table[ref base][code] → base.

    For each reference base (A,C,G,T,N order) the byte assigns a 2-bit code
    to each of the other four bases, in base order.
    """
    bases = "ACGTN"
    table: dict[str, list[str]] = {}
    for i, ref in enumerate(bases):
        alts = [b for b in bases if b != ref]
        by_code = [""] * 4
        byte = sm[i]
        for k, alt in enumerate(alts):
            code = (byte >> (6 - 2 * k)) & 0x3
            by_code[code] = alt
        table[ref] = by_code
    return table
