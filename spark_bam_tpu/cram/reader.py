"""CRAM 3.0 reader: containers → slices → ``BamRecord``s.

Handles the spec surface real writers use: single-ref / multiref /
unmapped slices, AP-delta coordinates, detached and downstream-mate
records, reference-less (``RR=false``) and reference-based feature decode
(pass ``reference=`` a FASTA path or ``{name: bytes}``), per-series codecs
from the compression header (EXTERNAL / HUFFMAN / BETA / GAMMA /
BYTE_ARRAY_*), and block compression raw/gzip/bzip2/lzma/rANS.

Container headers are self-delimiting, so ``container_infos`` doubles as
the split planner for ``load_cram`` — the CRAM analog of the BGZF
``.blocks`` table (SURVEY.md §2.8).
"""

from __future__ import annotations

import mmap
import struct
from dataclasses import dataclass

from spark_bam_tpu.bam.header import BamHeader, ContigLengths
from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.cram.bam_bridge import join_tags, reg2bin, subst_tables
from spark_bam_tpu.cram.codecs import BitReader, Decoders
from spark_bam_tpu.cram.container import (
    COMPRESSION_HEADER,
    CORE,
    EXTERNAL,
    FILE_HEADER,
    MAPPED_SLICE,
    Block,
    ContainerHeader,
    parse_file_definition,
)
from spark_bam_tpu.cram.nums import Cursor
from spark_bam_tpu.cram.structure import CompressionHeader, SliceHeader
from spark_bam_tpu.cram.writer import CF_DETACHED, CF_NO_SEQ, CF_QS_PRESERVED
from spark_bam_tpu.core.guard import (
    MalformedInputError,
    StructurallyInvalid,
    check_count,
    current_limits,
)
from spark_bam_tpu.core.pos import Pos

CF_MATE_DOWNSTREAM = 4

_M, _I, _D, _N, _S, _H, _P = 0, 1, 2, 3, 4, 5, 6


def contigs_from_sam_text(text: str) -> ContigLengths:
    entries = {}
    for line in text.splitlines():
        if line.startswith("@SQ"):
            fields = dict(
                kv.split(":", 1) for kv in line.split("\t")[1:] if ":" in kv
            )
            if "SN" in fields:
                entries[len(entries)] = (fields["SN"], int(fields.get("LN", 0)))
    return ContigLengths(entries)


@dataclass
class ContainerInfo:
    offset: int          # file offset of the container header
    end: int             # file offset one past the last block byte
    n_records: int
    record_counter: int


def load_cram_header(path) -> BamHeader:
    with CramReader(path) as r:
        return r.bam_header


class CramReader:
    def __init__(self, path, reference=None):
        self.path = path
        self._f = open(path, "rb")
        try:
            self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
            buf: bytes | mmap.mmap = self._mm
        except ValueError:  # empty file
            self._mm = None
            buf = b""
        self.buf = buf
        parse_file_definition(bytes(buf[:6]))
        cur = Cursor(buf, 26)
        header = ContainerHeader.parse(cur)
        blocks_start = cur.pos
        block = Block.parse(cur)
        if block.content_type != FILE_HEADER:
            raise StructurallyInvalid(
                "first CRAM container does not hold the SAM header",
                path=path,
            )
        text_cur = Cursor(block.data)
        text_len = text_cur.i32()
        self.sam_text = text_cur.read(text_len).decode("latin-1")
        self.contigs = contigs_from_sam_text(self.sam_text)
        self.first_data_offset = blocks_start + header.length
        if isinstance(reference, (str, bytes)) or hasattr(reference, "__fspath__"):
            from spark_bam_tpu.cram.fasta import read_fasta

            reference = read_fasta(reference)
        self.reference = reference

    @property
    def bam_header(self) -> BamHeader:
        return BamHeader(self.contigs, Pos(0, 0), 0, self.sam_text)

    # ------------------------------------------------------------- layout
    def container_infos(self) -> list[ContainerInfo]:
        """Header-only walk of the data containers (the split table)."""
        infos = []
        cur = Cursor(self.buf, self.first_data_offset)
        while cur.remaining() > 0:
            offset = cur.pos
            header = ContainerHeader.parse(cur)
            if header.is_eof:
                break
            end = cur.pos + header.length
            infos.append(
                ContainerInfo(offset, end, header.n_records, header.record_counter)
            )
            cur.pos = end
        return infos

    # ------------------------------------------------------------- decode
    def records(self, offset: int | None = None, end: int | None = None):
        """Iterate records of containers whose header starts in
        [offset, end) — defaults to the whole file."""
        cur = Cursor(self.buf, self.first_data_offset if offset is None else offset)
        while cur.remaining() > 0 and (end is None or cur.pos < end):
            container_start = cur.pos
            header = ContainerHeader.parse(cur)
            if header.is_eof:
                break
            region_end = cur.pos + header.length
            # Decode boundary: whatever a corrupt container throws deep in
            # the codec machinery (bad tag keys, inconsistent series
            # lengths, malformed UTF/latin frames) surfaces as one typed
            # error carrying the container offset.
            try:
                out = list(self._decode_container(cur, header, region_end))
            except MalformedInputError:
                raise
            except (
                ValueError,
                KeyError,
                IndexError,
                NotImplementedError,
                OverflowError,
                UnicodeDecodeError,
                struct.error,
            ) as e:
                raise StructurallyInvalid(
                    f"CRAM container decode failed: {e!r}",
                    path=self.path,
                    pos=container_start,
                ) from e
            yield from out
            cur.pos = region_end

    def __iter__(self):
        return self.records()

    def _decode_container(self, cur: Cursor, header: ContainerHeader, region_end: int):
        first = Block.parse(cur)
        if first.content_type != COMPRESSION_HEADER:
            raise StructurallyInvalid(
                "container does not start with a compression header"
            )
        ch = CompressionHeader.parse(first.data)
        counter = header.record_counter
        while cur.pos < region_end:
            sh_block = Block.parse(cur)
            if sh_block.content_type != MAPPED_SLICE:
                raise StructurallyInvalid(
                    f"expected slice header block, got type {sh_block.content_type}"
                )
            sh = SliceHeader.parse(sh_block.data)
            # A slice cannot hold more records than its container declares;
            # the slice count sizes per-record work below, so fence it here.
            check_count(sh.n_records, "CRAM slice records", header.n_records)
            blocks = [Block.parse(cur) for _ in range(sh.n_blocks)]
            yield from self._decode_slice(ch, sh, blocks, counter)
            counter += sh.n_records

    def _decode_slice(
        self, ch: CompressionHeader, sh: SliceHeader, blocks: list[Block], counter: int
    ):
        core = next((b for b in blocks if b.content_type == CORE), None)
        ext = {
            b.content_id: Cursor(b.data)
            for b in blocks
            if b.content_type == EXTERNAL
        }
        embedded_ref = None
        ref_origin = 0  # 0-based reference position of ref byte 0
        if sh.embedded_ref_id >= 0 and sh.embedded_ref_id in ext:
            # The embedded block holds only the slice's span: its first
            # byte is the base at the slice's 1-based alignment start.
            embedded_ref = ext[sh.embedded_ref_id].buf
            ref_origin = max(sh.start - 1, 0)
        dec = Decoders(BitReader(core.data if core else b""), ext)
        ds = ch.data_series

        def int_r(key: str, default: int | None = None):
            if key in ds:
                return dec.int_reader(ds[key])
            if default is None:
                def missing():
                    raise ValueError(f"data series {key} not encoded")
                return missing
            return lambda: default

        def byte_r(key: str):
            if key in ds:
                return dec.byte_reader(ds[key])
            def missing():
                raise ValueError(f"data series {key} not encoded")
            return missing

        def array_r(key: str):
            if key in ds:
                return dec.array_reader(ds[key])
            return lambda: b""

        def bulk_r(key: str):
            if key in ds:
                return dec.bulk_reader(ds[key])
            return lambda n: b"\xff" * n

        r_bf, r_cf = int_r("BF"), int_r("CF")
        r_ri = int_r("RI", -1)
        r_rl, r_ap = int_r("RL"), int_r("AP")
        r_rg = int_r("RG", -1)
        r_rn = array_r("RN")
        r_mf, r_ns = int_r("MF", 0), int_r("NS", -1)
        r_np, r_ts = int_r("NP", 0), int_r("TS", 0)
        r_nf = int_r("NF", 0)
        r_tl = int_r("TL", 0)
        r_fn, r_fp = int_r("FN", 0), int_r("FP", 0)
        r_fc = byte_r("FC")
        r_dl, r_rs = int_r("DL", 0), int_r("RS", 0)
        r_hc, r_pd = int_r("HC", 0), int_r("PD", 0)
        r_mq = int_r("MQ", 0)
        r_bb, r_in, r_sc, r_qq = (
            array_r("BB"), array_r("IN"), array_r("SC"), array_r("QQ"),
        )
        r_bs = byte_r("BS") if "BS" in ds else lambda: 0
        r_ba_bulk, r_qs_bulk = bulk_r("BA"), bulk_r("QS")
        r_ba = byte_r("BA") if "BA" in ds else lambda: ord("N")
        r_qs = byte_r("QS") if "QS" in ds else lambda: 0xFF
        tag_readers = {key: dec.array_reader(enc) for key, enc in ch.tags.items()}
        sub = subst_tables(ch.subst_matrix)

        out: list[BamRecord] = []
        links: list[int | None] = []
        last_ap = sh.start
        max_seq = current_limits().max_seq_len
        for i in range(sh.n_records):
            bf = r_bf()
            cf = r_cf()
            ri = r_ri() if sh.ref_seq_id == -2 else sh.ref_seq_id
            # RL sizes the seq/qual buffers and bulk reads below.
            rl = check_count(r_rl(), "CRAM read length", max_seq)
            if ch.ap_delta:
                last_ap += r_ap()
                ap = last_ap
            else:
                ap = r_ap()
            r_rg()
            name = ""
            if ch.read_names_included:
                name = r_rn().decode("latin-1")
            nf = None
            mate_ref, mate_pos, ts = -1, -1, 0
            if cf & CF_DETACHED:
                mf = r_mf()
                if not ch.read_names_included:
                    name = r_rn().decode("latin-1")
                mate_ref = r_ns()
                mate_pos = r_np() - 1
                ts = r_ts()
                if mf & 1:
                    bf |= 0x20
                if mf & 2:
                    bf |= 0x8
            elif cf & CF_MATE_DOWNSTREAM:
                nf = r_nf()
            if not name:
                name = f"q{counter + i}"
            tl = r_tl()
            line = ch.tag_dict[tl] if tl < len(ch.tag_dict) else []
            entries = []
            for tag, typ in line:
                key = (tag[0] << 16) | (tag[1] << 8) | typ
                entries.append((tag, typ, tag_readers[key]()))
            tags = join_tags(entries)

            pos = ap - 1
            if not (bf & 4):
                rec = self._decode_mapped(
                    bf, cf, ri, rl, pos, sub, embedded_ref, ref_origin,
                    ch.reference_required,
                    r_fn, r_fc, r_fp, r_bb, r_in, r_sc, r_qq, r_bs,
                    r_dl, r_rs, r_hc, r_pd, r_mq, r_ba, r_qs, r_qs_bulk,
                )
            else:
                if cf & CF_NO_SEQ:
                    seq, qual = "", b""
                else:
                    seq = r_ba_bulk(rl).decode("latin-1")
                    qual = r_qs_bulk(rl) if cf & CF_QS_PRESERVED else b"\xff" * rl
                # MQ is a mapped-only data series in CRAM: an unmapped
                # read's nonzero MAPQ is not representable and decodes as
                # 0 (htsjdk behaves identically).
                rec = BamRecord(
                    ri, pos, 0, reg2bin(pos, pos + 1) if pos >= 0 else 0,
                    bf, -1, -1, 0, "", [], seq, qual, b"",
                )
            if cf & CF_NO_SEQ:
                rec.seq, rec.qual = "", b""
            rec.read_name = name
            rec.tags = tags
            if cf & CF_DETACHED:
                rec.next_ref_id, rec.next_pos, rec.tlen = mate_ref, mate_pos, ts
            out.append(rec)
            links.append(nf)

        self._resolve_mates(out, links, ch.read_names_included)
        return out

    def _decode_mapped(
        self, bf, cf, ri, rl, pos, sub, embedded_ref, ref_origin,
        reference_required,
        r_fn, r_fc, r_fp, r_bb, r_in, r_sc, r_qq, r_bs,
        r_dl, r_rs, r_hc, r_pd, r_mq, r_ba, r_qs, r_qs_bulk,
    ) -> BamRecord:
        ref_seq = embedded_ref
        if ref_seq is None:
            ref_origin = 0
            if self.reference is not None and ri >= 0:
                ref_seq = self.reference.get(self.contigs.name(ri))

        fn = r_fn()
        feats = []
        fpos = 0
        for _ in range(fn):
            fc = r_fc()
            fpos += r_fp()
            c = chr(fc)
            if c == "b":
                payload = r_bb()
            elif c == "B":
                payload = (r_ba(), r_qs())
            elif c == "X":
                payload = r_bs()
            elif c == "I":
                payload = r_in()
            elif c == "i":
                payload = bytes([r_ba()])
            elif c == "S":
                payload = r_sc()
            elif c == "q":
                payload = r_qq()
            elif c == "Q":
                payload = r_qs()
            elif c == "D":
                payload = r_dl()
            elif c == "N":
                payload = r_rs()
            elif c == "H":
                payload = r_hc()
            elif c == "P":
                payload = r_pd()
            else:
                raise ValueError(f"unknown feature code {c!r}")
            feats.append((c, fpos, payload))
        mq = r_mq()
        qual = bytearray(
            r_qs_bulk(rl) if cf & CF_QS_PRESERVED else b"\xff" * rl
        )

        seq = bytearray(rl)
        cigar: list[tuple[int, int]] = []
        read_cur = 1   # 1-based read cursor
        ref_off = 0    # reference bases consumed

        def ref_base(k: int) -> int:
            if ref_seq is None:
                if reference_required:
                    raise ValueError(
                        "this CRAM was written reference-based (RR=true): "
                        "pass reference= (FASTA path or {name: bytes}) to "
                        "CramReader/load_cram to decode sequences"
                    )
                return ord("N")  # RR=false: bases are genuinely unknown
            idx = pos + k - ref_origin
            if 0 <= idx < len(ref_seq):
                return ref_seq[idx] & ~0x20  # uppercase
            return ord("N")

        def emit(op: int, length: int) -> None:
            if length <= 0:
                return
            if cigar and cigar[-1][1] == op:
                cigar[-1] = (cigar[-1][0] + length, op)
            else:
                cigar.append((length, op))

        def match_gap(length: int) -> None:
            nonlocal read_cur, ref_off
            for k in range(length):
                seq[read_cur - 1 + k] = ref_base(ref_off + k)
            emit(_M, length)
            read_cur += length
            ref_off += length

        for c, fpos, payload in feats:
            if fpos > read_cur and c not in ("Q", "q"):
                match_gap(fpos - read_cur)
            if c == "b":
                n = len(payload)
                seq[read_cur - 1: read_cur - 1 + n] = payload
                emit(_M, n)
                read_cur += n
                ref_off += n
            elif c == "B":
                base, q = payload
                seq[read_cur - 1] = base
                qual[read_cur - 1] = q
                emit(_M, 1)
                read_cur += 1
                ref_off += 1
            elif c == "X":
                rb = chr(ref_base(ref_off))
                alt = sub.get(rb.upper(), sub["N"])[payload & 0x3]
                seq[read_cur - 1] = ord(alt)
                emit(_M, 1)
                read_cur += 1
                ref_off += 1
            elif c in ("I", "i"):
                n = len(payload)
                seq[read_cur - 1: read_cur - 1 + n] = payload
                emit(_I, n)
                read_cur += n
            elif c == "S":
                n = len(payload)
                seq[read_cur - 1: read_cur - 1 + n] = payload
                emit(_S, n)
                read_cur += n
            elif c == "D":
                emit(_D, payload)
                ref_off += payload
            elif c == "N":
                emit(_N, payload)
                ref_off += payload
            elif c == "H":
                emit(_H, payload)
            elif c == "P":
                emit(_P, payload)
            elif c == "Q":
                qual[fpos - 1] = payload
            elif c == "q":
                qual[fpos - 1: fpos - 1 + len(payload)] = payload
        if read_cur <= rl:
            match_gap(rl - read_cur + 1)

        span = sum(n for n, op in cigar if op in (_M, _D, _N))
        end = pos + (span if span else 1)
        return BamRecord(
            ri, pos, mq, reg2bin(pos, end) if pos >= 0 else 0, bf,
            -1, -1, 0, "", cigar, seq.decode("latin-1"), bytes(qual), b"",
        )

    @staticmethod
    def _resolve_mates(
        out: list[BamRecord],
        links: list[int | None],
        names_included: bool,
    ) -> None:
        for i, nf in enumerate(links):
            if nf is None:
                continue
            j = i + nf + 1
            if j >= len(out):
                continue
            a, b = out[i], out[j]
            if not names_included:
                # Synthesized QNAMEs: NF-linked mates are one template and
                # must share one name (htsjdk generates one name per pair).
                b.read_name = a.read_name
            a.next_ref_id, a.next_pos = b.ref_id, b.pos
            b.next_ref_id, b.next_pos = a.ref_id, a.pos
            if b.flag & 0x10:
                a.flag |= 0x20
            if b.flag & 0x4:
                a.flag |= 0x8
            if a.flag & 0x10:
                b.flag |= 0x20
            if a.flag & 0x4:
                b.flag |= 0x8
            if a.ref_id == b.ref_id and a.ref_id >= 0:
                left = min(a.pos, b.pos)
                right = max(a.end_pos(), b.end_pos())
                span = right - left
                a.tlen = span if a.pos <= b.pos else -span
                b.tlen = -a.tlen

    # ------------------------------------------------------------ plumbing
    def close(self) -> None:
        if self._mm is not None:
            self._mm.close()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
