"""CRAM 3.0 writer: lower ``BamRecord``s into containers.

Reference-less encoding (the htslib ``no_ref`` convention, legal per spec
with preservation ``RR=false``): M/=/X cigar runs become explicit-bases
``b`` features, so readers reconstruct sequence + cigar with no FASTA in
hand. One slice per container; every data series goes to its own external
block (ITF8 ints / raw bytes / length-prefixed arrays), with the core
bit-stream left empty. Mates are always written detached (MF/NS/NP/TS
explicit), read names preserved.

Purpose-built for round-tripping the framework's own record model and for
generating CRAM fixtures; the reader handles the wider spec surface.
"""

from __future__ import annotations

from collections import defaultdict

from spark_bam_tpu.bam.record import BamRecord
from spark_bam_tpu.cram import codecs
from spark_bam_tpu.cram.bam_bridge import features_from_record, split_tags
from spark_bam_tpu.cram.container import (
    COMPRESSION_HEADER,
    CORE,
    EXTERNAL,
    GZIP,
    MAPPED_SLICE,
    RANS4x8,
    RAW,
    Block,
    ContainerHeader,
    eof_container,
    file_definition,
    sam_header_container,
)
from spark_bam_tpu.cram.nums import itf8
from spark_bam_tpu.cram.structure import CompressionHeader, SliceHeader

# Stable external-block content ids, one per data series.
SERIES_IDS = {
    "BF": 1, "CF": 2, "RI": 3, "RL": 4, "AP": 5, "RG": 6, "RN": 7, "MF": 8,
    "NS": 9, "NP": 10, "TS": 11, "NF": 12, "TL": 13, "FN": 14, "FC": 15,
    "FP": 16, "DL": 17, "BB": 18, "QQ": 19, "BS": 20, "IN": 21, "RS": 22,
    "PD": 23, "HC": 24, "SC": 25, "MQ": 26, "BA": 27, "QS": 28,
}

_METHODS = {"gzip": GZIP, "rans": RANS4x8, "raw": RAW}

# CF (CRAM record flag) bits.
CF_QS_PRESERVED = 1
CF_DETACHED = 2
CF_NO_SEQ = 8

_READ_CONSUMING = {0, 1, 4, 7, 8}  # M, I, S, =, X


def synthesize_sam_text(contigs) -> str:
    lines = ["@HD\tVN:1.6\tSO:unsorted"]
    for idx in range(len(contigs)):
        name, length = contigs[idx]
        lines.append(f"@SQ\tSN:{name}\tLN:{length}")
    return "\n".join(lines) + "\n"


class _ContainerBuilder:
    def __init__(self):
        self.streams: dict[str, bytearray] = defaultdict(bytearray)
        self.tag_streams: dict[int, bytearray] = defaultdict(bytearray)
        self.td_lines: list[tuple] = []
        self.td_index: dict[tuple, int] = {}
        self.n_records = 0
        self.bases = 0
        # ref id → [min 0-based start, max 0-based end) over mapped records;
        # -1 present iff the slice holds unmapped reads. Feeds the .crai.
        self.ref_spans: dict[int, list[int]] = {}

    def put_int(self, series: str, v: int) -> None:
        self.streams[series] += itf8(v)

    def put_byte(self, series: str, v: int) -> None:
        self.streams[series].append(v)

    def put_bytes(self, series: str, v: bytes) -> None:
        self.streams[series] += v

    def put_array(self, series: str, v: bytes) -> None:
        self.streams[series] += itf8(len(v)) + v

    def add(self, rec: BamRecord) -> None:
        flag = rec.flag
        seq = rec.seq
        rl = len(seq)
        cf = CF_QS_PRESERVED | CF_DETACHED
        if rl == 0:
            cf |= CF_NO_SEQ
            if not rec.is_unmapped:
                # Sequence '*' with a real cigar: read length comes from the
                # cigar; bases are written as N placeholders and discarded
                # again on decode (CF_NO_SEQ).
                rl = sum(ln for ln, op in rec.cigar if op in _READ_CONSUMING)
                seq = "N" * rl
        if rec.is_unmapped or rec.ref_id < 0:
            self.ref_spans.setdefault(-1, [0, 0])
        else:
            span = self.ref_spans.setdefault(rec.ref_id, [rec.pos, rec.end_pos()])
            span[0] = min(span[0], rec.pos)
            span[1] = max(span[1], rec.end_pos())
        self.put_int("BF", flag)
        self.put_int("CF", cf)
        self.put_int("RI", rec.ref_id)
        self.put_int("RL", rl)
        self.put_int("AP", rec.pos + 1)
        self.put_int("RG", -1)
        self.put_bytes("RN", rec.read_name.encode("latin-1") + b"\x00")
        mf = (1 if flag & 0x20 else 0) | (2 if flag & 0x8 else 0)
        self.put_int("MF", mf)
        self.put_int("NS", rec.next_ref_id)
        self.put_int("NP", rec.next_pos + 1)
        self.put_int("TS", rec.tlen)

        entries = split_tags(rec.tags)
        line = tuple((tag, typ) for tag, typ, _ in entries)
        tl = self.td_index.setdefault(line, len(self.td_lines))
        if tl == len(self.td_lines):
            self.td_lines.append(line)
        self.put_int("TL", tl)
        for tag, typ, value in entries:
            key = (tag[0] << 16) | (tag[1] << 8) | typ
            self.tag_streams[key] += itf8(len(value)) + value

        qual = rec.qual if len(rec.qual) == rl else b"\xff" * rl
        if not rec.is_unmapped:
            feats = features_from_record(rec.cigar, seq)
            self.put_int("FN", len(feats))
            prev = 0
            for code, fpos, payload in feats:
                self.put_byte("FC", code)
                self.put_int("FP", fpos - prev)
                prev = fpos
                if code == ord("b"):
                    self.put_array("BB", payload)
                elif code == ord("I"):
                    self.put_array("IN", payload)
                elif code == ord("S"):
                    self.put_array("SC", payload)
                elif code == ord("D"):
                    self.put_int("DL", payload)
                elif code == ord("N"):
                    self.put_int("RS", payload)
                elif code == ord("H"):
                    self.put_int("HC", payload)
                elif code == ord("P"):
                    self.put_int("PD", payload)
            self.put_int("MQ", rec.mapq)
            self.put_bytes("QS", qual)
        else:
            if not (cf & CF_NO_SEQ):
                self.put_bytes("BA", seq.encode("latin-1"))
                self.put_bytes("QS", qual)
        self.n_records += 1
        self.bases += rl

    # ------------------------------------------------------------ assembly
    def compression_header(self) -> CompressionHeader:
        enc = {}
        for series, cid in SERIES_IDS.items():
            if series == "RN":
                enc[series] = codecs.byte_array_stop(0, cid)
            elif series in ("BB", "QQ", "IN", "SC"):
                enc[series] = codecs.byte_array_len(
                    codecs.external(cid), codecs.external(cid)
                )
            else:
                enc[series] = codecs.external(cid)
        tag_enc = {
            key: codecs.byte_array_len(codecs.external(key), codecs.external(key))
            for key in self.tag_streams
        }
        td = [
            [(tag, typ) for tag, typ in line] for line in (self.td_lines or [()])
        ]
        return CompressionHeader(
            read_names_included=True,
            ap_delta=False,
            reference_required=False,
            tag_dict=td,
            data_series=enc,
            tags=tag_enc,
        )

    def serialize(
        self, record_counter: int, method: int
    ) -> tuple[bytes, int, int]:
        """Returns (container bytes, slice offset, slice size) — the offsets
        feed the .crai index entries."""
        ch_block = Block(
            COMPRESSION_HEADER, 0, self.compression_header().serialize()
        ).serialize(GZIP if method != RAW else RAW)

        ext_blocks = []
        for series, cid in SERIES_IDS.items():
            data = bytes(self.streams[series])
            if data:
                ext_blocks.append(Block(EXTERNAL, cid, data).serialize(method))
        for key, data in sorted(self.tag_streams.items()):
            ext_blocks.append(Block(EXTERNAL, key, bytes(data)).serialize(method))
        core_block = Block(CORE, 0, b"").serialize(RAW)

        content_ids = [SERIES_IDS[s] for s in SERIES_IDS if self.streams[s]]
        content_ids += sorted(self.tag_streams)
        slice_hdr = SliceHeader(
            ref_seq_id=-2,  # multiref: RI decoded per record
            start=0,
            span=0,
            n_records=self.n_records,
            record_counter=record_counter,
            n_blocks=1 + len(ext_blocks),
            content_ids=content_ids,
        )
        slice_hdr_block = Block(
            MAPPED_SLICE, 0, slice_hdr.serialize()
        ).serialize(RAW)

        blocks = (
            ch_block + slice_hdr_block + core_block + b"".join(ext_blocks)
        )
        header = ContainerHeader(
            length=len(blocks),
            ref_seq_id=-2,
            start=0,
            span=0,
            n_records=self.n_records,
            record_counter=record_counter,
            bases=self.bases,
            n_blocks=3 + len(ext_blocks),
            landmarks=[len(ch_block)],
        )
        slice_offset = len(ch_block)
        slice_size = len(blocks) - slice_offset
        return header.serialize() + blocks, slice_offset, slice_size


class CramWriter:
    def __init__(
        self,
        path,
        contigs,
        sam_text: str = "",
        records_per_container: int = 4096,
        method: str = "gzip",
        index: bool = True,
    ):
        self.path = path
        self.f = open(path, "wb")
        self.method = _METHODS[method]
        self.records_per_container = records_per_container
        self.index = index
        self.crai_entries: list = []
        self.counter = 0
        self.builder = _ContainerBuilder()
        text = sam_text or synthesize_sam_text(contigs)
        self.f.write(file_definition())
        self.f.write(sam_header_container(text))

    def write(self, rec: BamRecord) -> None:
        self.builder.add(rec)
        if self.builder.n_records >= self.records_per_container:
            self._flush()

    def write_all(self, records) -> None:
        for rec in records:
            self.write(rec)

    def _flush(self) -> None:
        if self.builder.n_records:
            from spark_bam_tpu.cram.crai import CraiEntry

            start_counter = self.counter
            self.counter += self.builder.n_records
            container_offset = self.f.tell()
            data, slice_offset, slice_size = self.builder.serialize(
                start_counter, self.method
            )
            self.f.write(data)
            for ref in sorted(self.builder.ref_spans):
                lo, hi = self.builder.ref_spans[ref]
                self.crai_entries.append(
                    CraiEntry(
                        ref,
                        lo + 1 if ref >= 0 else 0,
                        hi - lo if ref >= 0 else 0,
                        container_offset,
                        slice_offset,
                        slice_size,
                    )
                )
            self.builder = _ContainerBuilder()

    def close(self) -> None:
        self._flush()
        self.f.write(eof_container())
        self.f.close()
        if self.index:
            from spark_bam_tpu.cram.crai import write_crai

            write_crai(str(self.path) + ".crai", self.crai_entries)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
