"""CRAM index (.crai): the CRAM analog of the .bai for interval queries.

A .crai is gzip-compressed text, one line per (slice × reference) with six
tab-separated fields:

    ref_seq_id  alignment_start(1-based)  alignment_span
    container_offset(file bytes)  slice_offset(bytes into container data)
    slice_size(bytes)

Multiref slices appear as one line per reference they touch (the htslib
convention); seeking lands on the container, and decode + overlap filtering
narrows to the requested loci.
"""

from __future__ import annotations

import gzip
from dataclasses import dataclass


@dataclass(frozen=True)
class CraiEntry:
    ref_seq_id: int
    start: int          # 1-based alignment start (0 for unmapped lines)
    span: int
    container_offset: int
    slice_offset: int
    slice_size: int

    def overlaps(self, ref: int, start0: int, end0: int) -> bool:
        """Half-open 0-based [start0, end0) query against this line."""
        if self.ref_seq_id != ref or self.span <= 0:
            return False
        s = self.start - 1
        return s < end0 and start0 < s + self.span


def write_crai(path, entries: list[CraiEntry]) -> None:
    with gzip.open(path, "wt") as f:
        for e in entries:
            f.write(
                f"{e.ref_seq_id}\t{e.start}\t{e.span}\t"
                f"{e.container_offset}\t{e.slice_offset}\t{e.slice_size}\n"
            )


def read_crai(path) -> list[CraiEntry]:
    entries = []
    with gzip.open(path, "rt") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            fields = line.split("\t")
            entries.append(CraiEntry(*(int(x) for x in fields[:6])))
    return entries
