"""CRAM logical structures: compression header and slice header.

The compression header (one per data container) declares how every data
series and tag is encoded; the slice header binds a run of records to the
blocks holding their series streams.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from spark_bam_tpu.core.guard import StructurallyInvalid, check_count
from spark_bam_tpu.cram.codecs import Encoding
from spark_bam_tpu.cram.nums import Cursor, itf8, ltf8

# Data series and their value kinds (CRAM 3.0 §8.4). ``int`` series decode
# ITF8 under EXTERNAL; ``byte`` series decode raw bytes; ``array`` series
# use BYTE_ARRAY_* encodings.
INT_SERIES = (
    "BF", "CF", "RI", "RL", "AP", "RG", "MF", "NS", "NP", "TS", "NF",
    "TL", "FN", "FP", "DL", "RS", "PD", "HC", "MQ",
)
BYTE_SERIES = ("BA", "QS", "FC", "BS")
ARRAY_SERIES = ("RN", "BB", "QQ", "IN", "SC")

DEFAULT_SUBST_MATRIX = bytes([0x1B] * 5)  # codes 0..3 in base order, per ref base


@dataclass
class CompressionHeader:
    read_names_included: bool = True
    ap_delta: bool = False
    reference_required: bool = True
    subst_matrix: bytes = DEFAULT_SUBST_MATRIX
    tag_dict: list[list[tuple[bytes, int]]] = field(default_factory=lambda: [[]])
    data_series: dict[str, Encoding] = field(default_factory=dict)
    tags: dict[int, Encoding] = field(default_factory=dict)

    # ------------------------------------------------------------ serialize
    def serialize(self) -> bytes:
        pres = bytearray()
        entries = [
            (b"RN", bytes([self.read_names_included])),
            (b"AP", bytes([self.ap_delta])),
            (b"RR", bytes([self.reference_required])),
            (b"SM", self.subst_matrix),
            (b"TD", self._td_blob()),
        ]
        pres += itf8(len(entries))
        for key, val in entries:
            pres += key + val
        out = itf8(len(pres)) + bytes(pres)

        ds = bytearray()
        ds += itf8(len(self.data_series))
        for key, enc in self.data_series.items():
            ds += key.encode("latin-1") + enc.serialize()
        out += itf8(len(ds)) + bytes(ds)

        tg = bytearray()
        tg += itf8(len(self.tags))
        for key, enc in self.tags.items():
            tg += itf8(key) + enc.serialize()
        out += itf8(len(tg)) + bytes(tg)
        return bytes(out)

    def _td_blob(self) -> bytes:
        blob = bytearray()
        for line in self.tag_dict:
            for tag, typ in line:
                blob += tag + bytes([typ])
            blob.append(0)
        return itf8(len(blob)) + bytes(blob)

    # ---------------------------------------------------------------- parse
    @staticmethod
    def parse(data: bytes) -> "CompressionHeader":
        cur = Cursor(data)
        h = CompressionHeader()
        cur.itf8()  # preservation map byte size
        # Every count below fences a loop over parsed entries; each entry
        # is ≥ 3 bytes (2-byte key + ≥ 1 value byte), so a count beyond the
        # remaining bytes is provably corrupt before the loop runs.
        n_pres = check_count(
            cur.itf8(), "CRAM preservation-map entries", cur.remaining()
        )
        for _ in range(n_pres):
            key = cur.read(2)
            if key == b"RN":
                h.read_names_included = bool(cur.u8())
            elif key == b"AP":
                h.ap_delta = bool(cur.u8())
            elif key == b"RR":
                h.reference_required = bool(cur.u8())
            elif key == b"SM":
                h.subst_matrix = cur.read(5)
            elif key == b"TD":
                blob = cur.read(cur.itf8())
                h.tag_dict = []
                line: list[tuple[bytes, int]] = []
                i = 0
                while i < len(blob):
                    if blob[i] == 0:
                        h.tag_dict.append(line)
                        line = []
                        i += 1
                    else:
                        if i + 3 > len(blob):
                            raise StructurallyInvalid(
                                f"CRAM TD dictionary cut mid-entry at "
                                f"byte {i} of {len(blob)}"
                            )
                        line.append((bytes(blob[i: i + 2]), blob[i + 2]))
                        i += 3
                if not h.tag_dict:
                    h.tag_dict = [[]]
            else:
                raise StructurallyInvalid(
                    f"unknown preservation key {key!r}", pos=cur.pos
                )
        cur.itf8()  # data-series map byte size
        n_series = check_count(
            cur.itf8(), "CRAM data-series entries", cur.remaining()
        )
        for _ in range(n_series):
            key = cur.read(2).decode("latin-1")
            h.data_series[key] = Encoding.parse(cur)
        cur.itf8()  # tag map byte size
        n_tags = check_count(
            cur.itf8(), "CRAM tag-map entries", cur.remaining()
        )
        for _ in range(n_tags):
            key = cur.itf8()
            h.tags[key] = Encoding.parse(cur)
        return h


@dataclass
class SliceHeader:
    ref_seq_id: int
    start: int
    span: int
    n_records: int
    record_counter: int
    n_blocks: int
    content_ids: list[int]
    embedded_ref_id: int = -1
    ref_md5: bytes = bytes(16)
    tags: bytes = b""

    def serialize(self) -> bytes:
        return (
            itf8(self.ref_seq_id)
            + itf8(self.start)
            + itf8(self.span)
            + itf8(self.n_records)
            + ltf8(self.record_counter)
            + itf8(self.n_blocks)
            + itf8(len(self.content_ids))
            + b"".join(itf8(c) for c in self.content_ids)
            + itf8(self.embedded_ref_id)
            + self.ref_md5
            + self.tags
        )

    @staticmethod
    def parse(data: bytes) -> "SliceHeader":
        cur = Cursor(data)
        ref_seq_id = cur.itf8()
        start = cur.itf8()
        span = cur.itf8()
        n_records = cur.itf8()
        record_counter = cur.ltf8()
        n_blocks = cur.itf8()
        n_ids = check_count(
            cur.itf8(), "CRAM slice content ids", cur.remaining()
        )
        content_ids = [cur.itf8() for _ in range(n_ids)]
        embedded_ref_id = cur.itf8()
        ref_md5 = cur.read(16)
        tags = bytes(cur.buf[cur.pos:])
        return SliceHeader(
            ref_seq_id, start, span, n_records, record_counter,
            n_blocks, content_ids, embedded_ref_id, ref_md5, tags,
        )
