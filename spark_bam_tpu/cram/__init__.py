"""CRAM 3.0 container format: reader, writer, codecs.

The reference delegates ``.cram`` loading to an external library
(load/.../CanLoadBam.scala:348-382 → hadoop-bam ``CRAMInputFormat`` +
htsjdk). No such library exists here, so the capability is built in: a
from-scratch CRAM 3.0 implementation — containers/slices/blocks, ITF8/LTF8
varints, the core-block bit codecs (HUFFMAN/BETA/BYTE_ARRAY_*/EXTERNAL),
rANS 4x8 entropy coding, reference-based and reference-less record decode —
feeding the same ``BamRecord``/``Dataset`` surfaces as the BAM path.

Containers are the CRAM analog of BGZF blocks for split planning: they are
self-delimiting, so ``load_cram`` partitions a file by container byte
ranges exactly the way ``Blocks`` partitions BGZF files (SURVEY.md §2.8).
"""

from spark_bam_tpu.cram.reader import CramReader, load_cram_header
from spark_bam_tpu.cram.writer import CramWriter

