"""CRAM encodings: the per-data-series codecs declared in the compression
header.

An encoding is serialized as ``codec id (itf8), parameter byte-length
(itf8), parameters``. Implemented codecs (the set used by real-world
writers for the series this reader consumes):

    1 EXTERNAL        value lives in the external block `content id`
                      (ITF8 per int, raw byte per byte-series value)
    3 HUFFMAN         canonical Huffman over an explicit alphabet, read
                      from the core bit stream (0-bit codes for constants)
    4 BYTE_ARRAY_LEN  nested length encoding + nested value encoding
    5 BYTE_ARRAY_STOP values from an external block up to a stop byte
    6 BETA            fixed-width offset binary from the core bit stream
    9 GAMMA           Elias gamma from the core bit stream

Core-block bits are MSB-first. ``Slice`` wires instances to its core/
external block streams at decode time; the writer emits the same
descriptors it decodes.
"""

from __future__ import annotations

from dataclasses import dataclass

from spark_bam_tpu.core.guard import StructurallyInvalid, TruncatedInput
from spark_bam_tpu.cram.nums import Cursor, itf8

EXTERNAL = 1
HUFFMAN = 3
BYTE_ARRAY_LEN = 4
BYTE_ARRAY_STOP = 5
BETA = 6
GAMMA = 9

#: Widest sane bit-field: 64 bits covers every CRAM data series. A larger
#: declared width (BETA length, Huffman code length) is a corrupt header;
#: uncapped, it sizes a per-value bit loop (a 2³¹-bit read per record).
MAX_CODE_BITS = 64


class BitReader:
    """MSB-first bit reader over the core block."""

    __slots__ = ("buf", "pos", "bit")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0
        self.bit = 7

    def read_bit(self) -> int:
        try:
            b = (self.buf[self.pos] >> self.bit) & 1
        except IndexError:
            raise TruncatedInput(
                f"core bit stream exhausted at byte {self.pos}"
            ) from None
        if self.bit == 0:
            self.bit = 7
            self.pos += 1
        else:
            self.bit -= 1
        return b

    def read_bits(self, n: int) -> int:
        v = 0
        for _ in range(n):
            v = (v << 1) | self.read_bit()
        return v


class BitWriter:
    """MSB-first bit writer producing the core block."""

    def __init__(self):
        self.out = bytearray()
        self.cur = 0
        self.nbits = 0

    def write_bits(self, value: int, n: int) -> None:
        for k in range(n - 1, -1, -1):
            self.cur = (self.cur << 1) | ((value >> k) & 1)
            self.nbits += 1
            if self.nbits == 8:
                self.out.append(self.cur)
                self.cur = 0
                self.nbits = 0

    def getvalue(self) -> bytes:
        if self.nbits:
            return bytes(self.out) + bytes([self.cur << (8 - self.nbits)])
        return bytes(self.out)


@dataclass
class Encoding:
    codec: int
    params: bytes

    def serialize(self) -> bytes:
        return itf8(self.codec) + itf8(len(self.params)) + self.params

    @staticmethod
    def parse(cur: Cursor) -> "Encoding":
        codec = cur.itf8()
        n = cur.itf8()
        return Encoding(codec, cur.read(n))


def external(content_id: int) -> Encoding:
    return Encoding(EXTERNAL, itf8(content_id))


def byte_array_stop(stop: int, content_id: int) -> Encoding:
    return Encoding(BYTE_ARRAY_STOP, bytes([stop]) + itf8(content_id))


def byte_array_len(lengths: Encoding, values: Encoding) -> Encoding:
    return Encoding(BYTE_ARRAY_LEN, lengths.serialize() + values.serialize())


def huffman(values: list[int], lens: list[int]) -> Encoding:
    p = itf8(len(values)) + b"".join(itf8(v) for v in values)
    p += itf8(len(lens)) + b"".join(itf8(x) for x in lens)
    return Encoding(HUFFMAN, p)


def beta(offset: int, length: int) -> Encoding:
    return Encoding(BETA, itf8(offset) + itf8(length))


def _canonical_codes(values: list[int], lens: list[int]) -> dict[int, tuple[int, int]]:
    """symbol → (code, length), canonical assignment by (length, symbol)."""
    pairs = sorted(zip(lens, values))
    codes: dict[int, tuple[int, int]] = {}
    code = 0
    prev_len = pairs[0][0] if pairs else 0
    for length, sym in pairs:
        code <<= length - prev_len
        prev_len = length
        codes[sym] = (code, length)
        code += 1
    return codes


class Decoders:
    """Bind encodings to a slice's core/external streams and hand out
    per-series reader callables."""

    def __init__(self, core: BitReader, ext: dict[int, Cursor]):
        self.core = core
        self.ext = ext

    def _ext_cursor(self, params: bytes) -> Cursor:
        cid = Cursor(params).itf8()
        if cid not in self.ext:
            self.ext[cid] = Cursor(b"")  # absent block = empty series
        return self.ext[cid]

    def int_reader(self, enc: Encoding):
        if enc.codec == EXTERNAL:
            cur = self._ext_cursor(enc.params)
            return cur.itf8
        if enc.codec == HUFFMAN:
            return self._huffman_reader(enc)
        if enc.codec == BETA:
            p = Cursor(enc.params)
            offset = p.itf8()
            length = p.itf8()
            if not 0 <= length <= MAX_CODE_BITS:
                raise StructurallyInvalid(
                    f"BETA bit length {length} outside [0, {MAX_CODE_BITS}]"
                )
            core = self.core
            return lambda: core.read_bits(length) - offset
        if enc.codec == GAMMA:
            p = Cursor(enc.params)
            offset = p.itf8()
            core = self.core

            def read_gamma():
                n = 0
                while core.read_bit() == 0:
                    n += 1
                return ((1 << n) | core.read_bits(n)) - offset

            return read_gamma
        raise NotImplementedError(f"int codec {enc.codec}")

    def byte_reader(self, enc: Encoding):
        if enc.codec == EXTERNAL:
            cur = self._ext_cursor(enc.params)
            return cur.u8
        if enc.codec == HUFFMAN:
            return self._huffman_reader(enc)
        if enc.codec == BETA:
            return self.int_reader(enc)
        raise NotImplementedError(f"byte codec {enc.codec}")

    def _huffman_reader(self, enc: Encoding):
        p = Cursor(enc.params)
        n_values = p.itf8()
        if not 0 <= n_values <= p.remaining():
            # Alphabet entries are ≥ 1 byte each: a larger count is corrupt.
            raise StructurallyInvalid(
                f"Huffman alphabet count {n_values} exceeds the "
                f"{p.remaining()} parameter bytes present"
            )
        values = [p.itf8() for _ in range(n_values)]
        n_lens = p.itf8()
        if n_lens != n_values:
            raise StructurallyInvalid(
                f"Huffman table mismatch: {n_values} symbols, {n_lens} code "
                f"lengths"
            )
        lens = [p.itf8() for _ in range(n_lens)]
        if not values:
            raise StructurallyInvalid("Huffman table with empty alphabet")
        if any(not 0 <= l <= MAX_CODE_BITS for l in lens):
            raise StructurallyInvalid(
                f"Huffman code length outside [0, {MAX_CODE_BITS}]: {lens}"
            )
        if len(values) == 1 and lens[0] == 0:
            const = values[0]
            return lambda: const  # zero-bit constant
        codes = _canonical_codes(values, lens)
        by_len: dict[int, dict[int, int]] = {}
        for sym, (code, length) in codes.items():
            by_len.setdefault(length, {})[code] = sym
        core = self.core
        max_len = max(by_len)

        def read_huffman():
            code = 0
            length = 0
            while length <= max_len:
                code = (code << 1) | core.read_bit()
                length += 1
                tab = by_len.get(length)
                if tab is not None and code in tab:
                    return tab[code]
            raise StructurallyInvalid("bad Huffman code in core block")

        return read_huffman

    def bulk_reader(self, enc: Encoding):
        """callable(n) → n bytes of a byte series (fast path for EXTERNAL)."""
        if enc.codec == EXTERNAL:
            cur = self._ext_cursor(enc.params)
            return cur.read
        read_byte = self.byte_reader(enc)
        return lambda n: bytes(read_byte() for _ in range(n))

    def array_reader(self, enc: Encoding):
        """Byte-array series (RN, BB, QQ, IN, SC, tag values)."""
        if enc.codec == BYTE_ARRAY_STOP:
            p = Cursor(enc.params)
            stop = p.u8()
            cid = p.itf8()
            if cid not in self.ext:
                self.ext[cid] = Cursor(b"")
            cur = self.ext[cid]

            def read_stop() -> bytes:
                buf = cur.buf
                end = buf.find(bytes([stop]), cur.pos)
                if end < 0:
                    end = len(buf)
                v = bytes(buf[cur.pos: end])
                cur.pos = end + 1
                return v

            return read_stop
        if enc.codec == BYTE_ARRAY_LEN:
            p = Cursor(enc.params)
            len_enc = Encoding.parse(p)
            val_enc = Encoding.parse(p)
            read_len = self.int_reader(len_enc)
            if val_enc.codec == EXTERNAL:
                cur = self._ext_cursor(val_enc.params)

                def read_bal() -> bytes:
                    n = read_len()
                    return cur.read(n)

                return read_bal
            read_byte = self.byte_reader(val_enc)

            def read_bal_slow() -> bytes:
                return bytes(read_byte() for _ in range(read_len()))

            return read_bal_slow
        raise NotImplementedError(f"array codec {enc.codec}")
