"""Forcing jax onto a virtual multi-device CPU platform.

This image's sitecustomize imports jax at interpreter startup and pins
``JAX_PLATFORMS`` to the real TPU tunnel, so caller-set env vars alone are
latched too late; the platform must also be forced through the config API.
Shared by ``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip`` so
the subtle bootstrap lives in exactly one place.

This module must stay importable without pulling in jax at module scope.
"""

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"

DEFAULT_JAX_CACHE = "/tmp/spark_bam_jaxcache"


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Enable JAX's persistent compilation cache process-wide.

    First XLA compile of the 32 MB window kernel costs 20-40 s; with the
    persistent cache, respawned bench children, the CLI, and repeated test
    sessions reuse the compiled executable (VERDICT r3 ask 1a). Safe to
    call before or after backend init; no-op on jax builds without the
    config knobs."""
    import jax

    cache_dir = cache_dir or os.environ.get("SB_JAX_CACHE", DEFAULT_JAX_CACHE)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass  # cache is an optimization; correctness unaffected


_PROBED_BACKEND: dict = {}


def probe_default_backend(timeout_s: float = 45.0) -> str | None:
    """The default jax backend's platform, probed in a SUBPROCESS with a
    hard timeout.

    On tunnelled-TPU machines, in-process backend init can hang
    indefinitely when the tunnel is down (observed: hours); an ``auto``
    backend decision must never hang with it. Returns the platform string
    (``"tpu"``/``"cpu"``/…) or None when the probe fails or times out —
    callers fall back to CPU paths. Cached per process.
    """
    if "platform" not in _PROBED_BACKEND:
        import subprocess
        import sys

        # If this process already initialized a backend, the in-process
        # answer is instant and cannot hang — skip the subprocess.
        xb = sys.modules.get("jax._src.xla_bridge")
        if xb is not None and getattr(xb, "_backends", None):
            try:
                import jax

                _PROBED_BACKEND["platform"] = jax.devices()[0].platform
                return _PROBED_BACKEND["platform"]
            except Exception:
                pass

        # The probe must see the caller's platform choice even though
        # sitecustomize re-pins JAX_PLATFORMS at subprocess startup: pass
        # it out-of-band and re-assert via the config API (the same trick
        # force_cpu_devices uses).
        code = (
            "import os, jax\n"
            "p = os.environ.get('SB_PROBE_JAX_PLATFORMS')\n"
            "if p:\n"
            "    jax.config.update('jax_platforms', p)\n"
            "print(jax.devices()[0].platform)\n"
        )
        env = {
            **os.environ,
            "SB_PROBE_JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", ""),
        }
        platform = None
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout_s, env=env,
            )
            lines = out.stdout.strip().splitlines()
            if out.returncode == 0 and lines:
                platform = lines[-1].strip()
        except Exception:
            platform = None
        _PROBED_BACKEND["platform"] = platform
    return _PROBED_BACKEND["platform"]


def force_cpu_devices(n_devices: int, defer_init: bool = False) -> None:
    """Force jax onto ``n_devices`` virtual CPU devices.

    Must run before any jax backend is initialized (first ``jax.devices()`` /
    first traced computation); after that the host-device-count flag is
    latched and this has no effect.

    ``defer_init=True`` only sets the flags without touching a backend —
    required before ``jax.distributed.initialize()``, which must itself run
    before any backend init (multi-host bring-up, parallel/multihost.py).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    opt = f"{_COUNT_FLAG}={n_devices}"
    if _COUNT_FLAG in flags:
        flags = re.sub(rf"{_COUNT_FLAG}=\d+", opt, flags)
    else:
        flags = f"{flags} {opt}".strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    try:
        import jax
    except ImportError:
        # Env vars are set; a later jax install in this process still sees
        # them. Callers that need jax will fail at their own import site.
        return

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    if defer_init:
        return

    # Initializing here (with our flags set) both latches the virtual-device
    # count and lets us fail loud instead of silently running on the real
    # TPU tunnel when some earlier import already initialized a backend.
    if jax.default_backend() != "cpu" or len(jax.devices("cpu")) < n_devices:
        raise RuntimeError(
            f"force_cpu_devices({n_devices}) too late: a jax backend was "
            f"already initialized (default={jax.default_backend()!r}, "
            f"cpu devices={len(jax.devices('cpu'))}); call it before any "
            "jax.devices()/traced computation, or use a fresh process"
        )
