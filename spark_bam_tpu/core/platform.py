"""Forcing jax onto a virtual multi-device CPU platform.

This image's sitecustomize imports jax at interpreter startup and pins
``JAX_PLATFORMS`` to the real TPU tunnel, so caller-set env vars alone are
latched too late; the platform must also be forced through the config API.
Shared by ``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip`` so
the subtle bootstrap lives in exactly one place.

This module must stay importable without pulling in jax at module scope.
"""

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"

DEFAULT_JAX_CACHE = "/tmp/spark_bam_jaxcache"


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Enable JAX's persistent compilation cache process-wide.

    First XLA compile of the 32 MB window kernel costs 20-40 s; with the
    persistent cache, respawned bench children, the CLI, and repeated test
    sessions reuse the compiled executable (VERDICT r3 ask 1a). Safe to
    call before or after backend init; no-op on jax builds without the
    config knobs."""
    import jax

    cache_dir = cache_dir or os.environ.get("SB_JAX_CACHE", DEFAULT_JAX_CACHE)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass  # cache is an optimization; correctness unaffected


def force_cpu_devices(n_devices: int, defer_init: bool = False) -> None:
    """Force jax onto ``n_devices`` virtual CPU devices.

    Must run before any jax backend is initialized (first ``jax.devices()`` /
    first traced computation); after that the host-device-count flag is
    latched and this has no effect.

    ``defer_init=True`` only sets the flags without touching a backend —
    required before ``jax.distributed.initialize()``, which must itself run
    before any backend init (multi-host bring-up, parallel/multihost.py).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    opt = f"{_COUNT_FLAG}={n_devices}"
    if _COUNT_FLAG in flags:
        flags = re.sub(rf"{_COUNT_FLAG}=\d+", opt, flags)
    else:
        flags = f"{flags} {opt}".strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    try:
        import jax
    except ImportError:
        # Env vars are set; a later jax install in this process still sees
        # them. Callers that need jax will fail at their own import site.
        return

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    if defer_init:
        return

    # Initializing here (with our flags set) both latches the virtual-device
    # count and lets us fail loud instead of silently running on the real
    # TPU tunnel when some earlier import already initialized a backend.
    if jax.default_backend() != "cpu" or len(jax.devices("cpu")) < n_devices:
        raise RuntimeError(
            f"force_cpu_devices({n_devices}) too late: a jax backend was "
            f"already initialized (default={jax.default_backend()!r}, "
            f"cpu devices={len(jax.devices('cpu'))}); call it before any "
            "jax.devices()/traced computation, or use a fresh process"
        )
