"""Plan-driven remote data plane: coalesced range fetches over a shared pool.

``PrefetchChannel`` (core/prefetch.py) pipelines fixed chunks ahead of a
cursor — it hides latency for one sequential reader but knows nothing about
*which* bytes a job will touch. This module replaces it on the remote path
with a scheduler that does:

- **Plan-driven fetches.** The exact byte ranges a job will read are known
  up front — the ``.sbi`` block table / split plan (sbi/), or the block
  metadata an ``InflatePipeline`` already holds. ``PlannedChannel.set_plan``
  turns them into coalesced ranged GETs via ``plan_fetches``
  (core/ranges.py): adjacent block ranges merge into large requests, cold
  gaps beyond the coalesce threshold are skipped, oversized runs split so
  they can pipeline. Without a plan the channel derives a whole-file one on
  first read (every byte is potentially needed — the metadata-scan case).

- **Adaptive depth.** Read-ahead keeps ``depth`` plan segments in flight
  past the consumer. ``depth=0`` (the default) auto-tunes: every time the
  consumer stalls on a segment that is not ready, the window doubles up
  to ``max_depth`` — TCP-slow-start-style probing that converges on the
  bandwidth-delay product without measuring either. A nonzero ``depth``
  pins the window (the bench's depth ladder).

- **Hedged GETs.** A segment fetch running longer than ``hedge`` × the
  rolling median GET latency (``LatencyTracker``, core/faults.py) gets a
  speculative twin; first success wins. ``FaultPolicy.hedge_after``
  overrides the multiplier when set, so ``--faults hedge=2`` governs GETs
  and partitions alike. Transport retries also come from the policy
  (``with_retries``) instead of ad-hoc channel loops.

- **A shared fleet pool.** All channels in the process fetch through one
  thread pool bounded by a global in-flight quota (``pool``), so a fleet
  load of many BAMs (load/api.load_fleet) cannot stampede the object store
  no matter how many files ride the executor concurrently.

Config: ``RemoteConfig`` parses the same compact ``k=v,...`` spec pattern as
``FaultPolicy`` and threads through ``Config.remote`` / ``SPARK_BAM_REMOTE``
/ ``--remote``. ``mode=legacy`` restores the cursor-relative
``PrefetchChannel`` (the bench A/B). Proofs in tests/test_remote_plan.py;
design + tuning notes in docs/remote.md.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from concurrent.futures import FIRST_COMPLETED, Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeoutError
from concurrent.futures import wait as wait_futures
from dataclasses import dataclass
from functools import lru_cache
from typing import Iterable

from spark_bam_tpu import obs
from spark_bam_tpu.core.channel import ByteChannel
from spark_bam_tpu.core.config import parse_bytes
from spark_bam_tpu.core.faults import FaultPolicy, LatencyTracker, with_retries
from spark_bam_tpu.core.ranges import ByteRange, RangeSet, plan_fetches


# ------------------------------------------------------------------- config
@dataclass(frozen=True)
class RemoteConfig:
    """Data-plane knobs, parseable from a compact ``k=v,...`` spec so they
    thread through config/env/CLI unchanged (``Config.remote`` /
    ``SPARK_BAM_REMOTE`` / ``--remote``)."""

    mode: str = "auto"            # auto | plan | legacy (PrefetchChannel)
    depth: int = 0                # in-flight segments; 0 = adaptive
    max_depth: int = 64           # adaptive-depth ceiling
    coalesce_gap: int = 128 << 10  # merge ranges separated by ≤ this
    max_request: int = 512 << 10   # split coalesced runs beyond this
    hedge: float = 3.0            # hedge a GET at N× median latency; 0 = off
    pool: int = 64                # process-wide in-flight GET quota
    bucket_quota: int = 0         # per-bucket in-flight GET cap; 0 = off
    cache_bytes: int = 256 << 20  # completed-segment retention budget

    MODES = ("auto", "plan", "legacy")

    def __post_init__(self):
        if self.mode not in self.MODES:
            raise ValueError(
                f"Unknown remote mode {self.mode!r}: expected one of "
                f"{', '.join(self.MODES)}"
            )
        if self.depth < 0 or self.max_depth < 1:
            raise ValueError(
                f"Bad remote depth {self.depth}/{self.max_depth}: depth must "
                "be >= 0 (0 = adaptive) and max_depth >= 1"
            )
        if self.max_request <= 0 or self.coalesce_gap < 0:
            raise ValueError(
                f"Bad remote request shape: max_request {self.max_request} "
                f"must be > 0 and coalesce_gap {self.coalesce_gap} >= 0"
            )
        if self.pool < 1:
            raise ValueError(f"remote pool must be >= 1: {self.pool}")
        if self.bucket_quota < 0:
            raise ValueError(
                f"remote bucket quota must be >= 0 (0 = off): {self.bucket_quota}"
            )
        if self.hedge < 0:
            raise ValueError(f"remote hedge must be >= 0 (0 = off): {self.hedge}")

    _KEYS = {
        "mode": "mode",
        "depth": "depth",
        "max_depth": "max_depth",
        "gap": "coalesce_gap",
        "coalesce_gap": "coalesce_gap",
        "request": "max_request",
        "max_request": "max_request",
        "hedge": "hedge",
        "pool": "pool",
        "bucket": "bucket_quota",
        "bucket_quota": "bucket_quota",
        "cache": "cache_bytes",
        "cache_bytes": "cache_bytes",
    }
    _BYTE_KEYS = ("coalesce_gap", "max_request", "cache_bytes")

    @staticmethod
    @lru_cache(maxsize=64)
    def parse(spec: str) -> "RemoteConfig":
        """``"mode=plan,depth=8,gap=128KB,request=512KB,hedge=3,pool=64"``
        (any subset; ``""`` ⇒ defaults). ``hedge`` accepts ``off``/``none``
        to disable explicitly; byte-valued keys take size shorthand."""
        kw: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"Bad remote-config entry {part!r} in {spec!r}")
            key, value = (t.strip() for t in part.split("=", 1))
            field = RemoteConfig._KEYS.get(key.replace("-", "_"))
            if field is None:
                raise ValueError(
                    f"Unknown remote-config key {key!r}: expected one of "
                    f"{', '.join(sorted(set(RemoteConfig._KEYS)))}"
                )
            if field == "mode":
                kw[field] = value
            elif field in RemoteConfig._BYTE_KEYS:
                kw[field] = parse_bytes(value)
            elif field == "hedge":
                kw[field] = (
                    0.0 if value.lower() in ("off", "none", "") else float(value)
                )
            else:
                kw[field] = int(value)
        return RemoteConfig(**kw)

    @staticmethod
    def from_env(env=None) -> "RemoteConfig":
        return RemoteConfig.parse(
            (env or os.environ).get("SPARK_BAM_REMOTE", "")
        )


# Process-wide override (the --remote CLI flag installs here); None falls
# back to SPARK_BAM_REMOTE. Same seam shape as faults.install_chaos.
_INSTALLED: RemoteConfig | None = None


def set_remote_config(spec: "str | RemoteConfig | None") -> None:
    """Install a process-wide ``RemoteConfig`` override (``--remote``);
    ``None`` uninstalls (environment resumes governing)."""
    global _INSTALLED
    _INSTALLED = RemoteConfig.parse(spec) if isinstance(spec, str) else spec


def active_remote_config() -> RemoteConfig:
    return _INSTALLED if _INSTALLED is not None else RemoteConfig.from_env()


# -------------------------------------------------- shared pool + GET quota
#: One fetch pool for the whole process: fleet loads (many channels) share
#: it instead of spawning workers per channel, and the per-size quota
#: semaphores bound how many GETs are actually on the wire at once.
_POOL_WORKERS = 64
_pool_lock = threading.Lock()
_pool: ThreadPoolExecutor | None = None
_quotas: dict[int, threading.BoundedSemaphore] = {}


def _shared_pool() -> ThreadPoolExecutor:
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = ThreadPoolExecutor(
                max_workers=_POOL_WORKERS, thread_name_prefix="sbt-remote"
            )
        return _pool


def _quota_sem(n: int) -> threading.BoundedSemaphore:
    with _pool_lock:
        sem = _quotas.get(n)
        if sem is None:
            sem = _quotas[n] = threading.BoundedSemaphore(n)
        return sem


# The global quota bounds TOTAL wire concurrency; per-bucket quotas bound
# each origin's share of it, so one hot bucket in a fleet load cannot
# monopolize the pool (and cannot trip one store's rate limiting while
# the others idle). A bucket is the origin of the channel's URL
# (scheme://netloc); channels without a URL share the anonymous bucket "".
_bucket_sems: "dict[tuple[str, int], threading.BoundedSemaphore]" = {}
_bucket_inflight: "dict[str, dict[str, int]]" = {}


def _bucket_of(inner) -> str:
    url = getattr(inner, "url", "") or ""
    if "://" not in url:
        return ""
    from urllib.parse import urlsplit

    parts = urlsplit(url)
    return f"{parts.scheme}://{parts.netloc}"


def _bucket_sem(bucket: str, n: int) -> threading.BoundedSemaphore:
    with _pool_lock:
        sem = _bucket_sems.get((bucket, n))
        if sem is None:
            sem = _bucket_sems[(bucket, n)] = threading.BoundedSemaphore(n)
        return sem


def _bucket_enter(bucket: str) -> None:
    with _pool_lock:
        st = _bucket_inflight.setdefault(bucket, {"cur": 0, "high": 0})
        st["cur"] += 1
        if st["cur"] > st["high"]:
            st["high"] = st["cur"]


def _bucket_exit(bucket: str) -> None:
    with _pool_lock:
        _bucket_inflight[bucket]["cur"] -= 1


def bucket_inflight_stats() -> "dict[str, dict[str, int]]":
    """Per-bucket in-flight GET counters: {bucket: {cur, high}} (tests,
    operator stats)."""
    with _pool_lock:
        return {b: dict(st) for b, st in _bucket_inflight.items()}


def reset_bucket_stats() -> None:
    with _pool_lock:
        _bucket_inflight.clear()


# ------------------------------------------------------------------ channel
class PlannedChannel(ByteChannel):
    """Plan-driven read-ahead over a remote ``ByteChannel``.

    ``set_plan`` (before the first read) pins the request plan; reads then
    map onto plan segments, are served from in-flight/completed fetches,
    and trigger read-ahead of the next ``depth`` segments *in plan order*
    — read-ahead follows the job's byte ranges across gaps instead of the
    cursor. Reads outside the plan fall through to the inner channel
    (counted, not cached): plans cover the data a job touches, so off-plan
    reads are metadata probes and EOF sentinels.

    Segments with outstanding readers are pinned; completed unpinned
    segments are evicted oldest-first past ``cache_bytes`` (pending
    fetches are never evicted — discarding an in-flight GET just re-pays
    it). Thread-safe: the inflate fan-out calls ``read_at`` from many
    threads.
    """

    def __init__(
        self,
        inner: ByteChannel,
        plan: "Iterable[ByteRange | tuple[int, int]] | None" = None,
        config: RemoteConfig | None = None,
        policy: FaultPolicy | None = None,
    ):
        super().__init__()
        self.inner = inner
        self.cfg = config or active_remote_config()
        self.policy = policy or FaultPolicy.from_env()
        self._lock = threading.RLock()
        self._segments: list[ByteRange] = []
        self._starts: list[int] = []
        self._futs: dict[int, Future] = {}
        self._order: list[int] = []        # submission order (eviction scan)
        self._sizes: dict[int, int] = {}   # completed-segment byte sizes
        self._cached_bytes = 0
        self._pins: dict[int, int] = {}
        self._fetched_any = False
        self._closed = False
        self._depth = self.cfg.depth or 8
        self._latency = LatencyTracker()
        self._quota = _quota_sem(self.cfg.pool)
        self._bucket = _bucket_of(inner)
        self._bucket_quota = (
            _bucket_sem(self._bucket, self.cfg.bucket_quota)
            if self.cfg.bucket_quota else None
        )
        if plan is not None:
            self.set_plan(plan)

    # ------------------------------------------------------------- planning
    def set_plan(self, ranges: "Iterable[ByteRange | tuple[int, int]]") -> None:
        """Install the byte ranges this channel will be asked for. A no-op
        after the first fetch: by then the whole-file fallback plan is live
        and replacing it would orphan in-flight segments."""
        with self._lock:
            if self._fetched_any:
                return
            rs = RangeSet(
                r if isinstance(r, ByteRange) else ByteRange(*r)
                for r in ranges
            )
            self._install_plan(rs)

    def _install_plan(self, rs: RangeSet) -> None:
        self._segments = plan_fetches(
            rs, gap=self.cfg.coalesce_gap, max_request=self.cfg.max_request
        )
        self._starts = [s.start for s in self._segments]
        obs.gauge("remote.plan_segments").set(len(self._segments))

    def _ensure_plan(self) -> None:
        """Whole-file fallback plan on first read when no plan was given
        (metadata scans touch everything; the size probe is one HEAD)."""
        with self._lock:
            if self._segments or self._fetched_any:
                return
        size = self.inner.size  # outside the lock: may be a HEAD round-trip
        with self._lock:
            if not self._segments and not self._fetched_any:
                self._install_plan(RangeSet([ByteRange(0, max(size, 1))]))

    # ------------------------------------------------------------- fetching
    def _fetch_job(self, start: int, length: int) -> bytes:
        t0 = time.perf_counter()
        # Bucket quota OUTSIDE the global quota: a hot bucket's excess GETs
        # queue on their own semaphore without pinning pool-wide slots, so
        # other buckets' fetches keep flowing.
        if self._bucket_quota is not None:
            self._bucket_quota.acquire()
            waited_ms = (time.perf_counter() - t0) * 1e3
            if waited_ms > 1.0:
                obs.observe("remote.bucket_wait_ms", waited_ms, unit="ms")
        try:
            t0 = time.perf_counter()
            with self._quota:
                waited_ms = (time.perf_counter() - t0) * 1e3
                if waited_ms > 1.0:
                    obs.observe("remote.quota_wait_ms", waited_ms, unit="ms")
                t1 = time.perf_counter()
                _bucket_enter(self._bucket)
                try:
                    data = with_retries(
                        lambda: self.inner._read_at(start, length), self.policy,
                        "remote GET",
                    )
                finally:
                    _bucket_exit(self._bucket)
                ms = (time.perf_counter() - t1) * 1e3
        finally:
            if self._bucket_quota is not None:
                self._bucket_quota.release()
        self._latency.record(ms)
        obs.count("remote.gets")
        obs.count("remote.bytes", len(data))
        obs.observe("remote.get_ms", ms, unit="ms")
        return data

    def _submit_locked(self, idx: int) -> Future:
        """Ensure segment ``idx`` has a fetch in flight (lock held)."""
        fut = self._futs.get(idx)
        if fut is None:
            seg = self._segments[idx]
            self._fetched_any = True
            fut = _shared_pool().submit(
                self._fetch_job, seg.start, seg.end - seg.start
            )
            self._futs[idx] = fut
            self._order.append(idx)
            fut.add_done_callback(lambda f, i=idx: self._on_done(i, f))
        return fut

    def _on_done(self, idx: int, fut: Future) -> None:
        if fut.cancelled() or fut.exception() is not None:
            return
        with self._lock:
            if idx in self._futs and idx not in self._sizes:
                self._sizes[idx] = len(fut.result())
                self._cached_bytes += self._sizes[idx]

    def _evict_locked(self) -> None:
        """Drop completed unpinned segments oldest-first past the budget.
        Pending fetches and pinned segments survive, so the retained set
        can transiently exceed the budget by the in-flight window."""
        if self._cached_bytes <= self.cfg.cache_bytes:
            return
        for idx in self._order:
            if self._cached_bytes <= self.cfg.cache_bytes:
                break
            fut = self._futs.get(idx)
            if fut is None:
                continue
            if self._pins.get(idx) or not fut.done() or idx not in self._sizes:
                continue
            del self._futs[idx]
            self._cached_bytes -= self._sizes.pop(idx)
            obs.count("remote.evictions")
        self._order = [i for i in self._order if i in self._futs]

    def _grow_depth(self) -> None:
        """Consumer stalled on an unfetched-or-pending segment: the window
        is smaller than the bandwidth-delay product. Double it (the
        slow-start analog — each stall costs one RTT, so a multiplicative
        ramp reaches the BDP in O(log) stalls) unless depth is pinned."""
        if self.cfg.depth:
            return
        grown = min(self.cfg.max_depth, self._depth * 2)
        if grown != self._depth:
            self._depth = grown
            obs.gauge("remote.depth").set(grown)

    def _await(self, idx: int) -> bytes:
        """Block for segment ``idx``, hedging a straggler fetch."""
        with self._lock:
            fut = self._submit_locked(idx)
        if not fut.done():
            obs.count("remote.stalls")
            self._grow_depth()
        hedge_mult = (
            self.policy.hedge_after
            if self.policy.hedge_after is not None
            else self.cfg.hedge
        )
        median = self._latency.median() if hedge_mult else None
        if median is None:
            return fut.result()
        try:
            return fut.result(timeout=hedge_mult * median / 1e3)
        except FutureTimeoutError:
            pass
        obs.count("remote.hedges")
        seg = self._segments[idx]
        twin = _shared_pool().submit(
            self._fetch_job, seg.start, seg.end - seg.start
        )
        pending = {fut, twin}
        err: BaseException | None = None
        while pending:
            done, pending = wait_futures(pending, return_when=FIRST_COMPLETED)
            for f in done:
                if f.exception() is None:
                    if f is twin:
                        obs.count("remote.hedge_wins")
                        with self._lock:
                            # The twin becomes the cached copy (the
                            # straggler may never land).
                            if self._futs.get(idx) is fut:
                                self._futs[idx] = twin
                                if idx in self._sizes:
                                    self._cached_bytes -= self._sizes.pop(idx)
                                self._on_done_inline(idx, twin)
                    return f.result()
                err = f.exception()
        raise err  # both the primary and the hedge failed

    def _on_done_inline(self, idx: int, fut: Future) -> None:
        if idx in self._futs and idx not in self._sizes:
            self._sizes[idx] = len(fut.result())
            self._cached_bytes += self._sizes[idx]

    # -------------------------------------------------------------- reading
    def _segment_at(self, pos: int) -> int | None:
        """Index of the plan segment containing ``pos``, or None."""
        i = bisect.bisect_right(self._starts, pos) - 1
        if i >= 0 and self._segments[i].end > pos:
            return i
        return None

    def _read_at(self, pos: int, n: int) -> bytes:
        if n <= 0:
            return b""
        self._ensure_plan()
        with self._lock:
            first = self._segment_at(pos)
            last_pos = pos + n - 1
            last = self._segment_at(last_pos)
            window = []
            if first is not None:
                j = first
                while j < len(self._segments) and self._segments[j].start <= last_pos:
                    window.append(j)
                    j += 1
                for idx in window:
                    self._pins[idx] = self._pins.get(idx, 0) + 1
                    self._submit_locked(idx)
                # Read-ahead: the next ``depth`` plan segments past the
                # request, in plan order (gap-skipping by construction).
                ahead_from = window[-1] + 1
                for idx in range(
                    ahead_from, min(ahead_from + self._depth,
                                    len(self._segments))
                ):
                    self._submit_locked(idx)
            del last
        try:
            out = []
            cur = pos
            remaining = n
            wi = 0
            while remaining > 0:
                idx = window[wi] if wi < len(window) else None
                seg = self._segments[idx] if idx is not None else None
                if seg is not None and seg.start <= cur < seg.end:
                    chunk = self._await(idx)
                    off = cur - seg.start
                    piece = chunk[off: off + remaining]
                    if not piece:
                        break  # EOF inside the segment
                    out.append(piece)
                    cur += len(piece)
                    remaining -= len(piece)
                    if cur >= seg.end:
                        wi += 1
                    elif remaining > 0:
                        break  # short segment: EOF
                else:
                    # Off-plan bytes (gaps, EOF sentinels, probe reads):
                    # direct inner read up to the next planned segment.
                    nxt = bisect.bisect_right(self._starts, cur)
                    limit = (
                        self._segments[nxt].start
                        if nxt < len(self._segments) else cur + remaining
                    )
                    take = min(remaining, limit - cur)
                    if take <= 0:
                        # cur sits inside a segment not in the window —
                        # possible only on concurrent plan swap; re-resolve.
                        with self._lock:
                            ridx = self._segment_at(cur)
                        if ridx is None:
                            break
                        window.append(ridx)
                        with self._lock:
                            self._pins[ridx] = self._pins.get(ridx, 0) + 1
                            self._submit_locked(ridx)
                        wi = len(window) - 1
                        continue
                    obs.count("remote.unplanned_gets")
                    piece = self.inner._read_at(cur, take)
                    if not piece:
                        break
                    out.append(piece)
                    cur += len(piece)
                    remaining -= len(piece)
                    if len(piece) < take:
                        break
                    # Landed at a segment start: resolve it for next loop.
                    with self._lock:
                        ridx = self._segment_at(cur)
                        if ridx is not None:
                            window.append(ridx)
                            self._pins[ridx] = self._pins.get(ridx, 0) + 1
                            self._submit_locked(ridx)
                            wi = len(window) - 1
            return b"".join(out)
        finally:
            with self._lock:
                for idx in window:
                    left = self._pins.get(idx, 0) - 1
                    if left <= 0:
                        self._pins.pop(idx, None)
                    else:
                        self._pins[idx] = left
                self._evict_locked()

    @property
    def depth(self) -> int:
        """Current read-ahead window (adaptive unless pinned by config)."""
        return self._depth

    @property
    def size(self) -> int:
        return self.inner.size

    def close(self) -> None:
        self._closed = True
        with self._lock:
            futs = list(self._futs.values())
            self._futs.clear()
            self._order.clear()
            self._sizes.clear()
            self._cached_bytes = 0
        for f in futs:
            f.cancel()  # queued fetches die; running ones are abandoned
        self.inner.close()


# ------------------------------------------------------------------ routing
def wrap_remote(
    inner: ByteChannel,
    plan: "Iterable[ByteRange | tuple[int, int]] | None" = None,
    policy: FaultPolicy | None = None,
) -> ByteChannel:
    """The remote read-path wrapper ``open_channel``/cloud factories use:
    ``PlannedChannel`` under the active ``RemoteConfig``, or the legacy
    cursor-relative ``PrefetchChannel`` when ``mode=legacy`` (bench A/B)."""
    cfg = active_remote_config()
    if cfg.mode == "legacy":
        from spark_bam_tpu.core.prefetch import PrefetchChannel

        return PrefetchChannel(inner, chunk_size=1 << 20, depth=4, workers=8)
    return PlannedChannel(inner, plan=plan, config=cfg, policy=policy)
