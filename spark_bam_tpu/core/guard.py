"""Resource-guarded decode boundary: limits + malformed-input taxonomy.

Every parser that consumes untrusted bytes (bam/record, bam/header, bam/bai,
bgzf/header, bgzf/stream, sbi/format, the cram/ readers) trusts the length
fields it reads until this layer says otherwise. One corrupt byte used to be
able to hang a worker (an unbounded count loop), OOM a host (a 2 GB
``remaining``), or yield silently-wrong records (a short slice where a
truncation error belonged). Two halves live here:

- ``DecodeLimits`` — per-field resource ceilings (record bytes, header text,
  reference count/name length, CIGAR ops, sequence length, allocation
  budget), parseable from a compact ``k=v,...`` spec so it threads through
  config/env/CLI unchanged (``Config.limits`` / ``SPARK_BAM_LIMITS`` /
  ``--limits``). Parsers read the process-wide active limits via
  ``current_limits()``; ``scoped_limits`` overrides them for a test or a
  fuzz run.

- The ``MalformedInputError`` hierarchy — typed verdicts on bad bytes,
  plugging into the fault model (core/faults.py):

    ``TruncatedInput``       the bytes end before the structure does
                             (also an ``EOFError``: historical truncation
                             handlers keep working)
    ``StructurallyInvalid``  a field contradicts the format (negative
                             size, missing subfield, overflowing extent)
    ``LimitExceeded``        well-formed but beyond ``DecodeLimits``

  All three are ``ValueError`` + ``Unrecoverable``: deterministic damage
  that no retry fixes. Strict mode raises them with file/virtual-position
  context; tolerant mode quarantines the damaged record or block and
  resumes at the next provable boundary, counting losses in the
  ``guard.*`` metrics tallied here.

The structure-aware mutation fuzzer (tools/fuzz_decode.py) asserts the
contract: every mutant either parses clean, raises a typed
``MalformedInputError``, or quarantines-with-resume — never a hang, never
an over-budget allocation, never an untyped crash. Semantics in
docs/robustness.md ("Malformed inputs").
"""

from __future__ import annotations

import contextlib
import errno
import os
import threading
from dataclasses import dataclass
from functools import lru_cache

from spark_bam_tpu import obs
from spark_bam_tpu.core.config import parse_bytes
from spark_bam_tpu.core.faults import Unrecoverable


# ----------------------------------------------------------------- taxonomy
class MalformedInputError(ValueError, Unrecoverable):
    """The bytes are not a well-formed instance of the format being parsed.

    Deterministic damage: retrying re-reads the same bytes, so the fault
    model never burns retry budget on it (``Unrecoverable``). ``path`` and
    ``pos`` (a virtual/flat position, when the parser knows one) locate the
    damage for the strict-mode error message and the tolerant-mode
    quarantine ledger.
    """

    def __init__(self, msg: str, *, path=None, pos=None):
        self.path = path
        self.pos = pos
        ctx = []
        if path is not None:
            ctx.append(str(path))
        if pos is not None:
            ctx.append(f"at {pos}")
        super().__init__(f"{msg} [{', '.join(ctx)}]" if ctx else msg)


class TruncatedInput(MalformedInputError, EOFError):
    """The input ends before the declared structure does — the bytes that
    should complete it never existed. Subclasses ``EOFError`` so the
    historical clean-truncation handlers (record streams, index writers)
    keep catching it without modification."""


class StructurallyInvalid(MalformedInputError):
    """A field contradicts the format itself: a negative size, a missing
    mandatory subfield, declared sub-regions overflowing the declared
    extent. No limit tuning makes these bytes parseable."""


class LimitExceeded(MalformedInputError):
    """Structurally plausible but beyond the active ``DecodeLimits`` —
    the defense against resource-exhaustion fields (a 2 GB record, a 2³¹
    reference count) that would otherwise hang or OOM a worker."""


class RecordGapError(IOError, Unrecoverable):
    """Tolerant-mode record resync marker: the record at virtual position
    ``pos`` declared an untrustworthy length prefix, so the stream cannot
    locally skip it. Raised once by a tolerant record stream; the load
    layer re-finds the next provable record boundary with the checker and
    resumes (the block-layer analog is ``BlockGapError``)."""

    def __init__(self, pos, reason: str):
        super().__init__(f"unreadable BAM record at {pos}: {reason}")
        self.pos = pos
        self.reason = reason


class ResourceExhausted(OSError):
    """The environment ran out of a resource mid-operation — disk space
    (``ENOSPC``), quota (``EDQUOT``), a failing device (``EIO``) — while
    writing an artifact. Retryable by the fault model (an ``OSError``
    that is *not* ``Unrecoverable``): space gets freed, quotas get
    raised, devices get replaced. The durable-job plane (jobs/) pauses
    a journaled job on this instead of failing it; resume picks up from
    the last committed checkpoint."""

    def __init__(self, msg: str, *, errno_: "int | None" = None, path=None):
        super().__init__(errno_ or 0, msg, str(path) if path else None)


#: errnos that mean "the environment is out of a resource" rather than
#: "these bytes/paths are wrong" — the write-side mirror of the
#: read-side transient set.
_EXHAUSTED_ERRNOS = frozenset(
    getattr(errno, name)
    for name in ("ENOSPC", "EDQUOT", "EIO", "ENOMEM")
    if hasattr(errno, name)
)


def map_write_error(exc: OSError, what: str, path=None) -> OSError:
    """Classify an ``OSError`` escaping a writer: exhaustion errnos become
    :class:`ResourceExhausted` (retryable, job-pausing); anything else is
    returned unchanged so deterministic errors (``EACCES``, ``ENOENT``)
    keep their type. Callers ``raise map_write_error(e, ...) from e``."""
    if isinstance(exc, ResourceExhausted):
        return exc
    if exc.errno in _EXHAUSTED_ERRNOS:
        return ResourceExhausted(
            f"{what}: {exc.strerror or exc}", errno_=exc.errno, path=path
        )
    return exc


def preflight_space(path, need_bytes: int, margin: float = 1.1) -> None:
    """ENOSPC preflight: refuse to *start* a write that cannot fit.
    ``need_bytes`` is the caller's estimate; ``margin`` covers metadata
    and estimate error. Best-effort — filesystems without ``statvfs``
    skip the check and rely on the mid-write mapping instead."""
    if need_bytes <= 0:
        return
    target = os.path.dirname(os.path.abspath(str(path))) or "."
    try:
        st = os.statvfs(target)
    except (OSError, AttributeError):
        return
    free = st.f_bavail * st.f_frsize
    if free < need_bytes * margin:
        raise ResourceExhausted(
            f"preflight: {path} needs ~{int(need_bytes * margin)} bytes, "
            f"filesystem has {free} free",
            errno_=errno.ENOSPC, path=path,
        )


# ------------------------------------------------------------------- limits
@dataclass(frozen=True)
class DecodeLimits:
    """Resource ceilings for untrusted-byte parsers. Defaults are far above
    anything a well-formed file produces (ultralong nanopore records are
    tens of MB; SAM headers with full RG/PG provenance are single-digit
    MB) while keeping the worst single allocation a corrupt length field
    can force well under a worker's memory."""

    max_record_bytes: int = 64 << 20   # one BAM record (block_size)
    max_header_text: int = 64 << 20    # SAM header text bytes
    max_refs: int = 1 << 20            # reference-dictionary entries
    max_name_len: int = 4096           # one reference/read name
    max_cigar_ops: int = 1 << 16       # CIGAR ops per record (u16 in BAM)
    max_seq_len: int = 1 << 28         # bases per record
    alloc_budget: int = 1 << 30        # per-partition allocation ceiling

    def __post_init__(self):
        for f in (
            "max_record_bytes", "max_header_text", "max_refs",
            "max_name_len", "max_cigar_ops", "max_seq_len", "alloc_budget",
        ):
            if getattr(self, f) <= 0:
                raise ValueError(f"DecodeLimits.{f} must be > 0: "
                                 f"{getattr(self, f)}")

    _KEYS = {
        "record": "max_record_bytes",
        "max_record_bytes": "max_record_bytes",
        "header_text": "max_header_text",
        "text": "max_header_text",
        "max_header_text": "max_header_text",
        "refs": "max_refs",
        "max_refs": "max_refs",
        "name": "max_name_len",
        "max_name_len": "max_name_len",
        "cigar": "max_cigar_ops",
        "max_cigar_ops": "max_cigar_ops",
        "seq": "max_seq_len",
        "max_seq_len": "max_seq_len",
        "alloc": "alloc_budget",
        "alloc_budget": "alloc_budget",
    }

    @staticmethod
    @lru_cache(maxsize=64)
    def parse(spec: str) -> "DecodeLimits":
        """``"record=32MB,refs=1000,alloc=512MB"`` (any subset; ``""`` ⇒
        defaults). Values accept the usual byte-size shorthand."""
        kw: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"Bad decode-limit entry {part!r} in {spec!r}")
            key, value = (t.strip() for t in part.split("=", 1))
            field = DecodeLimits._KEYS.get(key.replace("-", "_"))
            if field is None:
                raise ValueError(
                    f"Unknown decode-limit key {key!r}: expected one of "
                    f"{', '.join(sorted(set(DecodeLimits._KEYS)))}"
                )
            kw[field] = parse_bytes(value)
        return DecodeLimits(**kw)

    @staticmethod
    def from_env(env=None) -> "DecodeLimits":
        return DecodeLimits.parse(
            (env or os.environ).get("SPARK_BAM_LIMITS", "")
        )


# Process-wide active limits: parsers deep below the config-threading
# surface (record decode, CRAM cursors) read these; ``--limits`` and the
# fuzz harness install overrides. None ⇒ fall through to the env spec.
_active: DecodeLimits | None = None


def current_limits() -> DecodeLimits:
    return _active if _active is not None else DecodeLimits.from_env()


def set_limits(limits: "DecodeLimits | str | None") -> None:
    global _active
    _active = DecodeLimits.parse(limits) if isinstance(limits, str) else limits


@contextlib.contextmanager
def scoped_limits(limits: "DecodeLimits | str"):
    """``with scoped_limits("record=1MB"): ...`` — scoped installation."""
    global _active
    prev = _active
    _active = DecodeLimits.parse(limits) if isinstance(limits, str) else limits
    try:
        yield _active
    finally:
        _active = prev


# ------------------------------------------------------------ guard helpers
def check_count(n: int, what: str, limit: int | None = None, *,
                path=None, pos=None) -> int:
    """Validate a count/length field read from untrusted bytes: negative ⇒
    ``StructurallyInvalid``, beyond ``limit`` ⇒ ``LimitExceeded``."""
    if n < 0:
        raise StructurallyInvalid(f"{what} is negative ({n})",
                                  path=path, pos=pos)
    if limit is not None and n > limit:
        raise LimitExceeded(f"{what} {n} exceeds limit {limit}",
                            path=path, pos=pos)
    return n


def check_available(have: int, need: int, what: str, *,
                    path=None, pos=None) -> None:
    """Explicit truncation check before consuming ``need`` bytes — the
    replacement for silent short slices."""
    if have < need:
        raise TruncatedInput(f"{what}: need {need} bytes, have {have}",
                             path=path, pos=pos)


# ------------------------------------------------------------ loss tallies
class _LossTally:
    """Process-wide quarantine counts, snapshotted by ``run_partitions`` so
    a ``JobReport`` can state exactly what a tolerant load lost."""

    __slots__ = ("lock", "records", "blocks")

    def __init__(self):
        self.lock = threading.Lock()
        self.records = 0
        self.blocks = 0


_loss = _LossTally()


def note_quarantined_records(n: int = 1) -> None:
    obs.count("guard.quarantined_records", n)
    with _loss.lock:
        _loss.records += n


def note_quarantined_block() -> None:
    obs.count("guard.quarantined_blocks")
    with _loss.lock:
        _loss.blocks += 1


def loss_totals() -> tuple[int, int]:
    """(quarantined records, quarantined blocks) since process start."""
    with _loss.lock:
        return _loss.records, _loss.blocks
