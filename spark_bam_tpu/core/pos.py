"""Virtual positions in a BGZF file.

A "virtual position" is (compressed offset of a BGZF block start, offset into
that block's *uncompressed* payload). Mirrors the reference's
``org.hammerlab.bgzf.Pos`` (bgzf/.../Pos.scala:12-43) including the packed
HTSJDK ``long`` encoding (48-bit block position << 16 | 16-bit offset).
"""

from __future__ import annotations

from typing import NamedTuple


class Pos(NamedTuple):
    block_pos: int  # byte offset of the BGZF block start in the compressed file
    offset: int     # offset into the block's uncompressed payload (< 65536)

    def __str__(self) -> str:
        return f"{self.block_pos}:{self.offset}"

    def to_htsjdk(self) -> int:
        """Pack into the HTSJDK-style 64-bit virtual offset."""
        return (self.block_pos << 16) | self.offset

    @staticmethod
    def from_htsjdk(vpos: int) -> "Pos":
        return Pos(vpos >> 16, vpos & 0xFFFF)

    def distance(self, other: "Pos", estimated_compression_ratio: float = 3.0) -> int:
        """Approximate *compressed*-byte distance ``self - other``.

        Intra-block uncompressed offsets are scaled down by the estimated
        compression ratio (reference Pos.scala:17-22, default ratio 3.0 from
        EstimatedCompressionRatio.scala:13).
        """
        return max(
            0,
            self.block_pos
            - other.block_pos
            + int((self.offset - other.offset) / estimated_compression_ratio),
        )


def parse_pos(s: str) -> Pos:
    """Parse ``"blockPos:offset"`` (or a bare block position) into a Pos."""
    if ":" in s:
        block, off = s.split(":", 1)
        return Pos(int(block), int(off))
    return Pos(int(s), 0)
