"""Byte-range grammar and range sets.

The reference accepts comma-separated ranges of the forms ``start-end``,
``start+length`` and ``point``, with byte-size shorthand for each value
(check/.../args/Range.scala:100-234, Ranges.scala:244-309). This module
provides the same grammar plus a minimal interval-set with the two queries
the planners need: point membership and overlap with a half-open window.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Sequence

from spark_bam_tpu.core.config import parse_bytes


@dataclass(frozen=True)
class ByteRange:
    """Half-open byte range [start, end)."""
    start: int
    end: int

    def __post_init__(self):
        if self.end < self.start:
            raise ValueError(f"Bad range: {self.start}-{self.end}")

    def __contains__(self, pos: int) -> bool:
        return self.start <= pos < self.end

    def overlaps(self, start: int, end: int) -> bool:
        return self.start < end and start < self.end


class RangeSet:
    """Normalized union of half-open byte ranges."""

    def __init__(self, ranges: Iterable[ByteRange]):
        merged: list[ByteRange] = []
        for r in sorted(ranges, key=lambda r: (r.start, r.end)):
            if merged and r.start <= merged[-1].end:
                merged[-1] = ByteRange(merged[-1].start, max(merged[-1].end, r.end))
            else:
                merged.append(r)
        self.ranges: Sequence[ByteRange] = tuple(merged)

    def __contains__(self, pos: int) -> bool:
        return any(pos in r for r in self.ranges)

    def overlaps(self, start: int, end: int) -> bool:
        return any(r.overlaps(start, end) for r in self.ranges)

    def __bool__(self) -> bool:
        return bool(self.ranges)

    def __eq__(self, other) -> bool:
        return isinstance(other, RangeSet) and self.ranges == other.ranges

    def __repr__(self) -> str:
        return "RangeSet(%s)" % ",".join(f"{r.start}-{r.end}" for r in self.ranges)


# ------------------------------------------------------------- fetch planner

def _split_run(start: int, end: int, max_request: int) -> list[ByteRange]:
    """One coalesced run → ~equal fetches of at most ``max_request`` bytes.
    ceil-divided so a run just over the cap becomes two near-halves rather
    than a full request plus a sliver."""
    length = end - start
    n = -(-length // max_request)
    step = -(-length // n)
    return [ByteRange(s, min(s + step, end)) for s in range(start, end, step)]


def plan_fetches(
    ranges: "RangeSet | Iterable[ByteRange]",
    *,
    gap: int = 128 << 10,
    max_request: int = 512 << 10,
) -> list[ByteRange]:
    """Coalesce the byte ranges a job will touch into ranged-GET requests.

    Adjacent ranges separated by at most ``gap`` cold bytes merge into one
    run (fetching a small gap is cheaper than paying another round-trip);
    runs longer than ``max_request`` split into near-equal fetches so they
    can pipeline. The result is the data plane's request plan
    (core/remote_plan.py): sorted, non-overlapping, covering every input
    byte, with every fetch at most ``max_request`` long and every fetched
    non-input byte inside a gap of at most ``gap`` bytes.
    """
    if gap < 0:
        raise ValueError(f"gap must be >= 0: {gap}")
    if max_request <= 0:
        raise ValueError(f"max_request must be > 0: {max_request}")
    rs = ranges if isinstance(ranges, RangeSet) else RangeSet(ranges)
    fetches: list[ByteRange] = []
    run_start = run_end = None
    for r in rs.ranges:
        if r.start == r.end:
            continue
        if run_start is None:
            run_start, run_end = r.start, r.end
        elif r.start - run_end <= gap:
            run_end = max(run_end, r.end)
        else:
            fetches.extend(_split_run(run_start, run_end, max_request))
            run_start, run_end = r.start, r.end
    if run_start is not None:
        fetches.extend(_split_run(run_start, run_end, max_request))
    return fetches


def parse_range(s: str) -> ByteRange:
    """One range: ``start-end`` | ``start+length`` | ``point``."""
    s = s.strip()
    for sep in ("-", "+"):
        # Split on the grammar separator, but not inside a leading number.
        idx = s.find(sep, 1)
        if idx > 0:
            left, right = s[:idx], s[idx + 1:]
            start = parse_bytes(left)
            other = parse_bytes(right)
            return ByteRange(start, other if sep == "-" else start + other)
    point = parse_bytes(s)
    return ByteRange(point, point + 1)


def parse_ranges(s: str | None) -> RangeSet | None:
    """Comma-separated list of ranges, or None for "unrestricted"."""
    if s is None or not s.strip():
        return None
    return RangeSet(parse_range(part) for part in s.split(",") if part.strip())
