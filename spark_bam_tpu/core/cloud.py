"""Built-in cloud-storage channels: ``gs://`` and ``s3://``.

The reference's founding problem was GCS seek latency (its docs' headline
numbers are all measured on GCS; google-cloud-nio + ``fs.gs.io.buffersize``
at cli/.../spark/ComputeSplits.scala:47-54). Here cloud objects ride the
same stack every remote byte does: ``HttpRangeChannel`` (keep-alive
range-GETs — core/remote.py) wrapped by the remote data plane
(plan-driven coalesced prefetch with hedged GETs, core/remote_plan.py;
or the legacy cursor read-ahead under ``mode=legacy``), so sequential
scans overlap round-trips and the inflate fan-out overlaps random ones.

Auth is env-sourced — no SDK dependency:

- ``gs://``: a bearer token from ``SPARK_BAM_GS_TOKEN`` or
  ``GOOGLE_OAUTH_ACCESS_TOKEN`` (e.g. ``gcloud auth print-access-token``)
  is sent as ``Authorization: Bearer …`` against the GCS XML API
  (``https://storage.googleapis.com/{bucket}/{object}``). Public buckets
  work tokenless.
- ``s3://``: SigV4 request signing (pure stdlib hmac/sha256) from
  ``AWS_ACCESS_KEY_ID``/``AWS_SECRET_ACCESS_KEY`` (+ optional
  ``AWS_SESSION_TOKEN``), region from ``AWS_REGION``/``AWS_DEFAULT_REGION``
  (default us-east-1). Without credentials, requests go unsigned (public
  buckets).

``SPARK_BAM_GS_ENDPOINT`` / ``SPARK_BAM_S3_ENDPOINT`` override the service
base URL — emulators (fake-gcs-server, MinIO) and the latency-injected
bench/test servers plug in there.

Import side effect: registers both schemes in ``core.channel``'s registry
(idempotent; an explicit ``register_scheme`` by the deployment wins because
later registrations override).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import urllib.parse

from spark_bam_tpu.core.channel import ByteChannel, register_scheme
from spark_bam_tpu.core.remote import HttpRangeChannel
from spark_bam_tpu.core.remote_plan import wrap_remote


def _split_bucket_key(url: str, scheme: str) -> tuple[str, str]:
    u = urllib.parse.urlsplit(url)
    if u.scheme != scheme or not u.netloc:
        raise ValueError(f"not a {scheme}:// url: {url}")
    return u.netloc, u.path.lstrip("/")


# ------------------------------------------------------------------- gs://

def gs_https_url(url: str):
    """``gs://bucket/object`` → (https URL, per-request header fn).

    The token is re-read from the environment on every request, so a
    long-running job can rotate ``SPARK_BAM_GS_TOKEN`` (OAuth access
    tokens expire hourly) without reopening channels."""
    bucket, key = _split_bucket_key(url, "gs")
    endpoint = os.environ.get(
        "SPARK_BAM_GS_ENDPOINT", "https://storage.googleapis.com"
    ).rstrip("/")
    https = f"{endpoint}/{bucket}/{urllib.parse.quote(key)}"

    def headers(method: str) -> dict:
        token = os.environ.get("SPARK_BAM_GS_TOKEN") or os.environ.get(
            "GOOGLE_OAUTH_ACCESS_TOKEN"
        )
        return {"Authorization": f"Bearer {token}"} if token else {}

    return https, headers


def open_gs(url: str, prefetch: bool = True) -> ByteChannel:
    https, headers = gs_https_url(url)
    ch: ByteChannel = HttpRangeChannel(https, headers=headers)
    return wrap_remote(ch) if prefetch else ch


# ------------------------------------------------------------------- s3://

def _sigv4_headers(
    method: str, host: str, path: str, region: str,
    access_key: str, secret_key: str, session_token: str | None,
    amz_date: str | None = None,
) -> dict:
    """AWS Signature Version 4 for a bodyless request (GET/HEAD), stdlib
    only. Range headers deliberately stay OUT of the signature (SigV4 only
    signs the headers listed in SignedHeaders; signing host+date suffices
    and keeps one signature valid for every ranged read of the object)."""
    now = amz_date or datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y%m%dT%H%M%SZ"
    )
    datestamp = now[:8]
    payload_hash = hashlib.sha256(b"").hexdigest()
    headers = {
        "host": host,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": now,
    }
    if session_token:
        headers["x-amz-security-token"] = session_token
    signed = ";".join(sorted(headers))
    canonical = "\n".join([
        method,
        urllib.parse.quote(path),
        "",  # query string
        "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
        signed,
        payload_hash,
    ])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", now, scope,
        hashlib.sha256(canonical.encode()).hexdigest(),
    ])

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, "s3")
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    out = {k2: v for k2, v in headers.items() if k2 != "host"}
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={signature}"
    )
    return out


def s3_https_url(url: str):
    """``s3://bucket/key`` → (https URL, per-request header fn). SigV4
    signs the actual method and a fresh timestamp on every request
    (signatures are valid ±15 min; HEAD and GET sign differently)."""
    bucket, key = _split_bucket_key(url, "s3")
    region = os.environ.get(
        "AWS_REGION", os.environ.get("AWS_DEFAULT_REGION", "us-east-1")
    )
    endpoint = os.environ.get("SPARK_BAM_S3_ENDPOINT")
    if endpoint:
        endpoint = endpoint.rstrip("/")
        https = f"{endpoint}/{bucket}/{urllib.parse.quote(key)}"
        path = f"/{bucket}/{key}"
        host = urllib.parse.urlsplit(endpoint).netloc
    else:
        host = f"{bucket}.s3.{region}.amazonaws.com"
        https = f"https://{host}/{urllib.parse.quote(key)}"
        path = f"/{key}"

    def headers(method: str) -> dict:
        access = os.environ.get("AWS_ACCESS_KEY_ID")
        secret = os.environ.get("AWS_SECRET_ACCESS_KEY")
        if not (access and secret):
            return {}
        return _sigv4_headers(
            method, host, path, region, access, secret,
            os.environ.get("AWS_SESSION_TOKEN"),
        )

    return https, headers


def open_s3(url: str, prefetch: bool = True) -> ByteChannel:
    https, headers = s3_https_url(url)
    ch: ByteChannel = HttpRangeChannel(https, headers=headers)
    return wrap_remote(ch) if prefetch else ch


register_scheme("gs", open_gs)
register_scheme("s3", open_s3)
