from spark_bam_tpu.core.pos import Pos
from spark_bam_tpu.core.config import Config, default_config

__all__ = ["Pos", "Config", "default_config"]
