"""Descriptive-stats pretty printing, reference-format-exact.

The reference reports N/μ/σ, med/mad, run-length-encoded element lists and a
percentile ladder everywhere results are summarized (org.hammerlab.stats).
Format contracts pinned by goldens (bgzf StreamTest.scala:36-58, CLI golden
outputs): R-6/Weibull quantiles (rank = p·(n+1) − 1), percentile p shown iff
``n·min(p,100−p)/100 ≥ 1``, values rounded to 1 decimal with trailing ``.0``
dropped, head…tail RLE truncation at 10 runs each side.
"""

from __future__ import annotations

import math
from typing import Iterable, Sequence


def fmt_num(x, round_digits: int = 1) -> str:
    """Round to 1 decimal; drop a trailing .0 (reference show for doubles)."""
    if isinstance(x, float):
        r = round(x, round_digits)
        if r == int(r):
            return str(int(r))
        return f"{r:.{round_digits}f}"
    return str(x)


def _rle(values: Sequence, limit: int = 10, fmt=fmt_num) -> str:
    runs: list[tuple[object, int]] = []
    for v in values:
        if runs and runs[-1][0] == v:
            runs[-1] = (v, runs[-1][1] + 1)
        else:
            runs.append((v, 1))

    def show(run):
        v, n = run
        return f"{fmt(v)}×{n}" if n > 1 else fmt(v)

    if len(runs) > 2 * limit:
        head = " ".join(show(r) for r in runs[:limit])
        tail = " ".join(show(r) for r in runs[-limit:])
        return f"{head} … {tail}"
    return " ".join(show(r) for r in runs)


def _quantile(sorted_vals: Sequence[float], p: float) -> float:
    """R-6 (Weibull) quantile: rank = p/100·(n+1) − 1, linear interpolation."""
    n = len(sorted_vals)
    rank = p / 100 * (n + 1) - 1
    if rank <= 0:
        return sorted_vals[0]
    if rank >= n - 1:
        return sorted_vals[-1]
    lo = int(math.floor(rank))
    frac = rank - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[lo + 1] * frac


def percentile_ladder(n: int) -> list[float]:
    """p included iff n·min(p, 100−p)/100 ≥ 1; a [50]-only ladder is empty."""
    candidates = [0.01, 0.1, 1, 5, 10, 25, 50, 75, 90, 95, 99, 99.9, 99.99]
    ladder = [p for p in candidates if n * min(p, 100 - p) / 100 >= 1 or p == 50]
    return [] if ladder == [50] else ladder


class Stats:
    """Summary statistics of a numeric sample, reference-style rendering.

    ``rounded=True`` renders every derived value rounded to integer (the
    check-blocks histogram mode, CheckBlocks.scala truncatedDouble).
    """

    def __init__(self, values: Iterable[float], rounded: bool = False):
        self.values = list(values)
        self.rounded = rounded
        self.n = len(self.values)
        if self.n:
            self.mean = sum(self.values) / self.n
            self.stddev = math.sqrt(
                sum((v - self.mean) ** 2 for v in self.values) / self.n
            )
            self.sorted = sorted(self.values)
            self.median = _quantile(self.sorted, 50)
            self.mad = _quantile(sorted(abs(v - self.median) for v in self.values), 50)

    @staticmethod
    def from_hist(pairs: Iterable[tuple[float, int]], rounded: bool = False) -> "Stats":
        """Stats of a histogram: (value, count) pairs expand by weight."""
        values: list[float] = []
        for v, count in sorted(pairs):
            values.extend([v] * int(count))
        return Stats(values, rounded=rounded)

    def _fmt(self, x) -> str:
        if self.rounded:
            return str(round(x))
        return fmt_num(x)

    def show(self) -> str:
        if not self.n:
            return "(empty)"
        f = self._fmt
        lines = [
            f"N: {self.n},"
            f" μ/σ: {f(round(self.mean, 1))}/{f(round(self.stddev, 1))},"
            f" med/mad: {f(self.median)}/{f(self.mad)}"
        ]
        if self.n > 1:
            lines.append(f" elems: {_rle(self.values, fmt=f)}")
            if self.sorted != self.values and len(set(self.values)) > 1:
                lines.append(f"sorted: {_rle(self.sorted, fmt=f)}")
            for p in percentile_ladder(self.n):
                val = round(_quantile(self.sorted, p), 1)
                pname = fmt_num(float(p), 2) if p != int(p) else str(int(p))
                lines.append(f"{pname:>4}:\t{f(val)}")
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.show()


def format_bytes_binary(n: int, include_b: bool = False) -> str:
    """hammerlab-bytes format: 1024-based, 3 significant figures, K/M/G/T
    suffix ("583K", "25.6K"; includeB ⇒ "519KB")."""
    suffix = "B" if include_b else ""
    for unit, shift in (("E", 60), ("P", 50), ("T", 40), ("G", 30), ("M", 20), ("K", 10)):
        if n >= (1 << shift):
            v = n / (1 << shift)
            if v < 10:
                s = f"{v:.2f}".rstrip("0").rstrip(".")
            elif v < 100:
                s = f"{v:.1f}".rstrip("0").rstrip(".")
            else:
                s = str(round(v))
            return f"{s}{unit}{suffix}"
    return f"{n}{'B' if include_b else ''}"
