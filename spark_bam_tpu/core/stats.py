"""Descriptive-stats pretty printing.

The reference reports N/μ/σ, med/mad, run-length-encoded element lists and a
percentile ladder everywhere results are summarized (org.hammerlab.stats;
format visible in bgzf StreamTest.scala:36-58 and the CLI golden outputs).
This reproduces that report shape.
"""

from __future__ import annotations

import math
from typing import Iterable, Sequence


def _fmt(x: float) -> str:
    if isinstance(x, float) and not x.is_integer():
        return f"{x:.1f}" if abs(x) >= 1 else f"{x:.2f}"
    return str(int(x))


def _rle(values: Sequence[int], limit: int = 10) -> str:
    """Run-length-encode: ``65498×24 34570``; head…tail truncation beyond 2*limit."""
    runs: list[tuple[int, int]] = []
    for v in values:
        if runs and runs[-1][0] == v:
            runs[-1] = (v, runs[-1][1] + 1)
        else:
            runs.append((v, 1))

    def show(run):
        v, n = run
        return f"{_fmt(v)}×{n}" if n > 1 else _fmt(v)

    if len(runs) > 2 * limit:
        head = " ".join(show(r) for r in runs[:limit])
        tail = " ".join(show(r) for r in runs[-limit:])
        return f"{head} … {tail}"
    return " ".join(show(r) for r in runs)


def _percentile(sorted_vals: Sequence[float], p: float) -> float:
    """Linear-interpolated percentile on a sorted sequence."""
    n = len(sorted_vals)
    if n == 1:
        return sorted_vals[0]
    rank = p / 100 * (n - 1)
    lo = int(math.floor(rank))
    hi = min(lo + 1, n - 1)
    frac = rank - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def percentile_ladder(n: int) -> list[float]:
    """Percentiles to report, widened as N grows (matches reference's scaling idea)."""
    ladder = [50.0]
    tiers = [(2, [25, 75]), (6, [10, 90]), (11, [5, 95]),
             (21, [1, 99]), (101, [0.1, 99.9]), (1001, [0.01, 99.99])]
    for min_n, (lo, hi) in tiers:
        if n >= min_n:
            ladder = [lo] + ladder + [hi]
    return ladder


class Stats:
    """Summary statistics of an integer/float sample, reference-style rendering."""

    def __init__(self, values: Iterable[float]):
        self.values = list(values)
        self.n = len(self.values)
        if self.n:
            self.mean = sum(self.values) / self.n
            self.stddev = math.sqrt(
                sum((v - self.mean) ** 2 for v in self.values) / self.n
            )
            s = sorted(self.values)
            self.sorted = s
            self.median = _percentile(s, 50)
            self.mad = _percentile(sorted(abs(v - self.median) for v in s), 50)

    def show(self, indent: str = "") -> str:
        if not self.n:
            return f"{indent}(empty)"
        lines = [
            f"N: {self.n}, μ/σ: {_fmt(round(self.mean, 1))}/{_fmt(round(self.stddev, 1))},"
            f" med/mad: {_fmt(self.median)}/{_fmt(self.mad)}"
        ]
        if self.n > 1:
            lines.append(f" elems: {_rle(self.values)}")
            if sorted(self.values) != self.values and len(set(self.values)) > 1:
                lines.append(f"sorted: {_rle(self.sorted)}")
            for p in percentile_ladder(self.n):
                val = round(_percentile(self.sorted, p), 1)
                pname = _fmt(p) if p != int(p) else str(int(p))
                lines.append(f"{pname:>4}:\t{_fmt(val)}")
        return "\n".join(indent + line for line in lines)

    def __str__(self) -> str:
        return self.show()
