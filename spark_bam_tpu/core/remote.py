"""Remote byte channels: HTTP(S) range-GET.

GCS latency is the reference's founding problem — every headline number is
measured on GCS (reference docs/benchmarks.md:53-59), and SURVEY.md §7
hard-part 5 names remote IO a first-class concern. ``HttpRangeChannel``
is the remote ``ByteChannel``: one ranged GET per ``_read_at``, keep-alive
connections per thread (the inflate/prefetch layers fan ``read_at`` out
across threads), auth injectable via ``headers`` (e.g. a
``Authorization: Bearer …`` token for GCS's JSON/XML APIs — the transport
below is exactly what gcsfs/s3fs speak).

Latency hiding is composed, not built in: ``open_channel`` wraps remote
channels in ``PrefetchChannel`` (aligned read-ahead pipeline,
core/prefetch.py) so sequential scans overlap round-trips, and the block
inflater's ``read_at`` fan-out overlaps random ones. See
tests/test_remote.py for the injected-latency proof.
"""

from __future__ import annotations

import http.client
import math
import random
import threading
import time
import urllib.parse

from spark_bam_tpu.core.channel import ByteChannel


def _content_range_start(content_range: str | None) -> int | None:
    """First byte position from ``Content-Range: bytes lo-hi/total``;
    None when absent or not a byte-range form (e.g. ``bytes */total``)."""
    if not content_range:
        return None
    value = content_range.strip()
    if not value.startswith("bytes"):
        return None
    span = value[len("bytes"):].strip().split("/", 1)[0]
    lo = span.split("-", 1)[0].strip()
    return int(lo) if lo.isdigit() else None


def _parse_retry_after(value: str | None) -> float:
    """``Retry-After`` as seconds: delta-seconds or an HTTP-date (RFC 9110
    §10.2.3 allows either form); unparseable/absent → 0 (jittered
    backoff applies)."""
    if not value:
        return 0.0
    try:
        wait = float(value)
        return wait if math.isfinite(wait) else 0.0
    except ValueError:
        pass
    try:
        from email.utils import parsedate_to_datetime

        dt = parsedate_to_datetime(value)
        # An HTTP-date already in the past must not yield a negative wait
        # (callers feed this to sleep schedules): retry immediately instead.
        return max(0.0, dt.timestamp() - time.time())
    except (TypeError, ValueError, OverflowError):
        return 0.0


class HttpRangeChannel(ByteChannel):
    """Seekable reads over HTTP/1.1 ``Range: bytes=…`` requests.

    Thread-safe: each thread gets its own keep-alive connection, so
    concurrent ``read_at`` calls (prefetch depth, inflate fan-out) become
    concurrent in-flight GETs.
    """

    #: transient statuses worth retrying (GCS/S3 throttling + 5xx blips)
    RETRY_STATUSES = (429, 500, 502, 503, 504)

    def __init__(self, url: str, headers=None,
                 timeout: float = 30.0, retries: int = 3):
        super().__init__()
        self._retries = max(0, retries)
        self.url = url
        u = urllib.parse.urlsplit(url)
        if u.scheme not in ("http", "https"):
            raise ValueError(f"not an http(s) url: {url}")
        self._secure = u.scheme == "https"
        self._host = u.hostname or ""
        self._port = u.port
        self._path = u.path or "/"
        if u.query:
            self._path += "?" + u.query
        # ``headers`` may be a dict (static) or a callable
        # ``headers(method) -> dict`` evaluated per request — auth schemes
        # that sign the method + a timestamp (S3 SigV4, expiring bearer
        # tokens) need fresh headers on every attempt.
        self._headers = headers if callable(headers) else dict(headers or {})
        self._timeout = timeout
        self._local = threading.local()
        self._conns: list[http.client.HTTPConnection] = []
        self._conns_lock = threading.Lock()
        self._size: int | None = None
        self._size_lock = threading.Lock()
        self._closed = False

    # ----------------------------------------------------------- transport
    def _conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            cls = (
                http.client.HTTPSConnection if self._secure
                else http.client.HTTPConnection
            )
            conn = cls(self._host, self._port, timeout=self._timeout)
            self._local.conn = conn
            with self._conns_lock:
                self._conns.append(conn)
        return conn

    def _request(self, method: str, extra_headers: dict):
        """One request with a single retry on a stale keep-alive socket."""
        base = (
            self._headers(method) if callable(self._headers)
            else self._headers
        )
        for attempt in (0, 1):
            conn = self._conn()
            try:
                conn.request(
                    method, self._path, headers={**base, **extra_headers}
                )
                return conn.getresponse()
            except (http.client.HTTPException, ConnectionError, OSError):
                conn.close()
                self._local.conn = None
                if attempt:
                    raise

    def _request_retrying(self, method: str, extra_headers: dict):
        """One logical request absorbing transient failures: throttle/5xx
        statuses AND connection drops mid-body (the common object-store
        blip), with bounded jittered exponential backoff (lockstep
        prefetch workers must not re-fire in synchronized bursts), a
        server-provided ``Retry-After`` honored when positive, and an
        early exit when the channel closes mid-backoff. Returns
        (resp, body)."""
        delay = 0.1
        for attempt in range(self._retries + 1):
            final = attempt == self._retries or self._closed
            wait = 0.0
            try:
                resp = self._request(method, extra_headers)
                body = resp.read()
            except (http.client.HTTPException, ConnectionError, OSError):
                # Reset during read(): drop the stale keep-alive so the
                # next attempt reconnects; retryable like a 5xx.
                conn = getattr(self._local, "conn", None)
                if conn is not None:
                    conn.close()
                    self._local.conn = None
                if final:
                    raise
            else:
                if resp.status not in self.RETRY_STATUSES or final:
                    return resp, body
                wait = _parse_retry_after(resp.headers.get("Retry-After"))
            if wait <= 0:
                wait = delay * (0.5 + random.random())
            time.sleep(min(wait, 5.0))
            delay *= 4
        raise AssertionError("unreachable: final attempt returns or raises")

    def _read_at(self, pos: int, n: int) -> bytes:
        if n <= 0 or self._closed:
            return b""
        resp, body = self._request_retrying(
            "GET", {"Range": f"bytes={pos}-{pos + n - 1}"}
        )
        if resp.status == 206:
            content_range = resp.headers.get("Content-Range")
            self._learn_size(content_range)
            # Verify the 206 actually starts where we asked: a proxy or
            # misbehaving server answering a different range would
            # otherwise hand corrupt bytes to the decoder as if correct.
            got = _content_range_start(content_range)
            if got is not None and got != pos:
                from spark_bam_tpu.core.guard import StructurallyInvalid

                raise StructurallyInvalid(
                    f"server answered range starting at {got}, "
                    f"requested {pos} (Content-Range: {content_range!r})",
                    path=self.url, pos=pos,
                )
            return body
        if resp.status == 200:
            # Server ignored the Range header and sent the full body. A
            # 200 is only honest when we asked from byte 0 and got at most
            # what we asked for; otherwise silently slicing would mask a
            # broken range path (and re-download the object per read).
            if pos == 0 and len(body) <= n:
                self._size = len(body)
                return body
            from spark_bam_tpu.core.guard import StructurallyInvalid

            raise StructurallyInvalid(
                f"server ignored Range header (HTTP 200 full body, "
                f"{len(body)} bytes) for range {pos}+{n}",
                path=self.url, pos=pos,
            )
        if resp.status == 416:  # requested range past EOF
            self._learn_size(resp.headers.get("Content-Range"))
            return b""
        raise IOError(f"GET {self.url} range {pos}+{n}: HTTP {resp.status}")

    def _learn_size(self, content_range: str | None):
        # "bytes 0-99/12345" or "bytes */12345"
        if content_range and "/" in content_range:
            total = content_range.rsplit("/", 1)[1]
            if total.isdigit():
                self._size = int(total)

    @property
    def size(self) -> int:
        # Double-checked: the HEAD (with its retry backoff) runs outside
        # the lock so a throttled probe can't stall every thread that
        # touches ``size``; a rare duplicate probe is harmless.
        if self._size is None:
            resp, _ = self._request_retrying("HEAD", {})
            length = resp.headers.get("Content-Length")
            if resp.status == 404:
                # Distinguishable "missing" (sidecar probes rely on it);
                # other statuses are real errors and must propagate.
                raise FileNotFoundError(f"HEAD {self.url}: HTTP 404")
            if resp.status != 200 or length is None:
                raise IOError(
                    f"HEAD {self.url}: HTTP {resp.status}, no length"
                )
            with self._size_lock:
                if self._size is None:
                    self._size = int(length)
        return self._size

    def close(self) -> None:
        self._closed = True
        with self._conns_lock:
            for conn in self._conns:
                try:
                    conn.close()
                except Exception:
                    pass
            self._conns.clear()
