"""Byte channels: seekable random-access byte sources.

Replaces the reference's L0 ``org.hammerlab.channel`` layer
(``SeekableByteChannel``, ``CachingChannel`` — SURVEY.md §1 L0). Local files
are served from ``mmap`` (zero-copy slices straight into NumPy). The class
is the single IO seam: ``open_channel`` routes ``http(s)://`` URLs to the
built-in range-GET backend (core/remote.py) behind a read-ahead
``PrefetchChannel``, and other ``scheme://`` URLs to factories registered
via ``register_scheme`` — only ``_read_at`` needs overriding in a backend,
while ``CachingChannel``/``PrefetchChannel`` supply the reuse and
latency-hiding that make high-latency stores viable (SURVEY.md §7 "Remote
storage IO"; latency-injection proof in tests/test_remote.py).
"""

from __future__ import annotations

import inspect
import io
import mmap
import os
import re
import struct
import threading
from collections import OrderedDict


class ByteChannel:
    """Positioned byte source. ``read_fully`` raises EOFError on short reads."""

    def __init__(self):
        self._pos = 0

    # -- subclass surface ---------------------------------------------------
    def _read_at(self, pos: int, n: int) -> bytes:
        raise NotImplementedError

    @property
    def size(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass

    # -- shared behavior ----------------------------------------------------
    def read_at(self, pos: int, n: int) -> bytes:
        """Positioned read that does NOT touch the shared cursor — the bulk
        IO primitive for concurrent readers of one channel (the cursor API
        below remains single-threaded). May be short at EOF."""
        return self._read_at(pos, n)

    def position(self) -> int:
        return self._pos

    def seek(self, pos: int) -> None:
        self._pos = pos

    def skip(self, n: int) -> None:
        self._pos += n

    def read(self, n: int) -> bytes:
        """Read up to n bytes (may be short at EOF)."""
        data = self._read_at(self._pos, n)
        self._pos += len(data)
        return data

    def read_fully(self, n: int) -> bytes:
        data = self.read(n)
        if len(data) != n:
            raise EOFError(f"wanted {n} bytes at {self._pos - len(data)}, got {len(data)}")
        return data

    def read_u8(self) -> int:
        return self.read_fully(1)[0]

    def read_i32(self) -> int:
        return struct.unpack("<i", self.read_fully(4))[0]

    def read_u16(self) -> int:
        return struct.unpack("<H", self.read_fully(2))[0]

    def read_u64(self) -> int:
        return struct.unpack("<Q", self.read_fully(8))[0]

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MMapChannel(ByteChannel):
    """mmap-backed channel for local files (the default)."""

    def __init__(self, path):
        super().__init__()
        self.path = str(path)
        self._f = open(self.path, "rb")
        self._size = os.fstat(self._f.fileno()).st_size
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ) if self._size else b""

    def _read_at(self, pos: int, n: int) -> bytes:
        if pos >= self._size:
            return b""
        return self._mm[pos: pos + n]

    def memoryview(self, pos: int, n: int) -> memoryview:
        """Zero-copy view (local-file fast path used by the batched inflater)."""
        return memoryview(self._mm)[pos: pos + n]

    @property
    def size(self) -> int:
        return self._size

    def close(self) -> None:
        if isinstance(self._mm, mmap.mmap):
            self._mm.close()
        self._f.close()


class FileStreamChannel(ByteChannel):
    """Buffered sequential channel over an arbitrary file object (non-mmap path)."""

    def __init__(self, fobj: io.RawIOBase, size: int | None = None):
        super().__init__()
        self._f = fobj
        self._size = size
        self._io_lock = threading.Lock()  # seek+read must be atomic

    def _read_at(self, pos: int, n: int) -> bytes:
        with self._io_lock:
            self._f.seek(pos)
            return self._f.read(n) or b""

    @property
    def size(self) -> int:
        if self._size is None:
            with self._io_lock:  # shares the fd cursor with _read_at
                if self._size is None:
                    cur = self._f.tell()
                    self._size = self._f.seek(0, io.SEEK_END)
                    self._f.seek(cur)
        return self._size

    def close(self) -> None:
        self._f.close()


class CachingChannel(ByteChannel):
    """LRU chunk cache over another channel.

    Analog of the reference's ``CachingChannel`` wrapped around every
    executor-side file handle (load/.../Channels.scala:9-27). Chunks are
    fixed-size and aligned; useful over high-latency channels.
    """

    def __init__(self, inner: ByteChannel, chunk_size: int = 256 << 10, max_chunks: int = 64):
        super().__init__()
        self.inner = inner
        self.chunk_size = chunk_size
        self.max_chunks = max_chunks
        self._cache: OrderedDict[int, bytes] = OrderedDict()
        self._cache_lock = threading.Lock()

    def _chunk(self, idx: int) -> bytes:
        with self._cache_lock:
            chunk = self._cache.get(idx)
            if chunk is not None:
                self._cache.move_to_end(idx)
                return chunk
        # Fetch outside the lock: misses may overlap; a duplicate fetch of
        # the same chunk is benign (last writer wins).
        chunk = self.inner._read_at(idx * self.chunk_size, self.chunk_size)
        with self._cache_lock:
            self._cache[idx] = chunk
            if len(self._cache) > self.max_chunks:
                self._cache.popitem(last=False)
        return chunk

    def _read_at(self, pos: int, n: int) -> bytes:
        out = []
        remaining = n
        while remaining > 0:
            idx, off = divmod(pos, self.chunk_size)
            chunk = self._chunk(idx)
            piece = chunk[off: off + remaining]
            if not piece:
                break
            out.append(piece)
            pos += len(piece)
            remaining -= len(piece)
        return b"".join(out)

    @property
    def size(self) -> int:
        return self.inner.size

    def close(self) -> None:
        self.inner.close()


# Custom URL schemes → channel factories (tests register latency-injected
# fakes; deployments can register gs://, s3://, … backends).
_SCHEMES: dict = {}

# Chaos injection seam (core/faults.py): when installed, every channel
# ``open_channel`` hands out is wrapped so deterministic faults reach every
# consumer. A plain module attribute (not an import of faults) so the
# disabled path costs one ``is None`` test and no import cycle exists.
_CHAOS_WRAPPER = None


def set_chaos_wrapper(wrapper) -> None:
    """Install ``wrapper(ch, path) -> ByteChannel`` over every opened
    channel (``faults.install_chaos``); ``None`` uninstalls."""
    global _CHAOS_WRAPPER
    _CHAOS_WRAPPER = wrapper

_URL_RE = re.compile(r"^([a-z][a-z0-9+.-]*)://")


def register_scheme(scheme: str, factory) -> None:
    """Register ``factory(url) -> ByteChannel`` for ``scheme://`` paths."""
    _SCHEMES[scheme] = factory


def _ensure_builtin_scheme(scheme: str) -> None:
    """Lazy-load the built-in cloud backends on first gs://-or-s3:// use
    (core.cloud registers both on import; explicit registrations win)."""
    if scheme in ("gs", "s3") and scheme not in _SCHEMES:
        import spark_bam_tpu.core.cloud  # noqa: F401  (registers schemes)


def is_url(path) -> bool:
    return bool(_URL_RE.match(str(path)))


def _raw_url_channel(url: str) -> ByteChannel:
    """One-shot metadata channel for a URL: the bare backend, no prefetch
    pool (a HEAD or single ranged GET doesn't want read-ahead)."""
    scheme = _URL_RE.match(url).group(1)
    _ensure_builtin_scheme(scheme)
    if scheme in _SCHEMES:
        fn = _SCHEMES[scheme]
        # Built-in cloud backends default prefetch=True; a metadata probe
        # wants the bare transport. Handlers without the knob get the
        # plain call.
        if "prefetch" in inspect.signature(fn).parameters:
            return fn(url, prefetch=False)
        return fn(url)
    if scheme in ("http", "https"):
        from spark_bam_tpu.core.remote import HttpRangeChannel

        return HttpRangeChannel(url)
    raise ValueError(f"no channel backend for scheme {scheme!r}: {url}")


def path_size(path) -> int:
    """Byte size of a path or URL (URLs via the channel backend)."""
    if is_url(path):
        with _raw_url_channel(str(path)) as ch:
            return ch.size
    return os.path.getsize(str(path))


def read_text(path) -> str:
    """Full text of a path or URL (sidecar files: ``.blocks``/``.records``)."""
    if is_url(path):
        with _raw_url_channel(str(path)) as ch:
            return bytes(ch.read_at(0, ch.size)).decode()
    with open(str(path), "rt") as f:
        return f.read()


def path_exists(path) -> bool:
    """Existence of a path or URL. URLs: a size probe — only a definitive
    "missing" (FileNotFoundError, e.g. HTTP 404) reads as absent; transient
    network/auth failures propagate rather than silently degrading sidecar
    lookups (``.blocks``/``.records``/``.crai``) to full scans."""
    if is_url(path):
        try:
            with _raw_url_channel(str(path)) as ch:
                return ch.size >= 0
        except FileNotFoundError:
            return False
    return os.path.exists(str(path))


def open_channel(path, cached: bool = False) -> ByteChannel:
    """Open a channel for a path — the single pluggable IO seam.

    Local paths are mmap-backed. ``http(s)://`` URLs get an
    ``HttpRangeChannel`` wrapped by the remote data plane (plan-driven
    coalesced prefetch — core/remote_plan.py — or the legacy cursor
    read-ahead under ``mode=legacy``; SURVEY.md §7 hard-part 5). Other
    ``scheme://`` URLs dispatch through ``register_scheme``.
    """
    s = str(path)
    m = _URL_RE.match(s)
    if m:
        scheme = m.group(1)
        _ensure_builtin_scheme(scheme)
        if scheme in _SCHEMES:  # registrations override built-ins
            ch: ByteChannel = _SCHEMES[scheme](s)
        elif scheme in ("http", "https"):
            from spark_bam_tpu.core.remote import HttpRangeChannel
            from spark_bam_tpu.core.remote_plan import wrap_remote

            ch = wrap_remote(HttpRangeChannel(s))
        else:
            raise ValueError(f"no channel backend for scheme {scheme!r}: {s}")
    else:
        ch = MMapChannel(path)
    if _CHAOS_WRAPPER is not None:
        ch = _CHAOS_WRAPPER(ch, s)
    return CachingChannel(ch) if cached else ch
