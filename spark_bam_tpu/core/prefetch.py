"""Read-ahead channel for high-latency byte sources.

GCS latency was hadoop-bam's original sin (SURVEY.md §7 hard-part 5:
"async prefetch of compressed ranges, one open per shard, 64 KiB-aligned
reads"). ``PrefetchChannel`` wraps any ``ByteChannel`` and keeps a bounded
pipeline of aligned chunks in flight ahead of the read cursor, so
sequential scans (MetadataStream, block inflation) overlap IO with compute
regardless of the backend's latency.

A remote backend only needs to subclass ``ByteChannel`` with ``_read_at``
(one ranged GET) — this wrapper supplies the pipelining; ``CachingChannel``
supplies reuse.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from concurrent.futures import Future, ThreadPoolExecutor

from spark_bam_tpu.core.channel import ByteChannel


class PrefetchChannel(ByteChannel):
    def __init__(
        self,
        inner: ByteChannel,
        chunk_size: int = 1 << 20,
        depth: int = 4,
        workers: int = 4,
        max_chunks: int | None = None,
    ):
        super().__init__()
        self.inner = inner
        self.chunk_size = chunk_size
        self.depth = depth
        # Retention is LRU over a bounded chunk set (not cursor-relative):
        # multiple readers at different offsets (InflatePipeline keeps two
        # windows in flight) must not evict each other's chunks mid-read.
        self.max_chunks = max_chunks or max(4 * (depth + 1), 16)
        self._pool = ThreadPoolExecutor(max_workers=workers)
        self._inflight: OrderedDict[int, Future] = OrderedDict()
        self._pins: dict[int, int] = {}
        self._lock = threading.Lock()

    def _fetch(self, idx: int) -> Future:
        # read_at callers fan out across threads (block inflater, bench
        # pipelines); the in-flight map is the only shared state.
        with self._lock:
            fut = self._inflight.get(idx)
            if fut is not None:
                self._inflight.move_to_end(idx)
            else:
                fut = self._pool.submit(
                    self.inner._read_at, idx * self.chunk_size, self.chunk_size
                )
                self._inflight[idx] = fut
        return fut

    def _read_at(self, pos: int, n: int) -> bytes:
        first = pos // self.chunk_size
        last = (pos + max(n, 1) - 1) // self.chunk_size
        # Pin the window this read will consume: eviction must not race a
        # concurrent reader at a far-apart offset into dropping our chunks
        # between fetch and result() (two readers with a small max_chunks
        # would otherwise thrash each other into re-fetching everything).
        with self._lock:
            for idx in range(first, last + 1):
                self._pins[idx] = self._pins.get(idx, 0) + 1
        try:
            # Kick off the window we need plus read-ahead.
            for idx in range(first, last + 1 + self.depth):
                self._fetch(idx)
            out = []
            remaining = n
            cur = pos
            for idx in range(first, last + 1):
                chunk = self._fetch(idx).result()
                off = cur - idx * self.chunk_size
                piece = chunk[off: off + remaining]
                if not piece:
                    break
                out.append(piece)
                cur += len(piece)
                remaining -= len(piece)
                if remaining <= 0:
                    break
        finally:
            with self._lock:
                for idx in range(first, last + 1):
                    left = self._pins.get(idx, 0) - 1
                    if left <= 0:
                        self._pins.pop(idx, None)
                    else:
                        self._pins[idx] = left
                self._evict_locked()
        return b"".join(out)

    def _evict_locked(self) -> None:
        # Retire least-recently-used chunks to bound memory — but never a
        # pinned chunk (an outstanding reader holds it) or a pending fetch
        # (dropping it just re-pays the request). May transiently stay over
        # max_chunks while every chunk is pinned or in flight.
        excess = len(self._inflight) - self.max_chunks
        if excess <= 0:
            return
        for idx in list(self._inflight):
            if excess <= 0:
                break
            fut = self._inflight[idx]
            if self._pins.get(idx) or not fut.done():
                continue
            del self._inflight[idx]
            excess -= 1

    @property
    def size(self) -> int:
        return self.inner.size

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        self.inner.close()
