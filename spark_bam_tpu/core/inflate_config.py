"""Read-path inflate knobs: the ``Config.inflate`` string spec.

Same compact-spec pattern as ``deflate``/``faults``/``remote`` so the
frozen Config stays hashable and the ``SPARK_BAM_INFLATE`` env var and
``--inflate`` CLI plumbing work unchanged:

    tokenize=auto,kernel=auto,donate=on

``tokenize`` picks where the DEFLATE *entropy phase* runs for the
two-phase device inflate (tpu/inflate.py):

* ``host``   — the native ``sbt_tokenize_deflate`` decoder tokenizes on
  host and packed token planes ship to HBM (3 bytes per output byte),
  the pre-PR-15 behavior and the permanent correctness fallback.
* ``device`` — raw compressed payload bytes ship instead and the
  bit-reader kernel (tpu/tokenize_device.py / ``tokenize_pallas``)
  decodes Huffman tables and emits token planes on-device; malformed
  members demote per window, never produce wrong bytes.
* ``auto``   — ``device`` on the TPU backend, ``host`` elsewhere. The
  honest default: the vmapped bit-reader is profitable where lanes are
  wide and H2D is the bottleneck; on the CPU backend XLA serializes the
  symbol loop per lane and the native tokenizer wins by orders of
  magnitude (measured in docs/benchmarks.md).

``kernel`` pins the device tokenizer's engine: ``pallas`` (grid lanes,
VMEM rows), ``xla`` (the vmap form), or ``auto`` (pallas on TPU with
permanent demote-to-XLA on Mosaic refusal — the ``lz77_resolve_pallas``
policy). ``donate`` controls ``jax.jit`` buffer donation through the
dispatch/materialize split so the inflate window ring reuses HBM
instead of re-allocating per window; ``off`` is a debugging escape
hatch only.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

TOKENIZE = ("host", "device", "auto")
KERNEL = ("xla", "pallas", "auto")
ONOFF = ("on", "off")


@dataclass(frozen=True)
class InflateConfig:
    tokenize: str = "auto"
    kernel: str = "auto"
    donate: str = "on"

    @property
    def donate_enabled(self) -> bool:
        return self.donate == "on"

    def resolve_tokenize(self, backend: str | None = None) -> str:
        """Collapse ``auto`` to a concrete mode for ``backend`` (the
        current jax backend when None). Device tokenization pays off
        where block lanes run in parallel — the TPU grid — and loses
        badly on the CPU backend's serialized vmap, so auto is
        backend-gated, not capability-gated."""
        if self.tokenize != "auto":
            return self.tokenize
        if backend is None:
            import jax

            backend = jax.default_backend()
        return "device" if backend == "tpu" else "host"

    @staticmethod
    @functools.lru_cache(maxsize=64)
    def parse(spec: str) -> "InflateConfig":
        """Parse a ``tokenize=...,kernel=...,donate=...`` spec ("" ⇒
        defaults). Raises ``ValueError`` on unknown keys/values — the
        CLI validates before any work starts, like every other knob."""
        kw: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                # Bare token shorthand: "--inflate device" reads naturally.
                if part in TOKENIZE:
                    kw["tokenize"] = part
                    continue
                raise ValueError(
                    f"Bad inflate spec {spec!r}: {part!r} is not key=value"
                )
            key, value = part.split("=", 1)
            key, value = key.strip(), value.strip()
            if key == "tokenize":
                if value not in TOKENIZE:
                    raise ValueError(
                        f"Bad inflate tokenize {value!r}: expected "
                        f"{' | '.join(TOKENIZE)}"
                    )
                kw["tokenize"] = value
            elif key == "kernel":
                if value not in KERNEL:
                    raise ValueError(
                        f"Bad inflate kernel {value!r}: expected "
                        f"{' | '.join(KERNEL)}"
                    )
                kw["kernel"] = value
            elif key == "donate":
                if value not in ONOFF:
                    raise ValueError(
                        f"Bad inflate donate {value!r}: expected on | off"
                    )
                kw["donate"] = value
            else:
                raise ValueError(f"Unknown inflate key {key!r} in {spec!r}")
        return InflateConfig(**kw)
